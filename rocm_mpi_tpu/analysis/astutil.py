"""Shared AST machinery for graftlint rules (stdlib `ast` only).

Everything here is resolution *heuristics*, deliberately scoped to the
idioms this codebase actually uses (see docs/ANALYSIS.md "What the
analyzer can and cannot see"): names are resolved within one module,
`functools.partial` chains one level deep, and anything unresolvable is
silently skipped — a lint rule must miss a contrived case rather than
spray false positives over real code.

No third-party imports (the pinned image must run the gate with nothing
but the stdlib), and no jax import (the analyzer must run in <5 s on CPU
as a pre-test gate).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field


# ---------------------------------------------------------------------------
# Name / attribute helpers
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, None for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str:
    """Dotted callee name of a call ('' when not a plain name chain)."""
    return dotted_name(call.func) or ""


def tail_name(dotted: str) -> str:
    """Last component of a dotted name ('jax.jit' -> 'jit')."""
    return dotted.rpartition(".")[2]


def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def int_const(node: ast.AST) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def int_tuple(node: ast.AST) -> tuple[int, ...] | None:
    """Literal int, or tuple/list of literal ints, as a tuple; else None."""
    n = int_const(node)
    if n is not None:
        return (n,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            v = int_const(elt)
            if v is None:
                return None
            out.append(v)
        return tuple(out)
    return None


def str_args(node: ast.AST) -> list[str]:
    """String literals in `node` if it is a str constant or a tuple/list
    of them (the axis-name argument shapes of jax collectives)."""
    s = str_const(node)
    if s is not None:
        return [s]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [s for elt in node.elts if (s := str_const(elt)) is not None]
    return []


# ---------------------------------------------------------------------------
# Import table
# ---------------------------------------------------------------------------


@dataclass
class ImportTable:
    """What each top-level-bound name refers to.

    module_aliases: local name -> imported module path, for names that are
      certainly modules (`import x`, `import x.y as z`, and
      `from pkg import mod` when the source module is a known package
      prefix we care about).
    from_imports: local name -> 'module.attr' for `from module import attr`.
    """

    module_aliases: dict[str, str] = field(default_factory=dict)
    from_imports: dict[str, str] = field(default_factory=dict)

    def origin(self, local: str) -> str:
        """Dotted origin of a local name ('' when not import-bound)."""
        if local in self.module_aliases:
            return self.module_aliases[local]
        return self.from_imports.get(local, "")


# `from PKG import name` binds a submodule (not a function/class) often
# enough for these prefixes that graftlint treats the bound name as a
# module alias for GL02's cross-module-mutation check.
_MODULE_SOURCE_PREFIXES = (
    "jax.experimental",
    "rocm_mpi_tpu",
)


def collect_imports(tree: ast.Module) -> ImportTable:
    table = ImportTable()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.partition(".")[0]
                table.module_aliases[local] = (
                    alias.name if alias.asname else alias.name.partition(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                local = alias.asname or alias.name
                full = f"{node.module}.{alias.name}"
                table.from_imports[local] = full
                if node.module.startswith(_MODULE_SOURCE_PREFIXES):
                    table.module_aliases.setdefault(local, full)
    return table


# ---------------------------------------------------------------------------
# Function indexing and partial resolution
# ---------------------------------------------------------------------------


def index_functions(tree: ast.Module) -> dict[str, ast.FunctionDef]:
    """name -> FunctionDef for every def in the module, nested included
    (last definition wins on collision — a heuristic, documented)."""
    out: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def resolve_callable_name(node: ast.AST, assignments: dict[str, ast.AST]) -> str | None:
    """Resolve an expression to the simple name of the function it wraps.

    Handles: a plain Name (chasing one level of `x = functools.partial(f, …)`
    / `x = f` assignment in the same module), and a direct
    `functools.partial(f, …)` call.
    """
    for _ in range(4):  # bounded chase
        if isinstance(node, ast.Name):
            if node.id in assignments:
                node = assignments[node.id]
                continue
            return node.id
        if isinstance(node, ast.Call) and tail_name(call_name(node)) == "partial":
            if node.args:
                node = node.args[0]
                continue
            return None
        return None
    return None


def collect_assignments(tree: ast.Module) -> dict[str, ast.AST]:
    """name -> RHS expression for simple single-target assignments anywhere
    in the module (used only to chase partial/alias chains)."""
    out: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node.value
    return out


# ---------------------------------------------------------------------------
# Traced-body discovery (jit / shard_map / pallas kernels)
# ---------------------------------------------------------------------------

_JIT_NAMES = frozenset({"jit", "pjit"})


def _is_jit_expr(node: ast.AST) -> bool:
    """True for expressions that produce a jitted transform: `jax.jit`,
    `jit`, `pjit`, `jax.jit(...)`, `functools.partial(jax.jit, ...)`."""
    name = dotted_name(node)
    if name is not None:
        return tail_name(name) in _JIT_NAMES
    if isinstance(node, ast.Call):
        cname = tail_name(call_name(node))
        if cname in _JIT_NAMES:
            return True
        if cname == "partial" and node.args:
            return _is_jit_expr(node.args[0])
    return False


def jit_decorators(fn: ast.FunctionDef) -> list[ast.AST]:
    return [d for d in fn.decorator_list if _is_jit_expr(d)]


@dataclass
class TracedBody:
    fn: ast.FunctionDef
    kind: str  # "jit" | "shard_map" | "pallas"
    call: ast.Call | None = None  # the wrapping call, when discovered via one


def traced_bodies(tree: ast.Module) -> list[TracedBody]:
    """Functions whose bodies run at trace time under jit / shard_map /
    pallas_call — by decorator, or by being passed (by name, possibly
    through a `functools.partial`) into such a call in this module.
    Nested defs inside a traced body are traced too.
    """
    functions = index_functions(tree)
    assignments = collect_assignments(tree)
    found: dict[ast.FunctionDef, TracedBody] = {}

    for name, fn in functions.items():
        if jit_decorators(fn):
            found[fn] = TracedBody(fn, "jit")

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = tail_name(call_name(node))
        if callee in ("shard_map", "pallas_call") or callee in _JIT_NAMES:
            kind = {"shard_map": "shard_map", "pallas_call": "pallas"}.get(
                callee, "jit"
            )
            if not node.args:
                continue
            target = resolve_callable_name(node.args[0], assignments)
            fn = functions.get(target) if target else None
            if fn is not None and fn not in found:
                found[fn] = TracedBody(fn, kind, node)

    # Close over nested defs: anything defined inside a traced body traces.
    out = dict(found)
    for body in list(found.values()):
        for node in ast.walk(body.fn):
            if isinstance(node, ast.FunctionDef) and node is not body.fn \
                    and node not in out:
                out[node] = TracedBody(node, body.kind)
    return list(out.values())


def pallas_kernel_functions(tree: ast.Module) -> list[tuple[ast.FunctionDef, ast.Call]]:
    """(kernel FunctionDef, pallas_call Call) pairs resolvable in-module."""
    functions = index_functions(tree)
    assignments = collect_assignments(tree)
    out = []
    seen = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if tail_name(call_name(node)) != "pallas_call" or not node.args:
            continue
        target = resolve_callable_name(node.args[0], assignments)
        fn = functions.get(target) if target else None
        if fn is not None and fn.name not in seen:
            seen.add(fn.name)
            out.append((fn, node))
    return out


def call_kwarg(call: ast.Call, name: str) -> ast.AST | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def walk_no_nested_functions(node: ast.AST):
    """ast.walk that does not descend into nested FunctionDef/Lambda."""
    stack = [node]
    first = True
    while stack:
        cur = stack.pop()
        if not first and isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        first = False
        yield cur
        stack.extend(ast.iter_child_nodes(cur))
