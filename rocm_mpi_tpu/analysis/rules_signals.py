"""GL07 — signal/faulthandler hygiene outside the health-plane owners.

The health plane's post-mortem hook (telemetry/flight.py) owns SIGUSR2
via `faulthandler.register`, and the resilience tier owns deliberate
process-fate decisions. A second `signal.signal`/`faulthandler.register`
anywhere else silently STEALS that disposition — Python keeps exactly
one handler per signal per process, last install wins — so the
watchdog's SIGUSR2 would dump nothing and the post-mortem bundle would
ship empty, with no error anywhere. Handler installs also don't compose
across libraries (orbax, jax's own faulthandler use at init), which is
why the framework routes every one of them through two audited homes:

* `rocm_mpi_tpu/telemetry/flight.py` — the SIGUSR2 post-mortem hook
* `rocm_mpi_tpu/resilience/`          — fault injection / supervision

Flagged everywhere else:

* calls to `signal.signal(...)` / `signal.sigaction` / `signal.setitimer`
  (module-attribute or from-import alias spellings)
* any import of `faulthandler` (importing it is the capability; every
  use of it manipulates process-wide dump state)

NOT flagged: reading signal CONSTANTS (`signal.SIGUSR2`) and sending
signals (`proc.send_signal`, `os.kill`) — observing or delivering a
signal is fine anywhere; only *handler installation* is owned.
"""

from __future__ import annotations

import ast

from rocm_mpi_tpu.analysis import astutil
from rocm_mpi_tpu.analysis.core import ModuleContext, Rule

_OWNER_FILES = (
    "rocm_mpi_tpu/telemetry/flight.py",
)
_OWNER_DIR_MARK = "/rocm_mpi_tpu/resilience/"

_INSTALLERS = frozenset({"signal", "sigaction", "setitimer"})


def _is_owner(ctx: ModuleContext) -> bool:
    return (
        ctx.posix_path.endswith(_OWNER_FILES)
        or _OWNER_DIR_MARK in ctx.posix_path
    )


class SignalHygieneRule(Rule):
    id = "GL07"
    name = "signal-hygiene"
    severity = "error"
    rationale = (
        "signal handlers don't compose: a stray signal.signal/"
        "faulthandler install silently steals the health plane's "
        "SIGUSR2 post-mortem hook (owners: telemetry/flight.py, "
        "resilience/)"
    )
    hint = "see docs/ANALYSIS.md#gl07"

    def check(self, ctx: ModuleContext):
        if _is_owner(ctx):
            return []
        imports = astutil.collect_imports(ctx.tree)
        signal_modules = {
            local for local, mod in imports.module_aliases.items()
            if mod == "signal"
        }
        installer_aliases = {
            local: origin.rpartition(".")[2]
            for local, origin in imports.from_imports.items()
            if origin in {f"signal.{fn}" for fn in _INSTALLERS}
        }
        findings = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[0] == "faulthandler":
                        findings.append(ctx.finding(
                            node, self,
                            "faulthandler import outside the health-"
                            "plane owners — its dump targets are "
                            "process-wide state the SIGUSR2 post-mortem "
                            "hook depends on",
                            "route post-mortem dumps through "
                            "telemetry.flight.install_postmortem_handler",
                        ))
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "faulthandler":
                    findings.append(ctx.finding(
                        node, self,
                        "faulthandler import outside the health-plane "
                        "owners",
                        "route post-mortem dumps through "
                        "telemetry.flight.install_postmortem_handler",
                    ))
            elif isinstance(node, ast.Call):
                fn = node.func
                spelled = None
                if (
                    isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in signal_modules
                    and fn.attr in _INSTALLERS
                ):
                    spelled = f"{fn.value.id}.{fn.attr}"
                elif isinstance(fn, ast.Name) and fn.id in installer_aliases:
                    spelled = f"{fn.id} (= signal.{installer_aliases[fn.id]})"
                if spelled is not None:
                    findings.append(ctx.finding(
                        node, self,
                        f"{spelled}() installs a process-wide signal "
                        "handler outside the owners — last install wins, "
                        "so this silently disarms the health plane's "
                        "SIGUSR2 hook",
                        "move the handler into telemetry/flight.py or "
                        "resilience/ (or deliver signals instead of "
                        "handling them)",
                    ))
        return findings
