"""GL05 — collective/axis-name consistency inside shard_map bodies.

A `psum`/`ppermute`/`axis_index` over an axis name that is not in the
surrounding mesh raises at trace time *on the sharded path only* — CPU
tests that exercise a 1-device mesh or the GSPMD variant never touch it,
so the typo ships to the chip session (where every failed trace costs a
flaky-tunnel round trip; SURVEY.md §0's whole point is that the comms
engineering is hand-tuned and easy to get quietly wrong).

Statically checkable slice: for functions passed to `shard_map` in this
module, every *literal* axis-name argument of a collective must appear in
the module's literal axis vocabulary — names in `Mesh(...)` /
`PartitionSpec(...)` / `P(...)` calls, `axis_name(s)=` kwargs, and
`AXIS_NAMES`-style module constants. Variables (the common in-tree case:
`grid.axis_names[ax]`) are skipped — the rule only judges what it can see.
Modules with no axis literals at all are skipped entirely.

The `batch` axis vocabulary (PR 13, docs/SERVING.md): on a space×batch
mesh the leading `batch` axis carries INDEPENDENT simulation lanes —
separate tenants. A permutation-family collective (`ppermute`,
`pshuffle`, `all_to_all`) over the literal `batch` axis moves one
tenant's state into another's lane — a cross-tenant leak no 1-device
CPU test ever executes — so it is a finding even though `batch` is in
the mesh vocabulary. Reductions (`psum`/`pmean`/…) over `batch` stay
clean: cross-lane diagnostics are legitimate.
"""

from __future__ import annotations

import ast

from rocm_mpi_tpu.analysis import astutil
from rocm_mpi_tpu.analysis.core import ModuleContext, Rule

_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter", "axis_index", "axis_size",
}

# The permutation family: collectives that MOVE state between mesh
# positions (vs reducing over them). Over the multi-tenant lane axis
# that is a cross-tenant leak (parallel.mesh.BATCH_AXIS contract).
_PERMUTING = {"ppermute", "pshuffle", "all_to_all"}
_BATCH_AXIS = "batch"  # literal twin of parallel.mesh.BATCH_AXIS


def _module_axis_vocabulary(tree: ast.Module) -> set[str]:
    vocab: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            callee = astutil.tail_name(astutil.call_name(node))
            if callee == "Mesh" and len(node.args) >= 2:
                vocab.update(astutil.str_args(node.args[1]))
            elif callee in ("PartitionSpec", "P"):
                for arg in node.args:
                    vocab.update(astutil.str_args(arg))
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis_names"):
                    vocab.update(astutil.str_args(kw.value))
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                "AXIS" in node.targets[0].id.upper():
            vocab.update(astutil.str_args(node.value))
    return vocab


class AxisConsistencyRule(Rule):
    id = "GL05"
    name = "collective-axis-consistency"
    severity = "error"
    rationale = (
        "a collective over an axis name missing from the mesh only fails "
        "on the sharded trace — 1-device CPU tests never reach it, so the "
        "typo surfaces mid-chip-session"
    )
    hint = "see docs/ANALYSIS.md#gl05"

    def check(self, ctx: ModuleContext):
        vocab = _module_axis_vocabulary(ctx.tree)
        if not vocab:
            return []
        findings = []
        for traced in astutil.traced_bodies(ctx.tree):
            if traced.kind != "shard_map":
                continue
            for node in astutil.walk_no_nested_functions(traced.fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = astutil.tail_name(astutil.call_name(node))
                if callee not in _COLLECTIVES:
                    continue
                literal_axes = []
                for arg in node.args:
                    literal_axes.extend(astutil.str_args(arg))
                for kw in node.keywords:
                    if kw.arg == "axis_name":
                        literal_axes.extend(astutil.str_args(kw.value))
                for axis in literal_axes:
                    if axis not in vocab:
                        findings.append(ctx.finding(
                            node, self,
                            f"collective '{callee}' over axis '{axis}' "
                            f"inside shard_map body '{traced.fn.name}', "
                            "but this module's mesh/spec axis names are "
                            f"{sorted(vocab)}",
                            "use an axis name from the mesh (or thread "
                            "grid.axis_names through instead of a "
                            "literal)",
                        ))
                    elif axis == _BATCH_AXIS and callee in _PERMUTING:
                        findings.append(ctx.finding(
                            node, self,
                            f"halo/permutation collective '{callee}' over "
                            f"the '{_BATCH_AXIS}' lane axis inside "
                            f"shard_map body '{traced.fn.name}' — lanes "
                            "are independent tenants (docs/SERVING.md); "
                            "permuting state across the batch axis leaks "
                            "one simulation into another",
                            "halo collectives belong on the space axes "
                            "only (reductions like psum over 'batch' — "
                            "cross-lane diagnostics — are fine)",
                        ))
        return findings
