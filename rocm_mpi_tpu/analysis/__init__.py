"""graftlint — AST-based shard-safety static analysis for this repo.

Six rule families, each grounded in a bug class this codebase has
actually shipped (rule catalog: docs/ANALYSIS.md):

    GL01 donation-safety        read-after-donate / async-save overlap
    GL02 trace-time-purity      module-global mutation visible to traces
    GL03 compat-drift           raw jax APIs outside utils/compat+backend
    GL04 pallas-hygiene         bare refs, skipped f32 upcast, grid/BlockSpec
    GL05 collective-axis        axis names missing from the mesh
    GL06 raw-timing             perf_counter/time() outside telemetry+metrics

Run the gate:  python -m rocm_mpi_tpu.analysis rocm_mpi_tpu apps bench.py
Suppress:      # graftlint: disable=GL01   (also disable-next=, disable-file=)

stdlib-only by design: the pinned jax-0.4.37 image runs it with no
optional deps, and a repo-wide walk stays under the tier-1 5 s budget.
"""

from rocm_mpi_tpu.analysis.core import (
    PARSE_RULE,
    Finding,
    Rule,
    all_rules,
    gate_exit_code,
    lint_file,
    lint_paths,
    lint_source,
)
from rocm_mpi_tpu.analysis.report import (
    counts_by_rule,
    rule_table,
    to_json,
    to_text,
)

__all__ = [
    "PARSE_RULE",
    "Finding",
    "Rule",
    "all_rules",
    "counts_by_rule",
    "gate_exit_code",
    "lint_file",
    "lint_paths",
    "lint_source",
    "rule_table",
    "to_json",
    "to_text",
]
