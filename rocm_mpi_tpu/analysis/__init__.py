"""graftlint — whole-program shard-safety static analysis for this repo.

Ten rule families, each grounded in a bug class this codebase has
actually shipped (rule catalog: docs/ANALYSIS.md):

    GL01 donation-safety        read-after-donate / async-save overlap
                                (interprocedural since v2: donating
                                callables resolve across modules)
    GL02 trace-time-purity      module-global mutation visible to traces
    GL03 compat-drift           raw jax APIs outside utils/compat+backend
    GL04 pallas-hygiene         bare refs, skipped f32 upcast, grid/BlockSpec
    GL05 collective-axis        axis names missing from the mesh
    GL06 raw-timing             perf_counter/time() outside telemetry+metrics
    GL07 signal-hygiene         signal/faulthandler outside flight+resilience
    GL08 collective-divergence  collectives under rank- or per-rank-file-
                                content-dependent control flow (whole-
                                program engine: analysis/engine.py)
    GL09 sidecar-atomicity      schema-versioned artifacts written without
                                tmp+rename / append-only discipline
    GL10 concurrency-discipline lock-guarded attrs accessed unlocked,
                                *_locked without the lock, lock-order
                                cycles, blocking under locks, serving
                                clock/sidecar-writer ownership (whole-
                                program engine: rules_concurrency.py)

Run the gate:  python -m rocm_mpi_tpu.analysis rocm_mpi_tpu apps bench.py
Suppress:      # graftlint: disable=GL01   (also disable-next=, disable-file=)
Baseline:      --baseline / --baseline-write (analysis/baseline.json)
Fast mode:     --changed (git-dirty files + import-graph neighbors)
Audit:         --strict-suppressions (dead disable directives -> GL99)

The AST side is paired with a ground-truth lowered-program audit
(`python -m rocm_mpi_tpu.analysis.lowered`): it compiles the steady-state
drivers of all three workloads and verifies the collective sequence is
identical across rank-roles and every declared donation actually aliased.

stdlib-only by design (the lowered audit is the one deliberate
exception — it imports jax, and only runs when invoked): the pinned
jax-0.4.37 image runs the AST gate with no optional deps, and a
repo-wide walk stays fast enough for tier-1.
"""

from rocm_mpi_tpu.analysis.core import (
    PARSE_RULE,
    Finding,
    Rule,
    all_rules,
    catalog_rules,
    gate_exit_code,
    lint_file,
    lint_paths,
    lint_source,
    source_digest,
)
from rocm_mpi_tpu.analysis.report import (
    counts_by_rule,
    findings_doc,
    rule_table,
    to_json,
    to_text,
    validate_findings_doc,
    write_findings,
)

__all__ = [
    "PARSE_RULE",
    "Finding",
    "Rule",
    "all_rules",
    "catalog_rules",
    "counts_by_rule",
    "findings_doc",
    "gate_exit_code",
    "lint_file",
    "lint_paths",
    "lint_source",
    "rule_table",
    "source_digest",
    "to_json",
    "to_text",
    "validate_findings_doc",
    "write_findings",
]
