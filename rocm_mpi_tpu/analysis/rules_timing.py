"""GL06 — raw timing outside the measurement chokepoints.

The telemetry subsystem exists because scattered `time.perf_counter()`
pairs produce walltime numbers with no sync discipline (jax dispatch is
async — an unsynced interval times the *enqueue*, not the work; on the
tunneled-chip transport even `block_until_ready` lies, see
utils/metrics.py) and no destination (the number is printed and lost
instead of landing in the per-rank stream the aggregation/regression
tooling reads). `time.time()` has the same two problems plus wall-clock
jumps.

The rule flags calls to `time.perf_counter[_ns]()` and `time.time[_ns]()`
— by module attribute or `from time import …` alias — everywhere except
the two owners that implement the discipline:

* `rocm_mpi_tpu/telemetry/`   (spans/events own the clock reads)
* `rocm_mpi_tpu/utils/metrics.py` (Timer + force, the sync-correct pair)

`time.monotonic()` is deliberately NOT flagged: the launcher's
supervision heartbeats and bench.py's budget bookkeeping are wall-clock
*control flow* (deadlines), not measurements, and monotonic is the right
tool there. `time.sleep` is obviously fine. The fix for a finding is a
telemetry span, a labeled `metrics.Timer`, or — for a genuine new
measurement primitive — moving the code into an owner.
"""

from __future__ import annotations

import ast

from rocm_mpi_tpu.analysis import astutil
from rocm_mpi_tpu.analysis.core import ModuleContext, Rule

_OWNER_FILES = (
    "rocm_mpi_tpu/utils/metrics.py",
)
_OWNER_DIR_MARK = "/rocm_mpi_tpu/telemetry/"

_FLAGGED = frozenset({"perf_counter", "perf_counter_ns", "time", "time_ns"})


def _is_owner(ctx: ModuleContext) -> bool:
    return (
        ctx.posix_path.endswith(_OWNER_FILES)
        or _OWNER_DIR_MARK in ctx.posix_path
    )


class RawTimingRule(Rule):
    id = "GL06"
    name = "raw-timing"
    severity = "error"
    rationale = (
        "bare time.perf_counter()/time.time() timing has no sync "
        "discipline (async dispatch: it times the enqueue, not the work) "
        "and bypasses the telemetry stream; use telemetry.span / a "
        "labeled metrics.Timer (owners: utils/metrics.py, telemetry/)"
    )
    hint = "see docs/ANALYSIS.md#gl06"

    def check(self, ctx: ModuleContext):
        if _is_owner(ctx):
            return []
        imports = astutil.collect_imports(ctx.tree)
        # Local aliases bound to the time module / its flagged functions.
        time_modules = {
            local for local, mod in imports.module_aliases.items()
            if mod == "time"
        }
        flagged_names = {
            local: origin.rpartition(".")[2]
            for local, origin in imports.from_imports.items()
            if origin in {f"time.{fn}" for fn in _FLAGGED}
        }
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            spelled = None
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id in time_modules
                and fn.attr in _FLAGGED
            ):
                spelled = f"{fn.value.id}.{fn.attr}"
            elif isinstance(fn, ast.Name) and fn.id in flagged_names:
                spelled = f"{fn.id} (= time.{flagged_names[fn.id]})"
            if spelled is not None:
                findings.append(ctx.finding(
                    node, self,
                    f"raw {spelled}() timing outside the measurement "
                    "chokepoints — unsynced against async dispatch and "
                    "invisible to telemetry",
                    "wrap the interval in telemetry.span(...) or a "
                    "labeled utils.metrics.Timer (both sync via the "
                    "device-fetch force())",
                ))
        return findings
