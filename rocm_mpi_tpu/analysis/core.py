"""graftlint core: findings, suppressions, the rule registry, file walking,
and the wiring of the whole-program interprocedural pass.

The analyzer is a pre-test gate (scripts/lint.sh, tests/test_self_lint.py)
so the whole pipeline is stdlib-only and cached: per-file findings are
keyed by a blake2 content hash (never mtime/size — a same-second
same-size edit must not serve a stale tree), whole-program findings by
the exact (path, hash) module set, and a repeat repo-wide run is a
near-no-op.

Suppressions (all take a comma-separated rule list or `all`):

    x = risky()          # graftlint: disable=GL01
    # graftlint: disable-next=GL02,GL03
    x = risky()
    # graftlint: disable-file=GL05      (anywhere in the file)

Suppressed findings are still produced (marked ``suppressed=True``) so
reporters can show them; only non-suppressed findings gate the exit code.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import os
import re
import tokenize
from pathlib import Path

PARSE_RULE = "GL00"  # pseudo-rule for unparseable-file warnings


@dataclasses.dataclass
class Finding:
    file: str
    line: int
    col: int
    rule: str
    severity: str  # "error" | "warning"
    message: str
    hint: str = ""
    suppressed: bool = False
    # Accepted by a committed baseline (analysis/baseline.py): shown in
    # reports, does not gate — how a new rule lands before the repo is
    # clean under it.
    baselined: bool = False

    def location(self) -> str:
        return f"{self.file}:{self.line}:{self.col}"


@dataclasses.dataclass
class ModuleContext:
    """Everything a rule gets to look at for one file."""

    path: str  # as given / repo-relative for reporting
    posix_path: str  # normalized forward-slash form for allowlists
    source: str
    tree: ast.Module

    def finding(self, node: ast.AST, rule, message: str, hint: str = "",
                severity: str | None = None) -> Finding:
        return Finding(
            file=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            rule=rule.id,
            severity=severity or rule.severity,
            message=message,
            hint=hint or rule.hint,
        )


class Rule:
    """One rule family. Subclasses set id/name/severity/hint and implement
    check(ctx) -> iterable[Finding]."""

    id: str = "GL??"
    name: str = ""
    severity: str = "error"
    hint: str = ""
    # One-line rationale shown by --list-rules and the docs generator.
    rationale: str = ""

    def check(self, ctx: ModuleContext):  # pragma: no cover - interface
        raise NotImplementedError


def all_rules() -> list[Rule]:
    """The registered PER-FILE rule families, GL-id order. GL08 is not
    here: collective divergence is a whole-program property, computed by
    the interprocedural pass (engine.analyze_modules) that lint_source
    runs over its one module and lint_paths runs over the full set."""
    from rocm_mpi_tpu.analysis.rules_collective import AxisConsistencyRule
    from rocm_mpi_tpu.analysis.rules_compat import CompatDriftRule
    from rocm_mpi_tpu.analysis.rules_donation import DonationSafetyRule
    from rocm_mpi_tpu.analysis.rules_pallas import PallasHygieneRule
    from rocm_mpi_tpu.analysis.rules_purity import TraceTimePurityRule
    from rocm_mpi_tpu.analysis.rules_sidecar import SidecarAtomicityRule
    from rocm_mpi_tpu.analysis.rules_signals import SignalHygieneRule
    from rocm_mpi_tpu.analysis.rules_timing import RawTimingRule

    return [
        DonationSafetyRule(),
        TraceTimePurityRule(),
        CompatDriftRule(),
        PallasHygieneRule(),
        AxisConsistencyRule(),
        RawTimingRule(),
        SignalHygieneRule(),
        SidecarAtomicityRule(),
    ]


def catalog_rules() -> list[Rule]:
    """Every rule family for reports and --list-rules: the per-file
    rules plus the interprocedural-only ones, GL-id order."""
    from rocm_mpi_tpu.analysis.rules_concurrency import ConcurrencyRule
    from rocm_mpi_tpu.analysis.rules_divergence import DivergenceRule

    return sorted(
        all_rules() + [DivergenceRule(), ConcurrencyRule()],
        key=lambda r: r.id,
    )


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable(?:-next|-file)?)\s*=\s*([A-Za-z0-9_,\s]+)"
)


@dataclasses.dataclass
class Suppressions:
    by_line: dict[int, set[str]]
    file_wide: set[str]

    def covers(self, finding: Finding) -> bool:
        rules = self.by_line.get(finding.line, set()) | self.file_wide
        return "ALL" in rules or finding.rule in rules


def _comment_tokens(source: str):
    """(lineno, text) of real COMMENT tokens only — a docstring that merely
    *documents* a directive must not install one. On tokenize failure
    (rare for ast-parseable source) no suppressions apply: the safe
    direction is findings staying live, never silently vanishing."""
    try:
        return [
            (tok.start[0], tok.string)
            for tok in tokenize.generate_tokens(io.StringIO(source).readline)
            if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError, ValueError):
        return []


def parse_suppressions(source: str) -> Suppressions:
    by_line: dict[int, set[str]] = {}
    file_wide: set[str] = set()
    for lineno, comment in _comment_tokens(source):
        m = _SUPPRESS_RE.search(comment)
        if not m:
            continue
        directive = m.group(1)
        rules = {r.strip().upper() for r in m.group(2).split(",") if r.strip()}
        if directive == "disable-file":
            file_wide |= rules
        elif directive == "disable-next":
            by_line.setdefault(lineno + 1, set()).update(rules)
        else:
            by_line.setdefault(lineno, set()).update(rules)
    return Suppressions(by_line=by_line, file_wide=file_wide)


# ---------------------------------------------------------------------------
# Linting
# ---------------------------------------------------------------------------


def _selected(rules: list[Rule], select) -> list[Rule]:
    if not select:
        return rules
    wanted = {s.strip().upper() for s in select}
    return [r for r in rules if r.id in wanted]


def lint_source(source: str, path: str = "<string>", select=None,
                rules: list[Rule] | None = None,
                interprocedural: bool = True,
                digest: str | None = None) -> list[Finding]:
    """Lint one source string: the per-file rules plus (by default) the
    interprocedural pass over this file as a one-module program (so
    GL08 and the interprocedural GL01 extension fire on self-contained
    inputs — fixtures, ad-hoc checks). lint_paths passes
    interprocedural=False per file and runs ONE whole-program pass over
    the full module set instead — same union, computed once.
    Unparseable source yields a single GL00 warning instead of raising
    — the gate must never crash on an input."""
    explicit_rules = rules is not None
    rules = _selected(rules if rules is not None else all_rules(), select)
    # Normalized absolute form so the chokepoint allowlists (GL03) match
    # regardless of cwd, `..` segments, or how the gate spelled the path.
    posix = Path(os.path.normpath(os.path.abspath(path))).as_posix()
    try:
        tree = _parse_cached(source, path, digest)
    except (SyntaxError, ValueError, RecursionError) as e:
        return [
            Finding(
                file=path,
                line=getattr(e, "lineno", 1) or 1,
                col=(getattr(e, "offset", 1) or 1),
                rule=PARSE_RULE,
                severity="warning",
                message=f"could not parse file ({type(e).__name__}: {e}); "
                        "skipped",
                hint="graftlint gates only what it can parse — fix the "
                     "syntax error to restore coverage",
            )
        ]
    ctx = ModuleContext(path=path, posix_path=posix, source=source, tree=tree)
    suppressions = parse_suppressions(source)
    findings: list[Finding] = []
    for rule in rules:
        for f in rule.check(ctx):
            f.suppressed = suppressions.covers(f)
            findings.append(f)
    if not explicit_rules and interprocedural:
        from rocm_mpi_tpu.analysis import engine

        mod = engine.ModuleInfo(
            path=path, name=engine.module_name_for_path(path),
            source=source, tree=tree, suppressions=suppressions,
        )
        findings.extend(engine.analyze_modules([mod], select=select))
        findings = _dedupe(findings)
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return findings


# (path, display, content hash) -> findings; makes the repo-wide tier-1
# run a near-no-op when invoked twice in one process (tests + gate).
# Content-hashed on purpose: the old (mtime, size) key missed
# same-second same-size edits and could serve a stale tree to the gate;
# a blake2 of the source (which we must read anyway) cannot.
_CACHE: dict[tuple[str, str | None, str], list[Finding]] = {}


def source_digest(source: str) -> str:
    return hashlib.blake2b(
        source.encode("utf-8", "surrogatepass"), digest_size=16
    ).hexdigest()


# (display path, digest) -> parsed tree. The per-file pass and the
# whole-program pass see the same module set, so one parse serves both
# (rules treat trees as read-only); without it every gate file was
# parsed twice per run.
_PARSE_CACHE: dict[tuple[str, str], ast.Module] = {}


def _parse_cached(source: str, path: str, digest: str | None) -> ast.Module:
    key = (path, digest or source_digest(source))
    tree = _PARSE_CACHE.get(key)
    if tree is None:
        tree = ast.parse(source, filename=path)
        _PARSE_CACHE[key] = tree
    return tree


def _read_source(path: Path):
    """(source, digest, error) — error is the OSError, if any."""
    try:
        source = path.read_text(encoding="utf-8", errors="replace")
    except OSError as e:
        return None, None, e
    return source, source_digest(source), None


def _unreadable_finding(path, error) -> Finding:
    return Finding(
        file=str(path), line=1, col=1,
        rule=PARSE_RULE, severity="warning",
        message=f"could not read file ({error}); skipped",
    )


def lint_file(path: Path, select=None, rules=None,
              display_path: str | None = None,
              preread=None) -> list[Finding]:
    source, digest, err = (
        preread if preread is not None else _read_source(path)
    )
    if err is not None:
        return [_unreadable_finding(display_path or str(path), err)]
    key = (str(path), display_path, digest)
    if select is None and rules is None and key in _CACHE:
        # deep-ish copies: a caller mutating a Finding (reporters toggling
        # flags) must not poison later cache hits
        return [dataclasses.replace(f) for f in _CACHE[key]]
    findings = lint_source(
        source, display_path or str(path), select=select, rules=rules,
        interprocedural=False,  # lint_paths runs ONE whole-program pass
        digest=digest,
    )
    if select is None and rules is None:
        _CACHE[key] = [dataclasses.replace(f) for f in findings]
    return findings


_SKIP_DIRS = {
    ".git", "__pycache__", ".jax_cache", "node_modules", ".venv", "venv",
    "analysis_fixtures",
}


def iter_python_files(paths) -> list[Path]:
    out: list[Path] = []
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in _SKIP_DIRS
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(Path(dirpath) / fn)
        elif p.suffix == ".py":
            out.append(p)
    return out


# Whole-program findings keyed by the exact module set (display paths +
# content hashes) and rule selection — the second tier-1 walk must stay
# a near-no-op even though the program pass is global by nature.
_PROGRAM_CACHE: dict[tuple, list[Finding]] = {}


def _program_findings(entries, select) -> list[Finding]:
    """Interprocedural pass (engine.analyze_modules) over the parsed
    module set. `entries` = [(display_path, source, digest)]; files the
    per-file pass could not parse contribute nothing (it already warned
    GL00 for them)."""
    from rocm_mpi_tpu.analysis import engine

    sel_key = (
        tuple(sorted(s.strip().upper() for s in select)) if select else None
    )
    key = (tuple(sorted((d, h) for d, _, h in entries)), sel_key)
    if key in _PROGRAM_CACHE:
        return [dataclasses.replace(f) for f in _PROGRAM_CACHE[key]]
    modules = []
    for display, source, digest in entries:
        try:
            tree = _parse_cached(source, display, digest)
        except (SyntaxError, ValueError, RecursionError):
            continue
        modules.append(engine.ModuleInfo(
            path=display,
            name=engine.module_name_for_path(display),
            source=source,
            tree=tree,
        ))
    findings = engine.analyze_modules(modules, select=select)
    _PROGRAM_CACHE[key] = [dataclasses.replace(f) for f in findings]
    return findings


def _dedupe(findings: list[Finding]) -> list[Finding]:
    """Drop exact duplicate sites (the per-file GL08/GL01 shims overlap
    with the whole-program pass on purpose — union semantics)."""
    unique: dict[tuple, Finding] = {}
    for f in findings:
        unique.setdefault((f.file, f.line, f.col, f.rule, f.message), f)
    return list(unique.values())


def read_entries(paths) -> list[tuple]:
    """[(display_path, source, digest)] for every .py under `paths` —
    the module-set view the incremental (--changed) neighborhood
    expansion works from."""
    entries = []
    for f in iter_python_files(paths):
        source, digest, err = _read_source(f)
        if err is None:
            entries.append((str(f), source, digest))
    return entries


def lint_paths(paths, select=None, restrict=None,
               interprocedural: bool = True) -> tuple[list[Finding], int]:
    """Lint files/dirs: the per-file rules plus (by default) the
    whole-program interprocedural pass over every module in the set.
    Returns (findings, files_scanned). Nonexistent paths raise
    FileNotFoundError (a mistyped gate path must fail loudly, not
    silently lint nothing).

    `restrict` (the --changed fast mode): a set of resolved posix paths
    — per-file findings are only computed and reported for those files,
    but the program pass still parses EVERYTHING (summaries of
    unchanged callees are what make the interprocedural verdict on the
    changed files sound)."""
    for raw in paths:
        if not Path(raw).exists():
            raise FileNotFoundError(f"lint path does not exist: {raw}")
    files = iter_python_files(paths)
    findings: list[Finding] = []
    entries = []
    scanned = 0
    for f in files:
        resolved = Path(os.path.normpath(os.path.abspath(f))).as_posix()
        selected = restrict is None or resolved in restrict
        preread = None
        if interprocedural or selected:
            preread = _read_source(f)  # ONE read serves both passes
            _, _, err = preread
            if interprocedural and err is None:
                entries.append((str(f), preread[0], preread[1]))
        if selected:
            scanned += 1
            findings.extend(lint_file(f, select=select, preread=preread))
    if interprocedural:
        prog = _program_findings(entries, select)
        if restrict is not None:
            prog = [
                p for p in prog
                if Path(os.path.normpath(os.path.abspath(p.file))).as_posix()
                in restrict
            ]
        findings.extend(prog)
    findings = _dedupe(findings)
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return findings, scanned


# ---------------------------------------------------------------------------
# Stale-suppression audit (--strict-suppressions)
# ---------------------------------------------------------------------------

STALE_RULE = "GL99"  # pseudo-rule for directives that suppress nothing


def _directive_is_live(directive: str, lineno: int, rules: set,
                       file_findings: list) -> bool:
    """Does this suppression directive cover at least one finding the
    analyzer actually produced? (Suppressed findings are still in the
    list — that is what makes this audit possible.)"""
    def covers(f) -> bool:
        return "ALL" in rules or f.rule in rules

    if directive == "disable-file":
        return any(covers(f) for f in file_findings)
    target = lineno + 1 if directive == "disable-next" else lineno
    return any(f.line == target and covers(f) for f in file_findings)


def audit_suppressions(paths, findings, restrict=None) -> list[Finding]:
    """One GL99 error per `# graftlint: disable…` directive under
    `paths` that covers no finding at all (rule renamed, code moved,
    fix landed): a dead directive is worse than none — it silently
    blesses the NEXT finding at that site. `findings` is the full
    (suppressed included) output of lint_paths over the same paths;
    `restrict` mirrors lint_paths' --changed semantics."""
    by_file: dict[str, list[Finding]] = {}
    for f in findings:
        by_file.setdefault(f.file, []).append(f)
    out: list[Finding] = []
    for path in iter_python_files(paths):
        if restrict is not None:
            resolved = Path(
                os.path.normpath(os.path.abspath(path))
            ).as_posix()
            if resolved not in restrict:
                continue
        source, _, err = _read_source(path)
        if err is not None:
            continue
        display = str(path)
        file_findings = by_file.get(display, [])
        for lineno, comment in _comment_tokens(source):
            m = _SUPPRESS_RE.search(comment)
            if not m:
                continue
            directive = m.group(1)
            rules = {
                r.strip().upper()
                for r in m.group(2).split(",") if r.strip()
            }
            if _directive_is_live(directive, lineno, rules,
                                  file_findings):
                continue
            listed = ",".join(sorted(rules))
            out.append(Finding(
                file=display, line=lineno, col=1,
                rule=STALE_RULE, severity="error",
                message=f"stale suppression: `# graftlint: "
                        f"{directive}={listed}` covers no finding "
                        f"(rule renamed, code moved, or the fix "
                        f"landed) — a dead directive silently blesses "
                        f"the next finding at this site",
                hint="delete the directive; re-add it only with a live "
                     "finding to point at",
            ))
    return out


def gate_exit_code(findings) -> int:
    """0 when no non-suppressed, non-baselined error-severity finding
    remains, else 1. Parse warnings (GL00) never fail the gate — a
    broken file is reported but must not wedge CI on code the analyzer
    cannot see anyway."""
    for f in findings:
        if not f.suppressed and not f.baselined and f.severity == "error":
            return 1
    return 0
