"""graftlint core: findings, suppressions, the rule registry, file walking.

The analyzer is a pre-test gate (scripts/lint.sh, tests/test_self_lint.py)
so the whole pipeline is stdlib-only and cached: one `ast.parse` per
(path, mtime, size), rules share the parsed tree, and a repo-wide run
stays well under the 5 s budget the tier-1 wiring assumes.

Suppressions (all take a comma-separated rule list or `all`):

    x = risky()          # graftlint: disable=GL01
    # graftlint: disable-next=GL02,GL03
    x = risky()
    # graftlint: disable-file=GL05      (anywhere in the file)

Suppressed findings are still produced (marked ``suppressed=True``) so
reporters can show them; only non-suppressed findings gate the exit code.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from pathlib import Path

PARSE_RULE = "GL00"  # pseudo-rule for unparseable-file warnings


@dataclasses.dataclass
class Finding:
    file: str
    line: int
    col: int
    rule: str
    severity: str  # "error" | "warning"
    message: str
    hint: str = ""
    suppressed: bool = False

    def location(self) -> str:
        return f"{self.file}:{self.line}:{self.col}"


@dataclasses.dataclass
class ModuleContext:
    """Everything a rule gets to look at for one file."""

    path: str  # as given / repo-relative for reporting
    posix_path: str  # normalized forward-slash form for allowlists
    source: str
    tree: ast.Module

    def finding(self, node: ast.AST, rule, message: str, hint: str = "",
                severity: str | None = None) -> Finding:
        return Finding(
            file=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            rule=rule.id,
            severity=severity or rule.severity,
            message=message,
            hint=hint or rule.hint,
        )


class Rule:
    """One rule family. Subclasses set id/name/severity/hint and implement
    check(ctx) -> iterable[Finding]."""

    id: str = "GL??"
    name: str = ""
    severity: str = "error"
    hint: str = ""
    # One-line rationale shown by --list-rules and the docs generator.
    rationale: str = ""

    def check(self, ctx: ModuleContext):  # pragma: no cover - interface
        raise NotImplementedError


def all_rules() -> list[Rule]:
    """The registered rule families, GL-id order."""
    from rocm_mpi_tpu.analysis.rules_collective import AxisConsistencyRule
    from rocm_mpi_tpu.analysis.rules_compat import CompatDriftRule
    from rocm_mpi_tpu.analysis.rules_donation import DonationSafetyRule
    from rocm_mpi_tpu.analysis.rules_pallas import PallasHygieneRule
    from rocm_mpi_tpu.analysis.rules_purity import TraceTimePurityRule
    from rocm_mpi_tpu.analysis.rules_signals import SignalHygieneRule
    from rocm_mpi_tpu.analysis.rules_timing import RawTimingRule

    return [
        DonationSafetyRule(),
        TraceTimePurityRule(),
        CompatDriftRule(),
        PallasHygieneRule(),
        AxisConsistencyRule(),
        RawTimingRule(),
        SignalHygieneRule(),
    ]


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable(?:-next|-file)?)\s*=\s*([A-Za-z0-9_,\s]+)"
)


@dataclasses.dataclass
class Suppressions:
    by_line: dict[int, set[str]]
    file_wide: set[str]

    def covers(self, finding: Finding) -> bool:
        rules = self.by_line.get(finding.line, set()) | self.file_wide
        return "ALL" in rules or finding.rule in rules


def _comment_tokens(source: str):
    """(lineno, text) of real COMMENT tokens only — a docstring that merely
    *documents* a directive must not install one. On tokenize failure
    (rare for ast-parseable source) no suppressions apply: the safe
    direction is findings staying live, never silently vanishing."""
    try:
        return [
            (tok.start[0], tok.string)
            for tok in tokenize.generate_tokens(io.StringIO(source).readline)
            if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError, ValueError):
        return []


def parse_suppressions(source: str) -> Suppressions:
    by_line: dict[int, set[str]] = {}
    file_wide: set[str] = set()
    for lineno, comment in _comment_tokens(source):
        m = _SUPPRESS_RE.search(comment)
        if not m:
            continue
        directive = m.group(1)
        rules = {r.strip().upper() for r in m.group(2).split(",") if r.strip()}
        if directive == "disable-file":
            file_wide |= rules
        elif directive == "disable-next":
            by_line.setdefault(lineno + 1, set()).update(rules)
        else:
            by_line.setdefault(lineno, set()).update(rules)
    return Suppressions(by_line=by_line, file_wide=file_wide)


# ---------------------------------------------------------------------------
# Linting
# ---------------------------------------------------------------------------


def _selected(rules: list[Rule], select) -> list[Rule]:
    if not select:
        return rules
    wanted = {s.strip().upper() for s in select}
    return [r for r in rules if r.id in wanted]


def lint_source(source: str, path: str = "<string>", select=None,
                rules: list[Rule] | None = None) -> list[Finding]:
    """Lint one source string. Unparseable source yields a single GL00
    warning instead of raising — the gate must never crash on an input."""
    rules = _selected(rules if rules is not None else all_rules(), select)
    # Normalized absolute form so the chokepoint allowlists (GL03) match
    # regardless of cwd, `..` segments, or how the gate spelled the path.
    posix = Path(os.path.normpath(os.path.abspath(path))).as_posix()
    try:
        tree = ast.parse(source, filename=path)
    except (SyntaxError, ValueError, RecursionError) as e:
        return [
            Finding(
                file=path,
                line=getattr(e, "lineno", 1) or 1,
                col=(getattr(e, "offset", 1) or 1),
                rule=PARSE_RULE,
                severity="warning",
                message=f"could not parse file ({type(e).__name__}: {e}); "
                        "skipped",
                hint="graftlint gates only what it can parse — fix the "
                     "syntax error to restore coverage",
            )
        ]
    ctx = ModuleContext(path=path, posix_path=posix, source=source, tree=tree)
    suppressions = parse_suppressions(source)
    findings: list[Finding] = []
    for rule in rules:
        for f in rule.check(ctx):
            f.suppressed = suppressions.covers(f)
            findings.append(f)
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return findings


# (path, mtime_ns, size) -> findings; makes the repo-wide tier-1 run a
# near-no-op when invoked twice in one process (tests + gate).
_CACHE: dict[tuple[str, int, int], list[Finding]] = {}


def lint_file(path: Path, select=None, rules=None,
              display_path: str | None = None) -> list[Finding]:
    try:
        stat = path.stat()
        key = (str(path), display_path, stat.st_mtime_ns, stat.st_size)
    except OSError:
        key = None
    if key is not None and select is None and rules is None and key in _CACHE:
        # deep-ish copies: a caller mutating a Finding (reporters toggling
        # flags) must not poison later cache hits
        return [dataclasses.replace(f) for f in _CACHE[key]]
    try:
        source = path.read_text(encoding="utf-8", errors="replace")
    except OSError as e:
        return [
            Finding(
                file=display_path or str(path), line=1, col=1,
                rule=PARSE_RULE, severity="warning",
                message=f"could not read file ({e}); skipped",
            )
        ]
    findings = lint_source(
        source, display_path or str(path), select=select, rules=rules
    )
    if key is not None and select is None and rules is None:
        _CACHE[key] = [dataclasses.replace(f) for f in findings]
    return findings


_SKIP_DIRS = {
    ".git", "__pycache__", ".jax_cache", "node_modules", ".venv", "venv",
    "analysis_fixtures",
}


def iter_python_files(paths) -> list[Path]:
    out: list[Path] = []
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in _SKIP_DIRS
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(Path(dirpath) / fn)
        elif p.suffix == ".py":
            out.append(p)
    return out


def lint_paths(paths, select=None) -> tuple[list[Finding], int]:
    """Lint files/dirs. Returns (findings, files_scanned). Nonexistent
    paths raise FileNotFoundError (a mistyped gate path must fail loudly,
    not silently lint nothing)."""
    for raw in paths:
        if not Path(raw).exists():
            raise FileNotFoundError(f"lint path does not exist: {raw}")
    files = iter_python_files(paths)
    findings: list[Finding] = []
    for f in files:
        findings.extend(lint_file(f, select=select))
    return findings, len(files)


def gate_exit_code(findings) -> int:
    """0 when no non-suppressed error-severity finding remains, else 1.
    Parse warnings (GL00) never fail the gate — a broken file is reported
    but must not wedge CI on code the analyzer cannot see anyway."""
    for f in findings:
        if not f.suppressed and f.severity == "error":
            return 1
    return 0
