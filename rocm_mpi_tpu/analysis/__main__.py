"""CLI: python -m rocm_mpi_tpu.analysis [paths...] [options].

Exit codes: 0 clean, 1 non-suppressed, non-baselined error-severity
findings, 2 usage / missing path / unreadable baseline. Parse failures
(GL00) are reported as warnings and never fail the gate.

The repo gate (scripts/lint.sh) runs:

    python -m rocm_mpi_tpu.analysis rocm_mpi_tpu apps bench.py \
        --baseline --strict-suppressions \
        --output output/lint/findings.json

which is the whole-program interprocedural pass (per-file rules + the
GL08/GL10/GL01 engine), compared against the committed baseline, with
the stale-suppression audit on and the machine-readable findings
artifact published atomically for chip_watcher to archive. `--changed` restricts the reported scope to
git-dirty files plus their import-graph neighbors — the fast dev loop.
"""

from __future__ import annotations

import argparse
import sys

from rocm_mpi_tpu.analysis import baseline as baseline_mod
from rocm_mpi_tpu.analysis import core, report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m rocm_mpi_tpu.analysis",
        description="graftlint: whole-program shard-safety analyzer "
                    "(rule catalog: docs/ANALYSIS.md)",
    )
    parser.add_argument("paths", nargs="*", help="files or directories")
    parser.add_argument("--json", action="store_true",
                        help="emit the versioned JSON document on stdout")
    parser.add_argument("--output", default=None, metavar="PATH",
                        help="also write the JSON document to PATH "
                        "(atomic tmp+rename; lint.sh banks "
                        "output/lint/findings.json)")
    parser.add_argument("--select", default=None, metavar="GL01,GL02",
                        help="run only these rule ids")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="include suppressed findings in text output")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--no-interprocedural", action="store_true",
                        help="per-file rules only (skip the whole-program "
                        "GL08/GL01 engine)")
    parser.add_argument("--baseline", nargs="?", metavar="PATH",
                        const=str(baseline_mod.DEFAULT_BASELINE),
                        default=None,
                        help="compare against a committed baseline: "
                        "baselined findings are reported but do not "
                        "gate (default PATH: analysis/baseline.json)")
    parser.add_argument("--baseline-write", nargs="?", metavar="PATH",
                        const=str(baseline_mod.DEFAULT_BASELINE),
                        default=None,
                        help="bank the current live findings as the "
                        "baseline and exit 0")
    parser.add_argument("--changed", action="store_true",
                        help="fast mode: lint only git-dirty files plus "
                        "their import-graph neighbors (falls back to a "
                        "full run when git state is unavailable)")
    parser.add_argument("--strict-suppressions", action="store_true",
                        help="audit suppression directives: a "
                        "`# graftlint: disable…` comment that covers no "
                        "finding at all becomes a GL99 error (dead "
                        "directives silently bless the next finding at "
                        "that site)")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in core.catalog_rules():
            print(f"{rule.id} {rule.name} [{rule.severity}]")
            print(f"    {rule.rationale}")
        return 0
    if not args.paths:
        parser.print_usage(sys.stderr)
        print(
            "error: no paths given (the repo gate runs: "
            "python -m rocm_mpi_tpu.analysis rocm_mpi_tpu apps bench.py)",
            file=sys.stderr,
        )
        return 2

    if args.changed and args.baseline_write is not None:
        print(
            "error: --changed cannot be combined with --baseline-write "
            "(a neighborhood-restricted scan would bank a truncated "
            "ledger, silently dropping every accepted finding outside "
            "the dirty set)",
            file=sys.stderr,
        )
        return 2

    select = args.select.split(",") if args.select else None
    restrict = None
    if args.changed:
        dirty = baseline_mod.git_dirty_files()
        if dirty is None:
            print("graftlint: --changed: git state unavailable; running "
                  "the full scope", file=sys.stderr)
        else:
            try:
                entries = core.read_entries(args.paths)
            except FileNotFoundError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            restrict = baseline_mod.expand_neighbors(entries, dirty)
    try:
        findings, files_scanned = core.lint_paths(
            args.paths, select=select, restrict=restrict,
            interprocedural=not args.no_interprocedural,
        )
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.strict_suppressions:
        # Audit against the FULL findings list (suppressed included) —
        # a directive is stale only when it covers nothing at all.
        # Runs before baseline handling so GL99 findings ride the
        # reports and gate like any other error.
        findings.extend(core.audit_suppressions(
            args.paths, findings, restrict=restrict,
        ))
        findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))

    if args.baseline_write is not None:
        baseline_mod.write_baseline(args.baseline_write, findings)
        live = [
            f for f in findings
            if not f.suppressed and f.severity == "error"
        ]
        print(
            f"graftlint: banked {len(live)} finding(s) into "
            f"{args.baseline_write}",
            file=sys.stderr,
        )
        return 0

    if args.baseline is not None:
        try:
            doc = baseline_mod.load_baseline(args.baseline)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        baseline_mod.apply_baseline(findings, doc)

    if args.output:
        report.write_findings(args.output, findings, files_scanned)
    if args.json:
        print(report.to_json(findings, files_scanned))
    else:
        print(report.to_text(findings, files_scanned,
                             show_suppressed=args.show_suppressed))
    return core.gate_exit_code(findings)


if __name__ == "__main__":
    sys.exit(main())
