"""CLI: python -m rocm_mpi_tpu.analysis [paths...] [options].

Exit codes: 0 clean, 1 non-suppressed error-severity findings, 2 usage /
missing path. Parse failures (GL00) are reported as warnings and never
fail the gate.
"""

from __future__ import annotations

import argparse
import sys

from rocm_mpi_tpu.analysis import core, report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m rocm_mpi_tpu.analysis",
        description="graftlint: AST-based shard-safety analyzer "
                    "(rule catalog: docs/ANALYSIS.md)",
    )
    parser.add_argument("paths", nargs="*", help="files or directories")
    parser.add_argument("--json", action="store_true",
                        help="emit the versioned JSON document")
    parser.add_argument("--select", default=None, metavar="GL01,GL02",
                        help="run only these rule ids")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="include suppressed findings in text output")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in core.all_rules():
            print(f"{rule.id} {rule.name} [{rule.severity}]")
            print(f"    {rule.rationale}")
        return 0
    if not args.paths:
        parser.print_usage(sys.stderr)
        print(
            "error: no paths given (the repo gate runs: "
            "python -m rocm_mpi_tpu.analysis rocm_mpi_tpu apps bench.py)",
            file=sys.stderr,
        )
        return 2

    select = args.select.split(",") if args.select else None
    try:
        findings, files_scanned = core.lint_paths(args.paths, select=select)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(report.to_json(findings, files_scanned))
    else:
        print(report.to_text(findings, files_scanned,
                             show_suppressed=args.show_suppressed))
    return core.gate_exit_code(findings)


if __name__ == "__main__":
    sys.exit(main())
