"""GL09 — sidecar atomicity.

The bug shape PRs 4–7 each hardened by hand, once per artifact family:
every schema-versioned sidecar this repo publishes — heartbeat sidecars
(PR 5), checkpoint manifests (PR 1/6), the elastic.jsonl event stream
(PR 6), the tuning cache (PR 7) — is read by an out-of-process consumer
(watchdog, monitor CLI, resume planner, trace-time resolve) that may
observe the file WHILE the writer is mid-write or freshly killed. A
plain `open(path, "w")` + `json.dump` publishes a torn file for that
window, and a torn schema-versioned artifact does not fail loudly: it
bricks the reader at the next real incident (the monitor can't show the
SHRUNK badge, the resume can't plan a mesh, every trace-time lookup
misses forever).

The committed discipline (each writer's docstring says so): **tmp +
rename** (`write to path+".tmp"`, then `os.replace`/`Path.replace` —
readers see old-complete or new-complete, never torn) or **append-only
JSONL** (a torn final line is droppable; every complete line is valid).

What fires: a JSON write — `json.dump(doc, fh)`, `fh.write(
json.dumps(...))`, or `target.write_text(json.dumps(...))` — through a
file opened in `"w"`/`"x"` mode (or a write_text target) whose payload
or path identifies a schema-versioned artifact, when the write is NOT
tmp+rename shaped: the target must be tmp-named (a literal containing
"tmp" somewhere in its derivation, e.g. `path + ".tmp"` /
`with_suffix(".json.tmp")`) AND the same scope must contain a rename
(`os.replace(...)` / `x.replace(...)`). Appends (`"a"` mode) never
fire.

Artifact evidence (both are deliberate, to keep scratch-file writes out
of scope): the dumped payload resolves to a dict literal carrying a
`"schema"`/`"kind"` key or a `"v"`/`"version"` version field, OR the
target path mentions one of the committed artifact families by name.
"""

from __future__ import annotations

import ast
import re

from rocm_mpi_tpu.analysis import astutil
from rocm_mpi_tpu.analysis.core import ModuleContext, Rule

# The committed artifact families (scripts/lint.sh schema-checks these
# names; chip_watcher archives them). `quarantine` and `soak-report`
# joined with the request-plane hardening (docs/SERVING.md "SLOs and
# admission"; docs/RESILIENCE.md §8); `fleet` covers the ticket
# journal and the merged fleet report (docs/SERVING.md "The fleet");
# `trace` covers the rmt-trace-report artifact and per-request Chrome
# exports (docs/TELEMETRY.md "Request tracing").
_ARTIFACT_NAME_RE = re.compile(
    r"(heartbeat|manifest|postmortem|bundle|elastic|cache|tuning|"
    r"baseline|findings|summary|quarantine|soak|fleet|trace)"
    r"[-\w.]*\.jsonl?\b"
)

_SCHEMA_KEYS = {"schema", "kind"}
_VERSION_KEYS = {"v", "version"}


def _literal_strings(node: ast.AST):
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            yield n.value
        elif isinstance(n, ast.JoinedStr):
            # Concatenate the literal parts with a placeholder where the
            # interpolations sit, so f"{d}/heartbeat-rank{k}.json" still
            # reads as one artifact name.
            yield "0".join(
                part.value for part in n.values
                if isinstance(part, ast.Constant)
                and isinstance(part.value, str)
            )


def _chase(node: ast.AST, assignments: dict, depth: int = 3) -> ast.AST:
    while depth > 0 and isinstance(node, ast.Name) \
            and node.id in assignments:
        node = assignments[node.id]
        depth -= 1
    return node


def _is_tmpish(node: ast.AST, assignments: dict) -> bool:
    """The target's derivation names a temporary: `path + ".tmp"`,
    `with_suffix(".json.tmp")`, an f-string with a tmp part, or simply a
    name containing 'tmp' (the repo's universal convention)."""
    if isinstance(node, ast.Name) and "tmp" in node.id.lower():
        return True
    chased = _chase(node, assignments)
    return any("tmp" in s.lower() for s in _literal_strings(chased))


def _payload_is_schema_versioned(node: ast.AST, assignments: dict) -> bool:
    chased = _chase(node, assignments)
    if not isinstance(chased, ast.Dict):
        return False
    keys = {
        k.value for k in chased.keys
        if isinstance(k, ast.Constant) and isinstance(k.value, str)
    }
    return bool(keys & _SCHEMA_KEYS) or bool(keys & _VERSION_KEYS)


def _path_is_artifact(node: ast.AST, assignments: dict) -> bool:
    chased = _chase(node, assignments)
    return any(
        _ARTIFACT_NAME_RE.search(s) for s in _literal_strings(chased)
    )


def _open_mode(call: ast.Call) -> str | None:
    """The literal mode of an open()/Path.open() call ('r' default);
    None when the mode is not statically visible. The method form
    (`p.open("w")`) carries the mode in args[0] — the path is the
    receiver, not an argument."""
    if astutil.tail_name(astutil.call_name(call)) != "open":
        return None
    mode_pos = 0 if isinstance(call.func, ast.Attribute) else 1
    if len(call.args) > mode_pos:
        mode_node = call.args[mode_pos]
    else:
        mode_node = astutil.call_kwarg(call, "mode")
    if mode_node is None:
        # open(p) / p.open() with no mode: read
        return "r"
    return astutil.str_const(mode_node)


class _ScopeScan:
    """One function (or module) body's open/write/rename facts."""

    def __init__(self, scope: ast.AST):
        self.assignments: dict[str, ast.AST] = {}
        # fh name -> (mode, path expr, open call)
        self.opens: dict[str, tuple] = {}
        self.renames_present = False
        # (site node, payload expr, target expr or fh name)
        self.json_writes: list[tuple] = []
        self._walk(scope)

    def _walk(self, scope: ast.AST) -> None:
        # One scope at a time: a rename in SOME OTHER function must not
        # legitimize this one's in-place write (each def is scanned as
        # its own scope by check()).
        for node in astutil.walk_no_nested_functions(scope):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                self.assignments[node.targets[0].id] = node.value
                if isinstance(node.value, ast.Call):
                    self._note_open(node.value, node.targets[0].id)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call) and \
                            isinstance(item.optional_vars, ast.Name):
                        self._note_open(
                            item.context_expr, item.optional_vars.id
                        )
            elif isinstance(node, ast.Call):
                tail = astutil.tail_name(astutil.call_name(node))
                if tail == "replace":
                    self.renames_present = True
                elif tail == "dump" and len(node.args) >= 2 and \
                        isinstance(node.args[1], ast.Name):
                    self.json_writes.append(
                        (node, node.args[0], node.args[1].id)
                    )
                elif tail == "write" and node.args and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name):
                    payload = node.args[0]
                    if self._is_json_payload(payload):
                        self.json_writes.append(
                            (node, payload, node.func.value.id)
                        )
                elif tail == "write_text" and node.args and \
                        isinstance(node.func, ast.Attribute):
                    payload = node.args[0]
                    if self._is_json_payload(payload):
                        self.json_writes.append(
                            (node, payload, node.func.value)
                        )

    def _note_open(self, call: ast.Call, name: str) -> None:
        mode = _open_mode(call)
        if mode is None:
            return
        if isinstance(call.func, ast.Attribute):
            path = call.func.value  # p.open(...): the receiver IS the path
        else:
            path = call.args[0] if call.args else None
        self.opens[name] = (mode, path, call)

    @staticmethod
    def _is_json_payload(node: ast.AST) -> bool:
        """json.dumps(...) somewhere in the written expression."""
        for n in ast.walk(node):
            if isinstance(n, ast.Call) and \
                    astutil.tail_name(astutil.call_name(n)) == "dumps":
                return True
        return False


class SidecarAtomicityRule(Rule):
    id = "GL09"
    name = "sidecar-atomicity"
    severity = "error"
    rationale = (
        "schema-versioned sidecars are read by out-of-process consumers "
        "mid-run; a non-atomic writer publishes a torn file that bricks "
        "the reader at the next real incident (the class hand-fixed in "
        "PRs 4-7: heartbeats, manifests, elastic.jsonl, tuning cache)"
    )
    hint = "see docs/ANALYSIS.md#gl09"

    def check(self, ctx: ModuleContext):
        findings = []
        scopes: list = [ctx.tree]
        scopes += [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        seen = set()
        for scope in scopes:
            scan = _ScopeScan(scope)
            for site, payload, target in scan.json_writes:
                key = (site.lineno, site.col_offset)
                if key in seen:
                    continue
                path_expr = None
                if isinstance(target, str):
                    opened = scan.opens.get(target)
                    if opened is None:
                        continue  # unknown handle — not judged
                    mode, path_expr, _ = opened
                    if not mode or mode[0] not in ("w", "x"):
                        continue  # append/read: the other discipline
                else:
                    path_expr = target  # write_text target
                if path_expr is None:
                    continue
                versioned = _payload_is_schema_versioned(
                    payload, scan.assignments
                ) or _path_is_artifact(path_expr, scan.assignments)
                if not versioned:
                    continue
                compliant = _is_tmpish(path_expr, scan.assignments) \
                    and scan.renames_present
                if compliant:
                    continue
                seen.add(key)
                findings.append(ctx.finding(
                    site, self,
                    "schema-versioned artifact is written in place "
                    "(no tmp+rename, not append-only) — a reader can "
                    "observe the torn file and every consumer of this "
                    "sidecar silently breaks",
                    "write to <path>.tmp and os.replace() it over the "
                    "final path (tuning/cache.write_doc and "
                    "telemetry/aggregate.write_json_atomic are the "
                    "reference writers), or use append-only JSONL",
                ))
        return findings
