"""graftlint reporters: human text (with a per-rule findings table) and a
versioned JSON document for tooling (tests/test_analysis_rules.py pins the
schema).

JSON schema (version 1):

    {"version": 1,
     "files_scanned": int,
     "counts": {"GL01": int, ...},          # non-suppressed, per rule
     "suppressed": int,
     "findings": [{"file": str, "line": int, "col": int, "rule": str,
                   "severity": "error"|"warning", "message": str,
                   "hint": str, "suppressed": bool}, ...]}
"""

from __future__ import annotations

import json

from rocm_mpi_tpu.analysis.core import PARSE_RULE, Finding, all_rules


def counts_by_rule(findings) -> dict[str, int]:
    """Non-suppressed finding count per registered rule id (zero rows
    included so a regression report always names every rule)."""
    counts = {r.id: 0 for r in all_rules()}
    counts[PARSE_RULE] = 0
    for f in findings:
        if not f.suppressed:
            counts[f.rule] = counts.get(f.rule, 0) + 1
    return counts


def to_json(findings, files_scanned: int) -> str:
    doc = {
        "version": 1,
        "files_scanned": files_scanned,
        "counts": counts_by_rule(findings),
        "suppressed": sum(1 for f in findings if f.suppressed),
        "findings": [
            {
                "file": f.file,
                "line": f.line,
                "col": f.col,
                "rule": f.rule,
                "severity": f.severity,
                "message": f.message,
                "hint": f.hint,
                "suppressed": f.suppressed,
            }
            for f in findings
        ],
    }
    return json.dumps(doc, indent=1)


def rule_table(findings) -> str:
    """The per-rule findings table (printed by the self-lint test so a
    regression names the rule that fired)."""
    counts = counts_by_rule(findings)
    names = {r.id: r.name for r in all_rules()}
    names[PARSE_RULE] = "parse-warning"
    width = max(len(n) for n in names.values()) + 2
    lines = ["rule   " + "name".ljust(width) + "findings"]
    for rule_id in sorted(counts):
        lines.append(
            f"{rule_id:6s} {names.get(rule_id, '?').ljust(width)}"
            f"{counts[rule_id]}"
        )
    return "\n".join(lines)


def format_finding(f: Finding) -> str:
    tag = " [suppressed]" if f.suppressed else ""
    hint = f"\n    hint: {f.hint}" if f.hint else ""
    return (
        f"{f.location()}: {f.rule} {f.severity}{tag}: {f.message}{hint}"
    )


def to_text(findings, files_scanned: int, show_suppressed: bool = False) -> str:
    shown = [f for f in findings if show_suppressed or not f.suppressed]
    lines = [format_finding(f) for f in shown]
    active = [f for f in findings if not f.suppressed]
    n_sup = sum(1 for f in findings if f.suppressed)
    summary = (
        f"graftlint: {files_scanned} file(s), {len(active)} finding(s)"
        + (f", {n_sup} suppressed" if n_sup else "")
    )
    if active:
        lines.append("")
        lines.append(rule_table(findings))
    lines.append(summary)
    return "\n".join(lines)
