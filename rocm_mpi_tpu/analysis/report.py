"""graftlint reporters: human text (with a per-rule findings table) and a
versioned JSON document for tooling (tests/test_analysis_rules.py pins the
schema; `telemetry regress --check-schema` recognizes the artifact).

JSON schema (version 3 — v2 plus the GL10 concurrency family's zero
row in counts and possible GL99 stale-suppression rows from the
--strict-suppressions audit):

    {"schema": "rmt-lint-findings",
     "version": 3,
     "files_scanned": int,
     "counts": {"GL01": int, ...},          # live (not suppressed, not
                                            # baselined), per rule
     "suppressed": int,
     "baselined": int,
     "findings": [{"file": str, "line": int, "col": int, "rule": str,
                   "severity": "error"|"warning", "message": str,
                   "hint": str, "suppressed": bool,
                   "baselined": bool}, ...]}

`write_findings` publishes the document tmp+rename — the findings
artifact is itself a schema-versioned sidecar, and GL09 would be a
hypocrite otherwise.
"""

from __future__ import annotations

import json
import os
import pathlib

from rocm_mpi_tpu.analysis.core import (
    PARSE_RULE, STALE_RULE, Finding, catalog_rules,
)

FINDINGS_SCHEMA = "rmt-lint-findings"
FINDINGS_VERSION = 3


def counts_by_rule(findings) -> dict[str, int]:
    """Live (non-suppressed, non-baselined) finding count per registered
    rule id (zero rows included so a regression report always names
    every rule)."""
    counts = {r.id: 0 for r in catalog_rules()}
    counts[PARSE_RULE] = 0
    for f in findings:
        if not f.suppressed and not f.baselined:
            counts[f.rule] = counts.get(f.rule, 0) + 1
    return counts


def findings_doc(findings, files_scanned: int) -> dict:
    return {
        "schema": FINDINGS_SCHEMA,
        "version": FINDINGS_VERSION,
        "files_scanned": files_scanned,
        "counts": counts_by_rule(findings),
        "suppressed": sum(1 for f in findings if f.suppressed),
        "baselined": sum(
            1 for f in findings if f.baselined and not f.suppressed
        ),
        "findings": [
            {
                "file": f.file,
                "line": f.line,
                "col": f.col,
                "rule": f.rule,
                "severity": f.severity,
                "message": f.message,
                "hint": f.hint,
                "suppressed": f.suppressed,
                "baselined": f.baselined,
            }
            for f in findings
        ],
    }


def to_json(findings, files_scanned: int) -> str:
    return json.dumps(findings_doc(findings, files_scanned), indent=1)


def write_findings(path, findings, files_scanned: int) -> None:
    """Publish the JSON document atomically (tmp + os.replace): the
    machine-readable artifact lint.sh banks and chip_watcher archives
    must never be observable torn — GL09's own discipline."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = str(path) + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(to_json(findings, files_scanned))
        fh.write("\n")
    os.replace(tmp, path)


def validate_findings_doc(doc, path: str = "<doc>") -> list[str]:
    """Schema problems of one findings document (empty list = valid) —
    shared with `telemetry regress --check-schema` so a drifted reporter
    fails the gate, not the next reader."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return [f"{path}: not a JSON object"]
    if doc.get("schema") != FINDINGS_SCHEMA:
        problems.append(f"{path}: schema != {FINDINGS_SCHEMA!r}")
    if doc.get("version") != FINDINGS_VERSION:
        problems.append(f"{path}: version != {FINDINGS_VERSION}")
    for field in ("files_scanned", "suppressed", "baselined"):
        if not isinstance(doc.get(field), int):
            problems.append(f"{path}: {field} is not an int")
    counts = doc.get("counts")
    if not isinstance(counts, dict) or not all(
        isinstance(k, str) and isinstance(v, int) for k, v in counts.items()
    ):
        problems.append(f"{path}: counts is not a str->int object")
    findings = doc.get("findings")
    if not isinstance(findings, list):
        return problems + [f"{path}: findings is not a list"]
    required = {
        "file": str, "line": int, "col": int, "rule": str,
        "severity": str, "message": str, "hint": str,
        "suppressed": bool, "baselined": bool,
    }
    for i, entry in enumerate(findings):
        if not isinstance(entry, dict):
            problems.append(f"{path}: findings[{i}] is not an object")
            continue
        for field, typ in required.items():
            if not isinstance(entry.get(field), typ):
                problems.append(
                    f"{path}: findings[{i}].{field} missing or wrong type"
                )
        if entry.get("severity") not in ("error", "warning"):
            problems.append(f"{path}: findings[{i}].severity invalid")
    return problems


def rule_table(findings) -> str:
    """The per-rule findings table (printed by the self-lint test so a
    regression names the rule that fired)."""
    counts = counts_by_rule(findings)
    names = {r.id: r.name for r in catalog_rules()}
    names[PARSE_RULE] = "parse-warning"
    names[STALE_RULE] = "stale-suppression"
    width = max(len(n) for n in names.values()) + 2
    lines = ["rule   " + "name".ljust(width) + "findings"]
    for rule_id in sorted(counts):
        lines.append(
            f"{rule_id:6s} {names.get(rule_id, '?').ljust(width)}"
            f"{counts[rule_id]}"
        )
    return "\n".join(lines)


def format_finding(f: Finding) -> str:
    tag = " [suppressed]" if f.suppressed else (
        " [baselined]" if f.baselined else ""
    )
    hint = f"\n    hint: {f.hint}" if f.hint else ""
    return (
        f"{f.location()}: {f.rule} {f.severity}{tag}: {f.message}{hint}"
    )


def to_text(findings, files_scanned: int, show_suppressed: bool = False) -> str:
    shown = [f for f in findings if show_suppressed or not f.suppressed]
    lines = [format_finding(f) for f in shown]
    active = [f for f in findings if not f.suppressed and not f.baselined]
    n_sup = sum(1 for f in findings if f.suppressed)
    n_base = sum(1 for f in findings if f.baselined and not f.suppressed)
    summary = (
        f"graftlint: {files_scanned} file(s), {len(active)} finding(s)"
        + (f", {n_sup} suppressed" if n_sup else "")
        + (f", {n_base} baselined" if n_base else "")
    )
    if active:
        lines.append("")
        lines.append(rule_table(findings))
    lines.append(summary)
    return "\n".join(lines)
