"""Ground-truth lowered-program audit: prove, from the compiled HLO,
what GL08/GL01 assert from the source.

The AST engine (analysis/engine.py) reasons about what the *Python*
will trace; this module checks what XLA actually *lowered* — the
steady-state drivers of all three workloads (diffusion / wave / SWE,
the same entry-point harness perf/traffic.py audits) are compiled on a
small virtual-CPU mesh and the optimized module is parsed for:

(a) **collective-sequence identity across rank-roles.** The per-role
    sequence is materialized per partition: every collective the role
    executes, in program order (while-loop bodies included — the scan
    and fori drivers keep their exchanges there), keyed by (op kind,
    channel id). The sequences must be identical for every role, which
    concretely requires no collective under a `conditional` branch
    computation (a lowered rank-divergent collective — GL08's hazard
    surviving to the executable), every collective channel-numbered,
    and permute source/target pair structures forming at most one
    send + one receive per partition.

(b) **real donation aliasing.** Every GL01-declared donation
    (`donate_argnums` on the driver) must appear in the module's
    `input_output_alias` table. jax drops an inapplicable donation
    with a warning CI never reads; a "donated" driver that silently
    copies is both a perf lie (the traffic budgets assume in-place
    ghost-write chains) and a masked GL01 hazard (the name is safe to
    re-read precisely because nothing aliased — until jax changes its
    mind).

Wired as a lint.sh gate stage (`python -m rocm_mpi_tpu.analysis.lowered`)
next to the HBM-traffic gate: CPU-only, no timing, deterministic. This
is the one analysis module that imports jax — and only inside the audit
entry points, never at import time.
"""

from __future__ import annotations

import dataclasses
import re

# ---------------------------------------------------------------------------
# HLO text parsing (stdlib-only: usable on canned fixtures without jax)
# ---------------------------------------------------------------------------

COLLECTIVE_OPS = frozenset({
    "collective-permute", "all-reduce", "all-gather", "all-to-all",
    "reduce-scatter", "collective-broadcast", "collective-permute-start",
    "all-reduce-start", "all-gather-start",
})

_COMP_RE = re.compile(
    r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->\s*.*\{$"
)
_OP_RE = re.compile(r"^(?:ROOT\s+)?%[\w.\-]+\s*=\s*(.*?)\s([\w\-]+)\(")
_CHANNEL_RE = re.compile(r"\bchannel_id=(\d+)")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)*)\}")
_PAIR_RE = re.compile(r"\{(\d+),(\d+)\}")
_SUBCOMP_RE = re.compile(
    r"\b(?:calls|body|condition|to_apply|true_computation|"
    r"false_computation)=%([\w.\-]+)"
)
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_NUM_PARTITIONS_RE = re.compile(r"\bnum_partitions=(\d+)")
_ALIAS_TABLE_RE = re.compile(r"input_output_alias=\{(.*?)\}\s*,\s*entry")
_ALIAS_ENTRY_RE = re.compile(
    r"\{[\d,\s]*\}:\s*\((\d+),\s*\{[\d,\s]*\}(?:,\s*(?:may|must)-alias)?\)"
)


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    kind: str
    channel: int | None
    pairs: tuple  # ((src, tgt), ...) for permutes, () otherwise
    in_conditional: bool
    loop_depth: int
    line: str  # the HLO line, for reporting


@dataclasses.dataclass
class _HloOp:
    kind: str
    line: str
    subcomps: tuple
    branch_comps: tuple


def _parse_computations(hlo_text: str) -> tuple[dict, str | None, int]:
    """(computations, entry name, num_partitions): computation name ->
    ordered [_HloOp]. Scheduled HLO is flat — computations are not
    nested — so a simple line scanner is exact."""
    comps: dict[str, list] = {}
    entry = None
    current: list | None = None
    for raw in hlo_text.splitlines():
        line = raw.strip()
        if current is None:
            m = _COMP_RE.match(line)
            if m:
                name = m.group(2)
                comps[name] = current = []
                if m.group(1):
                    entry = name
            continue
        if line.startswith("}"):
            current = None
            continue
        m = _OP_RE.match(line)
        if not m:
            continue
        body = line.split(", metadata=")[0]
        branches = _BRANCHES_RE.search(body)
        current.append(_HloOp(
            kind=m.group(2),
            line=line,
            subcomps=tuple(_SUBCOMP_RE.findall(body)),
            branch_comps=tuple(
                n.strip().lstrip("%")
                for n in branches.group(1).split(",")
            ) if branches else (),
        ))
    header = hlo_text.splitlines()[0] if hlo_text else ""
    m = _NUM_PARTITIONS_RE.search(header)
    nparts = int(m.group(1)) if m else 1
    return comps, entry, nparts


def collective_sequence(hlo_text: str) -> list[CollectiveOp]:
    """Every collective reachable from ENTRY, in program order, with
    its execution context (inside a conditional branch? how many loop
    bodies deep?)."""
    comps, entry, _ = _parse_computations(hlo_text)
    if entry is None:
        return []
    out: list[CollectiveOp] = []

    def visit(comp_name: str, in_conditional: bool, loop_depth: int,
              depth: int) -> None:
        if depth > 16:  # malformed/cyclic input: stop, never hang
            return
        for op in comps.get(comp_name, ()):
            if op.kind in COLLECTIVE_OPS:
                ch = _CHANNEL_RE.search(op.line)
                pm = _PAIRS_RE.search(op.line.split(", metadata=")[0])
                pairs = tuple(
                    (int(a), int(b))
                    for a, b in _PAIR_RE.findall(pm.group(1))
                ) if pm else ()
                out.append(CollectiveOp(
                    kind=op.kind,
                    channel=int(ch.group(1)) if ch else None,
                    pairs=pairs,
                    in_conditional=in_conditional,
                    loop_depth=loop_depth,
                    line=op.line[:160],
                ))
            is_loop = op.kind == "while"
            is_cond = op.kind == "conditional"
            for sub in op.subcomps:
                visit(sub, in_conditional or is_cond,
                      loop_depth + (1 if is_loop else 0), depth + 1)
            for sub in op.branch_comps:
                visit(sub, True, loop_depth, depth + 1)

    visit(entry, False, 0, 0)
    return out


def aliased_params(hlo_text: str) -> set[int]:
    """Entry-parameter numbers the module's input_output_alias table
    maps to an output — the donations XLA actually honored."""
    header = hlo_text.splitlines()[0] if hlo_text else ""
    m = _ALIAS_TABLE_RE.search(header)
    if not m:
        return set()
    return {int(p) for p in _ALIAS_ENTRY_RE.findall(m.group(1))}


# ---------------------------------------------------------------------------
# Role-sequence audit
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RoleAudit:
    """Per-rank-role collective sequences + the identity verdict."""

    num_partitions: int
    sequence: list  # CollectiveOp, program order
    role_sequences: dict  # role -> [(kind, channel)]
    problems: list

    @property
    def ok(self) -> bool:
        return not self.problems


def audit_roles(hlo_text: str) -> RoleAudit:
    comps_seq = collective_sequence(hlo_text)
    _, _, nparts = _parse_computations(hlo_text)
    problems: list[str] = []
    roles = list(range(nparts))
    role_sequences: dict[int, list] = {r: [] for r in roles}
    for op in comps_seq:
        if op.in_conditional:
            problems.append(
                f"collective under a conditional branch (a lowered "
                f"rank-divergent collective): {op.line}"
            )
            continue  # cannot attribute it to every role
        if op.channel is None:
            problems.append(
                f"collective without channel_id (cross-partition order "
                f"unpinned): {op.line}"
            )
        if op.kind.startswith("collective-permute") and op.pairs:
            srcs = [s for s, _ in op.pairs]
            tgts = [t for _, t in op.pairs]
            if len(srcs) != len(set(srcs)) or len(tgts) != len(set(tgts)):
                problems.append(
                    f"permute pair structure is not a partial "
                    f"permutation: {op.pairs}"
                )
            outside = [p for p in srcs + tgts if p >= nparts]
            if outside:
                problems.append(
                    f"permute names partitions outside the mesh "
                    f"({outside} >= {nparts}): {op.line}"
                )
        for r in roles:
            role_sequences[r].append((op.kind, op.channel))
    # No set-compare of the materialized role sequences: a single SPMD
    # module IS every partition's program, so the per-role lists are
    # identical by construction and such a check could never fire. The
    # cross-role identity verdict lives in the checks above — the only
    # ways one lowered module diverges per role are a collective under a
    # conditional (flagged, and excluded from the attributed sequences),
    # an unpinned channel order, or a malformed permute pair structure.
    return RoleAudit(
        num_partitions=nparts,
        sequence=comps_seq,
        role_sequences=role_sequences,
        problems=problems,
    )


# ---------------------------------------------------------------------------
# Donation audit
# ---------------------------------------------------------------------------


def expected_donated_params(args, donate_argnums) -> set[int]:
    """Flattened entry-parameter indices of the donated arguments (jit
    flattens args in order; each donated pytree covers a contiguous
    leaf range)."""
    import jax

    donated: set[int] = set()
    offset = 0
    wanted = set(donate_argnums)
    for i, arg in enumerate(args):
        n = len(jax.tree_util.tree_leaves(arg))
        if i in wanted:
            donated.update(range(offset, offset + n))
        offset += n
    return donated


def audit_donation(hlo_text: str, args, donate_argnums) -> list[str]:
    """Problems (empty = every declared donation actually aliased)."""
    aliased = aliased_params(hlo_text)
    expected = expected_donated_params(args, donate_argnums)
    missing = sorted(expected - aliased)
    if missing:
        return [
            f"declared donations not aliased by XLA (params {missing}; "
            f"alias table covers {sorted(aliased)}) — the driver "
            "silently copies what GL01 assumes it consumes"
        ]
    return []


# ---------------------------------------------------------------------------
# The three workloads' steady-state drivers
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DriverAudit:
    workload: str
    num_partitions: int
    n_collectives: int
    donated_params: int
    problems: list

    @property
    def ok(self) -> bool:
        return not self.problems


def _compiled_text(jitted, *args) -> str:
    return jitted.lower(*args).compile().as_text()


def audit_drivers(local: int = 16, steps: int = 2) -> list[DriverAudit]:
    """Compile + audit each workload's steady-state driver on the
    current (CPU) backend over a 2×1 mesh — the same geometry class the
    traffic gate uses, at a smaller shard so the full lint.sh stage
    stays well inside its budget. Callers own backend pinning
    (main() / tests set JAX_PLATFORMS=cpu + virtual devices)."""
    import jax.numpy as jnp

    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import (
        AcousticWave,
        HeatDiffusion,
        ShallowWater,
        SWEConfig,
        WaveConfig,
    )

    dims = (2, 1)
    shape = (local * dims[0], local * dims[1])
    lengths = (10.0 * dims[0], 10.0 * dims[1])
    rows: list[DriverAudit] = []

    def audit(workload, text, args, donate_argnums):
        roles = audit_roles(text)
        problems = list(roles.problems)
        if not roles.sequence:
            problems.append(
                "no collectives in the lowered program (the distributed "
                "driver audited away its exchanges?)"
            )
        problems += audit_donation(text, args, donate_argnums)
        rows.append(DriverAudit(
            workload=workload,
            num_partitions=roles.num_partitions,
            n_collectives=len(roles.sequence),
            donated_params=len(
                expected_donated_params(args, donate_argnums)
            ),
            problems=problems,
        ))

    # diffusion: the fused shard step (the per-step program the drivers
    # execute; donate=True is their steady-state aliasing)
    m = HeatDiffusion(DiffusionConfig(
        global_shape=shape, lengths=lengths, nt=8, warmup=0,
        dtype="f64", dims=dims,
    ))
    T, Cp = m.init_state()
    step, prepare = m.prepared_step_fn("shard", donate=True)
    C = prepare(Cp)
    audit("diffusion/shard", _compiled_text(step, T, C), (T, C), (0,))

    # wave: the fori-loop advance (collectives live in the while body)
    w = AcousticWave(WaveConfig(
        global_shape=shape, lengths=lengths, nt=8, warmup=0, dims=dims,
    ))
    U, Uprev, C2 = w.init_state()
    adv = w.advance_fn("perf")
    wargs = (U, Uprev, C2, jnp.int64(steps))
    audit("wave/perf", _compiled_text(adv, *wargs), wargs, (0, 1))

    # SWE: the coupled-state advance (h + (u, v) donated, masks not)
    s = ShallowWater(SWEConfig(
        global_shape=shape, lengths=lengths, nt=8, warmup=0, dims=dims,
    ))
    h, us = s.init_state()
    Mus = s.face_masks()
    sadv = s.advance_fn("perf")
    sargs = (h, us, Mus, jnp.int64(steps))
    audit("swe/perf", _compiled_text(sadv, *sargs), sargs, (0, 1))

    return rows


def audit_batched_drivers(local: int = 16, batch: int = 2,
                          steps: int = 2) -> list[DriverAudit]:
    """Compile + audit the SERVING layer's steady-state programs — the
    multi-tenant batched advances (models.*.batched_advance_fn, the
    exact callables `serving/service._Program.advance` executes per
    batch) plus the diffusion batched-hide edition — on the current
    (CPU) backend over a space×batch mesh (batch rows 1, space 2×1).

    The donation verdict is the serving pipeline's allocation
    contract (docs/SERVING.md "The pipeline"): every batched state
    leaf is declared donated, and a declared-but-unaliased donation
    would mean steady-state serving silently allocates a full batch of
    state per drain batch — the perf lie the `input_output_alias`
    check turns into a lint-stage failure. The collective checks ride
    along: the batched exchange's permutes must stay per-space-axis
    partial permutations (nothing ever permutes over `batch`), outside
    any lowered conditional."""
    import jax
    import numpy as np

    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import (
        AcousticWave,
        HeatDiffusion,
        ShallowWater,
        SWEConfig,
        WaveConfig,
    )

    dims = (2, 1)
    shape = (local * dims[0], local * dims[1])
    lengths = (10.0 * dims[0], 10.0 * dims[1])
    rows: list[DriverAudit] = []

    def audit(workload, text, args, donate_argnums):
        roles = audit_roles(text)
        problems = list(roles.problems)
        if not roles.sequence:
            problems.append(
                "no collectives in the lowered program (the batched "
                "driver audited away its exchanges?)"
            )
        problems += audit_donation(text, args, donate_argnums)
        rows.append(DriverAudit(
            workload=workload,
            num_partitions=roles.num_partitions,
            n_collectives=len(roles.sequence),
            donated_params=len(
                expected_donated_params(args, donate_argnums)
            ),
            problems=problems,
        ))

    def put(a, s):
        return jax.device_put(np.asarray(a), s)

    lane_steps = np.full(batch, steps, np.int32)

    # diffusion (one donated leaf), shard + the batched-hide overlap
    m = HeatDiffusion(DiffusionConfig(
        global_shape=shape, lengths=lengths, nt=8, warmup=0,
        dtype="f64", dims=dims, b_width=(local // 4, local // 4),
    ))
    T0, Cp = m.init_state()
    Tn = np.asarray(T0)
    for variant in ("shard", "hide"):
        adv, bg = m.batched_advance_fn(batch=batch, variant=variant)
        args = (
            put(np.stack([Tn] * batch), bg.sharding),
            put(Cp, bg.aux_sharding),
            put(lane_steps, bg.batch_sharding),
            steps,
        )
        audit(f"diffusion/batched-{variant}",
              _compiled_text(adv, *args), args, (0,))

    # wave (both leapfrog carries donated)
    w = AcousticWave(WaveConfig(
        global_shape=shape, lengths=lengths, nt=8, warmup=0, dims=dims,
    ))
    U0, _, C2 = w.init_state()
    Un = np.asarray(U0)
    wadv, wbg = w.batched_advance_fn(batch=batch)
    wargs = (
        put(np.stack([Un] * batch), wbg.sharding),
        put(np.stack([Un] * batch), wbg.sharding),
        put(C2, wbg.aux_sharding),
        put(lane_steps, wbg.batch_sharding),
        steps,
    )
    audit("wave/batched", _compiled_text(wadv, *wargs), wargs, (0, 1))

    # SWE (h + every velocity leaf donated; the face masks are not)
    s = ShallowWater(SWEConfig(
        global_shape=shape, lengths=lengths, nt=8, warmup=0, dims=dims,
    ))
    h0, us0 = s.init_state()
    Mus = s.face_masks()
    hn = np.asarray(h0)
    sadv, sbg = s.batched_advance_fn(batch=batch)
    zeros_b = np.zeros((batch,) + shape)
    sargs = (
        put(np.stack([hn] * batch), sbg.sharding),
        tuple(put(zeros_b, sbg.sharding) for _ in us0),
        tuple(put(M, sbg.aux_sharding) for M in Mus),
        put(lane_steps, sbg.batch_sharding),
        steps,
    )
    audit("swe/batched", _compiled_text(sadv, *sargs), sargs, (0, 1))

    return rows


def render_table(rows: list[DriverAudit]) -> str:
    head = (
        f"{'workload':16s} {'parts':>5s} {'collectives':>11s} "
        f"{'donated':>7s} status"
    )
    lines = [head, "-" * len(head)]
    for r in rows:
        status = "ok" if r.ok else "DIVERGENT/UNALIASED"
        lines.append(
            f"{r.workload:16s} {r.num_partitions:5d} "
            f"{r.n_collectives:11d} {r.donated_params:7d} {status}"
        )
        for p in r.problems:
            lines.append(f"    problem: {p}")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="python -m rocm_mpi_tpu.analysis.lowered",
        description="lowered-program audit: identical collective "
                    "sequences across rank-roles + real donation "
                    "aliasing on every workload's steady-state driver",
    )
    p.add_argument("--local", type=int, default=16,
                   help="per-device shard edge (default 16 — the audit "
                   "judges structure, not size)")
    p.add_argument("--json", action="store_true",
                   help="one JSON line per driver on stdout (table to "
                   "stderr)")
    args = p.parse_args(argv)

    # CPU pinning BEFORE any backend use — same contract as the traffic
    # gate: no accelerator, no tunnel, no flakiness.
    import jax

    from rocm_mpi_tpu.utils.backend import set_cpu_device_count

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    set_cpu_device_count(2)

    rows = audit_drivers(local=args.local)
    rows += audit_batched_drivers(local=args.local)
    table = render_table(rows)
    if args.json:
        import json as _json

        print(table, file=sys.stderr)
        for r in rows:
            print(_json.dumps({
                "metric": f"lowered {r.workload}",
                "partitions": r.num_partitions,
                "collectives": r.n_collectives,
                "donated_params": r.donated_params,
                "ok": r.ok,
                "problems": r.problems,
            }))
    else:
        print(table)
    bad = [r for r in rows if not r.ok]
    if bad:
        print(
            "lowered audit FAILED — "
            + ", ".join(r.workload for r in bad),
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
