"""GL10 — concurrency-discipline (racecheck).

PR 16 made the serving control plane a genuinely concurrent stdlib
program: a threaded `RequestQueue`, the fleet router, the ticket
journal — ~30 `with self._lock:` regions whose correctness rests on
hand-enforced conventions none of GL01–GL09 can see. Two shipped bug
classes prove the gap, and both were caught by review, not by the lint
gate:

* **PR 14 (N-writer quarantine append):** every rank appended to the
  same quarantine.jsonl — N identical writers, interleave risk. The fix
  was a rank-0 ownership guard; nothing policed it statically.
* **PR 15 (busy-mark ordering):** the drain pipeline marked
  `_inflight_n` busy BEFORE invoking the raising stage hook — one hook
  exception and the bubble gauge read 1.0 forever. A lock held across a
  raising call is the same shape with worse consequences: the lock
  leaks and every thread wedges.

Six facets, all flow-sensitive, the interprocedural ones riding the
GL08 engine's summaries (extended with acquire/blocking effects):

* **(a) guarded-attribute inference** — for a class owning a
  `threading.Lock/RLock/Condition`, an attribute mutated under the lock
  in ≥2 distinct regions is inferred lock-guarded; any read/write of it
  outside a lock region (and outside `__init__`) fires.
* **(b) the `*_locked` convention** — a `_retry_after_locked`-style
  method called on a path where no class lock is held; plus the
  explicit-acquire balance check: `self._lock.acquire()` with call
  sites before the matching `release()` outside try/finally (the PR-15
  shape — a raising call leaks the lock), or with no release at all.
* **(c) lock-order cycles** — the per-class lock-acquisition graph
  (direct `with` nesting plus self-call summaries); opposite
  acquisition orders across methods deadlock. Re-acquiring a held
  non-reentrant `Lock` is the degenerate cycle (self-deadlock);
  `RLock` is exempt.
* **(d) blocking-under-lock** — a call summarized as blocking
  (`time.sleep`, `Event.wait`, `Ticket.result`, `block_until_ready`,
  file I/O, `subprocess.*`) while a lock is held: every contending
  thread stalls behind the I/O. `self._cond.wait()` on the HELD
  Condition itself is the one blessed blocking call (that is what a
  Condition is for).
* **(e) single-clock-writer** — wall-clock reads (`time.time`,
  `time.monotonic`) in `serving/*` outside the designated clock
  chokepoints: the queue and router own the clock (the
  `poll_health(now=None)` / `expire_overdue(now=None)` injection
  seams); everyone else takes `now` as data. The `x if now is None
  else now` injection idiom and direct dict-literal stamp values
  (`{"t": time.time()}`) are exempt — those ARE the chokepoint shapes.
* **(f) single-writer appenders** — an append-mode open of a
  journal/quarantine/ticket sidecar path outside the owning writer
  (an `append_*`/`*_append` function or a `*Journal/*Ledger/*Writer`
  class). Promotes GL09's artifact regex into writer ownership: the
  PR-14 bug was N owners, not a torn write.

What never fires: module-level locks (no `self.` owner — out of scope
by design), attributes mutated under the lock in only one region (one
region is initialization discipline, not a guard contract), anything
reached through a receiver the resolver cannot see (`t._mark(...)` —
a miss is never a false positive), and `*_locked` methods themselves
(they hold the lock by contract; facet (b) polices their callers).
"""

from __future__ import annotations

import ast
import re

from rocm_mpi_tpu.analysis import astutil, engine
from rocm_mpi_tpu.analysis.core import ModuleContext, Rule
from rocm_mpi_tpu.analysis.rules_sidecar import (
    _chase, _literal_strings, _open_mode,
)

_LOCK_CTOR_TAILS = ("Lock", "RLock", "Condition")

# list/set/dict mutations through a method call on a self attribute —
# these are writes for guarded-attribute inference (self._front.sort()
# mutates _front as surely as assignment does).
_MUTATOR_TAILS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popleft", "clear",
    "sort", "reverse", "add", "discard", "update", "setdefault",
    "popitem", "appendleft",
})

# GL10e scope and owners. The substring (not prefix) match works for
# both the relative gate invocation and absolute test paths.
_SERVING_MARK = "rocm_mpi_tpu/serving/"
_CLOCK_OWNER_FILES = (
    "rocm_mpi_tpu/serving/queue.py",
    "rocm_mpi_tpu/serving/router.py",
)
_CLOCK_TAILS = frozenset({"time", "monotonic", "time_ns", "monotonic_ns"})

# GL10f: the single-writer sidecar families and their owner spellings.
_WRITER_PATH_RE = re.compile(r"(quarantine|journal|ticket)[-\w.]*\.jsonl\b")
_WRITER_CLASS_RE = re.compile(r"(Journal|Ledger|Writer)")


def _is_none_test(node: ast.AST) -> bool:
    """`x is None` / `x is not None` (the injectable-clock idiom test)."""
    if not isinstance(node, ast.Compare) or len(node.ops) != 1:
        return False
    if not isinstance(node.ops[0], (ast.Is, ast.IsNot)):
        return False
    sides = [node.left] + node.comparators
    return any(
        isinstance(s, ast.Constant) and s.value is None for s in sides
    )


def _lock_ctor_kind(value: ast.AST, imports) -> str | None:
    """"Lock"/"RLock"/"Condition" when `value` is a threading lock
    constructor call under the module's import table, else None."""
    if not isinstance(value, ast.Call):
        return None
    callee = astutil.call_name(value)
    head, _, tail = callee.rpartition(".")
    if head:
        if imports.module_aliases.get(head) == "threading" \
                and tail in _LOCK_CTOR_TAILS:
            return tail
        return None
    origin = imports.from_imports.get(callee)
    if origin and origin.startswith("threading."):
        kind = origin.rpartition(".")[2]
        return kind if kind in _LOCK_CTOR_TAILS else None
    return None


class _ClassInfo:
    """One class's lock attrs and direct methods."""

    def __init__(self, node: ast.ClassDef, imports):
        self.node = node
        self.methods = {
            n.name: n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.locks: dict[str, str] = {}  # attr -> Lock/RLock/Condition
        for fn in self.methods.values():
            for st in astutil.walk_no_nested_functions(fn):
                if isinstance(st, ast.Assign) and len(st.targets) == 1:
                    attr = engine._self_attr(st.targets[0])
                    if attr is None:
                        continue
                    kind = _lock_ctor_kind(st.value, imports)
                    if kind is not None:
                        self.locks[attr] = kind


def _target_attrs(target: ast.AST):
    """(node, attr) for every `self.Y`-rooted store in an assign target
    (tuple unpack, starred, and `self.Y[k] = ...` included)."""
    if isinstance(target, ast.Attribute):
        attr = engine._self_attr(target)
        if attr is not None:
            yield target, attr
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _target_attrs(elt)
    elif isinstance(target, ast.Starred):
        yield from _target_attrs(target.value)
    elif isinstance(target, ast.Subscript):
        attr = engine._self_attr(target.value)
        if attr is not None:
            yield target, attr


def _expr_walk(node: ast.AST):
    """ast.walk minus deferred scopes (lambdas, nested defs): their
    bodies do not execute at this program point."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.Lambda, ast.FunctionDef,
                          ast.AsyncFunctionDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


class _MethodScan:
    """One method's lock-flow events: attribute accesses, calls, and
    lock acquisitions, each tagged with the set of class locks held at
    that program point (held = {lock attr: region id})."""

    def __init__(self, cls: _ClassInfo, fn: ast.FunctionDef):
        self.cls = cls
        self.fn = fn
        self.attr_events: list = []     # (node, attr, is_write, held)
        self.call_events: list = []     # (node, callee str, held)
        self.acquire_events: list = []  # (node, lock attr, held-before)
        self.balance: list = []         # (node, message) — facet (b2)
        held: dict[str, object] = {}
        if fn.name.endswith("_locked"):
            # The convention IS the contract: the caller holds the lock.
            held = {lock: id(fn) for lock in cls.locks}
        self._stmts(fn.body, held)

    # -- statement walk ---------------------------------------------------

    def _stmts(self, body: list, held: dict) -> None:
        held = dict(held)
        for idx, st in enumerate(body):
            got = self._lock_method_stmt(st, "acquire")
            if got is not None:
                attr, call = got
                self.acquire_events.append((call, attr, dict(held)))
                self._check_balance(body, idx, attr, call)
                held[attr] = id(call)
                continue
            got = self._lock_method_stmt(st, "release")
            if got is not None:
                held.pop(got[0], None)
                continue
            self._stmt(st, held)

    def _stmt(self, st: ast.AST, held: dict) -> None:
        if isinstance(st, (ast.With, ast.AsyncWith)):
            inner = dict(held)
            for item in st.items:
                attr = engine._self_attr(item.context_expr)
                if attr is not None and attr in self.cls.locks:
                    self.acquire_events.append((st, attr, dict(inner)))
                    inner[attr] = id(st)
                else:
                    self._expr(item.context_expr, held)
            self._stmts(st.body, inner)
        elif isinstance(st, (ast.If, ast.While)):
            self._expr(st.test, held)
            self._stmts(st.body, held)
            self._stmts(st.orelse, held)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._expr(st.iter, held)
            for node, attr in _target_attrs(st.target):
                self._attr(node, attr, True, held)
            self._stmts(st.body, held)
            self._stmts(st.orelse, held)
        elif isinstance(st, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            self._stmts(st.body, held)
            for handler in st.handlers:
                self._stmts(handler.body, held)
            self._stmts(st.orelse, held)
            self._stmts(st.finalbody, held)
        elif isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = st.targets if isinstance(st, ast.Assign) \
                else [st.target]
            for t in targets:
                for node, attr in _target_attrs(t):
                    self._attr(node, attr, True, held)
                    if isinstance(st, ast.AugAssign):
                        self._attr(node, attr, False, held)
                # subscript keys and chained receivers still read
                if isinstance(t, ast.Subscript):
                    self._expr(t.slice, held)
            if getattr(st, "value", None) is not None:
                self._expr(st.value, held)
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                for node, attr in _target_attrs(t):
                    self._attr(node, attr, True, held)
                if isinstance(t, ast.Subscript):
                    self._expr(t.slice, held)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # deferred scope
        else:
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._expr(child, held)
                elif isinstance(child, ast.stmt):
                    self._stmt(child, held)

    # -- expression walk --------------------------------------------------

    def _expr(self, node: ast.AST, held: dict) -> None:
        if node is None:
            return
        for n in _expr_walk(node):
            if isinstance(n, ast.Attribute):
                attr = engine._self_attr(n)
                if attr is not None:
                    self._attr(n, attr, False, held)
            elif isinstance(n, ast.Call):
                callee = astutil.call_name(n)
                self.call_events.append((n, callee, dict(held)))
                # self.Y.append(...) mutates Y
                parts = callee.split(".")
                if len(parts) == 3 and parts[0] in ("self", "cls") \
                        and parts[2] in _MUTATOR_TAILS:
                    self._attr(n, parts[1], True, held)

    def _attr(self, node, attr: str, is_write: bool, held: dict) -> None:
        if attr in self.cls.locks:
            return  # the locks themselves are accessed unlocked by design
        self.attr_events.append((node, attr, is_write, dict(held)))

    # -- explicit acquire/release (facet b2) ------------------------------

    def _lock_method_stmt(self, st, which: str):
        """`self.X.acquire()` / `.release()` as a bare statement, X a
        class lock -> (X, call node)."""
        if not isinstance(st, ast.Expr) or not isinstance(
            st.value, ast.Call
        ):
            return None
        callee = astutil.call_name(st.value)
        parts = callee.split(".")
        if len(parts) == 3 and parts[0] in ("self", "cls") \
                and parts[2] == which and parts[1] in self.cls.locks:
            return parts[1], st.value
        return None

    def _release_in_finally(self, st, attr: str) -> bool:
        if not isinstance(st, ast.Try):
            return False
        for node in ast.walk(ast.Module(body=st.finalbody,
                                        type_ignores=[])):
            if isinstance(node, ast.Call):
                parts = astutil.call_name(node).split(".")
                if len(parts) == 3 and parts[0] in ("self", "cls") \
                        and parts[1] == attr and parts[2] == "release":
                    return True
        return False

    def _check_balance(self, body, idx: int, attr: str, call) -> None:
        rest = body[idx + 1:]
        release_at = None
        for j, st in enumerate(rest):
            if self._release_in_finally(st, attr):
                return  # acquire; try: ... finally: release — disciplined
            got = self._lock_method_stmt(st, "release")
            if got is not None and got[0] == attr:
                release_at = j
                break
        if release_at is None:
            self.balance.append((call, (
                f"`self.{attr}.acquire()` is never released on this "
                f"path — any exception (or plain fallthrough) leaks the "
                f"lock and wedges every other thread"
            )))
            return
        between = rest[:release_at]
        for st in between:
            for node in ast.walk(st):
                if isinstance(node, ast.Call):
                    self.balance.append((call, (
                        f"call site between `self.{attr}.acquire()` and "
                        f"its release outside try/finally — a raising "
                        f"call leaks the lock (the PR-15 busy-mark-"
                        f"before-hook bug shape)"
                    )))
                    return


# ---------------------------------------------------------------------------
# Facets a-d: per lock-owning class
# ---------------------------------------------------------------------------


def _class_call_summary(program, mod, cls: _ClassInfo, callee: str):
    """The engine summary for a call, identity-checked for self-calls
    (the module-wide bare-name index is last-wins; another class's
    same-named method must contribute no facts)."""
    if callee.startswith(("self.", "cls.")):
        parts = callee.split(".")
        if len(parts) == 2 and parts[1] in cls.methods \
                and mod.functions.get(parts[1]) is cls.methods[parts[1]]:
            return program.summary_for_call(mod, callee)
        return None
    if "." not in callee:
        return program.summary_for_call(mod, callee)
    return None


def _check_class(rule, ctx, program, mod, cls: _ClassInfo) -> list:
    findings = []
    scans = {name: _MethodScan(cls, fn)
             for name, fn in cls.methods.items()}

    # -- (a) guarded-attribute inference ---------------------------------
    regions: dict = {}  # attr -> lock -> set(region ids)
    for name, scan in scans.items():
        if name == "__init__":
            continue
        for _node, attr, is_write, held in scan.attr_events:
            if not is_write:
                continue
            for lock, region in held.items():
                regions.setdefault(attr, {}).setdefault(
                    lock, set()
                ).add(region)
    guarded: dict[str, list] = {}  # attr -> owner locks
    for attr, by_lock in regions.items():
        owners = [lock for lock, regs in by_lock.items()
                  if len(regs) >= 2]
        if owners:
            guarded[attr] = owners

    seen_a = set()
    for name, scan in scans.items():
        if name == "__init__":
            continue
        for node, attr, is_write, held in scan.attr_events:
            owners = guarded.get(attr)
            if not owners or any(lock in held for lock in owners):
                continue
            key = (node.lineno, attr)
            if key in seen_a:
                continue
            seen_a.add(key)
            lock = owners[0]
            n_regions = len(regions[attr][lock])
            verb = "written" if is_write else "read"
            findings.append(ctx.finding(
                node, rule,
                f"`self.{attr}` is lock-guarded (mutated under "
                f"`self.{lock}` in {n_regions} regions of "
                f"{cls.node.name}) but {verb} here without the lock",
                f"take `with self.{lock}:` around the access, or make "
                f"this a `*_locked` method and hold the lock at every "
                f"call site",
            ))

    # -- (b1) *_locked called without the lock ---------------------------
    for name, scan in scans.items():
        if name == "__init__" or name.endswith("_locked"):
            continue
        for node, callee, held in scan.call_events:
            parts = callee.split(".")
            if len(parts) != 2 or parts[0] not in ("self", "cls") \
                    or not parts[1].endswith("_locked"):
                continue
            if held:
                continue
            findings.append(ctx.finding(
                node, rule,
                f"`self.{parts[1]}()` follows the *_locked convention "
                f"but no {cls.node.name} lock is held on this path",
                f"call it inside `with self.{next(iter(cls.locks))}:`, "
                f"or rename the helper if it genuinely needs no lock",
            ))

    # -- (b2) explicit acquire/release balance ---------------------------
    for scan in scans.values():
        for node, message in scan.balance:
            findings.append(ctx.finding(
                node, rule, message,
                "prefer `with self.<lock>:`; if acquire/release must be "
                "explicit, release in a `finally:`",
            ))

    # -- (c) lock-order graph + self-deadlock ----------------------------
    graph: dict[str, dict] = {}  # lock -> {lock: witness node}
    for scan in scans.values():
        for node, attr, held in scan.acquire_events:
            for h in held:
                if h == attr:
                    if cls.locks[attr] != "RLock":
                        findings.append(ctx.finding(
                            node, rule,
                            f"re-acquires non-reentrant `self.{attr}` "
                            f"already held on this path — "
                            f"self-deadlock",
                            f"make `self.{attr}` an RLock or restructure "
                            f"so the lock is taken once",
                        ))
                else:
                    graph.setdefault(h, {}).setdefault(attr, node)
        for node, callee, held in scan.call_events:
            if not held:
                continue
            summary = _class_call_summary(program, mod, cls, callee)
            if summary is None:
                continue
            for l2 in sorted(summary.acquires_locks & set(cls.locks)):
                if l2 in held:
                    if cls.locks[l2] != "RLock":
                        findings.append(ctx.finding(
                            node, rule,
                            f"`{callee}` re-acquires non-reentrant "
                            f"`self.{l2}` already held here — "
                            f"self-deadlock",
                            f"make `self.{l2}` an RLock, or split a "
                            f"`*_locked` variant that assumes the lock",
                        ))
                    continue
                for h in held:
                    if h != l2:
                        graph.setdefault(h, {}).setdefault(l2, node)

    cycle = _find_cycle({a: set(bs) for a, bs in graph.items()})
    if cycle:
        order = " -> ".join(f"self.{lock}" for lock in cycle)
        witness = graph[cycle[0]][cycle[1]]
        findings.append(ctx.finding(
            witness, rule,
            f"lock-order cycle in {cls.node.name}: {order} — two "
            f"threads taking the locks in opposite orders deadlock",
            "pick one global acquisition order for the class's locks "
            "and take them in that order everywhere",
        ))

    # -- (d) blocking under a held lock ----------------------------------
    seen_d = set()
    for scan in scans.values():
        for node, callee, held in scan.call_events:
            if not held:
                continue
            parts = callee.split(".")
            btail = engine.blocking_tail(callee)
            if btail is not None:
                # self._cond.wait() on the held Condition is the point
                # of a Condition — the one blessed blocking call.
                if len(parts) == 3 and parts[0] in ("self", "cls") \
                        and parts[2] in ("wait", "wait_for") \
                        and parts[1] in held \
                        and cls.locks.get(parts[1]) == "Condition":
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen_d:
                    continue
                seen_d.add(key)
                lock = next(iter(held))
                findings.append(ctx.finding(
                    node, rule,
                    f"blocking call `{callee}` while holding "
                    f"`self.{lock}` — every thread contending the lock "
                    f"stalls behind it",
                    "move the blocking work outside the lock region; "
                    "snapshot state under the lock, block after",
                ))
                continue
            summary = _class_call_summary(program, mod, cls, callee)
            if summary is not None and summary.blocking:
                key = (node.lineno, node.col_offset)
                if key in seen_d:
                    continue
                seen_d.add(key)
                lock = next(iter(held))
                ops = ", ".join(sorted(summary.blocking))
                findings.append(ctx.finding(
                    node, rule,
                    f"`{callee}` is summarized as blocking ({ops}) and "
                    f"is called while holding `self.{lock}`",
                    "move the blocking work outside the lock region; "
                    "snapshot state under the lock, block after",
                ))
    return findings


def _find_cycle(graph: dict) -> list | None:
    """A directed cycle [a, b, ..., a] in the lock graph, or None."""
    color: dict = {}
    path: list = []

    def dfs(u):
        color[u] = 1
        path.append(u)
        for v in sorted(graph.get(u, ())):
            if color.get(v) == 1:
                return path[path.index(v):] + [v]
            if color.get(v, 0) == 0:
                found = dfs(v)
                if found:
                    return found
        color[u] = 2
        path.pop()
        return None

    for start in sorted(graph):
        if color.get(start, 0) == 0:
            found = dfs(start)
            if found:
                return found
    return None


# ---------------------------------------------------------------------------
# Facet e: single-clock-writer (serving scope only)
# ---------------------------------------------------------------------------


def _clock_findings(rule, ctx, tree, imports) -> list:
    posix = ctx.posix_path
    if _SERVING_MARK not in posix or posix.endswith(_CLOCK_OWNER_FILES):
        return []
    time_aliases = {
        local for local, m in imports.module_aliases.items()
        if m == "time"
    }
    clock_origins = {f"time.{t}" for t in _CLOCK_TAILS}
    from_clocks = {
        local for local, origin in imports.from_imports.items()
        if origin in clock_origins
    }
    exempt: set[int] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.IfExp) and _is_none_test(n.test):
            # the injection seam: `time.monotonic() if now is None
            # else now` (either arm may carry the clock)
            exempt.add(id(n.body))
            exempt.add(id(n.orelse))
        elif isinstance(n, ast.Dict):
            # direct dict-literal stamp values ({"t": time.time()})
            # are record fields, not control-flow clocks
            for v in n.values:
                exempt.add(id(v))
    findings = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call) or id(n) in exempt:
            continue
        callee = astutil.call_name(n)
        head, _, tail = callee.rpartition(".")
        is_clock = (head in time_aliases and tail in _CLOCK_TAILS) \
            or (not head and callee in from_clocks)
        if not is_clock:
            continue
        findings.append(ctx.finding(
            n, rule,
            "wall-clock read outside the serving clock chokepoints — "
            "the queue/router own time (wall_slo gate, "
            "poll_health/expire_overdue(now) seams); a second clock "
            "owner is the multi-controller divergence hazard the "
            "fleet design forbids",
            "accept `now` as a parameter with the `x if now is None "
            "else now` seam, or route through the owning component",
        ))
    return findings


# ---------------------------------------------------------------------------
# Facet f: single-writer appenders
# ---------------------------------------------------------------------------


def _is_writer_owner(cls_name, fn_name) -> bool:
    if fn_name and (fn_name.startswith("append_")
                    or fn_name.endswith("_append")):
        return True
    return bool(cls_name and _WRITER_CLASS_RE.search(cls_name))


def _writer_findings(rule, ctx, tree) -> list:
    scopes = [(tree, None, None)]

    def collect(node, cls_name):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                collect(child, child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                scopes.append((child, cls_name, child.name))
                collect(child, cls_name)

    collect(tree, None)
    findings = []
    for scope, cls_name, fn_name in scopes:
        if _is_writer_owner(cls_name, fn_name):
            continue
        assignments: dict = {}
        opens: list = []
        for node in astutil.walk_no_nested_functions(scope):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                assignments[node.targets[0].id] = node.value
            if isinstance(node, ast.Call):
                mode = _open_mode(node)
                if not mode or mode[0] != "a":
                    continue
                if isinstance(node.func, ast.Attribute):
                    path_expr = node.func.value
                else:
                    path_expr = node.args[0] if node.args else None
                if path_expr is None:
                    continue
                opens.append((node, path_expr))
        for node, path_expr in opens:
            chased = _chase(path_expr, assignments)
            if not any(_WRITER_PATH_RE.search(s)
                       for s in _literal_strings(chased)):
                continue
            findings.append(ctx.finding(
                node, rule,
                "append-mode open of a journal/quarantine sidecar "
                "outside its owning writer — N appenders interleave "
                "records and the ledger stops being a ledger (the "
                "PR-14 N-rank quarantine bug shape)",
                "route the append through the owning writer (an "
                "`append_*` helper or the *Journal/*Ledger class) "
                "behind a single-writer guard",
            ))
    return findings


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def check_concurrency(rule, ctx: ModuleContext, program, mod) -> list:
    """All six facets over one module, with `program` supplying the
    interprocedural acquire/blocking summaries."""
    findings = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        cls = _ClassInfo(node, mod.imports)
        if not cls.locks:
            continue
        findings.extend(_check_class(rule, ctx, program, mod, cls))
    findings.extend(_clock_findings(rule, ctx, mod.tree, mod.imports))
    findings.extend(_writer_findings(rule, ctx, mod.tree))
    return findings


class ConcurrencyRule(Rule):
    id = "GL10"
    name = "concurrency-discipline"
    severity = "error"
    rationale = (
        "the serving control plane's thread-safety rests on conventions "
        "(guarded attrs, *_locked, lock order, no blocking under locks, "
        "one clock owner, one sidecar writer) that shipped-bug history "
        "(PR-14 N-writer append, PR-15 busy-mark ordering) proves are "
        "violated silently without a static gate"
    )
    hint = "see docs/ANALYSIS.md#gl10"

    def check(self, ctx: ModuleContext):
        """Single-module fallback (the whole-program pass in
        engine.analyze_modules is the real engine; this treats the one
        file as a one-module program so fixtures and ad-hoc
        lint_source calls still get the rule)."""
        mod = engine.ModuleInfo(
            path=ctx.path,
            name=engine.module_name_for_path(ctx.path),
            source=ctx.source,
            tree=ctx.tree,
        )
        program = engine.Program([mod])
        return check_concurrency(self, ctx, program, mod)
