"""GL01 — donation safety.

The PR-1 race class, measured on the pinned jax-0.4.37 stack (see
utils/checkpoint.py's module docstring): a buffer donated into a jitted
call is reused by XLA, so any later read of the donated name sees garbage;
and an orbax save is asynchronous, so rebinding the saved state (the
donating advance reusing its buffer) while the save is still in flight
corrupts every mid-run checkpoint.

Two statically-checkable patterns, one rule id:

* **donated-reread** — a name is passed at a donated position of a call to
  a `donate_argnums`/`donate_argnames` jitted callable (resolved within
  the module: decorated defs and `f = jax.jit(g, donate_argnums=…)`
  assignments) and then *read* again in the same scope before being
  rebound.
* **save-overlap** — a name captured by an orbax CheckpointManager
  `.save(...)` is *rebound* (i.e. its old buffer handed back to a donating
  advance) before `.wait_until_finished()` / `.close()` on the same
  manager. Managers are recognized by assignment from a call whose name
  contains "manager" (`_manager(...)`, `CheckpointManager(...)`).

Both are flow-sensitive over a small abstract state (poisoned names +
in-flight saves); branches merge by union, loop bodies run twice so the
back edge is observed (the `while step < nt:` save/advance overlap is
exactly a back-edge bug).

Save-overlap is additionally *interprocedural within the module* (the
GL08/GL09 playbook): a local helper that calls `.save(...)` on a
manager parameter and returns without `wait_until_finished()`/`close()`
on every path gets a summary — "leaves the save of parameter j in
flight on manager parameter i" — which its call sites replay, so
`state = advance(state, n)` in the caller is still flagged when the
save it races lives two helpers down (`run_segmented` →
`_guarded_save` → `_save_once` in utils/checkpoint.py). Summaries
reach a fixpoint over the module's top-level defs; a helper whose every
path waits exports nothing, which is exactly why deleting the wait
re-creates the finding at the caller's rebind.
"""

from __future__ import annotations

import ast

from rocm_mpi_tpu.analysis import astutil
from rocm_mpi_tpu.analysis.core import ModuleContext, Rule


def _donated_positions(call: ast.Call):
    """(argnums, argnames) declared on a jit call expression, or None."""
    nums: tuple[int, ...] = ()
    names: tuple[str, ...] = ()
    found = False
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            got = astutil.int_tuple(kw.value)
            if got:
                nums, found = got, True
        elif kw.arg == "donate_argnames":
            got = astutil.str_args(kw.value)
            if got:
                names, found = tuple(got), True
    return (nums, names) if found else None


def _jit_call_donations(expr: ast.AST):
    """Donation spec from `jax.jit(...)` / `functools.partial(jax.jit, ...)`
    expressions (decorators or RHS of assignments)."""
    if not isinstance(expr, ast.Call):
        return None
    callee = astutil.tail_name(astutil.call_name(expr))
    if callee in ("jit", "pjit"):
        return _donated_positions(expr)
    if callee == "partial" and expr.args:
        inner = astutil.dotted_name(expr.args[0])
        if inner and astutil.tail_name(inner) in ("jit", "pjit"):
            return _donated_positions(expr)
    return None


def _collect_donating_callables(tree: ast.Module) -> dict:
    """local callable name -> (argnums, argnames)."""
    out: dict[str, tuple] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                spec = _jit_call_donations(dec)
                if spec:
                    out[node.name] = spec
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            spec = _jit_call_donations(node.value)
            if spec:
                out[node.targets[0].id] = spec
    return out


def _is_manager_ctor(expr: ast.AST) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    return "manager" in astutil.tail_name(astutil.call_name(expr)).lower()


def _names_in(node: ast.AST) -> set[str]:
    return {
        n.id
        for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


class _State:
    __slots__ = ("poisoned", "inflight")

    def __init__(self):
        self.poisoned: dict[str, ast.AST] = {}  # name -> donating call node
        self.inflight: dict[str, dict[str, ast.AST]] = {}  # mgr -> {name: save}

    def copy(self) -> "_State":
        s = _State()
        s.poisoned = dict(self.poisoned)
        s.inflight = {k: dict(v) for k, v in self.inflight.items()}
        return s

    def merge(self, other: "_State") -> None:
        self.poisoned.update(other.poisoned)
        for mgr, names in other.inflight.items():
            self.inflight.setdefault(mgr, {}).update(names)


def _param_names(fn: ast.FunctionDef) -> list[str]:
    """Positional parameter names, in call-argument order."""
    return [a.arg for a in fn.args.posonlyargs + fn.args.args]


def _arg_at(call: ast.Call, params: list[str], idx: int):
    """The Name node bound to positional parameter `idx` at this call
    site (positionally or by keyword), or None."""
    if idx < len(call.args):
        arg = call.args[idx]
        return arg if isinstance(arg, ast.Name) else None
    if idx < len(params):
        for kw in call.keywords:
            if kw.arg == params[idx] and isinstance(kw.value, ast.Name):
                return kw.value
    return None


class _FunctionChecker:
    def __init__(self, rule, ctx: ModuleContext, donating: dict,
                 summaries: dict | None = None, silent: bool = False):
        self.rule = rule
        self.ctx = ctx
        self.donating = donating
        self.summaries = summaries or {}
        self.silent = silent
        self.managers: set[str] = set()
        self.findings: list = []
        self._reported: set[tuple] = set()

    # ---- expression traversal (evaluation order, approximately) --------

    def expr(self, node: ast.AST, state: _State) -> None:
        """Visit an expression: check loads of poisoned names, apply
        donation / save / wait effects of calls."""
        if node is None:
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id in state.poisoned:
                don = state.poisoned[node.id]
                self._report(
                    node,
                    f"'{node.id}' is read after being donated to the jitted "
                    f"call on line {don.lineno}; donated buffers are reused "
                    "by XLA and may hold garbage",
                    "rebind the name from the call's result (x = f(x, ...)) "
                    "or drop donate_argnums for values read afterwards",
                )
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.Call):
            self._call(node, state)
            return
        for child in ast.iter_child_nodes(node):
            self.expr(child, state)

    def _call(self, call: ast.Call, state: _State) -> None:
        # The callee and arguments evaluate first — reading the name *in*
        # the donating call is the donation itself, not a re-read (but a
        # method call on a donated array, e.g. x.block_until_ready(), IS
        # a re-read and gets caught by the func traversal).
        for child in ast.iter_child_nodes(call.func):
            self.expr(child, state)
        for arg in call.args:
            self.expr(arg, state)
        for kw in call.keywords:
            self.expr(kw.value, state)

        # Donation effect.
        if isinstance(call.func, ast.Name) and call.func.id in self.donating:
            nums, names = self.donating[call.func.id]
            for i in nums:
                if i < len(call.args) and isinstance(call.args[i], ast.Name):
                    state.poisoned[call.args[i].id] = call
            for kw in call.keywords:
                if kw.arg in names and isinstance(kw.value, ast.Name):
                    state.poisoned[kw.value.id] = call
        # Async-save bookkeeping on recognized checkpoint managers.
        if isinstance(call.func, ast.Attribute) and \
                isinstance(call.func.value, ast.Name):
            recv = call.func.value.id
            if recv in self.managers:
                if call.func.attr == "save":
                    # arg 0 is the step LABEL (a host int), not a buffer
                    names = set()
                    for arg in call.args[1:]:
                        names |= _names_in(arg)
                    for kw in call.keywords:
                        names |= _names_in(kw.value)
                    state.inflight.setdefault(recv, {}).update(
                        {n: call for n in names}
                    )
                elif call.func.attr in ("wait_until_finished", "close"):
                    state.inflight.pop(recv, None)
        # Interprocedural save effect: a local helper summarized as
        # leaving saves in flight on a manager parameter replays that
        # effect here when the call binds a recognized manager to it
        # (module docstring — run_segmented → _guarded_save →
        # _save_once is the real chain this covers).
        if isinstance(call.func, ast.Name) and \
                call.func.id in self.summaries:
            params, effects = self.summaries[call.func.id]
            for mgr_idx, captured in effects.items():
                mgr_arg = _arg_at(call, params, mgr_idx)
                if mgr_arg is None or mgr_arg.id not in self.managers:
                    continue
                names = set()
                for i in captured:
                    arg = _arg_at(call, params, i)
                    if arg is not None:
                        names.add(arg.id)
                if names:
                    state.inflight.setdefault(mgr_arg.id, {}).update(
                        {n: call for n in names}
                    )

    # ---- statement traversal ------------------------------------------

    def stmts(self, body, state: _State) -> None:
        for stmt in body:
            self.stmt(stmt, state)

    def stmt(self, node: ast.stmt, state: _State) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate runtime scope
        if isinstance(node, ast.Assign):
            self.expr(node.value, state)
            if _is_manager_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.managers.add(t.id)
            for t in node.targets:
                self._store_target(t, state)
        elif isinstance(node, ast.AugAssign):
            self.expr(node.value, state)
            if isinstance(node.target, ast.Name):
                # aug-assign reads the old value too
                if node.target.id in state.poisoned:
                    self.expr(
                        ast.copy_location(
                            ast.Name(id=node.target.id, ctx=ast.Load()),
                            node.target,
                        ),
                        state,
                    )
                self._store_name(node.target, state)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self.expr(node.value, state)
                if isinstance(node.target, ast.Name):
                    self._store_name(node.target, state)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    state.poisoned.pop(t.id, None)
        elif isinstance(node, (ast.If,)):
            self.expr(node.test, state)
            a = state.copy()
            self.stmts(node.body, a)
            b = state.copy()
            self.stmts(node.orelse, b)
            state.poisoned = {}
            state.inflight = {}
            state.merge(a)
            state.merge(b)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            self.expr(node.iter, state)
            for _ in range(2):  # second pass observes the back edge
                self._store_target(node.target, state)
                self.stmts(node.body, state)
            self.stmts(node.orelse, state)
        elif isinstance(node, ast.While):
            for _ in range(2):
                self.expr(node.test, state)
                self.stmts(node.body, state)
            self.expr(node.test, state)
            self.stmts(node.orelse, state)
        elif isinstance(node, ast.Try):
            self.stmts(node.body, state)
            for handler in node.handlers:
                h = state.copy()
                self.stmts(handler.body, h)
                state.merge(h)
            self.stmts(node.orelse, state)
            self.stmts(node.finalbody, state)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self.expr(item.context_expr, state)
                if item.optional_vars is not None:
                    self._store_target(item.optional_vars, state)
            self.stmts(node.body, state)
        elif isinstance(node, ast.Return):
            self.expr(node.value, state)
        elif isinstance(node, ast.Expr):
            self.expr(node.value, state)
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.expr(child, state)

    def _store_target(self, target: ast.AST, state: _State) -> None:
        if isinstance(target, ast.Name):
            self._store_name(target, state)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._store_target(elt, state)
        elif isinstance(target, (ast.Subscript, ast.Attribute)):
            self.expr(target.value, state)

    def _store_name(self, target: ast.Name, state: _State) -> None:
        state.poisoned.pop(target.id, None)
        for mgr, names in state.inflight.items():
            if target.id in names:
                save = names[target.id]
                self._report(
                    target,
                    f"'{target.id}' is rebound while the async save on line "
                    f"{save.lineno} may still be reading its buffer (the "
                    "donating advance reuses it) — every mid-run checkpoint "
                    "of the old overlapped design was measured corrupt",
                    f"call {mgr}.wait_until_finished() after the save and "
                    "before advancing the state again",
                )

    def _report(self, node, message, hint) -> None:
        if self.silent:  # summary computation: effects only, no findings
            return
        key = (node.lineno, node.col_offset, message)
        if key in self._reported:
            return
        self._reported.add(key)
        self.findings.append(self.ctx.finding(node, self.rule, message, hint))


def _save_summaries(ctx: ModuleContext, donating: dict) -> dict:
    """Fixpoint over the module's top-level defs: func name ->
    (param_names, {mgr_param_idx: frozenset(captured_param_idxs)}) for
    every function that can RETURN with a save still in flight on one of
    its own parameters. Each function is analyzed with every parameter
    assumed manager-capable — the assumption only matters at call sites
    that actually bind a recognized manager there — and with the current
    summaries applied, so the effect propagates through wrapper chains
    (`_retrying_save` calling `_save_once`). A function whose every path
    waits/closes exports nothing."""
    funcs = {
        n.name: n for n in ctx.tree.body if isinstance(n, ast.FunctionDef)
    }
    summaries: dict = {}
    for _ in range(len(funcs) + 1):
        changed = False
        for name, fn in funcs.items():
            params = _param_names(fn)
            probe = _FunctionChecker(None, ctx, donating,
                                     summaries=summaries, silent=True)
            probe.managers = set(params)
            state = _State()
            probe.stmts(fn.body, state)
            effects: dict = {}
            for mgr, names_map in state.inflight.items():
                if mgr not in params:
                    continue
                captured = frozenset(
                    params.index(n) for n in names_map if n in params
                )
                if captured:
                    effects[params.index(mgr)] = captured
            if effects:
                entry = (params, effects)
                if summaries.get(name) != entry:
                    summaries[name] = entry
                    changed = True
            elif summaries.pop(name, None) is not None:
                changed = True
        if not changed:
            break
    return summaries


class DonationSafetyRule(Rule):
    id = "GL01"
    name = "donation-safety"
    severity = "error"
    rationale = (
        "donated buffers are reused by XLA; reading one after the donating "
        "call — or letting an async orbax save race the donating advance — "
        "silently yields garbage (both measured in PR 1)"
    )
    hint = "see docs/ANALYSIS.md#gl01"

    def check(self, ctx: ModuleContext):
        donating = _collect_donating_callables(ctx.tree)
        summaries = _save_summaries(ctx, donating)
        scopes: list = [ctx.tree]
        scopes += [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        findings = []
        for scope in scopes:
            checker = _FunctionChecker(self, ctx, donating,
                                       summaries=summaries)
            body = scope.body
            checker.stmts(body, _State())
            findings.extend(checker.findings)
        return findings
