"""GL02 — trace-time purity.

The old `bench.py` hazard: the kernel-form ladder mutated
`rocm_mpi_tpu.ops.pallas_kernels` module globals (`pk.EQC_BODY_FORM = …`)
to steer a trace. A cached or reused jitted program silently ignores the
mutated global — the knob looks applied and is not (fixed in PR 1 by
passing `body_form`/`pad_pow2` as explicit trace-time kwargs).

Two patterns:

* **cross-module mutation** — assignment (or `setattr`) to an attribute of
  an imported module, anywhere in the file. Writing another module's
  globals is exactly the silently-ignored-by-cached-traces hazard, and has
  no legitimate in-tree use (monkeypatching belongs in tests, which are
  outside the gate's scope).
* **global write in a traced body** — a `global` declaration inside a
  function that jit / shard_map / pallas_call traces (by decorator or by
  being passed into such a call). The write executes once at trace time,
  then never again — state that *looks* per-step and is not.
"""

from __future__ import annotations

import ast

from rocm_mpi_tpu.analysis import astutil
from rocm_mpi_tpu.analysis.core import ModuleContext, Rule


class TraceTimePurityRule(Rule):
    id = "GL02"
    name = "trace-time-purity"
    severity = "error"
    rationale = (
        "module-global state mutated at trace time is baked into (or "
        "silently ignored by) the cached compiled program — the bench.py "
        "kernel-form ladder shipped this bug; pass trace-time switches as "
        "explicit kwargs instead"
    )
    hint = "see docs/ANALYSIS.md#gl02"

    def check(self, ctx: ModuleContext):
        findings = []
        imports = astutil.collect_imports(ctx.tree)
        module_aliases = set(imports.module_aliases)

        # -- cross-module attribute mutation (anywhere in the file) -------
        for node in ast.walk(ctx.tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in module_aliases:
                    findings.append(ctx.finding(
                        t,
                        self,
                        f"assignment to '{t.value.id}.{t.attr}' mutates "
                        f"module '{imports.module_aliases[t.value.id]}' "
                        "globals — a cached/reused jitted program silently "
                        "ignores the mutated value",
                        "pass the switch as an explicit trace-time kwarg "
                        "(the bench.py body_form/pad_pow2 fix) or move the "
                        "knob behind a function API",
                    ))
            if isinstance(node, ast.Call) and \
                    astutil.tail_name(astutil.call_name(node)) == "setattr" \
                    and node.args and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in module_aliases:
                findings.append(ctx.finding(
                    node,
                    self,
                    f"setattr on module '{node.args[0].id}' mutates another "
                    "module's globals — invisible to cached traces",
                ))

        # -- `global` writes inside traced bodies -------------------------
        for traced in astutil.traced_bodies(ctx.tree):
            for node in astutil.walk_no_nested_functions(traced.fn):
                if isinstance(node, ast.Global):
                    findings.append(ctx.finding(
                        node,
                        self,
                        f"'global {', '.join(node.names)}' inside "
                        f"{traced.kind}-traced '{traced.fn.name}': the "
                        "write runs once at trace time, not per step, and "
                        "is dead in the compiled program",
                        "hoist the state out of the traced body or thread "
                        "it through the function's arguments/results",
                    ))
        return findings
