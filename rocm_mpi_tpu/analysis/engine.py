"""The interprocedural engine: package-wide call graph + per-function
summaries, propagated to a fixpoint.

graftlint's first seven rule families are per-file by design — and three
PRs in a row hand-fixed hazards a per-file pass is structurally blind
to: PR 6's elastic restore re-derived per-mesh machinery to keep
collective sequences matched across ranks, and PR 7 had to disable
autotune resolution under multi-controller jax because per-rank cache
files "could diverge ranks into mismatched collectives". Those are
whole-program properties. This module computes the whole-program facts:

* a **call graph** over every module in the analyzed set, resolved with
  the same deliberately-scoped heuristics as astutil (bare names and
  ``self.``/``cls.`` methods within a module, ``alias.func`` /
  ``from mod import func`` across modules — anything else is unresolved
  and contributes *no* facts, so a miss can never become a false
  positive);
* a **summary** per function: the collectives it issues in program
  order (its own plus its resolvable callees', to a fixpoint), whether
  its return value is rank-dependent (``process_index``/``axis_index``)
  or per-rank-file-content-dependent (it reads a file), and which of
  its parameters it donates into a jitted ``donate_argnums`` callable;
* two passes on top:
  - **GL08 collective-divergence** — a collective (or a call whose
    summary contains collectives, e.g. a halo exchange) reachable under
    rank-dependent or file-content-dependent control flow whose branch
    arms' collective sequences differ. Lock-step SPMD ranks that issue
    different collective sequences deadlock (one exchanges, its
    neighbor is gone) — the PR-6/PR-7 hazard class.
  - **interprocedural GL01** — the per-file donation rule re-run with
    program-wide knowledge: donating callables imported from other
    modules, and functions that donate a *parameter* (so the caller's
    binding is poisoned by the call).

Uniformity escapes the taint (matching the shipped fixes):

* ``jax.process_count()`` is uniform across ranks — branching on it is
  never divergence, and a ``process_count() > 1`` early return (the
  PR-7 fix shape) marks the continuation single-controller, where
  per-rank file content cannot diverge anything;
* ``broadcast_one_to_all`` / ``process_allgather`` RESULTS are uniform
  by construction (they are the blessed way to make a file-derived
  decision rank-consistent) — while the calls themselves still count as
  collectives in sequence summaries.

stdlib-only, no jax import — same contract as the rest of the analyzer.
"""

from __future__ import annotations

import ast
import dataclasses
from dataclasses import dataclass

from rocm_mpi_tpu.analysis import astutil
from rocm_mpi_tpu.analysis.core import (
    ModuleContext,
    Suppressions,
    parse_suppressions,
)

# Collective sequence entries are op tail-names; comparison of capped
# sequences treats "equal up to the cap" as equal (the safe direction:
# a missed finding, never a sprayed one).
MAX_SEQ = 24

# Device/host collectives whose per-rank issue order must match.
COLLECTIVE_TAILS = frozenset({
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter",
    # host-level (multihost_utils): collective across controllers
    "process_allgather", "broadcast_one_to_all", "sync_global_devices",
})

# Rank-varying value sources (per-device / per-process identity).
RANK_SOURCE_TAILS = frozenset({"process_index", "axis_index", "host_id"})

# File-content value sources: in multi-controller topologies every
# process reads ITS OWN filesystem, so content-derived values are
# rank-varying unless proven single-controller or broadcast.
FILE_SOURCE_TAILS = frozenset({
    "read", "read_text", "read_bytes", "readline", "readlines",
    "load", "loads",
})

# Calls whose RESULT is uniform across ranks even when inputs are not:
# the host-level collectives synchronize by construction (they are the
# blessed way to make a per-rank value rank-consistent).
UNIFORM_RESULT_TAILS = frozenset({
    "broadcast_one_to_all", "process_allgather",
})

_RANK, _FILE = "rank", "file"  # taint lattice: rank > file > None


def _max_taint(*ts):
    if _RANK in ts:
        return _RANK
    if _FILE in ts:
        return _FILE
    return None


# ---------------------------------------------------------------------------
# Program model
# ---------------------------------------------------------------------------


def module_name_for_path(path: str) -> str:
    """Dotted module name guess: anchored at the last path component
    named like a package root we know about, else the bare stem."""
    parts = [p for p in path.replace("\\", "/").split("/") if p]
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    for anchor in ("rocm_mpi_tpu", "apps"):
        if anchor in parts:
            tail = parts[parts.index(anchor):]
            if tail[-1] == "__init__":
                tail = tail[:-1]
            return ".".join(tail)
    return parts[-1] if parts else "<module>"


@dataclass
class ModuleInfo:
    path: str  # display path (findings report this)
    name: str  # dotted module name
    source: str
    tree: ast.Module
    imports: astutil.ImportTable = None  # type: ignore[assignment]
    functions: dict = None  # bare name -> FunctionDef (last wins)
    suppressions: Suppressions = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.imports is None:
            self.imports = astutil.collect_imports(self.tree)
        if self.functions is None:
            self.functions = astutil.index_functions(self.tree)
        if self.suppressions is None:
            self.suppressions = parse_suppressions(self.source)


@dataclass(frozen=True)
class FunctionSummary:
    """What a caller needs to know about one function."""

    collectives: tuple = ()  # ordered op tails, capped at MAX_SEQ
    returns_rank: bool = False
    returns_file: bool = False
    donates_params: frozenset = frozenset()  # positions donated inside
    # Concurrency effects (GL10, rules_concurrency.py): `self.<attr>`
    # names this function acquires as context managers or via
    # `.acquire()` — candidate lock acquisitions; the concurrency
    # checker intersects them with the owning class's known lock
    # attributes (a `with self._file:` here is harmless noise, never a
    # finding by itself).
    acquires_locks: frozenset = frozenset()
    # Blocking operation tails this function may perform, its own plus
    # its resolvable callees' (transitively, to the fixpoint): sleep /
    # Event.wait / Ticket.result / block_until_ready / file I/O /
    # subprocess. Consumed by GL10d (blocking-under-lock).
    blocking: frozenset = frozenset()


_EMPTY = FunctionSummary()


class Program:
    """All modules of one analysis run + their fixpoint summaries."""

    def __init__(self, modules: list[ModuleInfo]):
        self.modules: dict[str, ModuleInfo] = {m.name: m for m in modules}
        # (module name, function bare name) -> summary
        self.summaries: dict[tuple[str, str], FunctionSummary] = {}
        # (module name, callable bare name) -> (argnums, argnames) for
        # jit-donating defs/assignments, per module
        self.donating: dict[tuple[str, str], tuple] = {}
        # id(fn) -> flattened source-order node list; the fixpoint
        # re-reads every function per round and the tree never changes —
        # walking it once per function is the difference between a 5 s
        # and a 30 s repo pass
        self._fn_nodes: dict[int, list] = {}
        # set while a summarize is running: callee keys it consulted
        # (the fixpoint's reverse edges — later rounds only recompute
        # dependents of summaries that actually changed)
        self._consulted: set | None = None
        self._collect_donating()
        self._fixpoint()

    def nodes_of(self, fn: ast.AST) -> list:
        nodes = self._fn_nodes.get(id(fn))
        if nodes is None:
            nodes = list(_source_order(fn))
            self._fn_nodes[id(fn)] = nodes
        return nodes

    # -- donating callables (jit(donate_argnums=...) defs/assigns) ------

    def _collect_donating(self) -> None:
        from rocm_mpi_tpu.analysis.rules_donation import (
            _collect_donating_callables,
        )

        for mod in self.modules.values():
            for name, spec in _collect_donating_callables(mod.tree).items():
                self.donating[(mod.name, name)] = spec

    # -- call resolution -------------------------------------------------

    def resolve_call(self, mod: ModuleInfo, callee: str):
        """(module, FunctionDef) for a callee name as written at a call
        site in `mod`, or None. Scope-matched to the repo's idioms:
        bare names and self./cls. methods in-module; `alias.func` and
        `from m import func` across modules."""
        if not callee:
            return None
        head, _, rest = callee.partition(".")
        if not rest:
            fn = mod.functions.get(callee)
            if fn is not None:
                return mod, fn
            origin = mod.imports.from_imports.get(callee, "")
            return self._resolve_qualified(origin)
        if head in ("self", "cls") and "." not in rest:
            fn = mod.functions.get(rest)
            return (mod, fn) if fn is not None else None
        alias = mod.imports.module_aliases.get(head)
        if alias and "." not in rest:
            target = self.modules.get(alias)
            if target is not None:
                fn = target.functions.get(rest)
                if fn is not None:
                    return target, fn
        return self._resolve_qualified(callee)

    def _resolve_qualified(self, dotted: str):
        if not dotted or "." not in dotted:
            return None
        modname, _, fname = dotted.rpartition(".")
        target = self.modules.get(modname)
        if target is None:
            return None
        fn = target.functions.get(fname)
        return (target, fn) if fn is not None else None

    def summary_for_call(self, mod: ModuleInfo, callee: str) -> FunctionSummary:
        resolved = self.resolve_call(mod, callee)
        if resolved is None:
            return _EMPTY
        tmod, fn = resolved
        key = (tmod.name, fn.name)
        if self._consulted is not None:
            self._consulted.add(key)
        return self.summaries.get(key, _EMPTY)

    def donation_spec(self, mod: ModuleInfo, callee: str):
        """(argnums, argnames) when `callee` at a call site in `mod` is
        a donating jitted callable or a function whose summary donates
        parameters; else None."""
        if not callee:
            return None
        head, _, rest = callee.partition(".")
        if not rest:
            spec = self.donating.get((mod.name, callee))
            if spec is not None:
                return spec
            origin = mod.imports.from_imports.get(callee, "")
            if origin:
                modname, _, fname = origin.rpartition(".")
                spec = self.donating.get((modname, fname))
                if spec is not None:
                    return spec
        else:
            alias = mod.imports.module_aliases.get(head)
            if alias and "." not in rest:
                spec = self.donating.get((alias, rest))
                if spec is not None:
                    return spec
        summary = self.summary_for_call(mod, callee)
        if summary.donates_params:
            return (tuple(sorted(summary.donates_params)), ())
        return None

    # -- fixpoint --------------------------------------------------------

    def _fixpoint(self, max_rounds: int = 8) -> None:
        order = [
            (mod, fn)
            for mod in self.modules.values()
            for fn in _module_functions(mod)
        ]
        dependents: dict[tuple, set] = {}  # callee key -> dependent keys
        recompute = None  # None = everything (round 1)
        for _ in range(max_rounds):
            changed: set = set()
            for mod, fn in order:
                key = (mod.name, fn.name)
                if recompute is not None and key not in recompute:
                    continue
                self._consulted = set()
                new = _summarize(self, mod, fn)
                for callee_key in self._consulted:
                    dependents.setdefault(callee_key, set()).add(key)
                self._consulted = None
                if self.summaries.get(key) != new:
                    self.summaries[key] = new
                    changed.add(key)
            if not changed:
                return
            recompute = set()
            for ck in changed:
                recompute |= dependents.get(ck, set())
            if not recompute:
                return


def _module_functions(mod: ModuleInfo):
    """Every def in the module, nested and methods included, in source
    order (index_functions dedups by bare name — last wins, matching
    resolve semantics)."""
    seen = set()
    for fn in mod.functions.values():
        if id(fn) not in seen:
            seen.add(id(fn))
            yield fn


# ---------------------------------------------------------------------------
# Summaries
# ---------------------------------------------------------------------------


def _collective_tail(callee: str) -> str | None:
    tail = astutil.tail_name(callee)
    return tail if tail in COLLECTIVE_TAILS else None


# Call tails treated as blocking for GL10d (blocking-under-lock).
# Deliberately narrow: "join" (str.join) and "run" (model.run) are
# common non-blocking tails in this codebase and stay out; dotted
# subprocess.* calls are caught by the head check below instead.
BLOCKING_TAILS = frozenset({
    "sleep", "wait", "result", "block_until_ready", "open",
    "communicate", "check_call", "check_output", "Popen",
})

_SUBPROCESS_TAILS = frozenset({"run", "call", "check_call",
                               "check_output", "Popen"})


def blocking_tail(callee: str) -> str | None:
    """The blocking-op tail for a callee name, or None."""
    tail = astutil.tail_name(callee)
    if tail in BLOCKING_TAILS:
        return tail
    head = callee.partition(".")[0]
    if head == "subprocess" and tail in _SUBPROCESS_TAILS:
        return f"subprocess.{tail}"
    return None


def _self_attr(node) -> str | None:
    """`self.X` / `cls.X` -> "X" for a bare Attribute node."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id in ("self", "cls"):
        return node.attr
    return None


def _source_order(node: ast.AST):
    """DFS pre-order = source order (ast.walk is breadth-first, which
    would scramble collective sequences and assign-before-return taint)."""
    for child in ast.iter_child_nodes(node):
        yield child
        yield from _source_order(child)


def _summarize(program: Program, mod: ModuleInfo,
               fn: ast.FunctionDef) -> FunctionSummary:
    """One function's summary against the current summary table."""
    collectives: list[str] = []
    param_names = [a.arg for a in (
        fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
    )]
    param_index = {n: i for i, n in enumerate(param_names)}
    donates: set[int] = set()
    taint: dict[str, str] = {}
    returns_rank = False
    returns_file = False
    acquires: set[str] = set()
    blocking: set[str] = set()

    def expr_taint(node) -> str | None:
        return _expr_taint(program, mod, node, taint)

    nodes = program.nodes_of(fn)

    # Pass 1: name taints only (so a return further up the body still
    # sees assignments syntactically after deeper nesting; two rounds
    # catch one level of assign-chained taint).
    for _ in range(2):
        for node in nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                t = expr_taint(node.value)
                if t is not None:
                    taint[node.targets[0].id] = t

    # Pass 2: collectives in source order, donation effects, returns.
    # Nested defs are included (a nested def is almost always the
    # shard_map/pallas local invoked right there — its collectives
    # belong to this function's execution).
    for node in nodes:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None:
                    acquires.add(attr)
        if isinstance(node, ast.Call):
            callee = astutil.call_name(node)
            if callee.startswith(("self.", "cls.")):
                parts = callee.split(".")
                if len(parts) == 3 and parts[2] == "acquire":
                    acquires.add(parts[1])
            btail = blocking_tail(callee)
            if btail is not None:
                blocking.add(btail)
            tail = _collective_tail(callee)
            if tail is not None:
                collectives.append(tail)
            else:
                callee_summary = program.summary_for_call(mod, callee)
                collectives.extend(callee_summary.collectives)
                blocking |= callee_summary.blocking
            spec = program.donation_spec(mod, callee)
            if spec is not None:
                nums, names = spec
                for i in nums:
                    if i < len(node.args) and isinstance(
                        node.args[i], ast.Name
                    ) and node.args[i].id in param_index:
                        donates.add(param_index[node.args[i].id])
                for kw in node.keywords:
                    if kw.arg in names and isinstance(kw.value, ast.Name) \
                            and kw.value.id in param_index:
                        donates.add(param_index[kw.value.id])
        elif isinstance(node, ast.Return) and node.value is not None:
            t = expr_taint(node.value)
            if t == _RANK:
                returns_rank = True
            elif t == _FILE:
                returns_file = True

    return FunctionSummary(
        collectives=tuple(collectives[:MAX_SEQ]),
        returns_rank=returns_rank,
        returns_file=returns_file,
        donates_params=frozenset(donates),
        acquires_locks=frozenset(acquires),
        blocking=frozenset(blocking),
    )


def _expr_taint(program: Program, mod: ModuleInfo, node,
                taint: dict[str, str]) -> str | None:
    """rank/file/None for an expression under the given name taints."""
    if node is None or isinstance(node, (
        ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
    )):
        return None
    if isinstance(node, ast.Name):
        return taint.get(node.id)
    if isinstance(node, ast.Call):
        callee = astutil.call_name(node)
        tail = astutil.tail_name(callee)
        if tail in UNIFORM_RESULT_TAILS:
            return None  # uniform by construction, args notwithstanding
        arg_taints = [
            _expr_taint(program, mod, a, taint) for a in node.args
        ] + [
            _expr_taint(program, mod, kw.value, taint)
            for kw in node.keywords
        ]
        # method call on a tainted receiver propagates the receiver
        # (`doc.get("chunk")` stays file-tainted)
        if isinstance(node.func, ast.Attribute):
            arg_taints.append(
                _expr_taint(program, mod, node.func.value, taint)
            )
        if tail in RANK_SOURCE_TAILS:
            return _RANK
        if tail in FILE_SOURCE_TAILS:
            return _max_taint(_FILE, *arg_taints)
        summary = program.summary_for_call(mod, callee)
        if summary.returns_rank:
            return _RANK
        if summary.returns_file:
            return _max_taint(_FILE, *arg_taints)
        return _max_taint(*arg_taints)
    parts = [
        _expr_taint(program, mod, child, taint)
        for child in ast.iter_child_nodes(node)
        if isinstance(child, (ast.expr, ast.comprehension))
    ]
    return _max_taint(*parts)


# ---------------------------------------------------------------------------
# process_count() uniformity tests (the PR-7 fix shape)
# ---------------------------------------------------------------------------


def _is_process_count_call(node) -> bool:
    return isinstance(node, ast.Call) and \
        astutil.tail_name(astutil.call_name(node)) == "process_count"


def _process_count_test(test) -> str | None:
    """'multi' for a `process_count() > 1`-shaped test, 'single' for
    `process_count() == 1`, else None."""
    if not isinstance(test, ast.Compare) or len(test.ops) != 1:
        return None
    left, op, right = test.left, test.ops[0], test.comparators[0]
    if _is_process_count_call(right):
        left, right = right, left
        flip = {ast.Gt: ast.Lt, ast.Lt: ast.Gt, ast.GtE: ast.LtE,
                ast.LtE: ast.GtE}
        op_t = flip.get(type(op), type(op))
    else:
        op_t = type(op)
    if not _is_process_count_call(left):
        return None
    one = astutil.int_const(right)
    if one != 1:
        return None
    if op_t in (ast.Gt, ast.NotEq):
        return "multi"
    if op_t in (ast.Eq, ast.LtE):
        return "single"
    return None


def _always_exits(body: list) -> bool:
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


# ---------------------------------------------------------------------------
# GL08 — collective divergence
# ---------------------------------------------------------------------------


def _arm_collectives(program: Program, mod: ModuleInfo, body: list):
    """[(call node, op tail)] for every collective reachable in `body`
    (transitively through resolvable calls), in program order."""
    out = []
    for stmt in body:
        for node in _source_order(stmt):
            # nested defs inside the arm are included on purpose: they
            # are the shard_map/pallas locals invoked right there
            if not isinstance(node, ast.Call):
                continue
            callee = astutil.call_name(node)
            tail = _collective_tail(callee)
            if tail is not None:
                out.append((node, tail))
                continue
            seq = program.summary_for_call(mod, callee).collectives
            if seq:
                out.append((node, "+".join(seq[:4])))
    return out


class _DivergenceChecker:
    """Flow walk of one function (or the module body) for GL08."""

    def __init__(self, rule, ctx: ModuleContext, program: Program,
                 mod: ModuleInfo):
        self.rule = rule
        self.ctx = ctx
        self.program = program
        self.mod = mod
        self.taint: dict[str, str] = {}
        self.findings: list = []
        self._reported: set = set()

    def run(self, body: list, uniform: bool = False) -> None:
        self._block(body, uniform)

    # -- helpers ---------------------------------------------------------

    def _expr_taint(self, node) -> str | None:
        return _expr_taint(self.program, self.mod, node, self.taint)

    def _seq(self, body: list) -> tuple:
        return tuple(
            t for _, t in _arm_collectives(self.program, self.mod, body)
        )[:MAX_SEQ]

    def _report_arm(self, body: list, test, why: str) -> None:
        for call, tail in _arm_collectives(self.program, self.mod, body):
            key = (call.lineno, call.col_offset)
            if key in self._reported:
                continue
            self._reported.add(key)
            self.findings.append(self.ctx.finding(
                call, self.rule,
                f"collective '{tail}' is issued under {why} control flow "
                f"(the branch on line {test.lineno}) — ranks taking "
                "different paths issue mismatched collective sequences "
                "and deadlock in lock-step SPMD",
                "issue the same collective sequence on every rank: hoist "
                "the collective out of the branch, make the decision "
                "uniform (broadcast_one_to_all), or guard the whole path "
                "single-controller (process_count() == 1)",
            ))

    # -- statement walk --------------------------------------------------

    def _block(self, body: list, uniform: bool) -> None:
        for i, stmt in enumerate(body):
            if isinstance(stmt, ast.If):
                uniform = self._if(stmt, body[i + 1:], uniform)
            else:
                self._stmt(stmt, uniform)

    def _if(self, node: ast.If, rest: list, uniform: bool) -> bool:
        """Handle one If (needing the enclosing block's remainder: an
        early-exit arm's real 'else' is everything after the If).
        Returns the uniformity that holds for the remainder."""
        pc = _process_count_test(node.test)
        if pc is not None:
            # uniform test (process_count is the same everywhere):
            # never divergence; arms inherit their controller count,
            # and a `if process_count() > 1: return` early exit (the
            # PR-7 fix shape) proves the continuation single-controller
            self._block(node.body, pc == "single")
            self._block(node.orelse, pc == "multi")
            if pc == "multi" and _always_exits(node.body) \
                    and not node.orelse:
                return True
            return uniform
        t = self._test_taint(node.test, uniform)
        if t is not None:
            body_seq = self._seq(node.body)
            if _always_exits(node.body) and not node.orelse:
                # `if <tainted>: return/continue` — ranks that exit run
                # the exited arm; the others run the block remainder.
                else_arm = rest
            else:
                else_arm = node.orelse
            if body_seq != self._seq(else_arm):
                self._report_arm(node.body, node.test, self._why(t))
                self._report_arm(else_arm, node.test, self._why(t))
        self._block(node.body, uniform)
        self._block(node.orelse, uniform)
        return uniform

    @staticmethod
    def _why(kind: str) -> str:
        return ("rank-dependent (process_index/axis_index)"
                if kind == _RANK
                else "per-rank-file-content-dependent")

    def _test_taint(self, test, uniform: bool) -> str | None:
        t = self._expr_taint(test)
        if t == _FILE and uniform:
            return None  # single-controller: one filesystem, no skew
        return t

    def _stmt(self, node, uniform: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate scope; checked as its own function
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            t = self._expr_taint(node.value)
            if t is None:
                self.taint.pop(node.targets[0].id, None)
            else:
                self.taint[node.targets[0].id] = t
            return
        if isinstance(node, ast.While):
            t = self._test_taint(node.test, uniform)
            if t is not None and self._seq(node.body):
                # divergent trip counts: ranks fall out of the loop on
                # different iterations, each carrying collectives
                self._report_arm(node.body, node.test, self._why(t))
            self._block(node.body, uniform)
            self._block(node.orelse, uniform)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            t = self._expr_taint(node.iter)
            if t == _FILE and uniform:
                t = None
            if t is not None and self._seq(node.body):
                self._report_arm(node.body, node.iter, self._why(t))
            self._block(node.body, uniform)
            self._block(node.orelse, uniform)
            return
        if isinstance(node, ast.Try):
            self._block(node.body, uniform)
            for handler in node.handlers:
                self._block(handler.body, uniform)
            self._block(node.orelse, uniform)
            self._block(node.finalbody, uniform)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.optional_vars, ast.Name):
                    t = self._expr_taint(item.context_expr)
                    if t is not None:
                        self.taint[item.optional_vars.id] = t
            self._block(node.body, uniform)
            return


def check_divergence(rule, ctx: ModuleContext, program: Program,
                     mod: ModuleInfo) -> list:
    """GL08 findings for one module of `program`."""
    findings = []
    # EVERY def gets its own flow walk — not just mod.functions, whose
    # last-wins-by-bare-name dedup (a call-RESOLUTION heuristic) would
    # silently skip shadowed defs and same-named methods (a module with
    # five `step` methods would have four of them unchecked).
    scopes: list = [ctx.tree.body]
    scopes += [
        fn.body for fn in ast.walk(ctx.tree)
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for body in scopes:
        checker = _DivergenceChecker(rule, ctx, program, mod)
        checker.run(body)
        findings.extend(checker.findings)
    # one finding per site even when a nested scope re-walks the code
    unique = {}
    for f in findings:
        unique.setdefault((f.line, f.col, f.message), f)
    return list(unique.values())


# ---------------------------------------------------------------------------
# Interprocedural GL01 (donate in caller, read in callee / poisoned by
# a donating helper)
# ---------------------------------------------------------------------------


def check_donation_interprocedural(rule, ctx: ModuleContext,
                                   program: Program,
                                   mod: ModuleInfo) -> list:
    """Re-run the GL01 flow checker with the program-wide donating map:
    jit-donating callables imported from other modules, plus functions
    whose summaries donate a parameter. Only findings the per-file pass
    could NOT see are returned (callers dedupe by site anyway)."""
    import ast as _ast

    from rocm_mpi_tpu.analysis.rules_donation import (
        _collect_donating_callables,
        _FunctionChecker,
        _State,
    )

    local = _collect_donating_callables(mod.tree)
    extended = dict(local)
    # names bound by `from m import f` where m.f donates — either a
    # jit(donate_argnums=…) callable or a plain function whose summary
    # says it donates a parameter
    for name, origin in mod.imports.from_imports.items():
        if name in extended:
            continue
        modname, _, fname = origin.rpartition(".")
        spec = program.donating.get((modname, fname))
        if spec is None:
            summary = program.summaries.get((modname, fname), _EMPTY)
            if summary.donates_params:
                spec = (tuple(sorted(summary.donates_params)), ())
        if spec is not None:
            extended[name] = spec
    # local functions whose summary donates a parameter
    for fname, fn in mod.functions.items():
        if fname in extended:
            continue
        summary = program.summaries.get((mod.name, fn.name), _EMPTY)
        if summary.donates_params:
            extended[fname] = (tuple(sorted(summary.donates_params)), ())
    if extended == local:
        return []

    scopes: list = [mod.tree]
    scopes += [
        n for n in _ast.walk(mod.tree)
        if isinstance(n, (_ast.FunctionDef, _ast.AsyncFunctionDef))
    ]
    baseline_sites = set()
    findings = []
    for scope in scopes:
        base = _FunctionChecker(rule, ctx, local)
        base.stmts(scope.body, _State())
        for f in base.findings:
            baseline_sites.add((f.line, f.col, f.message))
        full = _FunctionChecker(rule, ctx, extended)
        full.stmts(scope.body, _State())
        for f in full.findings:
            if (f.line, f.col, f.message) not in baseline_sites:
                findings.append(f)
    unique = {}
    for f in findings:
        unique.setdefault((f.line, f.col, f.message), f)
    return list(unique.values())


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def analyze_modules(modules: list[ModuleInfo], select=None) -> list:
    """Whole-program findings (GL08 + interprocedural GL01 + GL10
    concurrency) over the given modules. Suppressions apply per module;
    findings come back sorted like the per-file pass."""
    from rocm_mpi_tpu.analysis.rules_concurrency import (
        ConcurrencyRule, check_concurrency,
    )
    from rocm_mpi_tpu.analysis.rules_divergence import DivergenceRule
    from rocm_mpi_tpu.analysis.rules_donation import DonationSafetyRule

    wanted = None
    if select:
        wanted = {s.strip().upper() for s in select}
    program = Program(modules)
    findings = []
    gl08 = DivergenceRule()
    gl01 = DonationSafetyRule()
    gl10 = ConcurrencyRule()
    for mod in program.modules.values():
        ctx = ModuleContext(
            path=mod.path, posix_path=mod.path, source=mod.source,
            tree=mod.tree,
        )
        batch = []
        if wanted is None or gl08.id in wanted:
            batch.extend(check_divergence(gl08, ctx, program, mod))
        if wanted is None or gl01.id in wanted:
            batch.extend(
                check_donation_interprocedural(gl01, ctx, program, mod)
            )
        if wanted is None or gl10.id in wanted:
            batch.extend(check_concurrency(gl10, ctx, program, mod))
        for f in batch:
            f.suppressed = mod.suppressions.covers(f)
            findings.append(f)
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return findings
