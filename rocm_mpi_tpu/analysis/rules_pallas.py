"""GL04 — Pallas kernel hygiene.

Grounded in this repo's kernel conventions (ops/pallas_kernels.py,
ops/wave_kernels.py, ops/swe_kernels.py) and the pallas_guide.md rules
they encode:

* **raw-ref use** — a Ref parameter (named ``*_ref`` by repo convention)
  used bare: passed to a jnp/host op or combined in arithmetic without
  ``ref[...]`` indexing or ``pl.load``/``pl.store``. Refs are memory
  handles, not arrays; host ops on them are undefined under Mosaic.
* **raw-precision arithmetic** — arithmetic on values loaded from refs
  without first routing through the f32 upcast chokepoint
  (``_upcast_for_compute`` / ``.astype``). bf16 is STORAGE-ONLY in this
  kernel family (r4, measured: per-step bf16 rounding froze the 252²
  trajectory); every kernel must upcast before computing.
* **index_map arity** — a BlockSpec index_map lambda whose parameter count
  differs from the pallas_call's literal grid rank (each grid axis feeds
  one index argument; a mismatch is a TypeError at trace time on TPU but
  silently untested on CPU paths that never take the compiled branch).
* **grid under-coverage** — with fully literal grid/block/out shapes,
  grid[i] * block[i] < shape[i] leaves cells unwritten.
* **raw wire-slab arithmetic** — the wire-precision seam (PR 12,
  parallel/wire.py): a slab received from ``neighbor_shift``/``ppermute``
  whose SENT payload was downcast (``.astype(jnp.bfloat16)``, an
  ``encode_slab``/``quantize_slab`` call, a wire bitcast) used in
  arithmetic without first decoding/upcasting back to the compute dtype.
  The storage-only-bf16 convention applied to the wire: reduced
  precision rides the collective, never the seam accumulation.
"""

from __future__ import annotations

import ast

from rocm_mpi_tpu.analysis import astutil
from rocm_mpi_tpu.analysis.core import ModuleContext, Rule

# Attribute reads that are fine on a bare ref (metadata, not data).
_REF_META_ATTRS = {"shape", "dtype", "ndim", "at", "size"}
# Callees that legitimately take a bare ref argument (pl.* memory ops;
# jnp helpers like zeros_like must take ref[...] loads, not bare refs).
_REF_OK_CALLEES = {"load", "store", "swap", "dslice", "ds"}
# Callees that launder taint (explicit precision control).
_UNTAINT_CALLEES = {"_upcast_for_compute", "astype"}
_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod,
              ast.Pow, ast.MatMult)

# ---- wire-seam vocabulary (parallel/wire.py + parallel/halo.py) ----------
# Ship points: the collective the wire payload rides.
_WIRE_SHIP_CALLEES = {"neighbor_shift", "ppermute"}
# Downcast markers inside a shipped expression (a reduced-precision
# payload on the wire).
_WIRE_ENCODE_CALLEES = {"encode_slab", "quantize_slab",
                        "bitcast_convert_type"}
_WIRE_NARROW_DTYPES = ("bfloat16", "int8", "uint16", "float16")
# Decode/upcast chokepoints that launder the received-slab taint.
_WIRE_DECODE_CALLEES = {"astype", "_upcast_for_compute", "decode_slab",
                        "dequantize_slab", "dequantize",
                        "_dequantize_int8"}


def _ref_params(fn: ast.FunctionDef) -> set[str]:
    return {
        a.arg for a in fn.args.args + fn.args.posonlyargs
        if a.arg.endswith("_ref")
    }


class _KernelChecker:
    def __init__(self, rule, ctx, fn, module_has_upcast: bool):
        self.rule = rule
        self.ctx = ctx
        self.fn = fn
        self.refs = _ref_params(fn)
        self.module_has_upcast = module_has_upcast
        self.tainted: set[str] = set()
        self.findings: list = []

    def run(self):
        if not self.refs:
            return []
        for node in astutil.walk_no_nested_functions(self.fn):
            if isinstance(node, ast.Name) and node.id in self.refs and \
                    isinstance(node.ctx, ast.Load):
                if not self._ref_use_ok(node):
                    self.findings.append(self.ctx.finding(
                        node, self.rule,
                        f"Ref '{node.id}' used bare in kernel "
                        f"'{self.fn.name}' — refs are memory handles; "
                        "host/jnp ops on them are undefined under Mosaic",
                        "read with ref[...] / pl.load and write with "
                        "ref[...] = / pl.store",
                    ))
        self._check_precision()
        return self.findings

    def _parent_map(self):
        parents = {}
        for node in astutil.walk_no_nested_functions(self.fn):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        return parents

    def _ref_use_ok(self, name: ast.Name) -> bool:
        parents = getattr(self, "_parents", None)
        if parents is None:
            parents = self._parents = self._parent_map()
        parent = parents.get(name)
        if isinstance(parent, ast.Subscript) and parent.value is name:
            return True
        if isinstance(parent, ast.Attribute) and parent.value is name:
            return parent.attr in _REF_META_ATTRS
        if isinstance(parent, ast.Call):
            callee = astutil.tail_name(astutil.call_name(parent))
            if callee in _REF_OK_CALLEES:
                return True
        return False

    # ---- storage-only-bf16 taint check ---------------------------------

    def _is_ref_load(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in self.refs:
            return True
        call = node if isinstance(node, ast.Call) else None
        if call and astutil.tail_name(astutil.call_name(call)) == "load":
            return any(
                isinstance(a, ast.Name) and a.id in self.refs
                for a in call.args
            )
        return False

    def _taint_of(self, node: ast.AST) -> bool:
        """Does evaluating `node` carry raw (never-upcast) ref data?"""
        if self._is_ref_load(node):
            return True
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Call):
            callee = astutil.tail_name(astutil.call_name(node))
            if callee in _UNTAINT_CALLEES:
                return False
            args = list(node.args) + [kw.value for kw in node.keywords]
            return any(self._taint_of(a) for a in args)
        if isinstance(node, ast.Attribute):
            if node.attr == "astype":
                return False
            return self._taint_of(node.value)
        if isinstance(node, (ast.Subscript, ast.Starred, ast.UnaryOp)):
            inner = node.value if not isinstance(node, ast.UnaryOp) \
                else node.operand
            return self._taint_of(inner)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._taint_of(e) for e in node.elts)
        if isinstance(node, ast.BinOp):
            return self._taint_of(node.left) or self._taint_of(node.right)
        if isinstance(node, ast.IfExp):
            return self._taint_of(node.body) or self._taint_of(node.orelse)
        return False

    def _stmts_in_order(self, body):
        """Statements in source order, compound bodies inline, nested
        function defs skipped (separate scope)."""
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            yield stmt
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if isinstance(sub, list):
                    yield from self._stmts_in_order(
                        [s for s in sub if isinstance(s, ast.stmt)]
                    )
            for handler in getattr(stmt, "handlers", []):
                yield from self._stmts_in_order(handler.body)

    def _expr_roots(self, stmt: ast.stmt):
        """The expressions a statement evaluates itself (compound bodies
        are separate statements and excluded)."""
        if isinstance(stmt, ast.Assign):
            return [stmt.value]
        if isinstance(stmt, ast.AugAssign):
            return [stmt.value, stmt.target]
        if isinstance(stmt, ast.AnnAssign):
            return [stmt.value] if stmt.value is not None else []
        if isinstance(stmt, (ast.Expr, ast.Return)):
            return [stmt.value] if stmt.value is not None else []
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return [stmt.iter]
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return [i.context_expr for i in stmt.items]
        return [c for c in ast.iter_child_nodes(stmt)
                if isinstance(c, ast.expr)]

    def _check_precision(self):
        # Only meaningful in modules that follow the upcast convention at
        # all — a module with no _upcast_for_compute/astype anywhere is a
        # plain-f32 experiment and gets a pass (documented heuristic;
        # probed once per module by PallasHygieneRule.check).
        if not self.module_has_upcast:
            return
        reported = set()
        for stmt in self._stmts_in_order(self.fn.body):
            # Check arithmetic against the CURRENT taint state first …
            for root in self._expr_roots(stmt):
                for node in astutil.walk_no_nested_functions(root):
                    if not (isinstance(node, ast.BinOp) and
                            isinstance(node.op, _ARITH_OPS)):
                        continue
                    if not (self._taint_of(node.left) or
                            self._taint_of(node.right)):
                        continue
                    key = (node.lineno, node.col_offset)
                    if key in reported:
                        continue
                    reported.add(key)
                    self.findings.append(self.ctx.finding(
                        node, self.rule,
                        f"arithmetic on raw ref data in kernel "
                        f"'{self.fn.name}' without the f32 upcast — bf16 "
                        "is storage-only in this kernel family (per-step "
                        "bf16 rounding measurably froze the 252² "
                        "trajectory, r4)",
                        "route operands through _upcast_for_compute (or "
                        ".astype(jnp.float32)) before computing, and "
                        ".astype(out_ref.dtype) once at the store",
                    ))
            # … then apply the statement's taint effects.
            if isinstance(stmt, ast.Assign):
                tainted = self._taint_of(stmt.value)
                for t in stmt.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            if tainted:
                                self.tainted.add(n.id)
                            else:
                                self.tainted.discard(n.id)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                tainted = self._taint_of(stmt.iter)
                for n in ast.walk(stmt.target):
                    if isinstance(n, ast.Name) and tainted:
                        self.tainted.add(n.id)


def _wire_stmts_in_order(body):
    """Statements in source order, compound bodies inline, nested
    function defs skipped (they are walked as their own scope)."""
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield stmt
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, attr, None)
            if isinstance(sub, list):
                yield from _wire_stmts_in_order(
                    [s for s in sub if isinstance(s, ast.stmt)]
                )
        for handler in getattr(stmt, "handlers", []):
            yield from _wire_stmts_in_order(handler.body)


def _expr_has_downcast(node: ast.AST) -> bool:
    """Does this (to-be-shipped) expression narrow its payload — an
    encode/quantize call, a wire bitcast, or .astype to a narrow dtype?"""
    for n in ast.walk(node):
        if not isinstance(n, ast.Call):
            continue
        callee = astutil.tail_name(astutil.call_name(n))
        if callee in _WIRE_ENCODE_CALLEES:
            return True
        if callee == "astype":
            for a in list(n.args) + [kw.value for kw in n.keywords]:
                dump = ast.dump(a)
                if any(d in dump for d in _WIRE_NARROW_DTYPES):
                    return True
    return False


class _WireSeamChecker:
    """Per-function flow check of the wire-precision seam: a name bound
    to the RESULT of a ship call (`x = neighbor_shift(payload, …)`)
    whose payload was downcast is tainted; arithmetic on it without a
    decode/upcast (`.astype`, `decode_slab`, …) fires GL04. Names
    holding downcast payloads propagate the marker, so
    `p = u.astype(jnp.bfloat16); g = ppermute(p, …)` taints `g` too."""

    def __init__(self, rule, ctx, fn):
        self.rule = rule
        self.ctx = ctx
        self.fn = fn
        self.downcast: set[str] = set()
        self.tainted: set[str] = set()
        self.findings: list = []

    def _taint_of(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Call):
            callee = astutil.tail_name(astutil.call_name(node))
            if callee in _WIRE_DECODE_CALLEES:
                return False
            args = list(node.args) + [kw.value for kw in node.keywords]
            return any(self._taint_of(a) for a in args)
        if isinstance(node, ast.Attribute):
            if node.attr in _WIRE_DECODE_CALLEES:
                return False
            return self._taint_of(node.value)
        if isinstance(node, ast.BinOp):
            return self._taint_of(node.left) or self._taint_of(node.right)
        if isinstance(node, (ast.Subscript, ast.Starred)):
            return self._taint_of(node.value)
        if isinstance(node, ast.UnaryOp):
            return self._taint_of(node.operand)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._taint_of(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self._taint_of(node.body) or self._taint_of(node.orelse)
        return False

    def _ship_of(self, value: ast.AST) -> ast.Call | None:
        """The ship call if `value` IS one (possibly wrapped in astype —
        which then untaints anyway)."""
        if isinstance(value, ast.Call) and astutil.tail_name(
            astutil.call_name(value)
        ) in _WIRE_SHIP_CALLEES:
            return value
        return None

    def run(self):
        for stmt in _wire_stmts_in_order(self.fn.body):
            for root in ast.iter_child_nodes(stmt):
                if not isinstance(root, ast.expr):
                    continue
                for node in astutil.walk_no_nested_functions(root):
                    if not (isinstance(node, ast.BinOp) and
                            isinstance(node.op, _ARITH_OPS)):
                        continue
                    if not (self._taint_of(node.left) or
                            self._taint_of(node.right)):
                        continue
                    self.findings.append(self.ctx.finding(
                        node, self.rule,
                        f"arithmetic on a reduced-precision received "
                        f"slab in '{self.fn.name}' without the f32 "
                        "upcast at the seam — wire precision "
                        "(bf16/int8 payloads) is wire-only; the seam "
                        "must consume decoded slabs "
                        "(parallel/wire.py)",
                        "decode/upcast the received slab "
                        "(.astype(jnp.float32) / wire.slab_codec "
                        "recv) before any arithmetic or seam "
                        "accumulation",
                    ))
            if isinstance(stmt, ast.Assign):
                value = stmt.value
                ship = self._ship_of(value)
                if ship is not None:
                    # `x = neighbor_shift(payload, …)`: x is tainted iff
                    # the payload was downcast (directly, or via a name
                    # holding a downcast payload).
                    taints = bool(ship.args) and (
                        _expr_has_downcast(ship.args[0])
                        or self._mentions_downcast(ship.args[0])
                    )
                else:
                    taints = self._taint_of(value)
                # A decode/upcast call clears the downcast marker —
                # UNLESS it is itself narrowing (`u.astype(jnp.bfloat16)`
                # spells astype too, but it is the encode).
                is_decode = (
                    isinstance(value, ast.Call)
                    and astutil.tail_name(astutil.call_name(value))
                    in _WIRE_DECODE_CALLEES
                    and not _expr_has_downcast(value)
                )
                marks_downcast = not is_decode and (
                    _expr_has_downcast(value)
                    or (ship is None and self._mentions_downcast(value))
                )
                for t in stmt.targets:
                    for n in ast.walk(t):
                        if not isinstance(n, ast.Name):
                            continue
                        (self.tainted.add if taints
                         else self.tainted.discard)(n.id)
                        (self.downcast.add if marks_downcast
                         else self.downcast.discard)(n.id)
        return self.findings

    def _mentions_downcast(self, node: ast.AST) -> bool:
        return any(
            isinstance(n, ast.Name) and n.id in self.downcast
            for n in ast.walk(node)
        )


class PallasHygieneRule(Rule):
    id = "GL04"
    name = "pallas-hygiene"
    severity = "error"
    rationale = (
        "hand-written kernels are where correctness quietly dies "
        "(HipKittens, arXiv:2511.08083): bare-Ref host ops, skipped f32 "
        "upcasts, and grid/BlockSpec mismatches all pass CPU tests and "
        "fail (or silently corrupt) on the chip"
    )
    hint = "see docs/ANALYSIS.md#gl04"

    def check(self, ctx: ModuleContext):
        findings = []
        module_has_upcast = any(
            astutil.tail_name(astutil.call_name(n)) in _UNTAINT_CALLEES
            for n in ast.walk(ctx.tree)
            if isinstance(n, ast.Call)
        )
        for fn, _call in astutil.pallas_kernel_functions(ctx.tree):
            findings.extend(
                _KernelChecker(self, ctx, fn, module_has_upcast).run()
            )
        # The wire-precision seam check runs on EVERY function (the
        # exchange seam lives in shard_map bodies, not Pallas kernels).
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(_WireSeamChecker(self, ctx, node).run())
        # Spec checks run on EVERY pallas_call, including ones whose
        # kernel body could not be resolved (or is shared with another
        # call that has a different grid).
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    astutil.tail_name(astutil.call_name(node)) == \
                    "pallas_call":
                findings.extend(self._check_specs(ctx, node))
        return findings

    # ---- grid / BlockSpec structural checks ----------------------------

    def _check_specs(self, ctx: ModuleContext, call: ast.Call):
        findings = []
        grid_node = astutil.call_kwarg(call, "grid")
        if grid_node is None:
            return findings
        grid = astutil.int_tuple(grid_node)
        grid_rank = None
        if isinstance(grid_node, (ast.Tuple, ast.List)):
            grid_rank = len(grid_node.elts)
        elif grid is not None:
            grid_rank = len(grid)

        specs = []  # (spec node, is_out) — coverage vs out_shape is only
        # meaningful for out_specs (input blocks may broadcast/reduce)
        for kw_name in ("in_specs", "out_specs"):
            node = astutil.call_kwarg(call, kw_name)
            if node is None:
                continue
            elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) \
                else [node]
            specs.extend((e, kw_name == "out_specs") for e in elts)

        out_shape = None
        shape_node = astutil.call_kwarg(call, "out_shape")
        if isinstance(shape_node, ast.Call):
            if shape_node.args:
                out_shape = astutil.int_tuple(shape_node.args[0])

        for spec, is_out in specs:
            if not (isinstance(spec, ast.Call) and
                    astutil.tail_name(astutil.call_name(spec)) ==
                    "BlockSpec"):
                continue
            index_map = None
            if len(spec.args) >= 2:
                index_map = spec.args[1]
            km = astutil.call_kwarg(spec, "index_map")
            if km is not None:
                index_map = km
            if grid_rank is not None and isinstance(index_map, ast.Lambda):
                arity = len(index_map.args.args)
                if arity != grid_rank:
                    findings.append(ctx.finding(
                        index_map, self,
                        f"BlockSpec index_map takes {arity} argument(s) "
                        f"but the grid has {grid_rank} axis/axes — each "
                        "grid axis feeds exactly one index argument",
                        "match the lambda's arity to len(grid)",
                    ))
            block = astutil.int_tuple(spec.args[0]) if spec.args else None
            if is_out and block and grid and out_shape and \
                    len(block) == len(grid) == len(out_shape):
                for g, b, s in zip(grid, block, out_shape):
                    if g * b < s:
                        findings.append(ctx.finding(
                            spec, self,
                            f"grid {grid} × block {block} covers only "
                            f"{tuple(g_ * b_ for g_, b_ in zip(grid, block))}"
                            f" of out_shape {out_shape} — trailing cells "
                            "are never written",
                            "size the grid as ceil(shape/block) per axis",
                        ))
                        break
        return findings
