"""GL08 — collective-divergence (interprocedural).

The bug class the last three PRs each dodged by hand: lock-step SPMD
ranks must issue the SAME collective sequence, in the same order, every
step — a rank that skips (or adds) an exchange leaves its neighbor
blocked inside a collective that will never complete. Not an error, a
distributed hang, and the per-file rules cannot see it because the
divergence spans functions and modules:

* **PR 7 (autotune):** under multi-controller jax every process resolves
  the tuning cache from ITS OWN filesystem; a per-rank winning `chunk`
  builds divergently traced scan programs — mismatched collective
  counts per invocation. Shipped fix: `process_count() > 1` → defaults
  (models/diffusion.auto_scan_chunk, parallel/deep_halo.auto_deep_k).
* **PR 6 (elastic restore):** resuming on a different mesh must rebuild
  the exchange machinery identically on every rank; a rank that
  branches on locally-read manifest content into a different
  rebuild-vs-reuse path issues a different warmup sequence.

What fires (engine.check_divergence walks the flow with the program
summaries):

* a collective — or a call whose summary transitively contains
  collectives, e.g. a halo exchange or a model step — reachable under
  control flow whose test is **rank-dependent** (`process_index`,
  `axis_index`, or a value returned by a function summarized as
  rank-dependent);
* the same, under a test that is **file-content-dependent** (values
  from `open/json.load/read_text` or functions summarized as file
  readers), unless the path is proven single-controller;
* branch arms whose collective **sequences differ** (one arm's sequence
  is compared against the other's, transitively) — equal sequences on
  both arms are legal however the test is tainted;
* a rank/file-dependent **early exit** (`if process_index() != 0:
  return`) followed by collectives in the continuation — the exact
  shape of a rank-0-only rebuild.

What never fires: branches on `process_count()` (uniform on every
rank), decisions laundered through `broadcast_one_to_all` /
`process_allgather` (their results are uniform by construction — the
blessed fix), rank-guarded host-only work (manifest writes, logging),
and anything the resolver cannot see (docs/ANALYSIS.md "can and cannot
see": a miss is never a false positive).
"""

from __future__ import annotations

from rocm_mpi_tpu.analysis.core import ModuleContext, Rule


class DivergenceRule(Rule):
    id = "GL08"
    name = "collective-divergence"
    severity = "error"
    rationale = (
        "SPMD ranks issuing different collective sequences deadlock; "
        "rank- or per-rank-file-content-dependent control flow around a "
        "collective is the PR-6/PR-7 hazard class, visible only "
        "interprocedurally"
    )
    hint = "see docs/ANALYSIS.md#gl08"

    def check(self, ctx: ModuleContext):
        """Single-module fallback (the whole-program pass in
        engine.analyze_modules is the real engine; this treats the one
        file as a one-module program so fixtures and ad-hoc
        lint_source calls still get the rule)."""
        from rocm_mpi_tpu.analysis import engine

        mod = engine.ModuleInfo(
            path=ctx.path,
            name=engine.module_name_for_path(ctx.path),
            source=ctx.source,
            tree=ctx.tree,
        )
        program = engine.Program([mod])
        return engine.check_divergence(self, ctx, program, mod)
