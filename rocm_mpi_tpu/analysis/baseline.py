"""Findings baseline + incremental (--changed) support.

**Baseline** (`--baseline` / `--baseline-write`): a committed ledger of
accepted findings (default: `rocm_mpi_tpu/analysis/baseline.json`) so a
new rule can land BEFORE the repo is clean under it — baselined
findings are still reported (marked, like suppressions) but do not gate;
any finding NOT in the baseline still fails. Keys are content-addressed
(file + rule + message hash), deliberately line-number-free: an edit
elsewhere in the file must not un-baseline an accepted finding, while
any change to the finding itself (message text embeds the hazard) makes
it a new, gating one. Counts matter: a baseline accepting one instance
does not absorb a second identical one.

**--changed**: the fast dev loop — per-file rules run only on git-dirty
files plus their import-graph neighbors (callers AND callees one hop
out: an interprocedural finding lands on the caller, so editing a
callee must re-lint everyone who uses it); the whole-program pass still
parses everything (sound summaries need the full module set) and its
findings are filtered to the same neighborhood.
"""

from __future__ import annotations

import hashlib
import json
import os
import pathlib
import subprocess

BASELINE_SCHEMA = "rmt-lint-baseline"
BASELINE_VERSION = 1

DEFAULT_BASELINE = pathlib.Path(__file__).with_name("baseline.json")


def _norm_file(path: str) -> str:
    """Stable cross-invocation spelling of a finding's file: posix,
    relative to cwd when possible (the gate always runs from the repo
    root, so committed keys stay machine-independent)."""
    p = pathlib.Path(path)
    try:
        p = p.resolve().relative_to(pathlib.Path.cwd().resolve())
    except (ValueError, OSError):
        pass
    return p.as_posix()


def finding_key(f) -> str:
    digest = hashlib.blake2b(
        f.message.encode("utf-8", "surrogatepass"), digest_size=8
    ).hexdigest()
    return f"{_norm_file(f.file)}|{f.rule}|{digest}"


def empty_doc() -> dict:
    return {
        "schema": BASELINE_SCHEMA,
        "v": BASELINE_VERSION,
        "entries": {},
    }


def write_baseline(path, findings) -> None:
    """Bank every live (non-suppressed) error finding, atomically
    (tmp + os.replace — the baseline is a schema-versioned artifact;
    GL09 discipline applies to its own tooling)."""
    entries: dict[str, dict] = {}
    for f in findings:
        if f.suppressed or f.severity != "error":
            continue
        key = finding_key(f)
        entry = entries.setdefault(key, {
            "file": _norm_file(f.file),
            "rule": f.rule,
            "message": f.message,
            "count": 0,
        })
        entry["count"] += 1
    doc = empty_doc()
    doc["entries"] = entries
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = str(path) + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def load_baseline(path) -> dict:
    """The baseline document. Raises ValueError on anything malformed —
    a gate input that cannot be trusted must fail loudly (exit 2), not
    silently accept or reject findings."""
    try:
        doc = json.loads(pathlib.Path(path).read_text())
    except OSError as e:
        raise ValueError(f"baseline {path}: unreadable ({e})") from e
    except ValueError as e:
        raise ValueError(f"baseline {path}: bad JSON ({e})") from e
    for p in validate_baseline_doc(doc, str(path)):
        raise ValueError(p)
    return doc


def validate_baseline_doc(doc, path: str = "<doc>") -> list[str]:
    """Schema problems (empty = valid); shared with `telemetry regress
    --check-schema`."""
    if not isinstance(doc, dict):
        return [f"{path}: not a JSON object"]
    problems = []
    if doc.get("schema") != BASELINE_SCHEMA:
        problems.append(f"{path}: schema != {BASELINE_SCHEMA!r}")
    if doc.get("v") != BASELINE_VERSION:
        problems.append(f"{path}: v != {BASELINE_VERSION}")
    entries = doc.get("entries")
    if not isinstance(entries, dict):
        return problems + [f"{path}: entries is not an object"]
    for key, entry in entries.items():
        if not isinstance(entry, dict):
            problems.append(f"{path}: entry {key!r} is not an object")
            continue
        if not isinstance(entry.get("count"), int) or entry["count"] < 1:
            problems.append(f"{path}: entry {key!r} needs count >= 1")
        for field in ("file", "rule", "message"):
            if not isinstance(entry.get(field), str):
                problems.append(f"{path}: entry {key!r} missing {field!r}")
    return problems


def apply_baseline(findings, doc) -> int:
    """Mark up to `count` live error findings per baseline key as
    baselined (reported, not gating). Returns how many were marked.
    Non-baselined findings are untouched — they still gate."""
    budget = {
        key: entry.get("count", 0)
        for key, entry in doc.get("entries", {}).items()
    }
    marked = 0
    for f in findings:
        if f.suppressed or f.severity != "error":
            continue
        key = finding_key(f)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            f.baselined = True
            marked += 1
    return marked


# ---------------------------------------------------------------------------
# --changed: git-dirty files + import-graph neighborhood
# ---------------------------------------------------------------------------


def git_dirty_files(root=".") -> set[str] | None:
    """Resolved posix paths of tracked-modified + untracked .py files,
    or None when git is unavailable (callers fall back to a full run —
    a broken fast path must widen coverage, never narrow it)."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
        out = subprocess.run(
            ["git", "status", "--porcelain", "--no-renames"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if top.returncode != 0 or out.returncode != 0:
        return None
    # Porcelain paths are relative to the repo TOPLEVEL, not the cwd —
    # anchoring them at `root` would mis-resolve every dirty path when
    # the analyzer runs from a subdirectory, and the restrict set would
    # silently lint nothing.
    base = pathlib.Path(top.stdout.strip() or root)
    dirty: set[str] = set()
    for line in out.stdout.splitlines():
        if len(line) < 4:
            continue
        name = line[3:].strip().strip('"')
        if not name.endswith(".py"):
            continue
        p = base / name
        dirty.add(pathlib.Path(os.path.normpath(p.absolute())).as_posix())
    return dirty


def expand_neighbors(entries, dirty: set[str]) -> set[str]:
    """dirty + one import-graph hop in BOTH directions over the parsed
    module set (`entries` = [(display_path, source, digest)] as built by
    core.lint_paths). Callers of a dirty module can gain or lose
    interprocedural findings; callees define the summaries the dirty
    module's own verdict depends on."""
    import ast as _ast

    from rocm_mpi_tpu.analysis import astutil, engine

    resolved = {}
    mod_names = {}
    imports_of: dict[str, set[str]] = {}
    for display, source, _ in entries:
        rp = pathlib.Path(
            os.path.normpath(os.path.abspath(display))
        ).as_posix()
        resolved[display] = rp
        name = engine.module_name_for_path(display)
        mod_names[display] = name
        try:
            tree = _ast.parse(source)
        except (SyntaxError, ValueError, RecursionError):
            imports_of[display] = set()
            continue
        table = astutil.collect_imports(tree)
        deps = set(table.module_aliases.values())
        deps |= {
            origin.rpartition(".")[0]
            for origin in table.from_imports.values()
        }
        imports_of[display] = {d for d in deps if d}
    name_to_display = {v: k for k, v in mod_names.items()}
    keep = set(dirty)
    dirty_names = {
        mod_names[d] for d in mod_names if resolved[d] in dirty
    }
    for display, deps in imports_of.items():
        # importer of a dirty module
        if deps & dirty_names:
            keep.add(resolved[display])
        # modules a dirty file imports
        if resolved[display] in dirty:
            for dep in deps:
                target = name_to_display.get(dep)
                if target is not None:
                    keep.add(resolved[target])
    return keep
