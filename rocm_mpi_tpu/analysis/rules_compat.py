"""GL03 — compat drift.

The installed jax is 0.4.37 while the code targets newer jax; every
"where does this live / what is it called" question is answered exactly
once, in `rocm_mpi_tpu/utils/compat.py` (API drift) and
`rocm_mpi_tpu/utils/backend.py` (backend knobs). A call site that goes to
`jax.experimental` / version-specific spellings directly re-introduces
per-call-site drift — the class of bug that made the seed's tier-1 suite
fail collection outright before PR 1 centralized the shims.

Checked spellings (each with its owning chokepoint, which is allowlisted):

* any `jax.experimental` import or attribute chain   -> utils.compat
* `from jax import shard_map` / `jax.shard_map`      -> utils.compat.shard_map
* `<compiled>.cost_analysis()` method calls          -> utils.compat.cost_analysis_dict
* `jax.config.update("jax_num_cpu_devices", …)`      -> utils.backend.set_cpu_device_count
* `lax.axis_size` attribute use                      -> utils.compat.axis_size
* `ShapeDtypeStruct(..., vma=…)`                     -> utils.compat.out_struct_like
"""

from __future__ import annotations

import ast

from rocm_mpi_tpu.analysis import astutil
from rocm_mpi_tpu.analysis.core import ModuleContext, Rule

# Files allowed to touch the raw APIs: the chokepoints themselves.
_COMPAT_OWNERS = ("rocm_mpi_tpu/utils/compat.py",)
_BACKEND_OWNERS = (
    "rocm_mpi_tpu/utils/compat.py",
    "rocm_mpi_tpu/utils/backend.py",
)


def _owned_by(ctx: ModuleContext, owners) -> bool:
    return ctx.posix_path.endswith(owners)


class CompatDriftRule(Rule):
    id = "GL03"
    name = "compat-drift"
    severity = "error"
    rationale = (
        "jax 0.4.37 vs modern-API drift (shard_map home, check_vma, "
        "cost_analysis shape, jax_num_cpu_devices) is fixed once in "
        "utils/compat.py + utils/backend.py; direct use re-opens the "
        "per-call-site drift that broke the seed's test collection"
    )
    hint = "see docs/ANALYSIS.md#gl03"

    def check(self, ctx: ModuleContext):
        findings = []
        in_compat = _owned_by(ctx, _COMPAT_OWNERS)
        in_backend_owner = _owned_by(ctx, _BACKEND_OWNERS)

        for node in ast.walk(ctx.tree):
            # ---- imports -------------------------------------------------
            if isinstance(node, ast.Import) and not in_compat:
                for alias in node.names:
                    if alias.name.split(".")[:2] == ["jax", "experimental"]:
                        findings.append(ctx.finding(
                            node, self,
                            f"direct import of '{alias.name}' — "
                            "jax.experimental contents move between "
                            "versions",
                            "import the shim from "
                            "rocm_mpi_tpu.utils.compat instead",
                        ))
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and not in_compat:
                mod = node.module
                if mod == "jax.experimental" or \
                        mod.startswith("jax.experimental."):
                    findings.append(ctx.finding(
                        node, self,
                        f"direct import from '{mod}' — jax.experimental "
                        "contents move between versions",
                        "import the shim from rocm_mpi_tpu.utils.compat "
                        "instead (it owns pallas/pallas_tpu/"
                        "multihost_utils/shard_map resolution)",
                    ))
                elif mod == "jax" and any(
                        a.name == "experimental" for a in node.names):
                    findings.append(ctx.finding(
                        node, self,
                        "direct import of jax.experimental",
                        "route through rocm_mpi_tpu.utils.compat",
                    ))
                elif mod == "jax" and any(
                        a.name == "shard_map" for a in node.names):
                    findings.append(ctx.finding(
                        node, self,
                        "shard_map imported from jax directly — its home "
                        "and check_vma/check_rep kwarg differ across "
                        "versions",
                        "use rocm_mpi_tpu.utils.compat.shard_map (renames "
                        "the replication-check kwarg to match the "
                        "installed jax)",
                    ))
            # ---- attribute chains ---------------------------------------
            elif isinstance(node, ast.Attribute):
                dotted = astutil.dotted_name(node) or ""
                # fire once per chain, on the innermost jax.experimental
                if dotted == "jax.experimental" and not in_compat:
                    findings.append(ctx.finding(
                        node, self,
                        "direct use of the jax.experimental namespace",
                        "route through rocm_mpi_tpu.utils.compat",
                    ))
                elif dotted == "jax.shard_map" and not in_compat:
                    findings.append(ctx.finding(
                        node, self,
                        "jax.shard_map used directly",
                        "use rocm_mpi_tpu.utils.compat.shard_map",
                    ))
                elif dotted.endswith("lax.axis_size") and not in_compat:
                    findings.append(ctx.finding(
                        node, self,
                        "lax.axis_size does not exist on jax 0.4.x",
                        "use rocm_mpi_tpu.utils.compat.axis_size (psum(1) "
                        "fallback)",
                    ))
            # ---- calls ---------------------------------------------------
            elif isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Attribute) and \
                        fn.attr == "cost_analysis" and not in_compat:
                    findings.append(ctx.finding(
                        node, self,
                        ".cost_analysis() returns a per-partition LIST on "
                        "0.4.x and a dict on newer jax",
                        "use rocm_mpi_tpu.utils.compat.cost_analysis_dict",
                    ))
                elif isinstance(fn, ast.Attribute) and fn.attr == "update" \
                        and not in_backend_owner:
                    if node.args and astutil.str_const(node.args[0]) == \
                            "jax_num_cpu_devices":
                        findings.append(ctx.finding(
                            node, self,
                            "jax_num_cpu_devices config knob does not "
                            "exist on jax 0.4.x (silently breaks the "
                            "virtual-CPU-mesh harness)",
                            "use rocm_mpi_tpu.utils.backend."
                            "set_cpu_device_count (XLA_FLAGS fallback)",
                        ))
                elif astutil.tail_name(astutil.call_name(node)) == \
                        "ShapeDtypeStruct" and not in_compat:
                    if astutil.call_kwarg(node, "vma") is not None:
                        findings.append(ctx.finding(
                            node, self,
                            "ShapeDtypeStruct(vma=…) is a jax>=0.9 "
                            "spelling; 0.4.x has no vma tracking",
                            "use rocm_mpi_tpu.utils.compat.out_struct_like",
                        ))
        return findings
