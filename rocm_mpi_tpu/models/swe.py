"""Linearized shallow-water equations — the framework's third workload.

Purpose: where the wave model shows the framework layers are
workload-agnostic for a *state pair with one exchanged field*, this model
exercises the genuinely coupled case — ndim+1 fields (surface height h and
one face velocity per axis) whose updates read neighbors of *different*
fields — on the same mesh/halo/Pallas/schedule machinery. This is the
shape of real multi-field stencil codes (ocean/atmosphere dynamical cores,
staggered-grid electromagnetics), and it is what drove the pytree-state
generalization of parallel.overlap.make_overlap_step (r4). No reference
analog (the reference ships exactly one physics model): additive, not
parity.

Physics and scheme: see ops/swe_kernels.py — forward-backward
(symplectic-Euler) time stepping of the C-grid-staggered linear system

    h' = h − dt·H·∇⁻·u,    u_a' = M_a ∘ (u_a − dt·g·∂a⁺ h')

in a closed basin (wall faces masked to exactly 0.0 — mask-as-data). Two
machine-checkable invariants the other workloads cannot offer together:

  * EXACT mass conservation — the closed-basin divergence telescopes to
    wall−wall = 0, so sum(h) is constant to fp rounding;
  * algebraic time-reversibility — the update has the closed-form inverse
    u = u' + dt·g·M∘∂⁺h';  h = h' + dt·H·∇⁻·u  (inverse sub-steps in
    reverse order), so a trajectory can be run back to its IC.

Variants mirror the flagship's ladder:
  "ap"   — global-array jnp rolls (GSPMD partitions; wraparound reads the
           opposite wall face, which the masks hold at 0 — exact).
  "perf" — shard_map + one exchange of the full state + the whole-block
           Pallas padded kernel (ops.swe_kernels.swe_step_padded_pallas).
  "hide" — perf's kernel on the boundary-slab/interior overlap
           decomposition, pytree state through parallel.overlap.
Plus run_deep (width-k ghost exchange of all fields once per k steps) and
run_vmem_resident (whole loop in one Pallas kernel, single shard).
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from rocm_mpi_tpu.utils.compat import shard_map

from rocm_mpi_tpu.config import DTYPES
from rocm_mpi_tpu.ops.diffusion import gaussian_ic
from rocm_mpi_tpu.ops.swe_kernels import masked_swe_step, swe_coeffs
from rocm_mpi_tpu.parallel.halo import exchange_halo
from rocm_mpi_tpu.parallel.mesh import GlobalGrid, init_global_grid
from rocm_mpi_tpu.utils import metrics


@dataclasses.dataclass(frozen=True)
class SWEConfig:
    """Knobs of a shallow-water run (same shape-vocabulary as
    DiffusionConfig/WaveConfig)."""

    global_shape: tuple[int, ...] = (128, 128)
    lengths: tuple[float, ...] = (10.0, 10.0)
    H0: float = 1.0  # resting depth
    g: float = 1.0  # gravity
    cfl: float = 0.5  # Courant number vs c = √(g·H0), < 1
    nt: int = 1000
    warmup: int = 10
    dtype: str = "f64"
    dims: tuple[int, ...] | None = None
    b_width: tuple[int, ...] = (32, 4)
    # On-wire halo slab precision (parallel/wire.py; same contract as
    # DiffusionConfig.wire_mode — stateful modes are deep-only).
    wire_mode: str = "f32"

    def __post_init__(self):
        if len(self.lengths) != len(self.global_shape):
            raise ValueError("lengths rank must match global_shape rank")
        if self.dtype not in DTYPES:
            raise ValueError(f"dtype must be one of {sorted(DTYPES)}")
        from rocm_mpi_tpu.parallel import wire

        wire.validate_mode(self.wire_mode)

    @property
    def ndim(self) -> int:
        return len(self.global_shape)

    @property
    def jax_dtype(self):
        return DTYPES[self.dtype]

    @property
    def spacing(self) -> tuple[float, ...]:
        return tuple(l / n for l, n in zip(self.lengths, self.global_shape))

    @property
    def wave_speed(self) -> float:
        return math.sqrt(self.g * self.H0)

    @property
    def dt(self) -> float:
        """CFL-stable forward-backward step: cfl·min(d)/(c·√ndim)."""
        return (
            self.cfl
            * min(self.spacing)
            / (self.wave_speed * math.sqrt(self.ndim))
        )


@dataclasses.dataclass
class SWERunResult:
    h: jax.Array
    us: tuple
    wtime: float
    nt: int
    warmup: int
    config: SWEConfig

    @property
    def wtime_it(self) -> float:
        return metrics.wtime_per_it(self.wtime, self.nt, self.warmup)

    @property
    def t_eff(self) -> float:
        # 2·(ndim+1) whole-array passes per step: read + write each of the
        # h and u_a state fields (masks are coefficient traffic, excluded —
        # the same accounting stance as the diffusion T_eff formula).
        return metrics.t_eff_gbs(
            self.h.shape, self.h.dtype.itemsize, self.wtime_it,
            n_passes=2 * (len(self.us) + 1),
        )

    @property
    def gpts(self) -> float:
        return metrics.gpts_per_s(self.h.shape, self.wtime_it)


class ShallowWater:
    """Forward-backward linear shallow water on a sharded global grid."""

    def __init__(
        self,
        config: SWEConfig,
        grid: GlobalGrid | None = None,
        devices=None,
    ):
        self.config = config
        if grid is None:
            grid = init_global_grid(
                *config.global_shape,
                lengths=config.lengths,
                dims=config.dims,
                devices=devices,
            )
        self.grid = grid

    def face_masks(self):
        """Per-axis face masks as data arrays: exactly 0.0 on the global
        high wall face (index n_a−1 along axis a), 1.0 elsewhere. The low
        wall is the zero-ghost convention (parallel.halo). Sharded like
        the state so every schedule slices them locally."""
        cfg, grid = self.config, self.grid
        dtype = cfg.jax_dtype

        @functools.partial(
            jax.jit, static_argnums=0, out_shardings=grid.sharding
        )
        def make(axis):
            gidx = lax.broadcasted_iota(
                jnp.int32, grid.global_shape, axis
            )
            return jnp.where(
                gidx >= grid.global_shape[axis] - 1,
                jnp.zeros(grid.global_shape, dtype),
                jnp.ones(grid.global_shape, dtype),
            )

        return tuple(make(a) for a in range(cfg.ndim))

    def init_state(self):
        """(h, us): Gaussian surface bump at rest (all velocities zero —
        wall faces therefore start, and the masks keep them, at 0)."""
        cfg, grid = self.config, self.grid
        dtype = cfg.jax_dtype

        @functools.partial(jax.jit, out_shardings=grid.sharding)
        def make_h():
            return gaussian_ic(
                grid.coord_mesh(dtype=dtype), cfg.lengths, dtype=dtype
            )

        @functools.partial(jax.jit, out_shardings=grid.sharding)
        def make_u():
            return jnp.zeros(grid.global_shape, dtype)

        return make_h(), tuple(make_u() for _ in range(cfg.ndim))

    def _step(self, variant: str, Mus):
        """(h, us) -> (h', us')."""
        cfg, grid = self.config, self.grid
        dt = cfg.dt
        cH, cg = swe_coeffs(dt, cfg.spacing, cfg.H0, cfg.g)

        if variant == "ap":

            def step(h, us):
                return masked_swe_step(h, us, Mus, cH, cg)

            return step
        if variant == "shard":
            # The explicit-decomposition jnp rung (the diffusion/wave
            # "shard" vocabulary): one exchange of the full state + the
            # pure-jnp padded forward-backward update, walls as mask
            # data. Pallas-free — the per-lane body the batched
            # multi-tenant advance vmaps (docs/SERVING.md).
            from rocm_mpi_tpu.ops.swe_kernels import swe_step_padded

            def step(h, us):
                def local(hl, *rest):
                    uls, Ml = rest[: cfg.ndim], rest[cfg.ndim:]
                    Sp = tuple(
                        exchange_halo(f, grid, wire_mode=cfg.wire_mode)
                        for f in (hl,) + tuple(uls)
                    )
                    return swe_step_padded(
                        Sp, Ml, (cfg.H0, cfg.g), dt, cfg.spacing
                    )

                outs = shard_map(
                    local,
                    mesh=grid.mesh,
                    in_specs=(grid.spec,) * (2 * cfg.ndim + 1),
                    out_specs=(grid.spec,) * (cfg.ndim + 1),
                    check_vma=False,
                )(h, *us, *Mus)
                return outs[0], tuple(outs[1:])

            return step
        if variant == "perf":
            from rocm_mpi_tpu.ops.swe_kernels import swe_step_padded_pallas

            def step(h, us):
                def local(hl, *rest):
                    uls, Ml = rest[: cfg.ndim], rest[cfg.ndim:]
                    Sp = tuple(
                        exchange_halo(f, grid, wire_mode=cfg.wire_mode)
                        for f in (hl,) + tuple(uls)
                    )
                    outs = swe_step_padded_pallas(
                        Sp, Ml, (cfg.H0, cfg.g), dt, cfg.spacing
                    )
                    return outs

                outs = shard_map(
                    local,
                    mesh=grid.mesh,
                    in_specs=(grid.spec,) * (2 * cfg.ndim + 1),
                    out_specs=(grid.spec,) * (cfg.ndim + 1),
                    check_vma=False,
                )(h, *us, *Mus)
                return outs[0], tuple(outs[1:])

            return step
        if variant == "hide":
            from rocm_mpi_tpu.ops.swe_kernels import swe_step_padded_pallas
            from rocm_mpi_tpu.parallel.overlap import make_overlap_step

            if grid.nprocs == 1:
                # No neighbors → nothing to hide (same routing policy as
                # the diffusion and wave models' single-device hide).
                return self._step("perf", Mus)

            def pu(Sp, Ml, lam, dt_, spacing):
                del lam
                return swe_step_padded_pallas(
                    Sp, Ml, (cfg.H0, cfg.g), dt_, spacing
                )

            # Walls ride the mask data — no Dirichlet where (the Cm-style
            # mask_boundary=False contract).
            local = make_overlap_step(
                grid, pu, cfg.b_width, mask_boundary=False,
                wire_mode=cfg.wire_mode,
            )

            def step(h, us):
                def shard_fn(hl, *rest):
                    uls, Ml = rest[: cfg.ndim], rest[cfg.ndim:]
                    return local(
                        (hl,) + tuple(uls), tuple(Ml), None, dt,
                        cfg.spacing,
                    )

                outs = shard_map(
                    shard_fn,
                    mesh=grid.mesh,
                    in_specs=(grid.spec,) * (2 * cfg.ndim + 1),
                    out_specs=(grid.spec,) * (cfg.ndim + 1),
                    check_vma=False,
                )(h, *us, *Mus)
                return outs[0], tuple(outs[1:])

            return step
        raise ValueError(
            f"unknown SWE variant {variant!r} (ap, shard, perf, hide)"
        )

    # ---- multi-tenant batching (docs/SERVING.md) ------------------------

    def make_batched_grid(self, batch: int, batch_dims: int = 1,
                          devices=None):
        """Space×batch mesh for `batch` lanes of this model's space
        problem (see HeatDiffusion.make_batched_grid)."""
        from rocm_mpi_tpu.parallel.mesh import init_batched_grid

        cfg = self.config
        return init_batched_grid(
            batch,
            *cfg.global_shape,
            lengths=cfg.lengths,
            space_dims=self.grid.dims,
            batch_dims=batch_dims,
            devices=devices,
        )

    def _make_batched_step(self, bgrid, variant: str):
        """(`step(hb, usb, Mus) -> (hb', usb')`, prepare-or-None) over
        lane-batched SWE state; the face masks `Mus` are UNBATCHED
        (wall geometry is config-derived, shared by every lane). Same
        vocabulary (and return convention) as
        HeatDiffusion._make_batched_step."""
        from rocm_mpi_tpu.parallel.halo import exchange_halo_batched

        cfg = self.config
        ndim = cfg.ndim
        dt = cfg.dt
        cH, cg = swe_coeffs(dt, cfg.spacing, cfg.H0, cfg.g)

        if variant == "ap":

            def step(hb, usb, Mus):
                return jax.vmap(
                    lambda h, us: masked_swe_step(h, us, Mus, cH, cg),
                    in_axes=(0, 0),
                )(hb, usb)

            return step, None

        if variant != "shard":
            raise ValueError(
                f"batched SWE advance supports variants 'shard', 'ap'; "
                f"got {variant!r} (the Pallas/overlap rungs are "
                "single-lane)"
            )

        from rocm_mpi_tpu.ops.swe_kernels import swe_step_padded

        def lane_local(hb_l, *rest):
            ub_ls, Ml = rest[:ndim], rest[ndim:]
            padded = tuple(
                exchange_halo_batched(f, bgrid, wire_mode=cfg.wire_mode)
                for f in (hb_l,) + tuple(ub_ls)
            )

            def lane(*Sp):
                return swe_step_padded(
                    Sp, Ml, (cfg.H0, cfg.g), dt, cfg.spacing
                )

            return jax.vmap(lane)(*padded)

        def step(hb, usb, Mus):
            outs = shard_map(
                lane_local,
                mesh=bgrid.mesh,
                in_specs=(bgrid.spec,) * (ndim + 1)
                + (bgrid.aux_spec,) * ndim,
                out_specs=(bgrid.spec,) * (ndim + 1),
                check_vma=False,
            )(hb, *usb, *Mus)
            return outs[0], tuple(outs[1:])

        return step, None

    def batched_advance_fn(
        self,
        batch: int | None = None,
        variant: str = "shard",
        bgrid=None,
        batch_dims: int = 1,
        devices=None,
    ):
        """(jitted `advance(hb, usb, Mus, lane_steps, n) -> (hb, usb)`,
        bgrid) — the SWE edition of the multi-tenant batched advance
        (HeatDiffusion.batched_advance_fn has the lane_steps/bitwise
        contract; every state field freezes together when a lane's count
        is reached). Donates (hb, usb) — aliasing proven from the
        compiled program by analysis/lowered.audit_batched_drivers."""
        if bgrid is None:
            if batch is None:
                raise ValueError("pass batch= or a prebuilt bgrid=")
            bgrid = self.make_batched_grid(batch, batch_dims, devices)
        step, _ = self._make_batched_step(bgrid, variant)
        shape1 = (-1,) + (1,) * bgrid.space.ndim

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def advance(hb, usb, Mus, lane_steps, n):
            def body(i, s):
                h, us = s
                nh, nus = step(h, us, Mus)
                active = (i < lane_steps).reshape(shape1)
                return (
                    jnp.where(active, nh, h),
                    tuple(
                        jnp.where(active, nu, u)
                        for nu, u in zip(nus, us)
                    ),
                )

            return lax.fori_loop(0, n, body, (hb, usb))

        return advance, bgrid

    def advance_fn(self, variant: str = "perf"):
        """jitted (h, us, Mus, n) -> (h, us) after n steps."""

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def advance(h, us, Mus, n):
            step = self._step(variant, Mus)
            return lax.fori_loop(
                0, n, lambda _, s: step(s[0], s[1]), (h, us)
            )

        return advance

    def scan_advance_fn(
        self,
        variant: str = "perf",
        nt: int | None = None,
        warmup: int | None = None,
        chunk: int | None = None,
        config: str | None = None,
    ):
        """(jitted (h, us, Mus, n) -> (h, us), chunk q) — the
        donation-aware scan driver, SWE edition (see
        HeatDiffusion.scan_advance_fn): the whole coupled state pytree is
        the scan carry and every state leaf is donated; the masks ride
        along undonated (they are read-only data). `n` must be a multiple
        of q. `config="auto"` gcd's an unset chunk from the tuning cache
        (op "swe.scan" — see the diffusion edition's contract)."""
        from rocm_mpi_tpu.models.diffusion import (
            auto_scan_chunk,
            effective_block_steps,
        )

        cfg = self.config
        nt_v = cfg.nt if nt is None else nt
        wu_v = cfg.warmup if warmup is None else warmup
        explicit = chunk is not None
        if not explicit:
            chunk = auto_scan_chunk("swe.scan", self.grid, cfg.jax_dtype,
                                    config)
        q = effective_block_steps(
            nt_v, wu_v, (nt_v - wu_v) if chunk is None else chunk,
            label="SWE scan driver chunk", warn=explicit,
        )

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def advance(h, us, Mus, n):
            step = self._step(variant, Mus)

            def q_steps(carry, _):
                return step(carry[0], carry[1]), None

            def body(_, carry):
                carry, _ = lax.scan(q_steps, carry, xs=None, length=q)
                return carry

            return lax.fori_loop(0, n // q, body, (h, us))

        return advance, q

    def _run_timed(self, advance, nt, warmup) -> SWERunResult:
        """Shared scaffold: warmup-advance / tic / advance / toc (the
        framework's timing protocol; `advance(h, us, Mus, n)` must serve
        both windows with one compiled program)."""
        cfg = self.config
        nt = cfg.nt if nt is None else nt
        warmup = cfg.warmup if warmup is None else warmup
        if not 0 <= warmup < nt:
            raise ValueError(f"need 0 <= warmup < nt, got {warmup}, {nt}")
        h, us = self.init_state()
        Mus = self.face_masks()
        timer = metrics.Timer(label="step_window", phase="step",
                              steps=nt - warmup, workload="swe")
        h, us = advance(h, us, Mus, warmup)
        timer.tic(h)
        h, us = advance(h, us, Mus, nt - warmup)
        wtime = timer.toc(h)
        return SWERunResult(
            h=h, us=us, wtime=wtime, nt=nt, warmup=warmup, config=cfg
        )

    def run(
        self, variant: str = "perf",
        nt: int | None = None, warmup: int | None = None,
        driver: str = "step", config: str | None = None,
    ) -> SWERunResult:
        """`driver="scan"` routes to the donation-aware scan driver
        (scan_advance_fn); "step" keeps the per-step fori_loop. Same step
        program either way — results are bitwise identical.
        `config="auto"` lets the scan chunk consult the tuning cache."""
        if driver not in ("step", "scan"):
            raise ValueError(f"driver must be 'step' or 'scan', got {driver!r}")
        if driver == "scan":
            advance, _ = self.scan_advance_fn(variant, nt=nt, warmup=warmup,
                                              config=config)
        else:
            advance = self.advance_fn(variant)
        return self._run_timed(advance, nt, warmup)

    def run_vmem_resident(
        self, nt: int | None = None, warmup: int | None = None,
        chunk: int | None = None, config: str | None = None,
    ) -> SWERunResult:
        """Single-shard fast path: the whole coupled loop inside one
        Pallas kernel, all ndim+1 fields VMEM-resident
        (ops.swe_kernels.swe_multi_step). `config="auto"` fills an unset
        chunk from the tuning cache (op "swe.vmem_loop"), resolved here
        outside any trace and gcd'd against the windows."""
        from rocm_mpi_tpu.models.diffusion import effective_block_steps
        from rocm_mpi_tpu.ops.pallas_kernels import DEFAULT_STEP_CHUNK
        from rocm_mpi_tpu.ops.swe_kernels import swe_multi_step

        cfg = self.config
        if self.grid.nprocs != 1:
            raise ValueError(
                "the VMEM-resident path requires an unsharded grid"
            )
        explicit = chunk is not None
        if config == "auto" and chunk is None:
            from rocm_mpi_tpu.ops.pallas_kernels import adoptable_vmem_chunk
            from rocm_mpi_tpu.tuning import resolve as tuning_resolve

            tuned = tuning_resolve.resolve(
                "swe.vmem_loop", cfg.global_shape, cfg.jax_dtype
            )
            if tuned and adoptable_vmem_chunk(tuned.get("chunk")):
                chunk = tuned["chunk"]
        elif config not in (None, "default", "auto"):
            raise ValueError(
                f"config must be None, 'default' or 'auto', got {config!r}"
            )
        # warn=explicit: a caller-requested chunk degrades loudly (the
        # wave/diffusion editions' policy); framework-plumbed and
        # auto-resolved preferences degrade silently.
        eff_chunk = effective_block_steps(
            cfg.nt if nt is None else nt,
            cfg.warmup if warmup is None else warmup,
            DEFAULT_STEP_CHUNK if chunk is None else chunk,
            warn=explicit, label="SWE VMEM chunk",
        )

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def advance(h, us, Mus, n):
            return swe_multi_step(
                h, us, Mus, cfg.dt, cfg.spacing, cfg.H0, cfg.g, n,
                chunk=eff_chunk, warn_on_cap=False,
            )

        return self._run_timed(advance, nt, warmup)

    DEFAULT_DEEP_STEPS = 8

    def effective_deep_depth(
        self,
        nt: int | None = None,
        warmup: int | None = None,
        block_steps: int | None = None,
        warn: bool = True,
    ) -> int:
        """The sweep depth run_deep will actually execute — the labeling
        source of truth (same policy as the diffusion and wave models,
        ADVICE r3: a DEFAULT depth clamps to the shard, an EXPLICIT one is
        gcd-degraded against the windows and then raises if it still
        exceeds the shard)."""
        from rocm_mpi_tpu.models.diffusion import effective_block_steps

        cfg = self.config
        explicit = block_steps is not None
        if block_steps is None:
            block_steps = min(
                self.DEFAULT_DEEP_STEPS, min(self.grid.local_shape)
            )
        eff = effective_block_steps(
            cfg.nt if nt is None else nt,
            cfg.warmup if warmup is None else warmup,
            block_steps,
            label="SWE deep-halo sweep depth",
            warn=warn,
            stacklevel=3,
        )
        if explicit and eff > min(self.grid.local_shape):
            raise ValueError(
                f"SWE deep-halo sweep depth {eff} exceeds a local shard "
                f"extent {self.grid.local_shape}; ghost slices need "
                "width <= shard"
            )
        return eff

    def deep_advance_fn(
        self,
        block_steps: int | None = None,
        nt: int | None = None,
        warmup: int | None = None,
        wire_mode: str | None = None,
    ):
        """(jitted (h, us, Mus, n_steps) -> (h, us), executed depth k) —
        the SWE deep schedule's advance as a first-class function
        (HeatDiffusion.deep_advance_fn); `n_steps` must be a multiple of
        k (the fori_loop trip count floors). Mus is accepted and ignored
        so the signature matches advance_fn's (deep sweeps build padded
        masks internally). `wire_mode` overrides the config's on-wire
        precision; stateful modes carry the exchange state internally."""
        from rocm_mpi_tpu.parallel.deep_halo import make_swe_deep_sweep

        cfg = self.config
        k = self.effective_deep_depth(nt, warmup, block_steps)
        wm = cfg.wire_mode if wire_mode is None else wire_mode
        sched = make_swe_deep_sweep(
            self.grid, k, cfg.dt, cfg.spacing, cfg.H0, cfg.g,
            wire_mode=wm,
        )

        if sched.init_wire is None:

            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def advance(h, us, Mus, n):
                del Mus
                # The padded face masks are geometry-only: built ONCE per
                # compiled advance (DeepSchedule.prepare), not inside every
                # sweep — the loop carries only the coupled state.
                Mp = sched.prepare(h)
                return lax.fori_loop(
                    0, n // k, lambda _, s: sched.sweep(s[0], s[1], Mp),
                    (h, us),
                )

        else:

            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def advance(h, us, Mus, n):
                del Mus
                Mp = sched.prepare(h)
                ws0 = sched.init_wire(h.dtype)
                out = lax.fori_loop(
                    0, n // k,
                    lambda _, s: sched.sweep(s[0], s[1], Mp, s[2]),
                    (h, us, ws0),
                )
                return out[0], out[1]

        return advance, k

    def run_deep(
        self,
        nt: int | None = None,
        warmup: int | None = None,
        block_steps: int | None = None,
        wire_mode: str | None = None,
    ) -> SWERunResult:
        """Sharded fast path: deep-halo sweeps — ONE width-k ghost
        exchange of the whole coupled state per k steps
        (parallel.deep_halo.make_swe_deep_sweep)."""
        advance, _ = self.deep_advance_fn(block_steps, nt, warmup,
                                          wire_mode=wire_mode)
        return self._run_timed(advance, nt, warmup)
