"""The transient heat-diffusion model — the framework's flagship workload.

One physics model at escalating performance levels, mirroring the
reference's app ladder (SURVEY.md §2.1 C1-C4):

  variant "ap"    — array-programming: global-array flux-form jnp ops; GSPMD
                    auto-partitions and inserts halo comms (C1 analog).
  variant "fused" — single fused jnp stencil, double-buffer-free functional
                    update (C3's math, compiler-scheduled).
  Pallas/overlap variants ("kp", "perf", "hide") are added by
  rocm_mpi_tpu.ops.pallas_kernels / parallel.overlap and registered here.

The hot loop lives *inside* one jitted `lax.fori_loop` — the TPU-first
answer to the reference's per-step `wait(@roc …); update_halo!` host
round-trips (scripts/diffusion_2D_perf.jl:47-52): nothing leaves the device
between tic and toc.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from rocm_mpi_tpu.utils.compat import shard_map

from rocm_mpi_tpu.config import DiffusionConfig
from rocm_mpi_tpu.ops.diffusion import (
    gaussian_ic,
    step_flux_form,
    step_fused,
    step_fused_padded,
)
from rocm_mpi_tpu.parallel.halo import exchange_halo, global_boundary_mask
from rocm_mpi_tpu.parallel.mesh import GlobalGrid, init_global_grid
from rocm_mpi_tpu.utils import metrics


def effective_block_steps(
    nt: int, warmup: int, k: int, *, label: str = "block_steps",
    warn: bool = True, stacklevel: int = 3,
) -> int:
    """The sweep/chunk depth actually usable for the given step counts:
    gcd(warmup, nt-warmup, k) — both windows must be multiples of the
    depth so one compiled program serves both. The single source of truth
    for every runner (and for callers labeling artifacts by depth), so the
    warned, reported, and executed k can never diverge.
    """
    import math
    import warnings

    if k < 1:
        raise ValueError(f"{label} must be >= 1, got {k}")
    eff = math.gcd(math.gcd(warmup, nt - warmup), k) or 1
    if warn and eff != k:
        warnings.warn(
            f"{label} degraded: {k} requested but warmup={warmup} / "
            f"timed={nt - warmup} force k={eff}; pick step counts "
            f"divisible by {k} to keep the full k-steps-per-sweep saving.",
            stacklevel=stacklevel,
        )
    return eff


def default_deep_depth(local_shape, itemsize: int) -> int:
    """run_deep's automatic sweep depth for a given per-device shard.

    Start from DEFAULT_DEEP_STEPS clamped to the shard extent, then halve
    while the k-padded shard exceeds the VMEM budget but a shallower sweep
    would fit — mid-size shards prefer a shallower VMEM-resident sweep
    over the HBM local sweep (e.g. a 672² f32 shard fits VMEM at k=16 but
    not k=32). Shards that fit at no depth run the temporal-blocked HBM
    local sweep, whose stripe ghosts cap the depth at DEFAULT_TB_STEPS.
    """
    from rocm_mpi_tpu.ops.pallas_kernels import (
        _VMEM_BLOCK_BUDGET_BYTES,
        DEFAULT_DEEP_STEPS,
        DEFAULT_TB_STEPS,
    )

    def padded_bytes(kk):
        b = itemsize
        for ln in local_shape:
            b *= ln + 2 * kk
        return b

    k = min(DEFAULT_DEEP_STEPS, min(local_shape))
    while k > DEFAULT_TB_STEPS and padded_bytes(k) > _VMEM_BLOCK_BUDGET_BYTES:
        k //= 2
    if padded_bytes(k) > _VMEM_BLOCK_BUDGET_BYTES:
        k = min(k, DEFAULT_TB_STEPS)
    return max(1, k)


def auto_scan_chunk(op: str, grid, dtype, config: str | None) -> int | None:
    """The scan drivers' `config="auto"` seam, shared by all three
    models: the tuning cache's preferred chunk for `op` at this
    shard/topology, or None (= the default whole-window policy) on a
    miss or a non-auto config. The caller still gcd's the preference
    against its windows (effective_block_steps) — auto never breaks the
    divisibility contract, it only prefers a different quantum."""
    if config in (None, "default"):
        return None
    if config != "auto":
        raise ValueError(
            f"config must be None, 'default' or 'auto', got {config!r}"
        )
    if jax.process_count() > 1:
        # Multi-controller: every process resolves from ITS OWN cache
        # file, and a divergent chunk means divergently traced programs
        # across ranks. The defaults are deterministic everywhere; auto
        # stays hands-off until a broadcast-consistent resolve exists.
        return None
    from rocm_mpi_tpu.tuning import resolve as tuning_resolve

    tuned = tuning_resolve.resolve(
        op, grid.local_shape, dtype, topology=grid.dims
    )
    if tuned and tuned.get("chunk"):
        return int(tuned["chunk"])
    return None


def warn_host_transport_ignored(variant: str, stacklevel: int = 3) -> None:
    """The one warning for halo_transport='host' on a variant that keeps its
    device-side communication (only 'shard' routes to the host-staged
    oracle). Shared so the message can't drift between call sites.
    Default stacklevel attributes to run()'s caller; direct callers pass 2.
    """
    import warnings

    warnings.warn(
        f"halo_transport='host' is not honored by variant {variant!r} — "
        "only variant 'shard' routes to the host-staged oracle stepper; "
        "all other variants keep their device-side communication (GSPMD "
        "or ppermute).",
        stacklevel=stacklevel,
    )


@dataclasses.dataclass
class RunResult:
    T: jax.Array  # final temperature field (global, sharded)
    wtime: float  # seconds over the timed steps
    nt: int
    warmup: int
    config: DiffusionConfig

    @property
    def wtime_it(self) -> float:
        return metrics.wtime_per_it(self.wtime, self.nt, self.warmup)

    @property
    def t_eff(self) -> float:
        return metrics.t_eff_gbs(
            self.T.shape, self.T.dtype.itemsize, self.wtime_it
        )

    @property
    def gpts(self) -> float:
        return metrics.gpts_per_s(self.T.shape, self.wtime_it)


class HeatDiffusion:
    """Heat diffusion on a sharded global grid, with selectable step variant."""

    def __init__(
        self,
        config: DiffusionConfig,
        grid: GlobalGrid | None = None,
        devices=None,
    ):
        self.config = config
        if grid is None:
            grid = init_global_grid(
                *config.global_shape,
                lengths=config.lengths,
                dims=config.dims,
                devices=devices,
            )
        if grid.global_shape != config.global_shape:
            raise ValueError(
                f"grid shape {grid.global_shape} != config {config.global_shape}"
            )
        if grid.lengths != config.lengths:
            raise ValueError(
                f"grid lengths {grid.lengths} != config {config.lengths}"
            )
        self.grid = grid
        self._step_fns: dict[str, Callable] = {}
        self._prep_fns: dict[str, Callable] = {}
        self.register_variant("ap", self._make_jnp_step(step_flux_form))
        self.register_variant("fused", self._make_jnp_step(step_fused))
        self.register_variant("shard", self._make_shard_step(step_fused_padded))
        # perf: the reference's fused hand-tuned kernel rung
        # (diffusion_2D_perf.jl) — explicit halo + Pallas stencil kernel,
        # Cm contract: the Dirichlet mask and the dt·λ/Cp divide live in a
        # coefficient prepared once per run, so each step is one kernel.
        from rocm_mpi_tpu.ops.pallas_kernels import kp_step_padded

        self.register_variant("perf", *self._make_masked_step())
        # kp: the kernel-programming teaching rung (diffusion_2D_kp.jl) —
        # three separate Pallas kernels per step, staggered-grid shapes.
        # 2D-only, like the reference's kp app. check_vma off:
        # interpret-mode pallas_call (CPU tests) emits constants with empty
        # vma that trip jax 0.9's varying-axes checker.
        if self.grid.ndim == 2:
            self.register_variant(
                "kp", self._make_shard_step(kp_step_padded, check_vma=False)
            )
        # hide: comm/compute overlap (diffusion_2D_perf_hide.jl's intended
        # variant (3), working) — boundary slabs + overlapped halo; N-D.
        self.register_variant("hide", *self._make_hide_step())

    # ---- state ----------------------------------------------------------

    def init_state(self):
        """(T, Cp) on-device, sharded over the grid mesh.

        T₀ = centered Gaussian via global cell-center coordinates — each
        device materializes its shard of the global IC, as each reference
        rank does through x_g/y_g (diffusion_2D_ap.jl:28). Cp = Cp0·ones
        (ap.jl:25).
        """
        cfg, grid = self.config, self.grid
        dtype = cfg.jax_dtype

        @functools.partial(jax.jit, out_shardings=grid.sharding)
        def make_T():
            coords = grid.coord_mesh(dtype=dtype)
            return gaussian_ic(coords, cfg.lengths, dtype=dtype)

        @functools.partial(jax.jit, out_shardings=grid.sharding)
        def make_Cp():
            return jnp.full(grid.global_shape, cfg.cp0, dtype=dtype)

        return make_T(), make_Cp()

    # ---- variants -------------------------------------------------------

    def register_variant(
        self, name: str, step_fn: Callable, prepare: Callable | None = None
    ):
        """step_fn(T, C, lam, dt, spacing, grid) -> new T.

        `prepare(Cp, lam, dt) -> C` (optional) builds the loop-invariant
        coefficient handed to every step — traced once per jitted program,
        OUTSIDE the time loop (e.g. the Cm masked coefficient of the perf
        rung). Without it, C is Cp itself.
        """
        self._step_fns[name] = step_fn
        if prepare is not None:
            self._prep_fns[name] = prepare
        else:
            self._prep_fns.pop(name, None)

    @property
    def variants(self) -> tuple[str, ...]:
        return tuple(self._step_fns)

    def _get_step(self, variant: str) -> Callable:
        try:
            return self._step_fns[variant]
        except KeyError:
            raise ValueError(
                f"unknown variant {variant!r} for a {self.grid.ndim}D grid; "
                f"available: {', '.join(self.variants)}"
            ) from None

    def _make_jnp_step(self, raw_step):
        def step(T, Cp, lam, dt, spacing, grid):
            del grid  # global formulation: GSPMD handles the decomposition
            return raw_step(T, Cp, lam, dt, spacing)

        return step

    def _make_shard_step(self, padded_update, check_vma: bool = True):
        """Explicit-decomposition step: shard_map + ppermute halo exchange.

        The manual counterpart of "ap": each device exchanges width-1 ghosts
        with its cartesian neighbors (exchange_halo = update_halo! analog),
        applies `padded_update` to its block, and Dirichlet-masks global
        boundary cells. This is the structure the perf/hide ladder builds on.
        """

        wire_mode = self.config.wire_mode

        def step(T, Cp, lam, dt, spacing, grid):
            def local_step(Tl, Cpl):
                Tp = exchange_halo(Tl, grid, wire_mode=wire_mode)
                new = padded_update(Tp, Cpl, lam, dt, spacing)
                return jnp.where(global_boundary_mask(grid), Tl, new)

            return shard_map(
                local_step,
                mesh=grid.mesh,
                in_specs=(grid.spec, grid.spec),
                out_specs=grid.spec,
                check_vma=check_vma,
            )(T, Cp)

        return step

    def _cm_prepare(self):
        """prepare(Cp, lam, dt) -> Cm: the masked coefficient of the Cm
        contract — (dt·λ)/Cp on updating cells, exactly 0.0 on global
        Dirichlet boundary cells — computed once per jitted program."""
        grid = self.grid

        def prepare(Cp, lam, dt):
            def local(Cpl):
                z = jnp.zeros_like(Cpl)
                return jnp.where(
                    global_boundary_mask(grid), z, (dt * lam) / Cpl
                )

            return shard_map(
                local, mesh=grid.mesh, in_specs=(grid.spec,),
                out_specs=grid.spec,
            )(Cp)

        return prepare

    def _make_masked_step(self):
        """perf rung, Cm contract (VERDICT r2 ask #1): `prepare` folds the
        Dirichlet mask and the (dt·λ)/Cp divide into one masked coefficient
        computed once per run, so the per-step program is exactly one
        Pallas kernel (plus the halo exchange when sharded) — the
        reference's per-step schedule (perf.jl:47-52) without its per-step
        divide + where-mask op chain. f64 runs interpret-mode off-TPU
        (tests); on TPU the Cm kernels raise for f64, as the unmasked
        Pallas path did.
        """
        from rocm_mpi_tpu.ops.pallas_kernels import fused_step_cm, masked_step

        grid = self.grid
        prepare = self._cm_prepare()

        if grid.nprocs == 1:
            # Unsharded: no neighbors, the block edge IS the global
            # boundary — one kernel per step, no exchange, no pad.
            def step(T, Cm, lam, dt, spacing, grid_):
                return masked_step(T, Cm, spacing)

            return step, prepare

        wire_mode = self.config.wire_mode

        def step(T, Cm, lam, dt, spacing, grid_):
            def local(Tl, Cml):
                Tp = exchange_halo(Tl, grid, wire_mode=wire_mode)
                return fused_step_cm(Tp, Cml, spacing)

            return shard_map(
                local, mesh=grid.mesh, in_specs=(grid.spec, grid.spec),
                out_specs=grid.spec, check_vma=False,
            )(T, Cm)

        return step, prepare

    def step_fn(self, variant: str):
        """jitted single step (T, Cp) -> T (no donation; compile-check safe)."""
        cfg, grid = self.config, self.grid
        step = self._get_step(variant)
        prep = self._prep_fns.get(variant)
        dt = cfg.jax_dtype(cfg.dt)

        @jax.jit
        def one_step(T, Cp):
            C = Cp if prep is None else prep(Cp, cfg.lam, dt)
            return step(T, C, cfg.lam, dt, cfg.spacing, grid)

        return one_step

    def prepared_step_fn(self, variant: str, donate: bool = False):
        """(jitted steady-state step(T, C) -> T, jitted prepare(Cp) -> C):
        the per-step program with the loop-invariant coefficient ALREADY
        prepared — exactly the program the multi-step drivers execute per
        iteration, which is what the perf traffic gate audits
        (rocm_mpi_tpu/perf/traffic.py): a step_fn-style program would
        charge the once-per-run prepare to every step.

        `donate=True` donates T — the drivers' steady-state aliasing
        (their loop carry reuses the field buffer), which is what lets
        XLA update the exchanged buffer in place instead of inserting a
        defensive copy. Callers must then rebind T from the result."""
        cfg, grid = self.config, self.grid
        step = self._get_step(variant)
        prep = self._prep_fns.get(variant)
        dt = cfg.jax_dtype(cfg.dt)

        @jax.jit
        def prepare(Cp):
            return Cp if prep is None else prep(Cp, cfg.lam, dt)

        @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
        def one_step(T, C):
            return step(T, C, cfg.lam, dt, cfg.spacing, grid)

        return one_step, prepare

    def _make_hide_step(self):
        """Overlap step (parallel.overlap): Pallas strips for f32/bf16, jnp
        strips for f64 (Mosaic has no f64) — BOTH on the Cm contract, so
        no caller pays a trailing whole-shard Dirichlet select. Returns
        (step, prepare)."""
        from rocm_mpi_tpu.parallel.overlap import make_overlap_step

        cfg, grid = self.config, self.grid
        compiled_dtype = jnp.dtype(cfg.jax_dtype).itemsize <= 4
        if grid.nprocs == 1:
            # No neighbors → nothing to hide; the boundary/interior strip
            # bookkeeping is pure overhead (measured r1: 8.2 vs 6.3 µs/step
            # at 252²). Route to the same masked per-step program as perf,
            # so hide == perf bit-identically on one device — the
            # reference's variant (2)/(3) distinction only exists once
            # communication exists. (f64 keeps the jnp shard step: Mosaic
            # has no f64, and the jnp path serves TPU parity runs.)
            if compiled_dtype:
                return self._make_masked_step()
            return self._make_shard_step(step_fused_padded), None
        # Cm contract on the strip ladder: the mask+divide live in the
        # prepared coefficient, each region update is one kernel (Pallas
        # for f32/bf16, the bitwise-identical jnp twin for f64), and held
        # cells come back unchanged from the update itself — the trailing
        # whole-shard select the old f64 path paid is dead work the Cm
        # zeros already guarantee (mask_boundary=False everywhere).
        if compiled_dtype:
            from rocm_mpi_tpu.ops.pallas_kernels import fused_step_cm as _cm_kernel
        else:
            from rocm_mpi_tpu.ops.diffusion import step_cm_padded as _cm_kernel

        pu = lambda tp, cm, lam, dt, spacing: _cm_kernel(tp, cm, spacing)
        local = make_overlap_step(
            grid, pu, cfg.b_width, mask_boundary=False,
            wire_mode=cfg.wire_mode,
        )
        prepare = self._cm_prepare()

        def step(T, C, lam, dt, spacing, grid_):
            return shard_map(
                lambda Tl, Cl: local(Tl, Cl, lam, dt, spacing),
                mesh=grid.mesh,
                in_specs=(grid.spec, grid.spec),
                out_specs=grid.spec,
                check_vma=False,
            )(T, C)

        return step, prepare

    def advance_fn(self, variant: str):
        """jitted (T, Cp, n_steps) -> T after n_steps.

        `n_steps` is *traced* (dynamic fori_loop bound) so the warmup call
        and the timed call share one compiled program — otherwise the timed
        window would include a recompile for the new static step count and
        the warmup would fail its purpose (perf.jl:48's it==11 tic assumes
        the code is warm).

        NOTE: donates T (argument 0) — the functional analog of the
        reference's `T, T2 = T2, T` double-buffer swap (perf.jl:50): XLA
        reuses the input buffer instead of allocating a second field. The
        caller must not reuse the passed-in T afterwards.
        """
        cfg, grid = self.config, self.grid
        step = self._get_step(variant)
        prep = self._prep_fns.get(variant)
        dt = cfg.jax_dtype(cfg.dt)

        @functools.partial(jax.jit, donate_argnums=0)
        def advance(T, Cp, n):
            # Loop-invariant coefficient (e.g. the perf rung's Cm), traced
            # once OUTSIDE the fori_loop — zero per-step host round-trips
            # and zero per-step mask/divide work by construction.
            C = Cp if prep is None else prep(Cp, cfg.lam, dt)
            body = lambda _, T: step(T, C, cfg.lam, dt, cfg.spacing, grid)
            return lax.fori_loop(0, n, body, T)

        return advance

    def scan_advance_fn(
        self,
        variant: str,
        nt: int | None = None,
        warmup: int | None = None,
        chunk: int | None = None,
        config: str | None = None,
    ):
        """(jitted (T, Cp, n) -> T, chunk q) — the donation-aware scan
        driver: the hot loop is a `lax.scan` over a STATIC q-step chunk
        inside a dynamic-trip fori_loop, with the carried field donated
        (`donate_argnums=0`). The scan carry is XLA's double buffer — the
        functional analog of the reference's `T, T2 = T2, T` swap
        (perf.jl:50) — so steady-state stepping allocates nothing: the
        donated input buffer and the scan carry pair are the only field
        storage the program ever touches.

        `q` defaults to the largest chunk serving both timing windows with
        one compiled program (gcd of warmup and the timed window —
        effective_block_steps); `n` must be a multiple of q (the outer
        trip count floors, the step-count convention the deep advance
        shares). `config="auto"` treats a tuning-cache chunk (op
        "diffusion.scan", keyed per shard/topology) as the preference an
        unset `chunk` gcd's from — traffic-neutral and bitwise-identical
        at any q (scan==step is pinned), so auto only moves window
        quantization. The caller must rebind T from the result (GL01:
        the passed-in buffer is donated).
        """
        cfg, grid = self.config, self.grid
        step = self._get_step(variant)
        prep = self._prep_fns.get(variant)
        dt = cfg.jax_dtype(cfg.dt)
        nt_v = cfg.nt if nt is None else nt
        wu_v = cfg.warmup if warmup is None else warmup
        explicit = chunk is not None
        if not explicit:
            chunk = auto_scan_chunk("diffusion.scan", grid, cfg.jax_dtype,
                                    config)
        q = effective_block_steps(
            nt_v, wu_v, (nt_v - wu_v) if chunk is None else chunk,
            label="scan driver chunk", warn=explicit,
        )

        @functools.partial(jax.jit, donate_argnums=0)
        def advance(T, Cp, n):
            C = Cp if prep is None else prep(Cp, cfg.lam, dt)

            def q_steps(carry, _):
                return step(carry, C, cfg.lam, dt, cfg.spacing, grid), None

            def body(_, carry):
                carry, _ = lax.scan(q_steps, carry, xs=None, length=q)
                return carry

            return lax.fori_loop(0, n // q, body, T)

        return advance, q

    # ---- multi-tenant batching (docs/SERVING.md) ------------------------

    def make_batched_grid(self, batch: int, batch_dims: int = 1,
                          devices=None):
        """The space×batch mesh for `batch` lanes of THIS model's space
        problem (mesh.init_batched_grid), space decomposition pinned to
        the model's own grid dims so a lane's spatial shards match its
        standalone twin's."""
        from rocm_mpi_tpu.parallel.mesh import init_batched_grid

        cfg = self.config
        return init_batched_grid(
            batch,
            *cfg.global_shape,
            lengths=cfg.lengths,
            space_dims=self.grid.dims,
            batch_dims=batch_dims,
            devices=devices,
        )

    def _make_batched_step(self, bgrid, variant: str):
        """(`step(Tb, C) -> Tb`, prepare-or-None) over `(batch, *space)`
        lane-batched state (C is the UNBATCHED space-shaped coefficient
        every lane shares — physics is a bin-key field,
        docs/SERVING.md; `prepare(Cp) -> C` is the loop-invariant
        coefficient transform, traced once per jitted program like the
        unbatched variants' prep). "shard" runs the explicit exchange
        machinery — shard_map over the space×batch mesh, the per-lane
        local step vmapped over the leading lane axis, halo collectives
        per-space-axis only; "hide" the lane-batched comm/compute
        overlap (make_batched_overlap_step on the Cm contract — the
        exchange hides under the vmapped interior compute);
        "ap"/"fused" vmap the global-array step and let GSPMD partition
        the batched array. Every form is bitwise-equal per lane to the
        unbatched variant (the serving layer's parity contract)."""
        from rocm_mpi_tpu.ops.diffusion import step_fused_padded
        from rocm_mpi_tpu.parallel.halo import exchange_halo_batched

        cfg = self.config
        space = bgrid.space
        dt = cfg.jax_dtype(cfg.dt)

        if variant in ("ap", "fused"):
            raw = (step_flux_form if variant == "ap" else step_fused)

            def step(Tb, C):
                return jax.vmap(
                    lambda T: raw(T, C, cfg.lam, dt, cfg.spacing)
                )(Tb)

            return step, None

        if variant == "hide":
            return self._make_batched_hide_step(bgrid)

        if variant != "shard":
            raise ValueError(
                f"batched advance supports variants 'shard', 'hide', "
                f"'ap', 'fused'; got {variant!r} (the Pallas rungs "
                "are single-lane)"
            )

        wire_mode = cfg.wire_mode

        def lane_local(Tb_l, Cl):
            # Tb_l: (local_batch, *local_space); Cl: local space block.
            Tp = exchange_halo_batched(Tb_l, bgrid, wire_mode=wire_mode)
            mask = global_boundary_mask(space)

            def lane(Tl, Tpl):
                new = step_fused_padded(Tpl, Cl, cfg.lam, dt, cfg.spacing)
                return jnp.where(mask, Tl, new)

            return jax.vmap(lane)(Tb_l, Tp)

        def step(Tb, C):
            return shard_map(
                lane_local,
                mesh=bgrid.mesh,
                in_specs=(bgrid.spec, bgrid.aux_spec),
                out_specs=bgrid.spec,
                check_vma=False,
            )(Tb, C)

        return step, None

    def _make_batched_hide_step(self, bgrid):
        """The lane-batched overlap step (docs/SERVING.md "The
        pipeline"): the masked-seam hide vmapped over the lane axis —
        one width-1 exchange of the whole lane batch whose collectives
        are dataflow-independent of every interior box, so XLA hides
        the (lane-aggregate) exchange under the vmapped interior
        compute. Runs the Cm jnp twin (`ops.diffusion.step_cm_padded`)
        on every dtype — the same kernel the single-lane f64 hide and
        the CPU traffic audit lower, bitwise-equal to the Pallas Cm
        form — with the mask+divide folded into the prepared
        coefficient (`prepare`), so held cells come back unchanged
        from the region updates themselves."""
        from rocm_mpi_tpu.ops.diffusion import step_cm_padded
        from rocm_mpi_tpu.parallel.overlap import make_batched_overlap_step

        cfg = self.config
        space = bgrid.space
        dt = cfg.jax_dtype(cfg.dt)
        pu = lambda tp, cm, lam, dt_, spacing: step_cm_padded(
            tp, cm, spacing
        )
        batched_local = make_batched_overlap_step(
            bgrid, pu, cfg.b_width, mask_boundary=False,
            wire_mode=cfg.wire_mode,
        )

        def prepare(Cp):
            def local(Cpl):
                z = jnp.zeros_like(Cpl)
                return jnp.where(
                    global_boundary_mask(space), z, (dt * cfg.lam) / Cpl
                )

            return shard_map(
                local, mesh=bgrid.mesh, in_specs=(bgrid.aux_spec,),
                out_specs=bgrid.aux_spec, check_vma=False,
            )(Cp)

        def step(Tb, Cm):
            return shard_map(
                lambda Tb_l, Cml: batched_local(
                    Tb_l, Cml, cfg.lam, dt, cfg.spacing
                ),
                mesh=bgrid.mesh,
                in_specs=(bgrid.spec, bgrid.aux_spec),
                out_specs=bgrid.spec,
                check_vma=False,
            )(Tb, Cm)

        return step, prepare

    def batched_step_fn(self, bgrid, variant: str = "shard",
                        donate: bool = False):
        """jitted steady-state `step(Tb, C) -> Tb` — one batched step as
        its own program (what the perf traffic gate audits: per-lane
        compiled bytes of the B-lane program vs B× the single-lane
        ideal, rocm_mpi_tpu/perf/traffic.py). For variants with a
        prepared coefficient (hide), C is the PREPARED operand —
        `batched_prepare_fn` builds it, exactly as prepared_step_fn
        splits the single-lane audit surface."""
        step, _ = self._make_batched_step(bgrid, variant)
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    def batched_prepare_fn(self, bgrid, variant: str = "shard"):
        """jitted `prepare(Cp) -> C` for the batched variant's
        loop-invariant coefficient (identity for the prep-less
        variants) — the audit-surface companion of batched_step_fn."""
        _, prep = self._make_batched_step(bgrid, variant)
        if prep is None:
            return jax.jit(lambda C: C)
        return jax.jit(prep)

    def batched_advance_fn(
        self,
        batch: int | None = None,
        variant: str = "shard",
        bgrid=None,
        batch_dims: int = 1,
        devices=None,
    ):
        """(jitted `advance(Tb, Cp, lane_steps, n) -> Tb`, bgrid) — the
        multi-tenant batched advance (docs/SERVING.md): `Tb` is
        `(batch, *space)` lane-batched state sharded `bgrid.spec`; `Cp`
        the single space-shaped coefficient all lanes share;
        `lane_steps` a `(batch,)` int32 of per-lane step counts (the bin
        scheduler's steps padding: the batch runs `n` = max steps, a
        lane freezes bitwise once its own count is reached — the
        pass-through select is exact, so every lane is bitwise-equal to
        a standalone run of its own length); `n` the dynamic trip count.
        Donates Tb (rebind from the result; the lowered audit proves
        the aliasing from the compiled program —
        analysis/lowered.audit_batched_drivers). One compiled program
        serves any lane_steps/n mix — the bin scheduler's
        compile-amortization contract (`compiles.steady_state == 0`).
        Variant "hide" runs the lane-batched overlap step with its Cm
        coefficient prepared once inside the jitted program, exactly
        like the unbatched drivers' prep."""
        if bgrid is None:
            if batch is None:
                raise ValueError("pass batch= or a prebuilt bgrid=")
            bgrid = self.make_batched_grid(batch, batch_dims, devices)
        step, prep = self._make_batched_step(bgrid, variant)
        shape1 = (-1,) + (1,) * bgrid.space.ndim

        @functools.partial(jax.jit, donate_argnums=0)
        def advance(Tb, Cp, lane_steps, n):
            C = Cp if prep is None else prep(Cp)

            def body(i, T):
                new = step(T, C)
                active = (i < lane_steps).reshape(shape1)
                return jnp.where(active, new, T)

            return lax.fori_loop(0, n, body, Tb)

        return advance, bgrid

    def batched_ladder_advance_fn(
        self,
        batch: int | None = None,
        bgrid=None,
        batch_dims: int = 1,
        devices=None,
    ):
        """(jitted `advance(Tb, Cp, hold, dt_lam, inv_d2, lane_steps, n) ->
        Tb`, bgrid) — the LADDER edition of the batched advance
        (docs/SERVING.md "Continuous batching"): this model's shape is
        the ladder RUNG, and each lane may embed a smaller original
        domain at the origin corner. Geometry rides traced per-lane
        operands instead of trace constants, so ONE compiled program
        serves every original shape on the rung:

          * `hold` — (batch, *space) bool, True on a lane's held cells:
            its original domain's global Dirichlet ring AND every cell
            outside the embedded domain (pad cells freeze bitwise at
            their initial value, exactly like a finished lane's steps);
          * `dt_lam` — (batch,) per-lane dt·λ; `inv_d2` — a TUPLE of
            ndim (batch,) per-axis reciprocal spacing² operands — dt·λ
            multiplied in the compute dtype, each reciprocal rounded
            exactly as XLA folds the standalone divide-by-constant
            (ops.diffusion.step_fused_padded_geom has the ulp
            rationale).

        Because the held ring separates each embedded interior from the
        padding, interior cells read only original-domain values — every
        lane is bitwise-equal to its standalone run ('shard' variant,
        lossless 'f32' wire; the service gates eligibility). Donates Tb.
        """
        if bgrid is None:
            if batch is None:
                raise ValueError("pass batch= or a prebuilt bgrid=")
            bgrid = self.make_batched_grid(batch, batch_dims, devices)
        step = self.batched_ladder_step_fn(bgrid)
        shape1 = (-1,) + (1,) * bgrid.space.ndim

        @functools.partial(jax.jit, donate_argnums=0)
        def advance(Tb, Cp, hold, dt_lam, inv_d2, lane_steps, n):
            def body(i, T):
                new = step(T, Cp, hold, dt_lam, *inv_d2)
                active = (i < lane_steps).reshape(shape1)
                return jnp.where(active, new, T)

            return lax.fori_loop(0, n, body, Tb)

        return advance, bgrid

    def batched_ladder_step_fn(self, bgrid):
        """The UNJITTED per-step program of `batched_ladder_advance_fn` —
        `step(Tb, Cp, hold, dt_lam, *inv_d2) -> Tb`, the shard_map'd
        body the advance's fori_loop repeats. Exposed separately so the
        traffic audit can price ONE ladder step (the HLO byte model
        reads the entry computation only; a loop body would be
        invisible to it).

        inv_d2 rides as ndim SEPARATE per-lane scalar operands, not one
        indexed (batch, ndim) vector: inside the fori_loop body XLA
        fuses the gathered-vector form differently from the standalone's
        folded constants and drifts a ulp — per-axis scalar operands
        compile to the identical multiplies
        (ops.diffusion.step_fused_padded_geom has the full story).
        """
        from rocm_mpi_tpu.ops.diffusion import step_fused_padded_geom
        from rocm_mpi_tpu.parallel.halo import exchange_halo_batched

        wire_mode = self.config.wire_mode
        ndim = bgrid.space.ndim

        def lane_local(Tb_l, Cl, Hb_l, dtlam_l, *invd2_l):
            Tp = exchange_halo_batched(Tb_l, bgrid, wire_mode=wire_mode)

            def lane(Tl, Tpl, Hl, a, *gs):
                new = step_fused_padded_geom(Tpl, Cl, a, gs)
                return jnp.where(Hl, Tl, new)

            return jax.vmap(lane)(Tb_l, Tp, Hb_l, dtlam_l, *invd2_l)

        return shard_map(
            lane_local,
            mesh=bgrid.mesh,
            in_specs=(bgrid.spec, bgrid.aux_spec, bgrid.spec,
                      bgrid.batch_spec)
            + (bgrid.batch_spec,) * ndim,
            out_specs=bgrid.spec,
            check_vma=False,
        )

    def batched_deep_advance_fn(
        self,
        batch: int | None = None,
        block_steps: int | None = None,
        bgrid=None,
        batch_dims: int = 1,
        devices=None,
        wire_mode: str | None = None,
    ):
        """(jitted `advance(Tb, Cp, n) -> Tb`, bgrid, k) — the deep-halo
        schedule against the space×batch mesh (make_deep_sweep with a
        BatchedGrid): one width-k exchange of the whole lane batch per k
        steps, the vmapped jnp local sweep. Uniform steps only (`n` a
        multiple of k for every lane — the bin scheduler routes
        heterogeneous-step bins to the per-step batched advance)."""
        from rocm_mpi_tpu.parallel.deep_halo import make_deep_sweep

        cfg = self.config
        if bgrid is None:
            if batch is None:
                raise ValueError("pass batch= or a prebuilt bgrid=")
            bgrid = self.make_batched_grid(batch, batch_dims, devices)
        k = block_steps
        if k is None:
            from rocm_mpi_tpu.ops.pallas_kernels import _compute_itemsize

            k = default_deep_depth(
                bgrid.space.local_shape, _compute_itemsize(cfg.jax_dtype)
            )
        wm = cfg.wire_mode if wire_mode is None else wire_mode
        dt = cfg.jax_dtype(cfg.dt)
        sched = make_deep_sweep(bgrid, k, cfg.lam, dt, cfg.spacing,
                                wire_mode=wm)

        @functools.partial(jax.jit, donate_argnums=0)
        def advance(Tb, Cp, n):
            Cm = sched.prepare(Cp)
            return lax.fori_loop(
                0, n // k, lambda _, x: sched.sweep(x, Cm), Tb
            )

        return advance, bgrid, sched.k

    # ---- driver ---------------------------------------------------------

    def run(
        self, variant: str = "ap", nt: int | None = None,
        warmup: int | None = None, driver: str = "step",
        config: str | None = None,
    ) -> RunResult:
        """Run `nt` steps; time all but the first `warmup` (perf.jl:47-53).

        `driver` selects the multi-step loop form: "step" is the classic
        per-step fori_loop advance; "scan" the donation-aware lax.scan
        driver (scan_advance_fn — allocation-free steady state). Both run
        the same step program in the same order; results are bitwise
        identical. The host-staged oracle path ignores the driver (it is
        a numpy loop). `config="auto"` lets the scan driver's chunk
        consult the tuning cache (scan_advance_fn).
        """
        cfg = self.config
        nt = cfg.nt if nt is None else nt
        warmup = cfg.warmup if warmup is None else warmup
        if not 0 <= warmup < nt:
            raise ValueError(f"need 0 <= warmup < nt, got {warmup}, {nt}")
        if driver not in ("step", "scan"):
            raise ValueError(f"driver must be 'step' or 'scan', got {driver!r}")
        if cfg.halo_transport == "host":
            if variant == "shard":
                return self._run_host_staged(nt, warmup)
            warn_host_transport_ignored(variant)
        if cfg.wire_mode != "f32" and variant in ("ap", "fused"):
            import warnings

            warnings.warn(
                f"wire_mode={cfg.wire_mode!r} is not honored by variant "
                f"{variant!r} — the GSPMD global-array variants have no "
                "explicit exchange to encode; use shard/perf/hide or the "
                "deep schedule.",
                stacklevel=2,
            )
        T, Cp = self.init_state()
        if driver == "scan":
            # q divides both windows by construction (gcd).
            advance, _ = self.scan_advance_fn(variant, nt=nt, warmup=warmup,
                                              config=config)
        else:
            advance = self.advance_fn(variant)
        timer = metrics.Timer(label="step_window", phase="step",
                              steps=nt - warmup, variant=variant,
                              driver=driver, workload="diffusion")
        if warmup:
            T = advance(T, Cp, warmup)
        timer.tic(T)
        T = advance(T, Cp, nt - warmup)
        wtime = timer.toc(T)
        return RunResult(T=T, wtime=wtime, nt=nt, warmup=warmup, config=cfg)

    def _run_single_shard(
        self, nt, warmup, multi_step_fn, granularity: int, granularity_kw: str,
        explicit: bool = False, extra_kw=None, program_cache=None,
    ) -> RunResult:
        """Shared scaffold of the single-shard fast paths: validate, pick a
        step granularity dividing both the warmup and timed windows (so one
        compiled program, built outside the timed window, serves both — the
        outer trip count stays dynamic), then tic/advance/toc.

        `multi_step_fn(T, Cp, lam, dt, spacing, n, <granularity_kw>=g)` is
        one of ops.pallas_kernels.fused_multi_step / fused_multi_step_hbm.
        `explicit` marks a caller-requested granularity: degradation (gcd
        against the windows, or the large-field chunk cap) then warns
        instead of staying silent.

        `program_cache` (a caller-held dict) keys the jitted advance by
        the full trace identity — physics config, granularity, kernel
        kwargs — so two runs of the SAME configuration reuse one
        compiled program instead of re-tracing per call (jax's jit cache
        keys on function identity, and each call here otherwise builds a
        fresh closure). bench.py's kernel-form ladder holds one dict
        across its rungs; the step counts stay out of the key on purpose
        (they ride the dynamic `n`).
        """
        cfg = self.config
        nt = cfg.nt if nt is None else nt
        warmup = cfg.warmup if warmup is None else warmup
        if not 0 <= warmup < nt:
            raise ValueError(f"need 0 <= warmup < nt, got {warmup}, {nt}")
        if self.grid.nprocs != 1:
            raise ValueError("single-shard fast paths require an unsharded grid")
        key = granularity_kw
        gran = effective_block_steps(
            nt, warmup, granularity, warn=explicit, label=key, stacklevel=4
        )

        T, Cp = self.init_state()
        dt = cfg.jax_dtype(cfg.dt)

        kw = {key: gran}
        if key == "chunk":
            kw["warn_on_cap"] = explicit
        if extra_kw:
            kw.update(extra_kw)

        cache_key = None
        advance = None
        if program_cache is not None:
            cache_key = (
                getattr(multi_step_fn, "__qualname__", repr(multi_step_fn)),
                cfg.global_shape, cfg.lengths, cfg.dtype,
                cfg.lam, cfg.cp0,
                tuple(sorted(kw.items())),
            )
            advance = program_cache.get(cache_key)

        if advance is None:

            @functools.partial(jax.jit, donate_argnums=0)
            def advance(T, Cp, n):
                return multi_step_fn(T, Cp, cfg.lam, dt, cfg.spacing, n, **kw)

            if cache_key is not None:
                program_cache[cache_key] = advance

        timer = metrics.Timer(label="step_window", phase="step",
                              steps=nt - warmup, variant=key,
                              workload="diffusion")
        T = advance(T, Cp, warmup)  # n=0 still compiles the shared program
        timer.tic(T)
        T = advance(T, Cp, nt - warmup)
        wtime = timer.toc(T)
        return RunResult(T=T, wtime=wtime, nt=nt, warmup=warmup, config=cfg)

    def run_vmem_resident(
        self,
        nt: int | None = None,
        warmup: int | None = None,
        chunk: int | None = None,
        body_form: str | None = None,
        pad_pow2: bool | None = None,
        config: str | None = None,
        program_cache: dict | None = None,
    ) -> RunResult:
        """Single-shard fast path: the whole nt-step loop inside one Pallas
        kernel, field VMEM-resident (ops.pallas_kernels.fused_multi_step).

        TPU-only optimization with no reference analog; only valid when the
        grid is unsharded (nprocs == 1) and fits the VMEM budget.

        `chunk` overrides the per-kernel step count (default
        DEFAULT_STEP_CHUNK): Mosaic compile time scales with the unroll, so
        a small chunk (e.g. 16) compiles in seconds where 256 takes tens —
        bench.py's floor measurement depends on this knob.

        `body_form`/`pad_pow2` select the kernel-form A/B candidates as
        trace-time kwargs (bench.py's stage-2.5 ladder); None keeps the
        module-constant hardware defaults. `config="auto"` fills any knob
        left None from the persistent tuning cache instead
        (tuning/resolve.py; a miss keeps the defaults, bitwise) — the
        resolution happens HERE, outside any trace, and the winners
        travel down as the same explicit kwargs. `program_cache` reuses
        compiled advances across same-config runs (_run_single_shard).
        """
        import rocm_mpi_tpu.ops.pallas_kernels as _pk
        from rocm_mpi_tpu.ops.pallas_kernels import (
            DEFAULT_STEP_CHUNK,
            fused_multi_step,
        )

        cfg = self.config
        if config == "auto":
            from rocm_mpi_tpu.tuning import resolve as tuning_resolve

            tuned = tuning_resolve.resolve(
                "diffusion.vmem_loop", cfg.global_shape, cfg.jax_dtype
            ) or {}
            if chunk is None and _pk.adoptable_vmem_chunk(
                tuned.get("chunk")
            ):
                chunk = tuned["chunk"]
                # Auto-resolved, not caller-requested: the gcd against
                # the windows below must not warn (explicit stays False).
                auto_chunk = True
            else:
                auto_chunk = False
            if body_form is None:
                body_form = tuned.get("body_form")
            if pad_pow2 is None:
                pad_pow2 = tuned.get("pad_pow2")
        elif config in (None, "default"):
            auto_chunk = False
        else:
            raise ValueError(
                f"config must be None, 'default' or 'auto', got {config!r}"
            )
        # Normalize the knobs to their effective values HERE (the same
        # resolution plan_vmem_loop would do at trace time): the
        # program-cache key must see "None" and the module default as
        # the identical trace they are, or bench's winner re-run would
        # re-trace the program its calibration rung already compiled.
        if body_form is None:
            body_form = _pk.EQC_BODY_FORM
        if pad_pow2 is None:
            pad_pow2 = _pk.VMEM_PAD_POW2
        return self._run_single_shard(
            nt,
            warmup,
            fused_multi_step,
            DEFAULT_STEP_CHUNK if chunk is None else chunk,
            "chunk",
            explicit=chunk is not None and not auto_chunk,
            extra_kw={"body_form": body_form, "pad_pow2": pad_pow2},
            program_cache=program_cache,
        )

    def run_hbm_blocked(
        self,
        nt: int | None = None,
        warmup: int | None = None,
        block_steps: int | None = None,
    ) -> RunResult:
        """Single-shard large-grid fast path: temporal blocking — every HBM
        sweep advances the field `block_steps` steps
        (ops.pallas_kernels.fused_multi_step_hbm), beating the 3-passes-per-
        step bound the reference's fused kernel is built around
        (perf.jl:55). Only valid when the grid is unsharded; the sharded
        variants keep per-step halo semantics.
        """
        from rocm_mpi_tpu.ops.pallas_kernels import (
            DEFAULT_TB_STEPS,
            fused_multi_step_hbm,
        )

        cfg = self.config
        k = DEFAULT_TB_STEPS if block_steps is None else block_steps
        nt_v = cfg.nt if nt is None else nt
        wu_v = cfg.warmup if warmup is None else warmup
        effective_block_steps(
            nt_v, wu_v, k, label="temporal blocking block_steps", stacklevel=2
        )
        return self._run_single_shard(
            nt, warmup, fused_multi_step_hbm, k, "block_steps"
        )

    def effective_deep_depth(
        self,
        nt: int | None = None,
        warmup: int | None = None,
        block_steps: int | None = None,
        warn: bool = True,
        config: str | None = None,
    ) -> int:
        """The sweep depth run_deep will actually execute for these
        arguments — THE source of truth for callers labeling artifacts by
        depth (apps/_common.py), so label and executed k cannot drift.
        Policy: defaults route through default_deep_depth (VMEM-aware,
        shard-clamped) — unless `config="auto"` finds a tuned depth for
        this shard/topology in the tuning cache
        (parallel.deep_halo.resolve_deep_k; note a different k is a
        different sweep SCHEDULE, fp-reordered vs the default depth, not
        a bitwise-neutral knob like the kernel forms); explicit depths
        keep make_deep_sweep's strict shard-extent validation; any of
        the three is then gcd'd against both timing windows.
        """
        cfg = self.config
        if block_steps is None:
            from rocm_mpi_tpu.ops.pallas_kernels import _compute_itemsize
            from rocm_mpi_tpu.parallel.deep_halo import resolve_deep_k

            k = resolve_deep_k(self.grid, cfg.jax_dtype, config)
            if k is None:
                # bf16 is storage-only in the local kernels (f32
                # in-kernel): size the depth at the compute width.
                k = default_deep_depth(
                    self.grid.local_shape, _compute_itemsize(cfg.jax_dtype)
                )
        else:
            k = block_steps
        return effective_block_steps(
            cfg.nt if nt is None else nt,
            cfg.warmup if warmup is None else warmup,
            k,
            label="deep-halo sweep depth",
            warn=warn,
            stacklevel=3,
        )

    def effective_wire_mode(
        self, wire_mode: str | None = None, config: str | None = None,
    ) -> str:
        """The state exchange's on-wire precision a deep run will use:
        an explicit `wire_mode` wins, else `config="auto"` consults the
        tuning cache (the PR-12 wire axis of the "diffusion.deep"
        entry), else the config's wire_mode field (default "f32")."""
        if wire_mode is not None:
            return wire_mode
        from rocm_mpi_tpu.parallel.deep_halo import resolve_deep_config

        tuned = resolve_deep_config(
            self.grid, self.config.jax_dtype, config
        )["wire_mode"]
        return tuned if tuned is not None else self.config.wire_mode

    def deep_advance_fn(
        self,
        block_steps: int | None = None,
        nt: int | None = None,
        warmup: int | None = None,
        config: str | None = None,
        wire_mode: str | None = None,
    ):
        """(jitted (T, Cp, n_steps) -> T, executed depth k) — the deep
        schedule's advance as a first-class function, so callers beyond
        run_deep (the --checkpoint segmented loop) can drive the sweep.
        `n_steps` must be a multiple of k (the fori_loop trip count
        floors) — the step-count convention every model's deep advance
        shares (wave/swe match). `config="auto"` lets an unset
        block_steps (and an unset wire_mode) consult the tuning cache
        (effective_deep_depth / effective_wire_mode). For the stateful
        wire modes the advance carries the exchange state internally
        (zero-initialized per call — the first-sweep contract) and still
        returns just T."""
        from rocm_mpi_tpu.parallel.deep_halo import make_deep_sweep

        cfg = self.config
        if cfg.halo_transport == "host":
            # The warning lives with the schedule builder so EVERY deep
            # caller (run_deep, the --checkpoint segmented loop) gets it.
            warn_host_transport_ignored("deep", stacklevel=3)
        k = self.effective_deep_depth(nt, warmup, block_steps,
                                      config=config)
        wm = self.effective_wire_mode(wire_mode, config)
        dt = cfg.jax_dtype(cfg.dt)
        sched = make_deep_sweep(self.grid, k, cfg.lam, dt, cfg.spacing,
                                wire_mode=wm)

        if sched.init_wire is None:

            @functools.partial(jax.jit, donate_argnums=0)
            def advance(T, Cp, n_steps):
                # The time-invariant coefficient's width-k exchange +
                # masking runs ONCE per compiled advance, outside the
                # sweep loop — the loop carries only the bare field
                # (DeepSchedule contract).
                Cm = sched.prepare(Cp)
                return lax.fori_loop(
                    0, n_steps // k, lambda _, x: sched.sweep(x, Cm), T
                )

        else:

            @functools.partial(jax.jit, donate_argnums=0)
            def advance(T, Cp, n_steps):
                Cm = sched.prepare(Cp)
                ws0 = sched.init_wire(T.dtype)

                def body(_, carry):
                    T_, ws = carry
                    return sched.sweep(T_, Cm, ws)

                T_out, _ws = lax.fori_loop(
                    0, n_steps // k, body, (T, ws0)
                )
                return T_out

        return advance, k

    def run_deep(
        self,
        nt: int | None = None,
        warmup: int | None = None,
        block_steps: int | None = None,
        config: str | None = None,
        wire_mode: str | None = None,
    ) -> RunResult:
        """Sharded fast path: deep-halo sweeps (parallel.deep_halo) — one
        width-k ghost exchange per k steps, the multi-chip form of temporal
        blocking. Works on any mesh (including 1 device, where it reduces
        to the VMEM-resident loop plus crop overhead). f32/bf16 only on
        real TPUs (the local kernel is Pallas). Default depth 32 — the
        measured single-chip optimum at 252² with the A/c kernel (r3:
        k=8 1.02 µs/step, k=16 0.889, k=32 0.848); on a pod slice larger
        k also divides the message count further. Mid-size shards prefer
        the deepest VMEM-fitting depth; HBM-resident shards cap the
        default at 8 (default_deep_depth).
        """
        cfg = self.config
        nt = cfg.nt if nt is None else nt
        warmup = cfg.warmup if warmup is None else warmup
        if not 0 <= warmup < nt:
            raise ValueError(f"need 0 <= warmup < nt, got {warmup}, {nt}")
        advance, _ = self.deep_advance_fn(
            block_steps=block_steps, nt=nt, warmup=warmup, config=config,
            wire_mode=wire_mode,
        )
        T, Cp = self.init_state()
        timer = metrics.Timer(label="step_window", phase="step",
                              steps=nt - warmup, variant="deep",
                              workload="diffusion")
        T = advance(T, Cp, warmup)
        timer.tic(T)
        T = advance(T, Cp, nt - warmup)
        wtime = timer.toc(T)
        return RunResult(T=T, wtime=wtime, nt=nt, warmup=warmup, config=cfg)

    def _run_host_staged(self, nt: int, warmup: int) -> RunResult:
        """Debug oracle: numpy stepper with host-staged halos
        (IGG_ROCMAWARE_MPI=0 analog; parallel.halo.HostStagedStepper)."""
        import numpy as np

        from rocm_mpi_tpu.parallel.halo import HostStagedStepper

        if jax.process_count() > 1:
            raise NotImplementedError(
                "halo_transport='host' is a single-process debug oracle; it "
                "needs every shard host-addressable. Run it on one host "
                "(virtual devices) to bisect transport vs math."
            )
        cfg = self.config
        T, Cp = self.init_state()
        T_np, Cp_np = np.asarray(T), np.asarray(Cp)
        stepper = HostStagedStepper(self.grid, cfg.lam, cfg.dt,
                                    wire_mode=cfg.wire_mode)
        timer = metrics.Timer(label="step_window", phase="step",
                              steps=nt - warmup, variant="shard-host",
                              workload="diffusion")
        T_np = stepper.run(T_np, Cp_np, warmup)
        timer.tic()
        T_np = stepper.run(T_np, Cp_np, nt - warmup)
        wtime = timer.toc()
        T_out = jax.device_put(T_np, self.grid.sharding)
        return RunResult(T=T_out, wtime=wtime, nt=nt, warmup=warmup, config=cfg)
