"""Physics models: the diffusion flagship at each performance level, plus
the acoustic-wave workload (the framework-generality demo)."""

from rocm_mpi_tpu.models.diffusion import HeatDiffusion, RunResult  # noqa: F401
from rocm_mpi_tpu.models.wave import AcousticWave, WaveConfig  # noqa: F401
