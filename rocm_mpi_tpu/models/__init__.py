"""Physics models: the diffusion workloads at each performance level."""
