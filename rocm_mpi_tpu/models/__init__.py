"""Physics models: the diffusion workloads at each performance level."""

from rocm_mpi_tpu.models.diffusion import HeatDiffusion, RunResult  # noqa: F401
