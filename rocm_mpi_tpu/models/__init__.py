"""Physics models: the diffusion flagship at each performance level, plus
the acoustic-wave and shallow-water workloads (the framework-generality
demos — single-field, state-pair, and coupled-multi-field stencils)."""

from rocm_mpi_tpu.models.diffusion import HeatDiffusion, RunResult  # noqa: F401
from rocm_mpi_tpu.models.swe import SWEConfig, ShallowWater  # noqa: F401
from rocm_mpi_tpu.models.wave import AcousticWave, WaveConfig  # noqa: F401
