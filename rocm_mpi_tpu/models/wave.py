"""Acoustic wave equation — the framework's second workload.

Purpose: demonstrate that the framework layers the diffusion flagship is
built from — cartesian mesh (parallel.mesh), ppermute halo exchange
(parallel.halo), Pallas padded-block kernels (ops.*), fetch-forced timers
(utils.metrics) — are workload-agnostic. This is what a *user* adding their
own stencil model to the framework writes; the reference has no analog (it
ships exactly one physics model), so this module is additive, not parity.

Physics: u_tt = c² ∇²u with Dirichlet boundaries (edge cells held at their
initial values — the same boundary design as the diffusion model, reusing
the zero-ghost halo convention). Leapfrog (central-difference) time
stepping over the state pair (U, U_prev):

    U⁺ = 2U − U⁻ + dt²·c²·∇²U

which is second-order accurate and exactly time-reversible — the
reversibility test in tests/test_wave.py runs the trajectory backward to
its initial state at rounding-level tolerance, a correctness check the
dissipative diffusion model cannot offer.

Variants mirror the flagship's ladder where it transfers:
  "ap"   — global-array jnp ops; GSPMD partitions and inserts comms.
  "perf" — shard_map + exchange_halo + whole-block Pallas kernel
           (ops.wave_kernels), explicit Dirichlet mask.
  "hide" — the masked-contract kernel (ops.wave_kernels
           .wave_step_padded_masked_pallas) on the boundary-slab/interior
           overlap decomposition (parallel.overlap): the U exchange is
           dataflow-independent of the interior update, so XLA may hide
           it — the second workload on the reference's intended variant
           (3) schedule (hide.jl:94-101). The Dirichlet hold rides the
           prepared (M, Cw) data operands (a branch-free select,
           fp-identical to perf on updating cells), so no trailing
           whole-shard `where` and no per-step mask rebuild.
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from rocm_mpi_tpu.utils.compat import shard_map

from rocm_mpi_tpu.config import DTYPES
from rocm_mpi_tpu.ops.diffusion import gaussian_ic
from rocm_mpi_tpu.ops.stencil import inn
from rocm_mpi_tpu.ops.wave_kernels import wave_step_padded  # noqa: F401  (re-export)
from rocm_mpi_tpu.parallel.halo import exchange_halo, global_boundary_mask
from rocm_mpi_tpu.parallel.mesh import GlobalGrid, init_global_grid
from rocm_mpi_tpu.utils import metrics


@dataclasses.dataclass(frozen=True)
class WaveConfig:
    """Knobs of a wave run (same shape-vocabulary as DiffusionConfig)."""

    global_shape: tuple[int, ...] = (128, 128)
    lengths: tuple[float, ...] = (10.0, 10.0)
    c0: float = 1.0  # wave speed
    cfl: float = 0.5  # Courant number, < 1 (dt already has the 1/√ndim factor)
    nt: int = 1000
    warmup: int = 10
    dtype: str = "f64"
    dims: tuple[int, ...] | None = None
    # Boundary-frame width of the hide variant (the reference's b_width
    # knob, hide.jl:42 — same default as DiffusionConfig; clamped per-shard
    # by parallel.overlap.effective_b_width).
    b_width: tuple[int, ...] = (32, 4)
    # On-wire halo slab precision (parallel/wire.py; same contract as
    # DiffusionConfig.wire_mode — stateful modes are deep-only).
    wire_mode: str = "f32"

    def __post_init__(self):
        if len(self.lengths) != len(self.global_shape):
            raise ValueError("lengths rank must match global_shape rank")
        if self.dtype not in DTYPES:
            raise ValueError(f"dtype must be one of {sorted(DTYPES)}")
        from rocm_mpi_tpu.parallel import wire

        wire.validate_mode(self.wire_mode)

    @property
    def ndim(self) -> int:
        return len(self.global_shape)

    @property
    def jax_dtype(self):
        return DTYPES[self.dtype]

    @property
    def spacing(self) -> tuple[float, ...]:
        return tuple(l / n for l, n in zip(self.lengths, self.global_shape))

    @property
    def dt(self) -> float:
        """CFL-stable leapfrog step: cfl·min(d)/(c0·√ndim)."""
        return (
            self.cfl * min(self.spacing) / (self.c0 * math.sqrt(self.ndim))
        )


def wave_step_fused(U, Uprev, C2, dt, spacing):
    """Global-array leapfrog step; edge cells pass through unchanged."""
    core = tuple(slice(1, -1) for _ in range(U.ndim))
    return U.at[core].set(
        wave_step_padded(U, inn(Uprev), inn(C2), dt, spacing)
    )


@dataclasses.dataclass
class WaveRunResult:
    U: jax.Array
    wtime: float
    nt: int
    warmup: int
    config: WaveConfig

    @property
    def wtime_it(self) -> float:
        return metrics.wtime_per_it(self.wtime, self.nt, self.warmup)

    @property
    def t_eff(self) -> float:
        # 4 whole-array passes per step: read U, U_prev, C2; write U⁺.
        return metrics.t_eff_gbs(
            self.U.shape, self.U.dtype.itemsize, self.wtime_it, n_passes=4
        )

    @property
    def gpts(self) -> float:
        return metrics.gpts_per_s(self.U.shape, self.wtime_it)


class AcousticWave:
    """Leapfrog acoustic wave on a sharded global grid."""

    def __init__(
        self,
        config: WaveConfig,
        grid: GlobalGrid | None = None,
        devices=None,
    ):
        self.config = config
        if grid is None:
            grid = init_global_grid(
                *config.global_shape,
                lengths=config.lengths,
                dims=config.dims,
                devices=devices,
            )
        self.grid = grid

    def init_state(self):
        """(U, U_prev, C2): Gaussian displacement at rest, uniform c²."""
        cfg, grid = self.config, self.grid
        dtype = cfg.jax_dtype

        @functools.partial(jax.jit, out_shardings=grid.sharding)
        def make_U():
            return gaussian_ic(
                grid.coord_mesh(dtype=dtype), cfg.lengths, dtype=dtype
            )

        @functools.partial(jax.jit, out_shardings=grid.sharding)
        def make_C2():
            return jnp.full(
                grid.global_shape, cfg.c0 * cfg.c0, dtype=dtype
            )

        U = make_U()
        return U, jnp.copy(U), make_C2()

    def _mask_prepare(self):
        """prepare(C2) -> (M, Cw): the interior mask (1.0 on updating
        cells, exactly 0.0 on the global Dirichlet edge) and the masked
        coefficient Cw = dt²·c²·M — the wave edition of the diffusion Cm
        contract, computed ONCE per jitted program from global-array ops
        (GSPMD shards them like the state). The leapfrog needs M itself
        because a zeroed coefficient alone gives 2U − U⁻ ≠ U
        (ops/wave_kernels.py module docstring)."""
        cfg, grid = self.config, self.grid
        dt = cfg.jax_dtype(cfg.dt)
        dt2 = dt * dt

        def prepare(C2):
            from rocm_mpi_tpu.ops.wave_kernels import interior_mask

            M = interior_mask(grid.global_shape, C2.dtype)
            return M, dt2 * C2 * M

        return prepare

    def _step(self, variant: str):
        """(step, prepare): `step(U, Uprev, C2, P) -> (U⁺, U)` with `P`
        the loop-invariant operands `prepare(C2)` builds once per jitted
        program (None for variants that need none)."""
        cfg, grid = self.config, self.grid
        dt = cfg.jax_dtype(cfg.dt)

        if variant == "ap":

            def step(U, Uprev, C2, P):
                del P
                return wave_step_fused(U, Uprev, C2, dt, cfg.spacing), U

            return step, None
        if variant == "shard":
            # The explicit-decomposition jnp rung (the diffusion model's
            # "shard" vocabulary): exchange_halo + the pure-jnp padded
            # leapfrog update + Dirichlet mask. Pallas-free by
            # construction — the f64-safe explicit path on TPU, and the
            # per-lane body the batched multi-tenant advance vmaps
            # (docs/SERVING.md: batched results must be bitwise-equal to
            # a standalone run of the SAME op sequence).
            def step(U, Uprev, C2, P):
                del P

                def local(Ul, Upl, C2l):
                    pad = exchange_halo(Ul, grid, wire_mode=cfg.wire_mode)
                    new = wave_step_padded(pad, Upl, C2l, dt, cfg.spacing)
                    return jnp.where(global_boundary_mask(grid), Ul, new)

                new = shard_map(
                    local,
                    mesh=grid.mesh,
                    in_specs=(grid.spec,) * 3,
                    out_specs=grid.spec,
                    check_vma=False,
                )(U, Uprev, C2)
                return new, U

            return step, None
        if variant == "perf":
            from rocm_mpi_tpu.ops.wave_kernels import wave_step_padded_pallas

            def step(U, Uprev, C2, P):
                del P

                def local(Ul, Upl, C2l):
                    pad = exchange_halo(Ul, grid,
                                        wire_mode=cfg.wire_mode)
                    new = wave_step_padded_pallas(
                        pad, Upl, C2l, dt, cfg.spacing
                    )
                    return jnp.where(global_boundary_mask(grid), Ul, new)

                new = shard_map(
                    local,
                    mesh=grid.mesh,
                    in_specs=(grid.spec,) * 3,
                    out_specs=grid.spec,
                    check_vma=False,
                )(U, Uprev, C2)
                return new, U

            return step, None
        if variant == "hide":
            # Comm/compute overlap for the leapfrog (VERDICT r3 #5): the
            # same boundary-slab/interior decomposition as the diffusion
            # flagship's hide rung (parallel.overlap, the reference's
            # intended variant (3) semantics, hide.jl:94-101) — only U is
            # exchanged; (U_prev, M, Cw) ride along as core-only aux
            # operands. Mask-as-data contract: the Dirichlet hold is a
            # branch-free select inside the region kernel (bitwise-
            # identical to perf's expression on updating cells), so no
            # trailing whole-shard `where` and no per-step mask rebuild.
            from rocm_mpi_tpu.ops.wave_kernels import (
                wave_step_padded_masked_pallas,
            )
            from rocm_mpi_tpu.parallel.overlap import make_overlap_step

            if grid.nprocs == 1:
                # No neighbors → nothing to hide; strip bookkeeping is pure
                # overhead. Route to perf (same policy as the diffusion
                # model's single-device hide).
                return self._step("perf")

            def pu(tp, aux, lam, dt_, spacing):
                del lam, dt_
                return wave_step_padded_masked_pallas(
                    tp, aux[0], aux[1], aux[2], spacing
                )

            local = make_overlap_step(
                grid, pu, cfg.b_width, mask_boundary=False,
                wire_mode=cfg.wire_mode,
            )

            def step(U, Uprev, C2, P):
                M, Cw = P
                new = shard_map(
                    lambda Ul, Upl, Ml, Cwl: local(
                        Ul, (Upl, Ml, Cwl), None, dt, cfg.spacing
                    ),
                    mesh=grid.mesh,
                    in_specs=(grid.spec,) * 4,
                    out_specs=grid.spec,
                    check_vma=False,
                )(U, Uprev, M, Cw)
                return new, U

            return step, self._mask_prepare()
        raise ValueError(
            f"unknown wave variant {variant!r} (ap, shard, perf, hide)"
        )

    # ---- multi-tenant batching (docs/SERVING.md) ------------------------

    def make_batched_grid(self, batch: int, batch_dims: int = 1,
                          devices=None):
        """Space×batch mesh for `batch` lanes of this model's space
        problem (see HeatDiffusion.make_batched_grid)."""
        from rocm_mpi_tpu.parallel.mesh import init_batched_grid

        cfg = self.config
        return init_batched_grid(
            batch,
            *cfg.global_shape,
            lengths=cfg.lengths,
            space_dims=self.grid.dims,
            batch_dims=batch_dims,
            devices=devices,
        )

    def _make_batched_step(self, bgrid, variant: str):
        """(`step(Ub, Upb, C2) -> (Ub⁺, Ub)`, prepare-or-None) over
        lane-batched leapfrog state; `C2` is the UNBATCHED squared wave
        speed every lane shares. Same vocabulary (and return
        convention) as HeatDiffusion._make_batched_step."""
        from rocm_mpi_tpu.parallel.halo import exchange_halo_batched

        cfg = self.config
        space = bgrid.space
        dt = cfg.jax_dtype(cfg.dt)

        if variant == "ap":

            def step(Ub, Upb, C2):
                new = jax.vmap(
                    lambda U, Up: wave_step_fused(U, Up, C2, dt,
                                                  cfg.spacing)
                )(Ub, Upb)
                return new, Ub

            return step, None

        if variant != "shard":
            raise ValueError(
                f"batched wave advance supports variants 'shard', 'ap'; "
                f"got {variant!r} (the Pallas/overlap rungs are "
                "single-lane)"
            )

        def lane_local(Ub_l, Upb_l, C2l):
            pad = exchange_halo_batched(Ub_l, bgrid,
                                        wire_mode=cfg.wire_mode)
            mask = global_boundary_mask(space)

            def lane(Ul, Upl, padl):
                new = wave_step_padded(padl, Upl, C2l, dt, cfg.spacing)
                return jnp.where(mask, Ul, new)

            return jax.vmap(lane)(Ub_l, Upb_l, pad)

        def step(Ub, Upb, C2):
            new = shard_map(
                lane_local,
                mesh=bgrid.mesh,
                in_specs=(bgrid.spec, bgrid.spec, bgrid.aux_spec),
                out_specs=bgrid.spec,
                check_vma=False,
            )(Ub, Upb, C2)
            return new, Ub

        return step, None

    def batched_advance_fn(
        self,
        batch: int | None = None,
        variant: str = "shard",
        bgrid=None,
        batch_dims: int = 1,
        devices=None,
    ):
        """(jitted `advance(Ub, Upb, C2, lane_steps, n) -> (Ub, Upb)`,
        bgrid) — the wave edition of the multi-tenant batched advance
        (HeatDiffusion.batched_advance_fn has the lane_steps/bitwise
        contract; both leapfrog carries freeze together when a lane's
        count is reached). Donates (Ub, Upb) — aliasing proven from the
        compiled program by analysis/lowered.audit_batched_drivers."""
        if bgrid is None:
            if batch is None:
                raise ValueError("pass batch= or a prebuilt bgrid=")
            bgrid = self.make_batched_grid(batch, batch_dims, devices)
        step, _ = self._make_batched_step(bgrid, variant)
        shape1 = (-1,) + (1,) * bgrid.space.ndim

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def advance(Ub, Upb, C2, lane_steps, n):
            def body(i, s):
                U, Up = s
                newU, newUp = step(U, Up, C2)
                active = (i < lane_steps).reshape(shape1)
                return (
                    jnp.where(active, newU, U),
                    jnp.where(active, newUp, Up),
                )

            return lax.fori_loop(0, n, body, (Ub, Upb))

        return advance, bgrid

    def batched_ladder_advance_fn(
        self,
        batch: int | None = None,
        bgrid=None,
        batch_dims: int = 1,
        devices=None,
    ):
        """(jitted `advance(Ub, Upb, C2, hold, dt2, inv_d2, lane_steps,
        n) -> (Ub, Upb)`, bgrid) — the wave edition of the LADDER
        batched advance (HeatDiffusion.batched_ladder_advance_fn has the
        full contract): per-lane `hold` masks (original Dirichlet ring +
        out-of-domain padding), per-lane `dt2` = dt² (batch,) and
        `inv_d2` = a TUPLE of ndim per-axis (batch,) 1/spacing²
        operands, precomputed host-side in f64 from each lane's
        ORIGINAL-shape config (ops.wave_kernels.wave_step_padded_geom;
        per-axis scalars, not an indexed vector — the diffusion
        edition's fori-fusion ulp note applies here too). Both leapfrog
        carries freeze together under `hold` exactly as under
        `lane_steps`. Donates (Ub, Upb)."""
        from rocm_mpi_tpu.ops.wave_kernels import wave_step_padded_geom
        from rocm_mpi_tpu.parallel.halo import exchange_halo_batched

        if bgrid is None:
            if batch is None:
                raise ValueError("pass batch= or a prebuilt bgrid=")
            bgrid = self.make_batched_grid(batch, batch_dims, devices)
        cfg = self.config
        ndim = bgrid.space.ndim
        shape1 = (-1,) + (1,) * ndim

        def lane_local(Ub_l, Upb_l, C2l, Hb_l, dt2_l, *invd2_l):
            pad = exchange_halo_batched(Ub_l, bgrid,
                                        wire_mode=cfg.wire_mode)

            def lane(Ul, Upl, padl, Hl, a, *gs):
                new = wave_step_padded_geom(padl, Upl, C2l, a, gs)
                return jnp.where(Hl, Ul, new)

            return jax.vmap(lane)(Ub_l, Upb_l, pad, Hb_l, dt2_l,
                                  *invd2_l)

        inner = shard_map(
            lane_local,
            mesh=bgrid.mesh,
            in_specs=(bgrid.spec, bgrid.spec, bgrid.aux_spec,
                      bgrid.spec, bgrid.batch_spec)
            + (bgrid.batch_spec,) * ndim,
            out_specs=bgrid.spec,
            check_vma=False,
        )

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def advance(Ub, Upb, C2, hold, dt2, inv_d2, lane_steps, n):
            def body(i, s):
                U, Up = s
                newU = inner(U, Up, C2, hold, dt2, *inv_d2)
                active = (i < lane_steps).reshape(shape1)
                return (
                    jnp.where(active, newU, U),
                    jnp.where(active, U, Up),
                )

            return lax.fori_loop(0, n, body, (Ub, Upb))

        return advance, bgrid

    def advance_fn(self, variant: str = "perf"):
        """jitted (U, Uprev, C2, n) -> (U after n steps, U after n-1)."""
        step, prep = self._step(variant)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def advance(U, Uprev, C2, n):
            P = None if prep is None else prep(C2)
            return lax.fori_loop(
                0, n, lambda _, s: step(s[0], s[1], C2, P), (U, Uprev)
            )

        return advance

    def scan_advance_fn(
        self,
        variant: str = "perf",
        nt: int | None = None,
        warmup: int | None = None,
        chunk: int | None = None,
        config: str | None = None,
    ):
        """(jitted (U, Uprev, C2, n) -> (U, Uprev), chunk q) — the
        donation-aware scan driver, wave edition (see
        HeatDiffusion.scan_advance_fn): the state pair is the scan carry
        (XLA's double buffer — the leapfrog's natural `U, U⁻ = U⁺, U`
        swap) and both leaves are donated. `n` must be a multiple of q.
        `config="auto"` gcd's an unset chunk from the tuning cache (op
        "wave.scan" — see the diffusion edition's contract)."""
        from rocm_mpi_tpu.models.diffusion import (
            auto_scan_chunk,
            effective_block_steps,
        )

        cfg = self.config
        step, prep = self._step(variant)
        nt_v = cfg.nt if nt is None else nt
        wu_v = cfg.warmup if warmup is None else warmup
        explicit = chunk is not None
        if not explicit:
            chunk = auto_scan_chunk("wave.scan", self.grid, cfg.jax_dtype,
                                    config)
        q = effective_block_steps(
            nt_v, wu_v, (nt_v - wu_v) if chunk is None else chunk,
            label="wave scan driver chunk", warn=explicit,
        )

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def advance(U, Uprev, C2, n):
            P = None if prep is None else prep(C2)

            def q_steps(carry, _):
                return step(carry[0], carry[1], C2, P), None

            def body(_, carry):
                carry, _ = lax.scan(q_steps, carry, xs=None, length=q)
                return carry

            return lax.fori_loop(0, n // q, body, (U, Uprev))

        return advance, q

    def _run_timed(self, advance, nt, warmup) -> WaveRunResult:
        """Shared run scaffold: validate the windows, init, then
        warmup-advance / tic / advance / toc (the same protocol as the
        diffusion runners; `advance(U, Uprev, C2, n) -> (U, Uprev)` must
        serve both windows with one compiled program)."""
        cfg = self.config
        nt = cfg.nt if nt is None else nt
        warmup = cfg.warmup if warmup is None else warmup
        if not 0 <= warmup < nt:
            raise ValueError(f"need 0 <= warmup < nt, got {warmup}, {nt}")
        U, Uprev, C2 = self.init_state()
        timer = metrics.Timer(label="step_window", phase="step",
                              steps=nt - warmup, workload="wave")
        U, Uprev = advance(U, Uprev, C2, warmup)
        timer.tic(U)
        U, Uprev = advance(U, Uprev, C2, nt - warmup)
        wtime = timer.toc(U)
        return WaveRunResult(
            U=U, wtime=wtime, nt=nt, warmup=warmup, config=cfg
        )

    def run(
        self, variant: str = "perf",
        nt: int | None = None, warmup: int | None = None,
        driver: str = "step", config: str | None = None,
    ) -> WaveRunResult:
        """`driver="scan"` routes to the donation-aware scan driver
        (scan_advance_fn); "step" keeps the per-step fori_loop. Same step
        program either way — results are bitwise identical.
        `config="auto"` lets the scan chunk consult the tuning cache."""
        if driver not in ("step", "scan"):
            raise ValueError(f"driver must be 'step' or 'scan', got {driver!r}")
        if driver == "scan":
            advance, _ = self.scan_advance_fn(variant, nt=nt, warmup=warmup,
                                              config=config)
        else:
            advance = self.advance_fn(variant)
        return self._run_timed(advance, nt, warmup)

    def run_vmem_resident(
        self, nt: int | None = None, warmup: int | None = None,
        chunk: int | None = None, config: str | None = None,
    ) -> WaveRunResult:
        """Single-shard fast path: the whole leapfrog loop inside one
        Pallas kernel, state pair VMEM-resident
        (ops.wave_kernels.wave_multi_step) — the wave edition of the
        diffusion flagship's schedule (HeatDiffusion.run_vmem_resident).
        `chunk` overrides the per-launch step count (the autotuner's
        measurement knob); `config="auto"` fills an unset chunk from the
        tuning cache (op "wave.vmem_loop") — resolved here, outside any
        trace, then gcd'd against the windows like every granularity.
        """
        from rocm_mpi_tpu.models.diffusion import effective_block_steps
        from rocm_mpi_tpu.ops.pallas_kernels import DEFAULT_STEP_CHUNK
        from rocm_mpi_tpu.ops.wave_kernels import wave_multi_step

        cfg = self.config
        if self.grid.nprocs != 1:
            raise ValueError("the VMEM-resident path requires an unsharded grid")
        explicit = chunk is not None
        if config == "auto" and chunk is None:
            from rocm_mpi_tpu.ops.pallas_kernels import adoptable_vmem_chunk
            from rocm_mpi_tpu.tuning import resolve as tuning_resolve

            tuned = tuning_resolve.resolve(
                "wave.vmem_loop", cfg.global_shape, cfg.jax_dtype
            )
            if tuned and adoptable_vmem_chunk(tuned.get("chunk")):
                chunk = tuned["chunk"]
        elif config not in (None, "default", "auto"):
            raise ValueError(
                f"config must be None, 'default' or 'auto', got {config!r}"
            )
        chunk = effective_block_steps(
            cfg.nt if nt is None else nt,
            cfg.warmup if warmup is None else warmup,
            DEFAULT_STEP_CHUNK if chunk is None else chunk,
            warn=explicit, label="wave VMEM chunk",
        )
        dt = cfg.jax_dtype(cfg.dt)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def advance(U, Uprev, C2, n):
            # warn_on_cap=False: the chunk is framework-plumbed here, not
            # caller-requested (same policy as diffusion _run_single_shard).
            return wave_multi_step(
                U, Uprev, C2, dt, cfg.spacing, n, chunk=chunk,
                warn_on_cap=False,
            )

        return self._run_timed(advance, nt, warmup)

    DEFAULT_DEEP_STEPS = 8

    def effective_deep_depth(
        self,
        nt: int | None = None,
        warmup: int | None = None,
        block_steps: int | None = None,
        warn: bool = True,
    ) -> int:
        """The sweep depth run_deep will actually execute for these
        arguments — THE source of truth for callers labeling artifacts by
        depth (apps/wave_2d.py), so label and executed k cannot drift.
        Policy (matching HeatDiffusion.effective_deep_depth, ADVICE r3):
        the DEFAULT depth clamps to the smallest shard extent (ghost
        slices need width <= shard); an EXPLICIT depth is first gcd'd
        against the windows (as diffusion's is) and raises only if the
        EFFECTIVE depth still exceeds the shard — the strict validation
        make_wave_deep_sweep applies, surfaced before any compile.
        """
        from rocm_mpi_tpu.models.diffusion import effective_block_steps

        cfg = self.config
        explicit = block_steps is not None
        if block_steps is None:
            block_steps = min(
                self.DEFAULT_DEEP_STEPS, min(self.grid.local_shape)
            )
        eff = effective_block_steps(
            cfg.nt if nt is None else nt,
            cfg.warmup if warmup is None else warmup,
            block_steps,
            label="wave deep-halo sweep depth",
            warn=warn,
            stacklevel=3,
        )
        if explicit and eff > min(self.grid.local_shape):
            raise ValueError(
                f"wave deep-halo sweep depth {eff} exceeds a local "
                f"shard extent {self.grid.local_shape}; ghost slices need "
                "width <= shard"
            )
        return eff

    def deep_advance_fn(
        self,
        block_steps: int | None = None,
        nt: int | None = None,
        warmup: int | None = None,
        wire_mode: str | None = None,
    ):
        """(jitted (U, Uprev, C2, n_steps) -> (U, Uprev), executed depth
        k) — the wave deep schedule's advance as a first-class function
        (HeatDiffusion.deep_advance_fn); `n_steps` must be a multiple of
        k (the fori_loop trip count floors). `wire_mode` overrides the
        config's on-wire precision; the stateful modes carry the
        exchange state internally (zero-initialized per call)."""
        from rocm_mpi_tpu.parallel.deep_halo import make_wave_deep_sweep

        cfg = self.config
        k = self.effective_deep_depth(nt, warmup, block_steps)
        dt = cfg.jax_dtype(cfg.dt)
        wm = cfg.wire_mode if wire_mode is None else wire_mode
        sched = make_wave_deep_sweep(self.grid, k, dt, cfg.spacing,
                                     wire_mode=wm)

        if sched.init_wire is None:

            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def advance(U, Uprev, C2, n):
                # The time-invariant c² is exchanged + masked ONCE per
                # compiled advance (DeepSchedule.prepare), not inside
                # every sweep — the loop carries only the leapfrog
                # state pair.
                P = sched.prepare(C2)
                return lax.fori_loop(
                    0, n // k, lambda _, s: sched.sweep(s[0], s[1], P),
                    (U, Uprev),
                )

        else:

            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def advance(U, Uprev, C2, n):
                P = sched.prepare(C2)
                ws0 = sched.init_wire(U.dtype)
                out = lax.fori_loop(
                    0, n // k,
                    lambda _, s: sched.sweep(s[0], s[1], P, s[2]),
                    (U, Uprev, ws0),
                )
                return out[0], out[1]

        return advance, k

    def run_deep(
        self,
        nt: int | None = None,
        warmup: int | None = None,
        block_steps: int | None = None,
        wire_mode: str | None = None,
    ) -> WaveRunResult:
        """Sharded fast path: deep-halo sweeps for the wave — one width-k
        ghost exchange of the leapfrog state pair per k steps
        (parallel.deep_halo.make_wave_deep_sweep), the second workload on
        the flagship multi-chip schedule (HeatDiffusion.run_deep).
        """
        advance, _ = self.deep_advance_fn(block_steps, nt, warmup,
                                          wire_mode=wire_mode)
        return self._run_timed(advance, nt, warmup)
