"""ctypes binding for the native host-staging engine (native/halostage.cpp).

The C++ library implements the same pack → stage → unpack → update cycle as
the pure-numpy HostStagedStepper (parallel/halo.py), multithreaded one task
per shard. The numpy version stays as the readable oracle; tests assert the
two are bit-identical. Build with `make -C native` (g++; no pybind11 —
plain ctypes over an extern-C ABI).
"""

from __future__ import annotations

import ctypes
import pathlib

import numpy as np

_LIB_PATH = (
    pathlib.Path(__file__).resolve().parent.parent.parent
    / "native"
    / "libhalostage.so"
)
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not _LIB_PATH.exists():
        return None
    lib = ctypes.CDLL(str(_LIB_PATH))
    if lib.rmt_abi_version() != 1:
        return None
    lib.rmt_host_staged_step.restype = ctypes.c_int
    lib.rmt_host_staged_step.argtypes = [
        ctypes.POINTER(ctypes.c_double),  # T
        ctypes.POINTER(ctypes.c_double),  # Cp
        ctypes.POINTER(ctypes.c_double),  # out
        ctypes.POINTER(ctypes.c_int64),  # shape
        ctypes.POINTER(ctypes.c_int64),  # dims
        ctypes.c_int,  # ndim
        ctypes.POINTER(ctypes.c_double),  # inv_d2
        ctypes.c_double,  # lam
        ctypes.c_double,  # dt
        ctypes.c_int,  # threads
    ]
    _lib = lib
    return _lib


def available() -> bool:
    """True when the built library is present and ABI-compatible."""
    return _load() is not None


def host_staged_step(
    T: np.ndarray,
    Cp: np.ndarray,
    dims,
    spacing,
    lam: float,
    dt: float,
    threads: int = 0,
) -> np.ndarray:
    """One native host-staged diffusion step; same contract as
    HostStagedStepper.step (f64, row-major, 2D/3D)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(
            "native halostage library not built — run `make -C native`"
        )
    T = np.ascontiguousarray(T, dtype=np.float64)
    Cp = np.ascontiguousarray(Cp, dtype=np.float64)
    out = np.empty_like(T)
    ndim = T.ndim
    shape = (ctypes.c_int64 * ndim)(*T.shape)
    dims_c = (ctypes.c_int64 * ndim)(*dims)
    inv_d2 = (ctypes.c_double * ndim)(*(1.0 / (d * d) for d in spacing))
    p = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
    rc = lib.rmt_host_staged_step(
        p(T), p(Cp), p(out), shape, dims_c, ndim, inv_d2,
        float(lam), float(dt), int(threads),
    )
    if rc != 0:
        raise ValueError(f"rmt_host_staged_step failed with code {rc}")
    return out
