"""Gather the global field to host process 0 for visualization (D4).

Reference: `gather!(T_nh, T_v)` assembles each rank's halo-stripped local
array into the global buffer on rank 0 for plotting
(/root/reference/scripts/diffusion_2D_ap.jl:31-34,45-46), via MPI_Gather.

TPU-native: shards are non-overlapping, so there is nothing to strip — a
device-to-host transfer of the global array *is* the gather. Single process:
`np.asarray` assembles all addressable shards. Multi-host (pod slice):
`multihost_utils.process_allgather` moves every shard to every host over DCN
and we keep the result on process 0 only, matching the reference's
rank-0-only `T_v`.
"""

from __future__ import annotations

import jax
import numpy as np


def gather_to_host0(x) -> np.ndarray | None:
    """Return the full global array as numpy on process 0 (None elsewhere)."""
    if jax.process_count() == 1:
        return np.asarray(jax.device_get(x))
    from rocm_mpi_tpu.utils.compat import multihost_utils

    full = multihost_utils.process_allgather(x, tiled=True)
    if jax.process_index() == 0:
        return np.asarray(full)
    return None
