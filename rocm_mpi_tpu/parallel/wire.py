"""The wire-precision plane: what a halo slab looks like ON THE WIRE.

At scale, halo bytes are the term that grows with the mesh (ROADMAP item
4; the memory-bound analyses in arXiv:2406.08923 and the Wormhole
data-movement accounting in arXiv:2605.07599 both identify wire traffic,
not FLOPs, as the scaling lever). The exchange schedule is already
message-minimal (PR 4's in-place rework, PR 4/7's traffic gates pin it
there) — the remaining lever is the *itemsize of the payload itself*.
This module owns that axis: the `wire_mode` registry, the per-mode slab
codecs (jax for the compiled exchange, a numpy twin for the host-staged
oracle), the per-mode byte accounting the telemetry annotations and the
perf wire-bytes ladder both consume, and the tolerance contract that
gates any non-f32 mode against the f64 host-staged oracle.

Modes (the wire-bytes ladder, fractions vs the full-precision wire):

* ``f32``        — full precision (the STATE dtype, so an f64 oracle run
                   ships f64). Bitwise-identical to the pre-wire-plane
                   exchange: the codec is the identity and traces the
                   exact same program.
* ``bf16``       — downcast the slab to bfloat16 on send, upcast to the
                   buffer dtype on receive BEFORE any seam arithmetic
                   (the storage-only-bf16 convention, applied to the
                   wire: graftlint GL04 polices the upcast). 0.5× wire.
* ``int8``       — per-slab symmetric int8 quantization (scale = the
                   slab's max-abs / 127, shipped alongside) with an
                   error-feedback residual carried in the exchange
                   state: the quantization error of send t is ADDED to
                   the slab of send t+1, so error is compensated across
                   the run, never accumulated. ~0.25× wire. Stateful.
* ``int8_delta`` — int8 over the DELTA against the previous send's
                   reconstruction: the outer rings of a deep-halo slab
                   barely change per sweep, so the delta has a far
                   smaller dynamic range than the slab and the same
                   scale buys ~k× finer quanta. Sender and receiver
                   each carry the running reconstruction (identical by
                   construction: both integrate the dequantized wire
                   values; the first sweep's "previous" is zero, so
                   sweep 1 ships a plain int8 slab). Same ~0.25× wire
                   as int8. Stateful.

Stateful modes carry their state as a FLAT tuple of arrays (fixed
structure — safe as a `lax.fori_loop`/`lax.scan` carry), one group of
``state_arity(mode)`` arrays per slab in exchange order (axis-major,
lo-then-hi — `slab_shapes` is the shape contract). Per-step variants are
stateless programs, so they support f32/bf16 only; the deep-halo
schedules (parallel/deep_halo.py) thread the state through their sweep
carry.

Import discipline: module import is stdlib-only (numpy/jax lazy, inside
functions) so the tuning gate's read side and the telemetry schema
checker can consult the mode tables without a backend.
"""

from __future__ import annotations

import math
from typing import NamedTuple

WIRE_MODES = ("f32", "bf16", "int8", "int8_delta")

# Modes that carry exchange state (error-feedback residuals / delta
# reconstructions) across calls.
STATEFUL_MODES = frozenset({"int8", "int8_delta"})

# Default wire-bytes ladder: max allowed fraction of a mode's on-wire
# bytes vs the full-precision (state-dtype) wire ideal. The committed
# rows live in rocm_mpi_tpu/perf/budgets.json ("wire"); this table is
# the fallback when a budgets file predates the ladder.
DEFAULT_LADDER = {
    "f32": 1.02,  # exact metric; tolerance covers rounding only
    "bf16": 0.55,
    "int8": 0.35,
    "int8_delta": 0.35,
}

# The tolerance contract: max allowed relative error (max-abs, vs the
# f64 host-staged oracle) of an f32-state run using this wire mode, at
# the certification drill's horizon. Calibrated against the drill in
# `check_tolerance` (headroom >= 4x measured); the end-to-end model
# parity tests (tests/test_wire.py) hold the same bounds on all three
# workloads. Any non-f32 mode must pass BOTH this contract and the
# wire-bytes ladder to be accepted (tuning/gate.py double-gates).
TOLERANCE = {
    "f32": 2e-4,
    "bf16": 2e-2,
    "int8": 6e-2,
    "int8_delta": 3e-2,
}


def validate_mode(mode: str) -> str:
    if mode not in WIRE_MODES:
        raise ValueError(
            f"unknown wire_mode {mode!r}; known: {WIRE_MODES}"
        )
    return mode


def is_stateful(mode: str) -> bool:
    return validate_mode(mode) in STATEFUL_MODES


def state_arity(mode: str) -> int:
    """State arrays carried per slab: int8 carries the error-feedback
    residual; int8_delta adds the sender's and receiver's running
    reconstructions (prev_send, prev_recv)."""
    if mode == "int8":
        return 1
    if mode == "int8_delta":
        return 3
    return 0


def payload_itemsize(mode: str, itemsize: int) -> int:
    """On-wire bytes per slab element. f32 mode ships the state dtype
    verbatim (an f64 oracle program ships 8-byte elements)."""
    validate_mode(mode)
    if mode == "bf16":
        return 2
    if mode in STATEFUL_MODES:
        return 1
    return int(itemsize)


def slab_overhead_bytes(mode: str, itemsize: int) -> int:
    """Per-slab side-channel bytes: the int8 modes ship one scale scalar
    (state dtype) alongside each quantized slab."""
    return int(itemsize) if mode in STATEFUL_MODES else 0


def wire_slab_nbytes(n_elems: int, itemsize: int, mode: str) -> int:
    """Exact on-wire bytes of ONE slab under `mode`."""
    return (
        int(n_elems) * payload_itemsize(mode, itemsize)
        + slab_overhead_bytes(mode, itemsize)
    )


def slab_shapes(local_shape, width: int, axes=None) -> list[tuple[int, ...]]:
    """Per-shard send/recv slab shapes in exchange order (axis-major,
    lo then hi). Axis k's slabs span the PADDED extent of every axis
    exchanged before it (the sequential corner trick extends the core
    edge with the earlier axes' received slabs) and the core extent
    after — the shape contract the stateful codecs' state arrays and
    `exchange_nbytes` both derive from."""
    local_shape = tuple(int(n) for n in local_shape)
    ndim = len(local_shape)
    axes = tuple(range(ndim) if axes is None else axes)
    width = int(width)
    shapes: list[tuple[int, ...]] = []
    done: list[int] = []
    for ax in axes:
        shape = tuple(
            width if a == ax
            else local_shape[a] + 2 * width if a in done
            else local_shape[a]
            for a in range(ndim)
        )
        shapes.append(shape)  # lo ghost (received from the -1 neighbor)
        shapes.append(shape)  # hi ghost
        done.append(ax)
    return shapes


def exchange_wire_nbytes(local_shape, itemsize: int, width: int = 1,
                         axes=None, mode: str = "f32") -> int:
    """Bytes an interior device SENDS per exchange under `mode` — the
    per-mode edition of halo.exchange_nbytes (which delegates here)."""
    return sum(
        wire_slab_nbytes(math.prod(s), itemsize, mode)
        for s in slab_shapes(local_shape, width, axes)
    )


def ladder_fraction(local_shape, width: int, mode: str,
                    itemsize: int = 4) -> float:
    """A mode's closed-form wire bytes as a fraction of the
    full-precision ideal at the same geometry — the number the
    wire-bytes ladder rows bound."""
    full = exchange_wire_nbytes(local_shape, itemsize, width, mode="f32")
    this = exchange_wire_nbytes(local_shape, itemsize, width, mode=mode)
    return this / full if full else 0.0


# ---------------------------------------------------------------------------
# State construction (global, sharded-compatible zeros)
# ---------------------------------------------------------------------------


def init_exchange_state(grid, width: int, mode: str, dtype, axes=None,
                        fields: int = 1):
    """The initial (zero) exchange state for ONE stateful exchange per
    sweep of `fields` same-shaped fields: a flat tuple of GLOBAL zero
    arrays, `state_arity(mode)` per slab per field, shaped so that
    `shard_map(..., in_specs=(grid.spec,)*len(state))` hands every shard
    exactly its per-slab state (`slab_shapes` scaled by the mesh dims).
    Zeros ARE the first-sweep contract: a zero residual adds nothing,
    and a zero delta reconstruction makes sweep 1 ship the plain slab."""
    import jax.numpy as jnp

    if not is_stateful(mode):
        return ()
    arity = state_arity(mode)
    out = []
    for _ in range(int(fields)):
        for shape in slab_shapes(grid.local_shape, width, axes):
            gshape = tuple(
                int(s) * int(d) for s, d in zip(shape, grid.dims)
            )
            for _j in range(arity):
                out.append(jnp.zeros(gshape, dtype))
    return tuple(out)


# ---------------------------------------------------------------------------
# The jax slab codec (used inside shard_map by halo.exchange_into)
# ---------------------------------------------------------------------------


def _quantize_int8(x):
    """Per-slab symmetric quantization: (int8 codes, scale scalar in
    x.dtype). An all-zero slab gets scale 1.0 (codes are 0 either way —
    no divide-by-zero, and a zeroed received scale still decodes to 0)."""
    import jax.numpy as jnp

    m = jnp.max(jnp.abs(x))
    scale = jnp.where(m > 0, m / 127.0, jnp.ones_like(m))
    q = jnp.clip(jnp.round(x / scale), -127.0, 127.0).astype(jnp.int8)
    return q, scale


def _dequantize_int8(q, scale, dtype):
    return q.astype(dtype) * scale.astype(dtype)


class SlabCodec(NamedTuple):
    """One slab's wire transform: `send(slab, state) -> (payload_tuple,
    state_after_send)` and `recv(shipped_tuple, state_after_send, dtype)
    -> (decoded, final_state)`. The payload is a tuple of arrays shipped
    leaf-by-leaf over the same ppermute; `state` is a tuple of
    `state_arity(mode)` arrays (empty for stateless modes)."""

    send: object
    recv: object


def slab_codec(mode: str) -> SlabCodec:
    import jax.numpy as jnp

    validate_mode(mode)

    if mode == "f32":

        def send(slab, state):
            return (slab,), state

        def recv(shipped, state, dtype):
            return shipped[0], state

    elif mode == "bf16":
        from jax import lax as _lax

        def send(slab, state):
            # Bitcast the bf16 payload to uint16 for the wire: XLA's
            # algebraic simplifier hoists a widening convert ACROSS a
            # collective-permute (narrow->permute->widen canonicalizes
            # to permute-at-f32 — observed on the CPU lowering, where
            # the wire ladder measured a "bf16" exchange shipping f32
            # bytes). A bitcast is opaque to that rewrite, so the wire
            # provably carries 2-byte elements.
            return (_lax.bitcast_convert_type(
                slab.astype(jnp.bfloat16), jnp.uint16
            ),), state

        def recv(shipped, state, dtype):
            # The f32 upcast at the seam (GL04): the decoded slab, not
            # the wire payload, is what seam arithmetic may touch.
            return _lax.bitcast_convert_type(
                shipped[0], jnp.bfloat16
            ).astype(dtype), state

    elif mode == "int8":

        def send(slab, state):
            (resid,) = state
            comp = slab + resid  # error feedback: carry last send's error
            q, scale = _quantize_int8(comp)
            deq = _dequantize_int8(q, scale, slab.dtype)
            return (q, scale), (comp - deq,)

        def recv(shipped, state, dtype):
            q, scale = shipped
            return _dequantize_int8(q, scale, dtype), state

    else:  # int8_delta

        def send(slab, state):
            resid, prev_send, prev_recv = state
            comp = slab + resid
            q, scale = _quantize_int8(comp - prev_send)
            deq = _dequantize_int8(q, scale, slab.dtype)
            new_prev = prev_send + deq
            return (q, scale), (comp - new_prev, new_prev, prev_recv)

        def recv(shipped, state, dtype):
            resid, prev_send, prev_recv = state
            q, scale = shipped
            decoded = prev_recv + _dequantize_int8(q, scale, dtype)
            # The receiver's reconstruction integrates exactly what the
            # sender's did (the dequantized wire values), so the two
            # stay identical by construction — including the zero
            # first-sweep and the domain-edge case (an omitted ppermute
            # delivers zeros: scale 0 -> delta 0 -> the ghost stays 0).
            return decoded, (resid, prev_send, decoded)

    return SlabCodec(send, recv)


# ---------------------------------------------------------------------------
# The numpy twin (host-staged oracle + the tolerance-contract drill)
# ---------------------------------------------------------------------------


class NumpyWireCodec:
    """Per-slab numpy twin of `slab_codec`, with the state held
    internally (the host-staged stepper is the one stateful object in
    the oracle world). `apply(key, slab)` returns the slab as the
    receiver would decode it; `key` identifies the logical wire (sender
    coords, axis, direction) so each wire keeps its own residual /
    reconstruction across steps. `feedback=False` disables the
    error-feedback residual (drift-comparison tests only — it is what
    "compensated, not accumulated" means, made measurable)."""

    def __init__(self, mode: str, feedback: bool = True):
        self.mode = validate_mode(mode)
        self.feedback = feedback
        self._resid: dict = {}
        self._prev: dict = {}

    def apply(self, key, slab):
        import numpy as np

        if self.mode == "f32":
            return slab
        if self.mode == "bf16":
            return _np_bf16_round(slab).astype(slab.dtype)
        resid = self._resid.get(key, 0.0)
        comp = slab + resid if self.feedback else slab
        prev = self._prev.get(key, 0.0) if self.mode == "int8_delta" else 0.0
        d = comp - prev
        m = float(np.max(np.abs(d)))
        scale = m / 127.0 if m > 0 else 1.0
        deq = np.clip(np.round(d / scale), -127.0, 127.0) * scale
        decoded = prev + deq
        if self.feedback:
            self._resid[key] = comp - decoded
        if self.mode == "int8_delta":
            self._prev[key] = decoded
        return decoded.astype(slab.dtype)


def _np_bf16_round(x):
    """Round-to-nearest-even float -> bfloat16 -> float, in numpy (no ml
    dtypes dependency): bf16 is f32 with the mantissa cut to 7 bits."""
    import numpy as np

    f = np.asarray(x, np.float32)
    u = f.view(np.uint32)
    rounded = ((u + 0x7FFF + ((u >> 16) & 1)) & 0xFFFF0000).astype(np.uint32)
    out = rounded.view(np.float32)
    return out.astype(np.asarray(x).dtype)


# ---------------------------------------------------------------------------
# The tolerance contract (vs the f64 host-staged oracle)
# ---------------------------------------------------------------------------


class ContractResult(NamedTuple):
    mode: str
    ok: bool
    rel_err: float
    bound: float
    steps: int


class _OracleGrid(NamedTuple):
    """The duck-typed subset of GlobalGrid the host-staged stepper
    reads — device-free on purpose, so the contract drill (and the
    tuning gate that calls it) never needs a multi-device backend."""

    global_shape: tuple[int, ...]
    dims: tuple[int, ...]
    spacing: tuple[float, ...]

    @property
    def ndim(self) -> int:
        return len(self.global_shape)

    @property
    def local_shape(self) -> tuple[int, ...]:
        return tuple(
            n // d for n, d in zip(self.global_shape, self.dims)
        )


_CERT_CACHE: dict = {}


def check_tolerance(mode: str, shape=(32, 32), dims=(2, 2),
                    steps: int = 60) -> ContractResult:
    """The certification drill: run the f64 host-staged diffusion oracle
    plain and with the wire codec on the ghost slabs, and bound the
    relative max-abs divergence by the mode's TOLERANCE row. Device-free
    (numpy end to end) and deterministic — cheap enough for the tuning
    gate to consult on every validate."""
    import numpy as np

    from rocm_mpi_tpu.parallel.halo import HostStagedStepper

    validate_mode(mode)
    bound = TOLERANCE[mode]
    shape = tuple(int(n) for n in shape)
    dims = tuple(int(d) for d in dims)
    grid = _OracleGrid(
        global_shape=shape, dims=dims,
        spacing=tuple(10.0 / n for n in shape),
    )
    lam, cp0 = 1.0, 1.0
    h2 = min(d * d for d in grid.spacing)
    dt = h2 * cp0 / lam / (2 * grid.ndim + 0.1)

    coords = np.meshgrid(
        *[(np.arange(n) + 0.5) * d - 5.0
          for n, d in zip(shape, grid.spacing)],
        indexing="ij",
    )
    T0 = np.exp(-sum(c * c for c in coords)).astype(np.float64)
    Cp = np.full(shape, cp0, np.float64)

    oracle = HostStagedStepper(grid, lam, dt, use_native=False)
    wired = HostStagedStepper(grid, lam, dt, use_native=False,
                              wire_mode=mode)
    ref = oracle.run(T0.copy(), Cp, steps)
    got = wired.run(T0.copy(), Cp, steps)
    rel = float(np.max(np.abs(got - ref)) / max(np.max(np.abs(ref)), 1e-30))
    return ContractResult(mode, rel <= bound, rel, bound, steps)


def certify(mode: str) -> ContractResult:
    """Cached `check_tolerance` at the standard drill geometry — the
    tolerance half of the tuning gate's double gate. The cache key
    includes the mode's CURRENT bound so a (test-)doctored TOLERANCE row
    re-runs the drill instead of serving a stale verdict."""
    key = (mode, TOLERANCE[validate_mode(mode)])
    out = _CERT_CACHE.get(key)
    if out is None:
        out = _CERT_CACHE[key] = check_tolerance(mode)
    return out
