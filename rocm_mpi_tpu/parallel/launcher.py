"""N-rank process launcher — the `srun -n N --mpi=pmix` analog (D9).

The reference's multi-process entry point is a cluster launcher
(/root/reference/README.md:18); this framework's launcher contract is the
RMT_* env block consumed by parallel.distributed.maybe_initialize_distributed
(RMT_COORDINATOR/RMT_NUM_PROCS/RMT_PROCESS_ID). `spawn_ranks` plays that
launcher on one machine: it spawns N real Python processes wired by the
contract, each with its own virtual CPU devices, so sharded programs cross
genuine process boundaries (gloo) without a cluster. One implementation
serves the 2-process test harness (tests/test_distributed.py) and the
N-rank mechanics script (scripts/run_multiproc_mechanics.py).

Robustness contract:
  * every rank's pipes are drained CONCURRENTLY (a rank blocked writing
    >64 KB to an unread pipe mid-collective would deadlock the others);
  * a rank that outlives `timeout` is killed and its flushed output kept;
  * every still-running rank is killed on any exit path (no leaked gloo
    ranks holding the coordinator port).
"""

from __future__ import annotations

import os
import pathlib
import socket
import subprocess
import sys
import threading

_ROOT = pathlib.Path(__file__).resolve().parents[2]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_ranks(
    argv,
    nprocs: int = 2,
    timeout: float = 240,
    init_timeout_s: int = 60,
):
    """Spawn `nprocs` ranks of `[sys.executable] + argv` under the RMT_*
    launcher contract; return [(proc, (stdout, stderr)), ...] in rank
    order. Callers judge returncodes (a killed-at-timeout rank reports
    its signal code with whatever it flushed)."""
    port = _free_port()
    base = os.environ.copy()
    # Ranks size their own device count (--cpu-devices); an inherited
    # XLA_FLAGS device-count force would conflict with it.
    base.pop("XLA_FLAGS", None)
    procs = []
    for pid in range(nprocs):
        env = dict(
            base,
            JAX_PLATFORMS="cpu",
            RMT_DISTRIBUTED="1",
            RMT_COORDINATOR=f"127.0.0.1:{port}",
            RMT_NUM_PROCS=str(nprocs),
            RMT_PROCESS_ID=str(pid),
            RMT_INIT_TIMEOUT_S=str(init_timeout_s),
            # The spawned interpreter only gets the script's own dir on
            # sys.path; prepend (never clobber) so inherited entries
            # stay importable.
            PYTHONPATH=os.pathsep.join(
                [str(_ROOT)]
                + ([base["PYTHONPATH"]] if "PYTHONPATH" in base else [])
            ),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable] + [str(a) for a in argv],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                cwd=_ROOT,
            )
        )
    outs: list = [None] * nprocs

    def drain(i: int, p) -> None:
        # Any failure records SOMETHING into outs[i]: callers unpack
        # (stdout, stderr) per rank, and a None would turn a rank failure
        # into an opaque TypeError at the call site. The post-kill
        # communicate gets its own timeout too — a grandchild that
        # inherited the pipes keeps them open past the kill.
        try:
            outs[i] = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            try:
                outs[i] = p.communicate(timeout=30)
            except Exception as exc:  # noqa: BLE001
                outs[i] = ("", f"rank {i} drain failed post-kill: {exc!r}")
        except Exception as exc:  # noqa: BLE001
            p.kill()
            outs[i] = ("", f"rank {i} drain failed: {exc!r}")

    threads = [
        threading.Thread(target=drain, args=(i, p), daemon=True)
        for i, p in enumerate(procs)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return list(zip(procs, outs))
