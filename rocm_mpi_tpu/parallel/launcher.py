"""N-rank process launcher — the `srun -n N --mpi=pmix` analog (D9).

The reference's multi-process entry point is a cluster launcher
(/root/reference/README.md:18); this framework's launcher contract is the
RMT_* env block consumed by parallel.distributed.maybe_initialize_distributed
(RMT_COORDINATOR/RMT_NUM_PROCS/RMT_PROCESS_ID). `spawn_ranks` plays that
launcher on one machine: it spawns N real Python processes wired by the
contract, each with its own virtual CPU devices, so sharded programs cross
genuine process boundaries (gloo) without a cluster. One implementation
serves the 2-process test harness (tests/test_distributed.py), the N-rank
mechanics script (scripts/run_multiproc_mechanics.py), and the resilience
tier's rank-failure drills (tests/test_resilience.py).

Robustness contract:
  * every rank's pipes are drained CONCURRENTLY (a rank blocked writing
    >64 KB to an unread pipe mid-collective would deadlock the others);
  * a supervision thread heartbeats rank liveness: the FIRST nonzero
    rank exit is recorded (rank, rc, time) and, after `peer_grace_s`,
    still-running peers — almost certainly hung in a collective waiting
    on the dead rank — are killed and named in the report, instead of
    every survivor burning the full `timeout` on a bare kill;
  * a rank that outlives `timeout` is killed and its flushed output kept;
  * every still-running rank is killed on any exit path (no leaked gloo
    ranks holding the coordinator port);
  * `inject_fault` forwards a resilience.faults spec to every rank via
    RMT_INJECT_FAULT, so rank-failure paths are drilled in the real
    multi-process harness (docs/RESILIENCE.md §3);
  * `telemetry_dir` turns on per-rank telemetry collection
    (RMT_TELEMETRY_DIR — each rank appends telemetry-rank{k}.jsonl,
    docs/TELEMETRY.md) and, after all ranks exit, merges the streams
    into <dir>/telemetry-summary.json — the launcher is the one place
    that outlives every rank, so it owns the merge;
  * `health_dir` arms the runtime health plane (docs/TELEMETRY.md
    "Health plane"): ranks run the flight recorder (RMT_HEALTH /
    RMT_HEALTH_DIR → heartbeat-rank{k}.json sidecars + an in-process
    SIGUSR2 faulthandler), and the supervision thread becomes a
    PROGRESS-AWARE watchdog — it tails the sidecars and flags a rank
    whose step counter stalls while the cross-rank median advances (the
    stalled-collective signature, telemetry.health.ProgressWatch; wall
    clock alone cannot tell the victim from the peers it wedged). A
    flagged rank gets SIGUSR2 (all-thread traceback into its
    post-mortem sidecar), `postmortem-rank{k}.json` is composed out of
    process, the rank is killed, the existing peer-grace kill reaps the
    survivors, and everything is bundled into <health_dir>/postmortem/
    with a merged timeline trace. The wall-clock heartbeat log line
    gains per-rank progress ages; with the health plane OFF it stays
    byte-for-byte the legacy line.
"""

from __future__ import annotations

import dataclasses
import os
import pathlib
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time

_ROOT = pathlib.Path(__file__).resolve().parents[2]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class LaunchReport:
    """What the supervision thread observed: who failed first, when, and
    which hung peers it had to put down."""

    first_failure: tuple[int, int, float] | None = None  # (rank, rc, t_s)
    killed_after_failure: list[int] = dataclasses.field(default_factory=list)
    events: list[str] = dataclasses.field(default_factory=list)
    # Progress-watchdog verdicts (health_dir runs): one dict per flagged
    # rank — rank, step, median_step, stalled_for_s, last phase, t.
    watchdog_verdicts: list[dict] = dataclasses.field(default_factory=list)
    # Vanish detection (vanish_grace_s runs): the rank that exited rc=0
    # while its peers were still running past the grace — the
    # preempted/evicted-rank signature (a clean rc that orphans a
    # collective). first_failure is set alongside, with rc 0.
    vanished: int | None = None

    def note(self, msg: str) -> None:
        self.events.append(msg)
        if os.environ.get("RMT_LAUNCH_VERBOSE"):
            print(f"[launcher] {msg}", file=sys.stderr, flush=True)


class RankResults(list):
    """`[(proc, (stdout, stderr)), ...]` in rank order, with the
    supervision report attached — existing callers keep unpacking the
    list; resilience callers read `.report`."""

    report: LaunchReport


def spawn_ranks(
    argv,
    nprocs: int = 2,
    timeout: float = 240,
    init_timeout_s: int = 60,
    inject_fault: str | None = None,
    heartbeat_s: float = 10.0,
    peer_grace_s: float = 20.0,
    telemetry_dir=None,
    health_dir=None,
    stall_grace_s: float = 6.0,
    postmortem_grace_s: float = 1.5,
    vanish_grace_s: float | None = None,
    preempt_grace_s: float | None = None,
    forward_preempt: bool = False,
    on_spawn=None,
):
    """Spawn `nprocs` ranks of `[sys.executable] + argv` under the RMT_*
    launcher contract; return RankResults of (proc, (stdout, stderr)) in
    rank order, with `.report` carrying first-failure/heartbeat data.
    Callers judge returncodes (a killed-at-timeout or killed-after-peer-
    failure rank reports its signal code with whatever it flushed).
    With `telemetry_dir` every rank collects telemetry into it and the
    merged summary is written at exit; with `health_dir` the supervision
    thread runs the progress-aware watchdog over the ranks' heartbeat
    sidecars (`stall_grace_s` of no progress while the cross-rank median
    is ahead; `postmortem_grace_s` between SIGUSR2 and the kill, so the
    in-process faulthandler gets to write its dump) — module docstring
    has the full story.

    `vanish_grace_s` (default off — legacy behavior is byte-identical)
    arms VANISH detection: a rank that exits rc=0 while peers are still
    running looks like normal completion skew for the grace window, but
    past it — peers still alive, almost certainly wedged in a collective
    the clean-exited rank abandoned — the exit is reclassified as a
    death (`report.vanished`, first_failure with rc 0) and the wedged
    peers are killed. With `health_dir` armed the verdict additionally
    requires every surviving rank's PROGRESS content to be at least the
    grace old (a slow-but-progressing straggler — e.g. the final save on
    a loaded box — is never reclassified); without the health plane,
    elapsed time is all there is, so size the grace above the ranks'
    normal completion skew. This is how a preempted/evicted rank (fault
    kind `die`) is caught without a nonzero rc to scan for; the elastic
    supervisor (resilience.elastic) turns the verdict into a mesh
    shrink.

    `preempt_grace_s` forwards a SIGTERM grace deadline to every rank
    (RMT_PREEMPT_GRACE_S — resilience.preempt.install_from_env arms the
    handler; docs/RESILIENCE.md §7): a preempted rank lands one final
    save at its next segment boundary — if the measured save wall fits
    the grace — and exits RC_PREEMPTED, which the elastic supervisor
    classifies as resumable, never a failure. `forward_preempt` makes
    the LAUNCHER itself preemption-aware: a SIGTERM delivered to this
    process is relayed to every live rank (handler installation routed
    through resilience.preempt.install_forwarder — the GL07 owner seam;
    this module only ever SENDS signals). `on_spawn(procs)` is called
    once with the Popen list right after all ranks spawn — the elastic
    rejoin probe uses it to deliver grow-time preemptions; exceptions
    in the callback are noted, never fatal."""
    port = _free_port()
    base = os.environ.copy()
    # Ranks size their own device count (--cpu-devices); an inherited
    # XLA_FLAGS device-count force would conflict with it.
    base.pop("XLA_FLAGS", None)
    procs = []
    for pid in range(nprocs):
        env = dict(
            base,
            JAX_PLATFORMS="cpu",
            RMT_DISTRIBUTED="1",
            RMT_COORDINATOR=f"127.0.0.1:{port}",
            RMT_NUM_PROCS=str(nprocs),
            RMT_PROCESS_ID=str(pid),
            RMT_INIT_TIMEOUT_S=str(init_timeout_s),
            # The spawned interpreter only gets the script's own dir on
            # sys.path; prepend (never clobber) so inherited entries
            # stay importable.
            PYTHONPATH=os.pathsep.join(
                [str(_ROOT)]
                + ([base["PYTHONPATH"]] if "PYTHONPATH" in base else [])
            ),
        )
        if inject_fault:
            env["RMT_INJECT_FAULT"] = inject_fault
        if preempt_grace_s is not None:
            env["RMT_PREEMPT_GRACE_S"] = str(preempt_grace_s)
        if telemetry_dir:
            os.makedirs(telemetry_dir, exist_ok=True)
            env["RMT_TELEMETRY"] = "1"
            env["RMT_TELEMETRY_DIR"] = str(telemetry_dir)
        if health_dir:
            # The flight-recorder contract (telemetry/flight.py): ranks
            # write heartbeat sidecars here and register the SIGUSR2
            # faulthandler (apps/_common.setup_health reads these).
            os.makedirs(health_dir, exist_ok=True)
            if pid == 0:
                # Sidecars are THIS launch's state: stale heartbeat /
                # post-mortem files from a previous run in a reused dir
                # would feed the watchdog old counters during the new
                # ranks' slow startup (python + distributed init takes
                # longer than the stall grace) and get a healthy rank
                # flagged and killed for last run's incident.
                for stale in pathlib.Path(health_dir).glob(
                    "heartbeat-rank*.json"
                ):
                    stale.unlink(missing_ok=True)
                for pattern in ("postmortem-rank*.json",
                                "postmortem-rank*.traceback"):
                    for stale in pathlib.Path(health_dir).glob(pattern):
                        stale.unlink(missing_ok=True)
                # Including last run's bundle: "clean runs leave no
                # bundle" must hold for a clean RERUN of a dir that saw
                # an incident — else the watcher archives the previous
                # incident as if it belonged to this burst.
                stale_bundle = pathlib.Path(health_dir) / "postmortem"
                if stale_bundle.is_dir():
                    shutil.rmtree(stale_bundle, ignore_errors=True)
            env["RMT_HEALTH"] = "1"
            env["RMT_HEALTH_DIR"] = str(health_dir)
        procs.append(
            subprocess.Popen(
                [sys.executable] + [str(a) for a in argv],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                cwd=_ROOT,
            )
        )
    outs: list = [None] * nprocs
    report = LaunchReport()
    done = threading.Event()
    if on_spawn is not None:
        try:
            on_spawn(list(procs))
        except Exception as exc:  # noqa: BLE001 — a probe must not kill a launch
            report.note(f"on_spawn callback failed: {exc!r}")
    restore_forwarder = None
    if forward_preempt:
        # The SIGTERM relay: handler INSTALLATION lives in resilience/
        # (a GL07 signal-hygiene owner); the launcher only sends.
        from rocm_mpi_tpu.resilience import preempt as _preempt

        restore_forwarder = _preempt.install_forwarder(procs)

    def drain(i: int, p) -> None:
        # Any failure records SOMETHING into outs[i]: callers unpack
        # (stdout, stderr) per rank, and a None would turn a rank failure
        # into an opaque TypeError at the call site. The post-kill
        # communicate gets its own timeout too — a grandchild that
        # inherited the pipes keeps them open past the kill.
        try:
            outs[i] = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            try:
                outs[i] = p.communicate(timeout=30)
            except Exception as exc:  # noqa: BLE001
                outs[i] = ("", f"rank {i} drain failed post-kill: {exc!r}")
        except Exception as exc:  # noqa: BLE001
            p.kill()
            outs[i] = ("", f"rank {i} drain failed: {exc!r}")

    watch = None
    if health_dir:
        from rocm_mpi_tpu.telemetry import health as _health

        watch = _health.ProgressWatch(stall_grace_s=stall_grace_s)

    def watchdog_tick(now: float) -> None:
        """One progress-watchdog poll (health_dir runs only): tail the
        sidecars, and on the first stalled-collective verdict dump +
        post-mortem + kill the flagged rank — the kill turns into a
        nonzero exit the first-failure path below already knows how to
        handle (peer-grace kill of the wedged survivors)."""
        from rocm_mpi_tpu.telemetry import health as _health

        beats, _ = _health.load_heartbeats(health_dir)
        watch.observe(beats, now)
        if report.watchdog_verdicts:
            return  # one verdict round per launch: the rest is cleanup
        for verdict in watch.verdicts(now):
            rank = verdict["rank"]
            if procs[rank].poll() is not None:
                continue  # already dead: the exit path will report it
            report.note(
                f"watchdog: rank {rank} stalled at step {verdict['step']} "
                f"(cross-rank median {verdict['median_step']}, no progress "
                f"for {verdict['stalled_for_s']}s, last phase "
                f"{verdict['last_phase']!r}) — SIGUSR2 then kill"
            )
            try:
                if hasattr(signal, "SIGUSR2"):
                    procs[rank].send_signal(signal.SIGUSR2)
                    # Give the in-process faulthandler time to append its
                    # all-thread dump (cancellable wait, not sleep).
                    done.wait(postmortem_grace_s)
            except (OSError, ValueError):
                pass
            try:
                path = _health.write_postmortem(health_dir, rank, verdict)
                report.note(f"watchdog: wrote {path}")
            except Exception as exc:  # noqa: BLE001 — never wedge the kill
                report.note(f"watchdog: post-mortem failed: {exc!r}")
            report.watchdog_verdicts.append(verdict)
            if procs[rank].poll() is None:
                procs[rank].kill()

    def supervise() -> None:
        """Heartbeat rank liveness; on the first nonzero exit, give hung
        peers `peer_grace_s` to finish on their own, then kill them —
        a gloo collective never completes once a participant is dead.
        With `health_dir`, each pass also runs the progress watchdog."""
        t0 = time.monotonic()
        next_beat = t0 + heartbeat_s
        failure_t = None
        first_clean_exit = None  # (rank, t) — vanish_grace_s runs only
        while not done.is_set():
            now = time.monotonic()
            alive = [i for i, p in enumerate(procs) if p.poll() is None]
            if not alive:
                return
            if watch is not None:
                try:
                    watchdog_tick(now)
                except Exception as exc:  # noqa: BLE001
                    report.note(f"watchdog: tick failed: {exc!r}")
            if report.first_failure is None:
                for i, p in enumerate(procs):
                    rc = p.poll()
                    if rc is not None and rc != 0:
                        failure_t = now
                        report.first_failure = (i, rc, now - t0)
                        report.note(
                            f"first failure: rank {i} rc={rc} at "
                            f"{now - t0:.1f}s; peers get {peer_grace_s}s "
                            "grace"
                        )
                        break
            if (
                vanish_grace_s is not None
                and report.first_failure is None
            ):
                if first_clean_exit is None:
                    for i, p in enumerate(procs):
                        if p.poll() == 0:
                            first_clean_exit = (i, now)
                            break
                elif now - first_clean_exit[1] >= vanish_grace_s and (
                    watch is None
                    or all(
                        age >= vanish_grace_s
                        for rk, age in watch.ages(now).items()
                        if rk in alive
                    )
                ):
                    # Peers are STILL running this long after a clean
                    # exit: not completion skew — the exited rank
                    # abandoned a collective its peers are wedged in.
                    # With the health plane on, elapsed time alone is
                    # not enough: a slow-but-progressing survivor (its
                    # sidecar content still changing — e.g. the final
                    # save on a loaded box) must never be reclassified
                    # as orphaned; only peers whose progress is as old
                    # as the vanish grace are.
                    rank, exit_t = first_clean_exit
                    report.vanished = rank
                    report.first_failure = (rank, 0, exit_t - t0)
                    report.note(
                        f"vanish: rank {rank} exited rc=0 at "
                        f"{exit_t - t0:.1f}s but ranks {alive} are still "
                        f"running {vanish_grace_s}s later — treating the "
                        "exit as a death and killing the orphaned peers"
                    )
                    for i in alive:
                        if procs[i].poll() is None:
                            procs[i].kill()
                            report.killed_after_failure.append(i)
                    return
            elif failure_t is not None and now - failure_t >= peer_grace_s:
                for i in alive:
                    if procs[i].poll() is None:
                        procs[i].kill()
                        report.killed_after_failure.append(i)
                report.note(
                    f"killed hung peer rank(s) {report.killed_after_failure}"
                    f" {peer_grace_s}s after rank "
                    f"{report.first_failure[0]} failed"
                )
                return
            if heartbeat_s and now >= next_beat:
                if watch is None:
                    # The legacy line, byte for byte: the resilience
                    # drills (and whoever greps their logs) pin it.
                    report.note(
                        f"heartbeat at {now - t0:.1f}s: ranks {alive} alive"
                    )
                else:
                    ages = watch.ages(now)
                    detail = ", ".join(
                        f"rank{rk} {ages[rk]:.1f}s" for rk in sorted(ages)
                    ) or "no sidecars yet"
                    report.note(
                        f"heartbeat at {now - t0:.1f}s: ranks {alive} "
                        f"alive; last progress age: {detail}"
                    )
                next_beat = now + heartbeat_s
            done.wait(0.25)

    threads = [
        threading.Thread(target=drain, args=(i, p), daemon=True)
        for i, p in enumerate(procs)
    ]
    monitor = threading.Thread(target=supervise, daemon=True)
    try:
        for t in threads:
            t.start()
        monitor.start()
        for t in threads:
            t.join()
    finally:
        done.set()
        if restore_forwarder is not None:
            restore_forwarder()
        for p in procs:
            if p.poll() is None:
                p.kill()
    if telemetry_dir:
        # Merge AFTER every rank is dead: the per-rank writers are
        # append-only, so this reads complete (or cleanly-torn) streams.
        # Best-effort by the same rule as the event log — observability
        # must never be what fails a launch.
        try:
            from rocm_mpi_tpu.telemetry import aggregate

            summary = aggregate.write_summary(telemetry_dir)
            report.note(
                f"telemetry: merged rank streams {summary['ranks']} "
                f"({summary['records']} records) into "
                f"{telemetry_dir}/telemetry-summary.json"
            )
        except Exception as exc:  # noqa: BLE001
            report.note(f"telemetry merge failed: {exc!r}")
    if health_dir and report.watchdog_verdicts:
        # The post-mortem bundle: per-rank post-mortems + heartbeats +
        # bundle.json naming the verdicts + the merged timeline trace.
        # Clean runs (zero verdicts) deliberately leave no postmortem/
        # directory — an empty bundle would read as a silent incident.
        try:
            from rocm_mpi_tpu.telemetry import health as _health

            bundle = _health.bundle_postmortem(
                health_dir, report.watchdog_verdicts
            )
            report.note(
                f"watchdog: bundled post-mortem for rank(s) "
                f"{[v['rank'] for v in report.watchdog_verdicts]} "
                f"into {bundle}"
            )
        except Exception as exc:  # noqa: BLE001
            report.note(f"watchdog: bundling failed: {exc!r}")
    results = RankResults(zip(procs, outs))
    results.report = report
    return results
