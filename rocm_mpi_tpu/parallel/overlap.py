"""Communication/computation overlap (D8) — the perf_hide rung, working.

Reference intent (/root/reference/scripts/diffusion_2D_perf_hide.jl): split
the update into a boundary frame of width `b_width` computed on a
HIGH-priority HSA queue and an interior computed on a LOW-priority queue,
with `update_halo!` issued between the two waits so the exchange hides
behind interior compute. The shipped code never got there: its active
variant (2) under-covers the frame and skips the halo entirely, and the true
overlap variant (3) is commented "not ready yet" (hide.jl:84-101;
SURVEY.md §3.4 caveat). This module implements variant (3)'s *semantics* —
for any number of dimensions (2D frame, 3D shell) — and lets XLA's
latency-hiding scheduler do the queue juggling:

Per step, inside one shard_map program:
  1. `ppermute` the current field's edge slices to the cartesian neighbors
     (the halo exchange) — depends only on the field's edges;
  2. update the interior region — it reads the UNPADDED local block
     directly (its width-1 stencil window never leaves the shard), so it
     depends on NO ghost value and XLA is free to run the collective and
     the interior compute concurrently (this dataflow independence is the
     whole trick: no user-visible queues, priorities, or signals —
     SURVEY.md §2.2 D8, made explicit rather than left to XLA's
     slice-of-concatenate simplifier);
  3. update the boundary slabs once their ghosts arrive;
  4. write every region's result into one output buffer with
     `lax.dynamic_update_slice` — no per-axis concatenate tree, no
     staging copies; with the masked-coefficient contract (below) held
     cells come back unchanged from the region update itself, so there is
     no trailing whole-shard Dirichlet `jnp.where` either.

Traffic (the A_eff accounting docs/PERF.md formalizes): the old splice
rebuilt the shard through a tree of `jnp.concatenate`s (one staging copy
per axis level) and then paid a whole-shard select; the in-place splice
writes each region exactly once into a buffer XLA can alias with the
input block.

Unlike the reference's two-queue scheme, correctness never rests on manual
signal ordering (hide.jl:69,86-90): the schedule is derived from dataflow,
so there is nothing to race (SURVEY.md §5.2).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from rocm_mpi_tpu import telemetry
from rocm_mpi_tpu.parallel.halo import exchange_halo, exchange_halo_batched
from rocm_mpi_tpu.parallel.mesh import GlobalGrid


def effective_b_width(local_shape, b_width) -> tuple[int, ...]:
    """Clamp the boundary-frame width per axis to at most half the shard
    (the reference's b_width=(32,4) knob, hide.jl:42, made shape-safe).
    A short b_width tuple is extended by repeating its last entry (so the
    2D default applies to 3D grids)."""
    b_width = tuple(b_width)
    if len(b_width) < len(local_shape):
        b_width = b_width + (b_width[-1],) * (len(local_shape) - len(b_width))
    for ln in local_shape:
        if ln < 2:
            raise ValueError(
                f"hide variant needs every shard axis >= 2 cells (local "
                f"shape {tuple(local_shape)}); use variant 'shard' for "
                "degenerate decompositions"
            )
    return tuple(
        max(1, min(int(b), ln // 2)) for b, ln in zip(b_width, local_shape)
    )


def make_overlap_step(
    grid: GlobalGrid,
    padded_update: Callable,
    b_width: tuple[int, ...],
    mask_boundary: bool = True,
    wire_mode: str = "f32",
):
    """Build the shard-local overlap step (any ndim).

    `padded_update(Tp, C, lam, dt, spacing)` is any core-update kernel with
    the padded contract (jnp or Pallas). Returns
    `local_step(Tl, Cl, lam, dt, spacing) -> Tl_new`.

    `Tl` — the exchanged state — may itself be a pytree of same-shaped
    arrays (r4: the shallow-water workload's (h, u, v), whose coupled
    update reads neighbors of every field): each leaf is halo-exchanged
    and region-sliced, `padded_update` receives the padded pytree, returns
    the same-structure core pytree, and the slab/interior splice happens
    leaf-wise. A bare array is the one-leaf case — the diffusion and wave
    callers are unchanged, op for op.

    `Cl` may be any pytree of core-shaped operands (a bare coefficient
    array for the diffusion rungs; a (U_prev, C2) tuple for the wave
    leapfrog; the face masks for SWE) — each leaf is sliced to the region
    and the whole tree is handed to `padded_update` as its second
    argument. Aux operands are read core-only, never exchanged.

    `wire_mode` selects the exchange's on-wire slab precision
    (parallel/wire.py): the per-step overlap program is stateless, so
    only "f32" (bitwise-unchanged) and "bf16" are legal here — the
    exchange decodes every received slab back to the buffer dtype
    BEFORE it reaches the slab updates, so the masked seam (the region
    kernels below) only ever consumes upcast, full-precision-dtype
    ghosts (the GL04 contract).

    `mask_boundary=False` drops the Dirichlet hold entirely: for the
    masked contracts (Cm — the boundary-masked coefficient of
    models.diffusion `_make_masked_step`; the mask-as-data operands of the
    wave and SWE models), held cells already come back unchanged from the
    region update, so any select would be dead work. This is the contract
    every in-repo caller uses. `mask_boundary=True` keeps a hold for
    external padded_updates without a masked form; its edge-cell
    indicators are precomputed at build time (numpy constants closed over
    here — only the ndim scalar `axis_index` compares remain in the traced
    step, not a per-step iota/compare chain).

    The shard is decomposed axis-by-axis into boundary slabs and one
    interior box: axis 0 contributes the first/last `b` rows (full extent
    elsewhere), axis 1 the first/last `b` columns of the remaining middle,
    and so on; the innermost box is the ghost-free interior. Only the
    axis-0/…​ slabs read exchanged ghosts — the interior reads the unpadded
    local block, which is what makes the exchange hideable.
    """
    from rocm_mpi_tpu.parallel import wire

    # Mode validity checked here; the stateful-mode refusal (this
    # program is stateless) fires at trace time inside exchange_halo,
    # so a model whose config carries a deep-only wire mode can still
    # BUILD its per-step variants and run its deep schedule.
    wire.validate_mode(wire_mode)
    bw = effective_b_width(grid.local_shape, b_width)
    splice = _make_region_splice(grid, padded_update, bw, mask_boundary)

    def local_step(Tl, Cpl, lam, dt, spacing):
        if telemetry.enabled():
            # Trace-time: the slab geometry this compiled overlap step
            # uses (the per-leaf halo.exchange byte annotations fire
            # inside exchange_halo below).
            telemetry.annotate(
                "overlap.step", b_width=tuple(int(b) for b in bw),
                leaves=len(jax.tree_util.tree_leaves(Tl)),
                wire=wire_mode,
            )
        # (1) halo exchange of the current state — edge-slice ppermutes,
        # one exchange per state leaf (SWE: 3 fields; diffusion/wave: 1),
        # at the wire mode's on-wire precision (received slabs arrive
        # already decoded to the buffer dtype).
        Tp = jax.tree_util.tree_map(
            lambda t: exchange_halo(t, grid, wire_mode=wire_mode), Tl
        )  # core + 2 per axis
        return splice(Tl, Tp, Cpl, lam, dt, spacing)

    return local_step


def make_batched_overlap_step(
    bgrid,
    padded_update: Callable,
    b_width: tuple[int, ...],
    mask_boundary: bool = False,
    wire_mode: str = "f32",
):
    """The lane-batched overlap step (docs/SERVING.md "The pipeline"):
    the masked-seam hide of `make_overlap_step`, vmapped over the
    leading lane axis of a `BatchedGrid` — the batched serving program
    itself hides its exchange under interior compute, the paper's
    tentpole at batch scale.

    Inside a shard_map over `bgrid.mesh`, `batched_local(Tb_l, Cpl,
    lam, dt, spacing)` takes the local `(local_batch, *local_space)`
    block of `bgrid.spec`-sharded state and the UNBATCHED lane-shared
    aux block. The exchange runs through `exchange_halo_batched`
    (aggregate lane bytes booked on the wire annotation; halo
    collectives stay strictly per-space-axis — nothing ever permutes
    over `batch`), and the region splice is vmapped per lane: the
    interior boxes still read the UNPADDED lane block, so their
    dataflow independence from the (lane-batched) collective — the
    whole hide trick — survives the vmap unchanged.

    Stateless wire modes only (f32/bf16), enforced by
    `exchange_halo_batched`. `mask_boundary` defaults to False — every
    in-repo batched caller is on the Cm masked-coefficient contract."""
    from rocm_mpi_tpu.parallel import wire

    wire.validate_mode(wire_mode)
    space = bgrid.space
    bw = effective_b_width(space.local_shape, b_width)
    splice = _make_region_splice(space, padded_update, bw, mask_boundary)

    def batched_local(Tb_l, Cpl, lam, dt, spacing):
        if telemetry.enabled():
            telemetry.annotate(
                "overlap.step.batched",
                b_width=tuple(int(b) for b in bw),
                lanes=int(jax.tree_util.tree_leaves(Tb_l)[0].shape[0]),
                leaves=len(jax.tree_util.tree_leaves(Tb_l)),
                wire=wire_mode,
            )
        Tp_b = jax.tree_util.tree_map(
            lambda t: exchange_halo_batched(t, bgrid,
                                            wire_mode=wire_mode),
            Tb_l,
        )
        return jax.vmap(
            lambda Tl, Tpl: splice(Tl, Tpl, Cpl, lam, dt, spacing)
        )(Tb_l, Tp_b)

    return batched_local


def _make_region_splice(
    grid: GlobalGrid,
    padded_update: Callable,
    bw: tuple[int, ...],
    mask_boundary: bool,
):
    """Build `splice(Tl, Tp, Cpl, lam, dt, spacing) -> Tl_new`: the
    boundary-slab/interior decomposition and the in-place DUS splice of
    `make_overlap_step`, factored over an ALREADY-exchanged padded
    state `Tp` so the single-lane and lane-batched steps share one
    seam (the batched edition exchanges through
    `exchange_halo_batched` and vmaps this per lane)."""
    local = grid.local_shape
    ndim = grid.ndim

    def boxes(axis, prefix):
        """Enumerate the region boxes (per-axis (lo, hi) core ranges) —
        the same decomposition the concatenate tree used to assemble,
        computed once at build time."""
        if axis == ndim:
            return [tuple(prefix)]  # the interior box
        n, b = local[axis], bw[axis]
        rest = [(0, local[a]) for a in range(axis + 1, ndim)]
        out = [
            tuple(prefix + [(0, b)] + rest),  # lo slab: reads ghosts
            tuple(prefix + [(n - b, n)] + rest),  # hi slab: reads ghosts
        ]
        if n - 2 * b > 0:
            out[1:1] = boxes(axis + 1, prefix + [(b, n - b)])
        return out

    all_boxes = boxes(0, [])

    def ghost_free(bounds):
        """True when the box's width-1 stencil window never leaves the
        unpadded shard — it can (and must, for overlap) read `Tl`."""
        return all(
            lo >= 1 and hi <= local[a] - 1
            for a, (lo, hi) in enumerate(bounds)
        )

    if mask_boundary:
        # Build-time edge indicators (numpy): cell lies on the shard face
        # that COULD be a global-domain face. The traced step only adds
        # the per-axis scalar axis_index compares.
        edge_lo, edge_hi = [], []
        for ax in range(ndim):
            lo = np.zeros(local, bool)
            hi = np.zeros(local, bool)
            lo[tuple(0 if a == ax else slice(None) for a in range(ndim))] = True
            hi[tuple(-1 if a == ax else slice(None) for a in range(ndim))] = True
            edge_lo.append(lo)
            edge_hi.append(hi)

    def splice(Tl, Tp, Cpl, lam, dt, spacing):
        def region(bounds):
            """Candidate update of the core box given by `bounds`. Slab
            boxes read the padded state; ghost-free boxes (the interior)
            read the raw block — no dataflow edge to the collective."""
            core_idx = tuple(slice(lo, hi) for lo, hi in bounds)
            cp = jax.tree_util.tree_map(lambda a: a[core_idx], Cpl)
            if ghost_free(bounds):
                raw_idx = tuple(slice(lo - 1, hi + 1) for lo, hi in bounds)
                tp = jax.tree_util.tree_map(lambda a: a[raw_idx], Tl)
            else:
                pad_idx = tuple(slice(lo, hi + 2) for lo, hi in bounds)
                tp = jax.tree_util.tree_map(lambda a: a[pad_idx], Tp)
            return padded_update(tp, cp, lam, dt, spacing)

        # (2)+(3) region updates, (4) spliced in place: every box is
        # written exactly once, so the seed buffer's values never survive
        # — XLA may alias it with Tl's storage (dead after the exchange),
        # and each region+DUS link lowers to an in-place update-slice
        # fusion (observed on the CPU backend) instead of the old concat
        # tree's whole-shard staging copies.
        new = Tl
        for bounds in all_boxes:
            res = region(bounds)
            origin = tuple(lo for lo, _ in bounds)
            new = jax.tree_util.tree_map(
                lambda o, r: lax.dynamic_update_slice(o, r, origin),
                new, res,
            )
        if not mask_boundary:
            return new
        # Dirichlet hold for unmasked padded_updates: global-domain edge
        # cells keep their old values (edge indicators are build-time
        # constants; only the axis_index compares are traced per step).
        mask = None
        for ax, name in enumerate(grid.axis_names):
            idx = lax.axis_index(name)
            m = ((idx == 0) & edge_lo[ax]) | (
                (idx == grid.dims[ax] - 1) & edge_hi[ax]
            )
            mask = m if mask is None else mask | m
        return jax.tree_util.tree_map(
            lambda old, nw: jnp.where(mask, old, nw), Tl, new
        )

    return splice
