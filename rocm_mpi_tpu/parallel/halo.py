"""Halo exchange (D2) — `update_halo!` re-designed for the TPU.

Reference behavior: each rank's local array overlaps its cartesian neighbors
by 2 cells; `update_halo!(T)` refreshes the overlap with MPI point-to-point,
GPU-direct when IGG_ROCMAWARE_MPI=1, staged through host memory when =0
(/root/reference/scripts/diffusion_2D_ap.jl:42, scripts/setenv.sh:11-18).

TPU-native design: shards are non-overlapping; ghost cells are *transient*.
Inside `shard_map`, `exchange_halo(u, grid)` pads every sharded axis of the
local block with `width` cells fetched from the cartesian neighbors via
`lax.ppermute` — which XLA lowers to collective-permute riding the ICI, the
interconnect analog of GPU-direct MPI (no host staging, SURVEY.md §2.4).
Axes are exchanged sequentially, so the second axis sends slices that
include the first axis's already-received ghosts and corner ghosts arrive
from diagonal neighbors for free (the standard two-stage corner trick).

Traffic discipline (the A_eff accounting the perf gate audits,
docs/PERF.md): padding is ONE preallocated buffer — `place_core` writes
the block into the ghost-ringed buffer once, `exchange_into` then writes
each received ghost slice in place with `lax.dynamic_update_slice`. The
old form rebuilt the whole padded array with a fresh `jnp.concatenate`
copy per exchanged axis (ndim whole-shard staging copies per exchange);
the in-place form stages exactly one, which XLA's buffer assignment can
further alias away. `exchange_into` is exposed separately so callers that
already hold a padded buffer (the overlap and deep-halo schedules) reuse
it without re-staging the core.

Non-periodic boundaries: ppermute entries are omitted at the domain edge, so
edge ghosts arrive as zeros. Their values are never *used*: the global
boundary cells they would feed are Dirichlet-fixed and masked out by
`global_boundary_mask` (the reference equivalently never updates
`T[1,:]`-type cells — ap.jl:41 updates the interior view only).

The host-staged fallback (`HostStagedStepper`, the IGG_ROCMAWARE_MPI=0
analog) lives here too: a pure-numpy step driver usable as a transport-free
correctness oracle — "is it the device collective or my math?" (SURVEY.md §4.4).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from rocm_mpi_tpu import telemetry
from rocm_mpi_tpu.parallel import wire
from rocm_mpi_tpu.parallel.mesh import GlobalGrid


def exchange_nbytes(local_shape, itemsize: int, width: int = 1,
                    axes=None, wire_mode: str = "f32") -> int:
    """Bytes an interior device SENDS per `exchange_halo` call: two
    width-`width` edge slices per exchanged axis, sized against the
    block as it grows (the sequential corner trick means axis k's slices
    include axis <k's padding), at the ON-WIRE itemsize of `wire_mode`
    (parallel/wire.py: bf16 ships 2-byte elements, the int8 modes 1-byte
    plus a per-slab scale scalar; "f32" means the state dtype verbatim).
    Edge-of-domain devices send less (their ppermute entries are
    omitted); the interior figure is the per-device capacity number
    telemetry wants — reporting the state itemsize for a reduced-
    precision exchange would corrupt the `halo bytes/s` aggregate and
    any regress baseline built on it."""
    return wire.exchange_wire_nbytes(
        local_shape, int(itemsize), width, axes, wire_mode
    )


def neighbor_shift(x, axis_name: str, direction: int):
    """Send `x` to the neighbor `direction` steps up the mesh axis
    (non-periodic: edge devices receive zeros)."""
    from rocm_mpi_tpu.utils.compat import axis_size

    n = axis_size(axis_name)
    if direction == +1:
        perm = [(i, i + 1) for i in range(n - 1)]
    elif direction == -1:
        perm = [(i + 1, i) for i in range(n - 1)]
    else:
        raise ValueError("direction must be +1 or -1")
    return lax.ppermute(x, axis_name, perm)


def place_core(u, width: int = 1, axes=None):
    """Preallocate the ghost-ringed buffer and write `u` into its core.

    Returns a zero buffer grown by 2*width along each `axes` entry with
    `u` placed at offset `width` there — ONE staging write, the only
    whole-block copy the in-place exchange pays. Edge-of-domain ghost
    slices that no neighbor overwrites stay zero, which IS the framework's
    zero-ghost boundary convention.
    """
    axes = set(range(u.ndim) if axes is None else axes)
    shape = tuple(
        n + 2 * width if a in axes else n for a, n in enumerate(u.shape)
    )
    start = tuple(width if a in axes else 0 for a in range(u.ndim))
    return lax.dynamic_update_slice(jnp.zeros(shape, u.dtype), u, start)


def exchange_into(buf, grid: GlobalGrid, width: int = 1, axes=None,
                  wire_mode: str = "f32", wire_state=None):
    """Fill the ghost ring of a padded buffer with neighbor slices
    (inside shard_map). `buf` is a `place_core`-shaped buffer: core at
    offset `width` along every exchanged axis.

    Axis k's sends span the ghosts of axes < k (the two-stage corner
    trick) and only the core of axes > k, so the wire bytes match
    `exchange_nbytes` exactly. The corner extensions are assembled from
    the RECEIVED slabs of earlier axes — tiny width×width concatenates —
    never by re-reading the updated buffer: every received slab then
    lands via one `lax.dynamic_update_slice` in a single-consumer chain,
    which XLA's buffer assignment executes fully in place (re-slicing the
    updated buffer for later sends would force it to materialize a
    defensive whole-buffer copy — the staging cost this module exists to
    remove). Non-periodic boundaries: ppermute entries are omitted at the
    domain edge, so edge devices receive zeros — harmless writes into the
    zero ring.

    `wire_mode` selects the on-wire slab representation (the
    wire-precision plane, parallel/wire.py): "f32" ships the slab
    verbatim — the identical program to the pre-wire-plane exchange;
    "bf16" downcasts each send and upcasts on receive, BEFORE the slab
    touches the buffer or any later axis's corner assembly (the seam
    only ever consumes decoded, buffer-dtype slabs). The stateful modes
    ("int8", "int8_delta") additionally take and return `wire_state` —
    the flat per-slab state tuple `wire.init_exchange_state` builds —
    and the return value becomes `(buf, new_state)`.
    """
    axes = tuple(range(grid.ndim) if axes is None else axes)
    exchanged = set(axes)
    ndim = buf.ndim
    width = int(width)
    stateful = wire.is_stateful(wire_mode)
    if stateful and wire_state is None:
        raise ValueError(
            f"wire_mode {wire_mode!r} carries error-feedback state across "
            "exchanges; per-step (stateless) paths support f32/bf16 only — "
            "use the deep-halo schedules (run_deep / --deep), which thread "
            "the state through their sweep carry"
        )
    codec = wire.slab_codec(wire_mode)
    arity = wire.state_arity(wire_mode)
    new_state: list = []
    slab_i = 0

    def core_extent(a):
        return buf.shape[a] - (2 * width if a in exchanged else 0)

    recv: dict = {}  # (axis, side) -> received slab
    done: list = []
    for ax in axes:
        name = grid.axis_names[ax]
        n = core_extent(ax)

        def core_edge(off):
            # The buffer's own edge hyperslab (pre-update reads only):
            # core extent on every other exchanged axis.
            idx = tuple(
                slice(off, off + width) if a == ax
                else slice(width, width + core_extent(a))
                if a in exchanged else slice(None)
                for a in range(ndim)
            )
            return buf[idx]

        def send_slab(lo_side):
            # Core edge, extended along each already-exchanged axis with
            # the matching edge pieces of ITS received slabs — at each
            # step the extents line up because recv[(a, ·)] spans full
            # padded extent on axes exchanged before `a` and core extent
            # after (the same invariant this concat establishes).
            piece = core_edge(width if lo_side else n)
            edge = slice(0, width) if lo_side else slice(n - width, n)
            sel = tuple(
                edge if a == ax else slice(None) for a in range(ndim)
            )
            for a in done:
                piece = jnp.concatenate(
                    [recv[(a, "lo")][sel], piece, recv[(a, "hi")][sel]],
                    axis=a,
                )
            return piece

        for side, lo_side, direction in (("lo", False, +1),
                                         ("hi", True, -1)):
            if wire_mode == "f32":
                # Bitwise-identical fast path: no codec ops traced.
                recv[(ax, side)] = neighbor_shift(
                    send_slab(lo_side), name, direction
                )
            else:
                st = tuple(
                    wire_state[slab_i * arity + j] for j in range(arity)
                ) if stateful else ()
                payload, st = codec.send(send_slab(lo_side), st)
                shipped = tuple(
                    neighbor_shift(p, name, direction) for p in payload
                )
                recv[(ax, side)], st = codec.recv(shipped, st, buf.dtype)
                new_state.extend(st)
            slab_i += 1
        done.append(ax)

    for i, ax in enumerate(done):
        n = core_extent(ax)
        for side, off in (("lo", 0), ("hi", n + width)):
            starts = tuple(
                off if a == ax
                else 0 if a in done[:i] or a not in exchanged
                else width
                for a in range(ndim)
            )
            buf = lax.dynamic_update_slice(buf, recv[(ax, side)], starts)
    if stateful:
        return buf, tuple(new_state)
    return buf


def exchange_halo(u, grid: GlobalGrid, width: int = 1, axes=None,
                  wire_mode: str = "f32", wire_state=None):
    """Pad the local block `u` with neighbor ghost cells (inside shard_map).

    Returns an array grown by 2*width along each exchanged axis. This is the
    `update_halo!(T)` analog: one call per step, all axes
    (diffusion_2D_ap.jl:42). Composition of `place_core` + `exchange_into`
    — one staged copy, ghost slices written in place.

    `wire_mode` selects the on-wire slab precision (exchange_into has the
    contract); the stateful modes take/return `wire_state` and the result
    becomes `(padded, new_state)`. The default "f32" traces the exact
    pre-wire-plane program — bitwise identical on every workload.
    """
    axes = tuple(range(grid.ndim) if axes is None else axes)
    if telemetry.enabled():
        # Trace-time annotation: shapes are concrete while jax traces, so
        # "this program moves N bytes per exchange" is recordable exactly
        # once per compiled program (telemetry.events.annotate dedups).
        # `bytes` is the TRUE on-wire figure for the active wire mode —
        # a bf16 exchange must never book f32 bytes into the halo
        # bytes/s aggregate or a regress baseline.
        telemetry.annotate(
            "halo.exchange",
            bytes=exchange_nbytes(u.shape, u.dtype.itemsize, width, axes,
                                  wire_mode),
            width=width,
            block=tuple(int(n) for n in u.shape),
            wire=wire_mode,
        )
    return exchange_into(place_core(u, width, axes), grid, width, axes,
                         wire_mode=wire_mode, wire_state=wire_state)


def exchange_halo_batched(ub, bgrid, width: int = 1, axes=None,
                          wire_mode: str = "f32"):
    """Per-lane halo exchange of a lane-leading batched block (inside a
    shard_map over a space×batch mesh, docs/SERVING.md): `ub` is the
    local block of `bgrid.spec`-sharded state, shape
    ``(local_batch, *local_space)``, and the exchange is `exchange_halo`
    vmapped over the leading lane axis — the halo collectives stay
    strictly per-space-axis (ppermute's batching rule carries the lane
    dim along each slab, so lane k's ghosts only ever come from lane
    k's spatial neighbors; nothing is permuted over the `batch` axis —
    lanes are separate tenants).

    Stateless wire modes only (f32/bf16): the error-feedback state of
    the int8 modes is per-logical-wire, and a lane-batched exchange
    would need a per-lane state plane nothing carries yet."""
    if wire.is_stateful(wire_mode):
        raise ValueError(
            f"wire_mode {wire_mode!r} is stateful; batched exchanges "
            "support the stateless modes (f32/bf16) only"
        )
    space = bgrid.space if hasattr(bgrid, "space") else bgrid
    if telemetry.enabled():
        telemetry.annotate(
            "halo.exchange.batched",
            lanes=int(ub.shape[0]),
            bytes=int(ub.shape[0]) * exchange_nbytes(
                ub.shape[1:], ub.dtype.itemsize, width, axes, wire_mode
            ),
            width=width,
            block=tuple(int(n) for n in ub.shape[1:]),
            wire=wire_mode,
        )
    return jax.vmap(
        lambda u: exchange_into(
            place_core(u, width, axes), space, width, axes,
            wire_mode=wire_mode,
        )
    )(ub)


class HaloProgram(NamedTuple):
    """A halo exchange family bound to one decomposition: the grid it was
    derived for, the ghost width, the bound `exchange(u)` closure (inside
    shard_map), and `nbytes(itemsize)` — the per-interior-device wire
    bytes of one call (the telemetry/traffic accounting figure, at the
    program's wire mode)."""

    grid: GlobalGrid
    width: int
    exchange: Callable
    nbytes: Callable
    wire_mode: str = "f32"


def build_for_mesh(grid: GlobalGrid, width: int = 1,
                   wire_mode: str = "f32") -> HaloProgram:
    """Bind the halo exchange family to `grid` — the derivation
    `rebuild_for_mesh` re-runs when the decomposition changes."""
    wire.validate_mode(wire_mode)
    return HaloProgram(
        grid=grid,
        width=width,
        exchange=lambda u, axes=None: exchange_halo(
            u, grid, width, axes, wire_mode=wire_mode
        ),
        nbytes=lambda itemsize, axes=None: exchange_nbytes(
            grid.local_shape, itemsize, width, axes, wire_mode
        ),
        wire_mode=wire_mode,
    )


def rebuild_for_mesh(
    program_or_grid, dims=None, devices=None, width: int | None = None
) -> HaloProgram:
    """Re-derive the halo programs for a NEW decomposition of the same
    global domain (docs/RESILIENCE.md "Elastic recovery"): an elastic
    resume lands a checkpoint on a different mesh, and every per-mesh
    derived quantity — neighbor structure, ghost slice shapes, wire
    bytes, the boundary-mask geometry the exchange's zero-ghost
    convention leans on — must come from the NEW dims, never be reused
    from the old. Accepts a HaloProgram (rebuilds its grid and width) or
    a GlobalGrid; `dims`/`devices` follow mesh.rebuild_for_mesh (default:
    the plan_dims sub-mesh over the current devices)."""
    from rocm_mpi_tpu.parallel import mesh as _mesh

    if isinstance(program_or_grid, HaloProgram):
        old_grid = program_or_grid.grid
        width = program_or_grid.width if width is None else width
    else:
        old_grid = program_or_grid
        width = 1 if width is None else width
    wire_mode = (
        program_or_grid.wire_mode
        if isinstance(program_or_grid, HaloProgram) else "f32"
    )
    new_grid = _mesh.rebuild_for_mesh(old_grid, dims=dims, devices=devices)
    if any(width > ln for ln in new_grid.local_shape):
        raise ValueError(
            f"halo width {width} exceeds a local shard extent "
            f"{new_grid.local_shape} on the rebuilt mesh {new_grid.dims}"
        )
    return build_for_mesh(new_grid, width, wire_mode=wire_mode)


def global_boundary_mask(grid: GlobalGrid, dtype=bool):
    """Per-shard mask of global-domain boundary cells (inside shard_map).

    True where the cell lies on the global boundary — the cells the
    reference never updates (interior-only update, ap.jl:41). Uses
    `lax.axis_index` to locate the shard in the cartesian topology.
    """
    local = grid.local_shape
    mask = jnp.zeros(local, dtype=bool)
    for ax, name in enumerate(grid.axis_names):
        ln = local[ax]
        n_g = grid.global_shape[ax]
        gidx = lax.axis_index(name) * ln + lax.broadcasted_iota(
            jnp.int32, local, ax
        )
        mask = mask | (gidx == 0) | (gidx == n_g - 1)
    return mask.astype(dtype) if dtype is not bool else mask


class HostStagedStepper:
    """Pure-numpy diffusion stepper with explicitly host-staged halos.

    The IGG_ROCMAWARE_MPI=0 analog (README.md:25-35): every step, each
    shard's boundary slices are copied through host memory to its neighbors'
    ghost buffers, then each shard is updated independently. Device-free by
    construction, so any disagreement with the `shard` variant isolates the
    device collective path — the same bisection affordance the reference's
    toggle provides. Debug/oracle use only; O(host-memory-bandwidth).
    """

    def __init__(
        self, grid: GlobalGrid, lam: float, dt: float,
        use_native: bool | None = None, wire_mode: str = "f32",
    ):
        self.grid = grid
        self.lam = lam
        self.dt = dt
        # The wire-precision oracle twin: apply the numpy wire codec to
        # every ghost slab copied between shards, with the error-feedback
        # / delta state held per logical wire in the codec itself (this
        # stepper is the oracle world's one stateful object). "f32" is
        # the identity — the classic oracle, bit for bit.
        self.wire_mode = wire.validate_mode(wire_mode)
        self._codec = (
            wire.NumpyWireCodec(wire_mode) if wire_mode != "f32" else None
        )
        if use_native is None:
            from rocm_mpi_tpu.parallel import native_halo

            use_native = native_halo.available() and grid.ndim <= 3
        # The native C++ engine stages full-precision ghosts only; any
        # reduced-precision wire must run the numpy path.
        self.use_native = use_native and wire_mode == "f32"

    def _shard_slices(self, coords) -> tuple[slice, ...]:
        local = self.grid.local_shape
        return tuple(
            slice(c * ln, (c + 1) * ln) for c, ln in zip(coords, local)
        )

    def step(self, T: np.ndarray, Cp: np.ndarray) -> np.ndarray:
        """One host-staged step. Dispatches to the native C++ engine
        (native/halostage.cpp, bit-identical, multithreaded) when built;
        falls back to the readable numpy implementation below."""
        if (
            self.use_native
            and T.dtype == np.float64
            and Cp.dtype == np.float64
        ):
            from rocm_mpi_tpu.parallel import native_halo

            return native_halo.host_staged_step(
                T, Cp, self.grid.dims, self.grid.spacing, self.lam, self.dt
            )
        return self.step_python(T, Cp)

    def step_python(self, T: np.ndarray, Cp: np.ndarray) -> np.ndarray:
        grid = self.grid
        ndim = grid.ndim
        local = grid.local_shape
        spacing = grid.spacing

        # Phase 1 — host-staged halo exchange: every shard's padded block is
        # assembled in host memory, ghost slices read from neighbor shards
        # (zeros at the domain edge, as in exchange_halo). The two phases
        # here are REAL host-level seams — the one stepper whose halo and
        # interior costs telemetry can time directly rather than probe.
        padded = {}
        with telemetry.span("halo.host_staged", phase="halo") as hsp:
            copied = 0
            for coords in np.ndindex(*grid.dims):
                block = np.zeros(
                    tuple(ln + 2 for ln in local), dtype=T.dtype
                )
                inner = tuple(slice(1, -1) for _ in range(ndim))
                core = self._shard_slices(coords)
                block[inner] = T[core]
                for ax in range(ndim):
                    for side, nb_off in (("lo", -1), ("hi", +1)):
                        nb = list(coords)
                        nb[ax] += nb_off
                        if not 0 <= nb[ax] < grid.dims[ax]:
                            continue  # domain edge: ghost stays zero (unused)
                        nb_core = self._shard_slices(nb)
                        src = list(nb_core)
                        dst = [slice(1, 1 + ln) for ln in local]
                        if nb_off == -1:  # ghost row 0 <- neighbor's last row
                            src[ax] = slice(
                                nb_core[ax].stop - 1, nb_core[ax].stop
                            )
                            dst[ax] = slice(0, 1)
                        else:  # last ghost row <- neighbor's first row
                            src[ax] = slice(
                                nb_core[ax].start, nb_core[ax].start + 1
                            )
                            dst[ax] = slice(local[ax] + 1, local[ax] + 2)
                        ghost = T[tuple(src)]
                        if self._codec is not None:
                            # One logical wire per (receiver, axis,
                            # side): the codec's residual/reconstruction
                            # state persists across steps under this key.
                            ghost = self._codec.apply(
                                (coords, ax, side), ghost
                            )
                        block[tuple(dst)] = ghost
                        copied += wire.wire_slab_nbytes(
                            ghost.size, T.dtype.itemsize, self.wire_mode
                        )
                padded[coords] = block
            hsp.set(bytes=copied)

        # Phase 2 — independent per-shard update (fused stencil), global
        # boundary cells Dirichlet-fixed. Multiply by the precomputed
        # reciprocal (not divide) so results are bit-identical to the native
        # engine (native/halostage.cpp) and the Pallas kernels.
        inv_d2 = tuple(1.0 / (d * d) for d in spacing)
        out = np.array(T, copy=True)
        with telemetry.span("interior.host_staged", phase="interior"):
            for coords, block in padded.items():
                inner = tuple(slice(1, -1) for _ in range(ndim))
                core = self._shard_slices(coords)
                lap = np.zeros(local, dtype=T.dtype)
                for ax in range(ndim):
                    hi_s = tuple(
                        slice(2, None) if a == ax else slice(1, -1)
                        for a in range(ndim)
                    )
                    lo_s = tuple(
                        slice(None, -2) if a == ax else slice(1, -1)
                        for a in range(ndim)
                    )
                    lap += (
                        block[hi_s] - 2.0 * block[inner] + block[lo_s]
                    ) * inv_d2[ax]
                new = T[core] + self.dt * self.lam / Cp[core] * lap
                # Dirichlet mask: global boundary cells keep old values.
                keep = np.zeros(local, dtype=bool)
                for ax in range(ndim):
                    gidx = coords[ax] * local[ax] + np.arange(local[ax])
                    edge = (gidx == 0) | (gidx == grid.global_shape[ax] - 1)
                    sh = [1] * ndim
                    sh[ax] = local[ax]
                    keep |= edge.reshape(sh)
                out[core] = np.where(keep, T[core], new)
        return out

    def run(self, T: np.ndarray, Cp: np.ndarray, nt: int) -> np.ndarray:
        # The one per-step HOST loop in the framework, so it feeds the
        # health plane directly: a "step" fault point (deterministic
        # drills) and a flight-recorder step bump per step — the halo /
        # interior spans in step_python already land in the flight ring
        # via the events tap. Both are one-global-read no-ops when the
        # recorder / fault plan are off.
        from rocm_mpi_tpu.resilience import faults
        from rocm_mpi_tpu.telemetry import flight

        for i in range(nt):
            faults.fault_point("step", step=i + 1)
            # Additive: the recorder's step counter is process-global,
            # and a second .run() restarting at 1 would be masked by
            # its monotonic guard.
            flight.progress(step_inc=1)
            T = self.step(T, Cp)
        return T
