"""Parallelism layer: device-mesh global grid, halo exchange, gather, overlap."""

from rocm_mpi_tpu.parallel.mesh import (  # noqa: F401
    BatchedGrid,
    GlobalGrid,
    init_batched_grid,
    init_global_grid,
    suggest_dims,
)
from rocm_mpi_tpu.parallel.gather import gather_to_host0  # noqa: F401
from rocm_mpi_tpu.parallel.halo import (  # noqa: F401
    HostStagedStepper,
    exchange_halo,
    exchange_into,
    global_boundary_mask,
    neighbor_shift,
    place_core,
)
from rocm_mpi_tpu.parallel.ring import ring_exchange, ring_exchange_demo  # noqa: F401
