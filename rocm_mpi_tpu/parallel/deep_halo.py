"""Deep-halo sweeps: width-k ghost exchange every k steps.

The multi-chip form of temporal blocking, and the TPU-first endpoint of the
reference's communication-ladder: where the reference hides a width-1 halo
exchange behind interior compute every step
(/root/reference/scripts/diffusion_2D_perf_hide.jl:94-101, its intended
variant (3)), the deep-halo sweep removes most exchanges altogether —
each device receives a k-wide ghost region once, then advances its block k
steps entirely locally (the ghost light cone keeps the core exact; stale
ghost cells are cropped at sweep end). Communication drops from one
latency-bound message per neighbor per step to one k-times-larger message
per neighbor per k steps — the shape ICI wants: fewer, larger transfers,
k× less exposed latency. Same total exchanged volume, identical math
(fp-reordering aside) to k per-step updates.

Correctness argument (the same light-cone bound as the HBM temporal
blocking in ops.pallas_kernels._tb_kernel): after s local steps, values at
ghost depth ≥ s+1 are stale and roll-wraparound garbage has penetrated
s-1 cells into the k-wide ghost ring; for s ≤ k neither reaches the core.
Dirichlet global-boundary cells are held by a zero update coefficient, and
off-domain ghost cells (domain edge) hold zeros with a zero coefficient —
the zero-ghost convention used framework-wide.

Cp handling: the update coefficient needs neighbor Cp values in the ghost
ring, so each sweep also exchanges Cp's halo. Cp is time-invariant, so this
is redundant work — but it is two small ppermutes per axis amortized over
k steps, and keeping it inside the sweep keeps the carried loop state to
the bare field.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax, shard_map

from rocm_mpi_tpu.parallel.halo import exchange_halo
from rocm_mpi_tpu.parallel.mesh import GlobalGrid


def padded_update_coefficient(Cp_padded, grid: GlobalGrid, width: int,
                              lam, dt):
    """Masked dt·λ/Cp for a width-`width` padded block (inside shard_map).

    Zero where the cell must not update: global Dirichlet boundary cells,
    and off-domain ghost cells (where the exchanged `Cp_padded` is itself
    zero — guarded so the division cannot produce inf).
    """
    shape = Cp_padded.shape
    mask = None
    for ax, name in enumerate(grid.axis_names):
        ln = grid.local_shape[ax]
        n_g = grid.global_shape[ax]
        gidx = (
            lax.axis_index(name) * ln
            + lax.broadcasted_iota(jnp.int32, shape, ax)
            - width
        )
        m = (gidx <= 0) | (gidx >= n_g - 1)
        mask = m if mask is None else (mask | m)
    safe = jnp.where(Cp_padded == 0, jnp.ones_like(Cp_padded), Cp_padded)
    return jnp.where(mask, jnp.zeros_like(Cp_padded), (dt * lam) / safe)


def make_deep_sweep(grid: GlobalGrid, k: int, lam, dt, spacing):
    """Build sweep(T, Cp) -> T advanced k steps, one halo exchange total.

    The local k-step kernel is the same unrolled roll-based Pallas program
    as the single-chip VMEM-resident path (ops.pallas_kernels.multi_step_cm)
    — the deep-halo design makes every chip's inner loop identical to the
    fastest single-chip loop, with communication only at sweep boundaries.
    Shards too large for VMEM route to the temporal-blocked HBM sweep
    (multi_step_cm_hbm, k ≤ 8): the same schedule at every scale —
    exchange once, advance k steps locally, crop.
    """
    if k < 1:
        raise ValueError(f"sweep depth k must be >= 1, got {k}")
    if any(k > ln for ln in grid.local_shape):
        raise ValueError(
            f"sweep depth {k} exceeds a local shard extent "
            f"{grid.local_shape}; ghost slices need width <= shard"
        )
    from rocm_mpi_tpu.ops.pallas_kernels import (
        _TB_G,
        _TB_TM,
        _VMEM_BLOCK_BUDGET_BYTES,
        multi_step_cm,
        multi_step_cm_hbm,
    )

    core = tuple(slice(k, -k) for _ in range(grid.ndim))

    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)

    def jnp_k_steps(Tp, Cm):
        # Any-shape/any-k fallback: the same roll+Cm semantics as the
        # Pallas kernels, XLA-fused. Slower (no temporal blocking) but
        # never shape-constrained — the HBM kernel's stripe divisibility
        # and k <= 8 bound do not always survive run_deep's depth
        # degradation (effective_block_steps), and a crashed sweep is
        # strictly worse than a slower one.
        for _ in range(k):
            lap = None
            for ax in range(Tp.ndim):
                term = (
                    jnp.roll(Tp, -1, ax) + jnp.roll(Tp, 1, ax) - 2.0 * Tp
                ) * inv_d2[ax]
                lap = term if lap is None else lap + term
            Tp = Tp + Cm * lap
        return Tp

    def local_sweep(Tl, Cpl):
        Tp = exchange_halo(Tl, grid, width=k)
        Cpp = exchange_halo(Cpl, grid, width=k)
        Cm = padded_update_coefficient(Cpp, grid, k, lam, dt)
        n0p = Tp.shape[0]
        if Tp.size * Tp.dtype.itemsize <= _VMEM_BLOCK_BUDGET_BYTES:
            Tp = multi_step_cm(Tp, Cm, spacing, k)
        elif (
            Tp.ndim in (2, 3)
            and k <= _TB_G
            and n0p % _TB_TM == 0
            and (n0p // _TB_TM) >= 2
        ):
            Tp = multi_step_cm_hbm(Tp, Cm, spacing, k)
        else:
            Tp = jnp_k_steps(Tp, Cm)
        return Tp[core]

    def sweep(T, Cp):
        return shard_map(
            local_sweep,
            mesh=grid.mesh,
            in_specs=(grid.spec, grid.spec),
            out_specs=grid.spec,
            check_vma=False,
        )(T, Cp)

    return sweep
