"""Deep-halo sweeps: width-k ghost exchange every k steps.

The multi-chip form of temporal blocking, and the TPU-first endpoint of the
reference's communication-ladder: where the reference hides a width-1 halo
exchange behind interior compute every step
(/root/reference/scripts/diffusion_2D_perf_hide.jl:94-101, its intended
variant (3)), the deep-halo sweep removes most exchanges altogether —
each device receives a k-wide ghost region once, then advances its block k
steps entirely locally (the ghost light cone keeps the core exact; stale
ghost cells are cropped at sweep end). Communication drops from one
latency-bound message per neighbor per step to one k-times-larger message
per neighbor per k steps — the shape ICI wants: fewer, larger transfers,
k× less exposed latency. Same total exchanged volume, identical math
(fp-reordering aside) to k per-step updates.

Correctness argument (the same light-cone bound as the HBM temporal
blocking in ops.pallas_kernels._tb_kernel): after s local steps, values at
ghost depth ≥ s+1 from the core are stale (the outermost ghost layer is
either roll-wraparound garbage or held, depending on the local kernel —
both contaminate inward one cell per step); for s ≤ k neither reaches the
core. Dirichlet global-boundary cells are held by a zero update
coefficient, and off-domain ghost cells (domain edge) hold zeros with a
zero coefficient — the zero-ghost convention used framework-wide.

Time-invariant operands are exchanged ONCE per compiled advance, not once
per sweep: every builder returns a `DeepSchedule(prepare, sweep, k)`
where `prepare` runs the ghost exchange + masking of the loop-invariant
operands (diffusion's Cp→Cm, the wave's C2→(M, Cw), the SWE face masks)
as its own shard_map program whose *block-padded* output the caller
hoists outside the `fori_loop` — the carried loop state stays the bare
field(s), and the per-sweep program exchanges exactly the state. (The old
form re-exchanged the coefficient inside every sweep; the perf gate's
traffic audit, docs/PERF.md, is what made that cost visible.)
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax.numpy as jnp
from jax import lax

from rocm_mpi_tpu.utils.compat import shard_map

from rocm_mpi_tpu import telemetry
from rocm_mpi_tpu.parallel import wire
from rocm_mpi_tpu.parallel.halo import exchange_halo
from rocm_mpi_tpu.parallel.mesh import GlobalGrid


class DeepSchedule(NamedTuple):
    """A deep-halo schedule: `prepare(*aux)` exchanges/masks the
    loop-invariant operands once (returning block-padded global arrays —
    each shard's slice is its k-padded block), `sweep(state…, prepared)`
    advances the state k steps with one state exchange. Callers jit
    `prepare` outside their step loop and carry only the state.

    `rebuild(new_grid)` re-derives the SAME schedule (physics constants,
    depth, local form, wire mode) for a new decomposition — the
    elastic-resume path (rebuild_for_mesh below): ghost widths, padded
    block geometry, face masks, and the VMEM-vs-HBM local-kernel routing
    all depend on the shard shape, so nothing built for the old mesh may
    be reused.

    `wire_mode` is the state exchange's on-wire precision
    (parallel/wire.py; the loop-invariant `prepare` exchange always
    ships full precision — it runs once per compiled advance, so its
    bytes are not the term that grows with the mesh, and coefficient
    error would bias every step). For the stateful modes
    (int8/int8_delta) `init_wire(dtype)` builds the flat zero wire-state
    tuple and `sweep` grows a trailing wire-state argument + return:
    `sweep(state…, prepared, wire_state) -> (state…, wire_state)` — the
    drivers carry it alongside the field(s). `init_wire` is None for
    stateless modes and the sweep signature is unchanged."""

    prepare: Callable
    sweep: Callable
    k: int
    rebuild: Callable | None = None
    wire_mode: str = "f32"
    init_wire: Callable | None = None


def _validate_depth(grid: GlobalGrid, k: int, label: str = "sweep depth"):
    if k < 1:
        raise ValueError(f"{label} k must be >= 1, got {k}")
    if any(k > ln for ln in grid.local_shape):
        raise ValueError(
            f"{label} {k} exceeds a local shard extent "
            f"{grid.local_shape}; ghost slices need width <= shard"
        )


def padded_hold_mask(shape, grid: GlobalGrid, width: int):
    """Boolean mask over a width-`width` padded block (inside shard_map):
    True where the cell must NOT update — global Dirichlet boundary cells
    and off-domain ghost cells, located by global index."""
    mask = None
    for ax, name in enumerate(grid.axis_names):
        ln = grid.local_shape[ax]
        n_g = grid.global_shape[ax]
        gidx = (
            lax.axis_index(name) * ln
            + lax.broadcasted_iota(jnp.int32, shape, ax)
            - width
        )
        m = (gidx <= 0) | (gidx >= n_g - 1)
        mask = m if mask is None else (mask | m)
    return mask


def padded_update_coefficient(Cp_padded, grid: GlobalGrid, width: int,
                              lam, dt):
    """Masked dt·λ/Cp for a width-`width` padded block (inside shard_map).

    Zero where the cell must not update: global Dirichlet boundary cells,
    and off-domain ghost cells (where the exchanged `Cp_padded` is itself
    zero — guarded so the division cannot produce inf).
    """
    mask = padded_hold_mask(Cp_padded.shape, grid, width)
    safe = jnp.where(Cp_padded == 0, jnp.ones_like(Cp_padded), Cp_padded)
    return jnp.where(mask, jnp.zeros_like(Cp_padded), (dt * lam) / safe)


def resolve_deep_config(grid: GlobalGrid, dtype,
                        config: str | None) -> dict:
    """The tuned deep-halo configuration for this shard/topology:
    ``{"k": int | None, "wire_mode": str | None}`` — None fields mean
    "use the model's default policy". The deep edition of the
    `config="auto"` seam: consults the tuning cache (tuning/resolve.py,
    op "diffusion.deep", keyed by the LOCAL shard shape and mesh dims —
    the winner shifts with both) and re-validates the cached depth
    against this grid's shard extents, because a cache entry tuned on
    one mesh can outlive a reshard that shrank the shards
    (`_validate_depth`'s own rule, applied silently: a stale depth falls
    back to the default policy rather than crashing an auto run). The
    wire mode rides the same entry (the PR-12 wire axis) — resolve's
    sanitizer already dropped unknown modes, and the gate/validate CLI
    is the loud half that rejects an uncertified or over-ladder one."""
    nothing = {"k": None, "wire_mode": None}
    if config in (None, "default"):
        return nothing
    if config != "auto":
        raise ValueError(
            f"config must be None, 'default' or 'auto', got {config!r}"
        )
    import jax

    if jax.process_count() > 1:
        # Multi-controller: each process resolves from its own cache
        # file, and ranks disagreeing on k (or on the wire mode — a
        # bf16 sender into an f32 receiver is a dtype-mismatched
        # collective) build schedules with MISMATCHED collectives — a
        # distributed hang, not an error. The default policy is
        # deterministic on every rank; auto stays hands-off until a
        # broadcast-consistent resolve exists.
        return nothing
    from rocm_mpi_tpu.tuning import resolve as tuning_resolve

    tuned = tuning_resolve.resolve(
        "diffusion.deep", grid.local_shape, dtype, topology=grid.dims
    )
    if not tuned:
        return nothing
    out = dict(nothing)
    if tuned.get("k"):
        k = int(tuned["k"])
        if k >= 1 and all(k <= ln for ln in grid.local_shape):
            out["k"] = k
    if tuned.get("wire_mode"):
        out["wire_mode"] = str(tuned["wire_mode"])
    return out


def resolve_deep_k(grid: GlobalGrid, dtype, config: str | None) -> int | None:
    """The tuned sweep depth alone (resolve_deep_config's k field) —
    the pre-wire-axis spelling, kept for existing callers."""
    return resolve_deep_config(grid, dtype, config)["k"]


def rebuild_for_mesh(sched: DeepSchedule, new_grid: GlobalGrid,
                     dims=None, devices=None) -> DeepSchedule:
    """Re-derive `sched` for a new decomposition of the same global
    domain (docs/RESILIENCE.md "Elastic recovery"). `new_grid` is the
    rebuilt GlobalGrid (mesh.rebuild_for_mesh output), or the OLD grid
    together with `dims`/`devices` to rebuild here. Depth validation is
    the builder's own (_validate_depth): a mesh grown so far that k
    exceeds a shard extent fails loudly, exactly as a fresh build would."""
    if sched.rebuild is None:
        raise ValueError(
            "this DeepSchedule predates the rebuild path (built by hand?) "
            "— reconstruct it with its make_*_deep_sweep builder"
        )
    if dims is not None or devices is not None:
        from rocm_mpi_tpu.parallel import mesh as _mesh

        new_grid = _mesh.rebuild_for_mesh(new_grid, dims=dims,
                                          devices=devices)
    return sched.rebuild(new_grid)


def make_deep_sweep(grid, k: int, lam, dt, spacing,
                    local_form: str = "auto",
                    wire_mode: str = "f32") -> DeepSchedule:
    """Build the diffusion DeepSchedule: `prepare(Cp)` -> block-padded Cm
    (ONE width-k Cp exchange per compiled advance), `sweep(T, Cm)` -> T
    advanced k steps with one width-k T exchange (at `wire_mode`
    precision on the wire; stateful modes grow the sweep signature —
    DeepSchedule docstring has the contract).

    The local k-step kernel is the same unrolled roll-based Pallas program
    as the single-chip VMEM-resident path (ops.pallas_kernels.multi_step_cm)
    — the deep-halo design makes every chip's inner loop identical to the
    fastest single-chip loop, with communication only at sweep boundaries.
    Shards too large for VMEM route to the temporal-blocked HBM sweep
    (multi_step_cm_hbm; k ≤ 16 with a depth-dependent stripe geometry,
    gated on the Mosaic compile envelope — tb_slab_fits): the same
    schedule at every scale — exchange once, advance k steps locally,
    crop. `local_form="jnp"` forces the any-shape XLA fallback — the form
    whose compiled byte counts the perf traffic gate audits on CPU
    (rocm_mpi_tpu/perf/traffic.py); "auto" is the production routing.

    `grid` may be a `mesh.BatchedGrid` (space×batch, docs/SERVING.md):
    the sweep then advances `(batch, *space)` lane-batched state —
    `prepare` takes the UNBATCHED space-shaped Cp every lane shares
    (physics is a bin-key field: one coefficient serves the whole
    batch), the local k-step body is vmapped over the leading lane
    axis, and the halo collectives stay per-space-axis. Batched sweeps
    pin the jnp local form (Pallas-under-vmap routing is not in the
    audited envelope) and the stateless wire modes (f32/bf16).
    """
    from rocm_mpi_tpu.parallel.mesh import BatchedGrid

    batched = isinstance(grid, BatchedGrid)
    space = grid.space if batched else grid
    _validate_depth(space, k, "sweep depth")
    wire.validate_mode(wire_mode)
    stateful_wire = wire.is_stateful(wire_mode)
    if local_form not in ("auto", "jnp"):
        raise ValueError(f"local_form must be 'auto' or 'jnp', got {local_form!r}")
    if batched:
        if stateful_wire:
            raise ValueError(
                f"wire_mode {wire_mode!r} is stateful; batched deep sweeps "
                "support the stateless modes (f32/bf16) only"
            )
        # The vmapped local body stays on the any-shape XLA form: the
        # Pallas kernels' batching path is untested/unaudited here, and
        # a crashed batched sweep serves no tenant.
        local_form = "jnp"
    from rocm_mpi_tpu.ops.pallas_kernels import (
        _TB_MAX_STEPS,
        _VMEM_BLOCK_BUDGET_BYTES,
        _compute_nbytes,
        multi_step_cm,
        multi_step_cm_hbm,
        tb_geometry,
        tb_slab_fits,
    )

    core = tuple(slice(k, -k) for _ in range(space.ndim))
    inner = tuple(slice(1, -1) for _ in range(space.ndim))
    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)

    def jnp_k_steps(Tp, Cm):
        # Any-shape/any-k fallback: the padded-slice stencil + an in-place
        # `dynamic_update_slice` of the advanced inner box (the outermost
        # ghost layer is held — same light-cone contamination geometry as
        # the Pallas kernels' roll wraparound, and no whole-block roll
        # staging copies). Slower than temporal blocking but never
        # shape-constrained — the HBM kernel's stripe divisibility, k <= 16
        # bound, and compile-envelope gate do not always survive run_deep's
        # depth degradation (effective_block_steps), and a crashed sweep is
        # strictly worse than a slower one.
        ndim = Tp.ndim
        for _ in range(k):
            lap = None
            for ax in range(ndim):
                hi = tuple(
                    slice(2, None) if a == ax else slice(1, -1)
                    for a in range(ndim)
                )
                lo = tuple(
                    slice(None, -2) if a == ax else slice(1, -1)
                    for a in range(ndim)
                )
                term = (Tp[hi] - 2.0 * Tp[inner] + Tp[lo]) * inv_d2[ax]
                lap = term if lap is None else lap + term
            Tp = lax.dynamic_update_slice(
                Tp, Tp[inner] + Cm[inner] * lap, (1,) * ndim
            )
        return Tp

    def local_prepare(Cpl):
        Cpp = exchange_halo(Cpl, space, width=k)
        return padded_update_coefficient(Cpp, space, k, lam, dt)

    def tb_ok(Tp):
        n0p = Tp.shape[0]
        return (
            k <= _TB_MAX_STEPS
            and Tp.ndim in (2, 3)
            and tb_slab_fits(k, Tp.shape, Tp.dtype)
            and n0p % tb_geometry(k)[1] == 0
            and (n0p // tb_geometry(k)[1]) >= 2
        )

    def local_sweep(Tl, Cm, *wsl):
        if stateful_wire:
            Tp, ws2 = exchange_halo(Tl, space, width=k, wire_mode=wire_mode,
                                    wire_state=tuple(wsl))
        else:
            Tp = exchange_halo(Tl, space, width=k, wire_mode=wire_mode)
            ws2 = ()
        if local_form == "jnp":
            route = "jnp"
            Tp = jnp_k_steps(Tp, Cm)
        elif _compute_nbytes(Tp) <= _VMEM_BLOCK_BUDGET_BYTES:
            route = "vmem"
            Tp = multi_step_cm(Tp, Cm, spacing, k)
        elif tb_ok(Tp):
            route = "hbm-tb"
            Tp = multi_step_cm_hbm(Tp, Cm, spacing, k)
        else:
            route = "jnp"
            Tp = jnp_k_steps(Tp, Cm)
        if telemetry.enabled():
            # Trace-time: which local kernel this compiled sweep routed to
            # (the halo.exchange byte annotation fired inside exchange_halo).
            telemetry.annotate("deep.sweep", k=k, route=route,
                               steps_per_exchange=k, wire=wire_mode)
        return (Tp[core],) + ws2 if stateful_wire else Tp[core]

    aux_spec = grid.aux_spec if batched else grid.spec

    def prepare(Cp):
        # Batched: Cp is the UNBATCHED space-shaped coefficient every
        # lane shares — same local program, replicated over batch rows.
        return shard_map(
            local_prepare,
            mesh=grid.mesh,
            in_specs=(aux_spec,),
            out_specs=aux_spec,
            check_vma=False,
        )(Cp)

    if stateful_wire:

        def sweep(T, Cm, wire_state):
            ws = tuple(wire_state)
            outs = shard_map(
                local_sweep,
                mesh=grid.mesh,
                in_specs=(grid.spec, aux_spec) + (grid.spec,) * len(ws),
                out_specs=(grid.spec,) * (1 + len(ws)),
                check_vma=False,
            )(T, Cm, *ws)
            return outs[0], tuple(outs[1:])

    else:
        if batched:
            import jax

            from rocm_mpi_tpu.parallel.halo import exchange_halo_batched

            def sweep_body(Tb_l, Cm):
                # The exchange runs through exchange_halo_batched so
                # the trace-time `halo.exchange.batched` annotation
                # books the TRUE lane-aggregate wire bytes — vmapping
                # exchange_halo would annotate a single lane's slab
                # and under-report the wire by the lane count. Only
                # the k-step local kernel is vmapped (shared Cm rides
                # unbatched in its closure).
                Tp_b = exchange_halo_batched(Tb_l, grid, width=k,
                                             wire_mode=wire_mode)
                if telemetry.enabled():
                    telemetry.annotate(
                        "deep.sweep", k=k, route="jnp",
                        steps_per_exchange=k, wire=wire_mode,
                        lanes=int(Tb_l.shape[0]),
                    )
                return jax.vmap(
                    lambda Tp: jnp_k_steps(Tp, Cm)[core]
                )(Tp_b)
        else:
            sweep_body = local_sweep

        def sweep(T, Cm):
            return shard_map(
                sweep_body,
                mesh=grid.mesh,
                in_specs=(grid.spec, aux_spec),
                out_specs=grid.spec,
                check_vma=False,
            )(T, Cm)

    return DeepSchedule(
        prepare, sweep, k,
        rebuild=lambda g: make_deep_sweep(g, k, lam, dt, spacing,
                                          local_form=local_form,
                                          wire_mode=wire_mode),
        wire_mode=wire_mode,
        init_wire=(
            (lambda dtype: wire.init_exchange_state(grid, k, wire_mode,
                                                    dtype))
            if stateful_wire else None
        ),
    )


def padded_face_mask(shape, grid: GlobalGrid, axis: int, width: int, dtype):
    """Face mask for the u_axis field over a width-`width` padded block
    (inside shard_map): exactly 0.0 on the global high wall face (global
    index n_g−1 along `axis`) and on off-domain ghost faces along `axis`,
    1.0 elsewhere. Zeroed wall faces seal the closed basin — off-domain
    ghost values then cannot influence any in-domain cell no matter how
    many local steps a sweep takes (flux across a wall is identically 0),
    which is what lets the SWE deep sweep evolve its ghost ring freely and
    crop it. Off-domain faces along OTHER axes need no zeroing: their
    influence would have to cross that axis's wall to reach the domain."""
    name = grid.axis_names[axis]
    ln = grid.local_shape[axis]
    n_g = grid.global_shape[axis]
    gidx = (
        lax.axis_index(name) * ln
        + lax.broadcasted_iota(jnp.int32, shape, axis)
        - width
    )
    invalid = (gidx >= n_g - 1) | (gidx < 0)
    return jnp.where(
        invalid, jnp.zeros(shape, dtype), jnp.ones(shape, dtype)
    )


def make_swe_deep_sweep(grid: GlobalGrid, k: int, dt, spacing, H,
                        g, wire_mode: str = "f32") -> DeepSchedule:
    """Deep-halo DeepSchedule for the shallow-water workload:
    `prepare(h)` -> the block-padded face masks (geometry-only; `h` just
    donates dtype and sharding — computed ONCE per compiled advance),
    `sweep(h, us, Mus_padded)` -> (h, us) advanced k steps with ONE
    width-k ghost exchange of the whole ndim+1-field coupled state (same
    light-cone argument as make_deep_sweep: the forward-backward update
    moves information one cell per step in each direction, so width-k
    ghosts keep the core exact for k steps).

    Local compute: the VMEM-resident masked multi-step kernel
    (ops.swe_kernels.swe_multi_step_masked) when the padded state fits,
    else the identical-semantics jnp roll fallback (masked_swe_step — the
    one definition of the update)."""
    _validate_depth(grid, k, "sweep depth")
    wire.validate_mode(wire_mode)
    stateful_wire = wire.is_stateful(wire_mode)
    from rocm_mpi_tpu.ops.pallas_kernels import (
        _VMEM_BLOCK_BUDGET_BYTES,
        _compute_nbytes,
    )
    from rocm_mpi_tpu.ops.swe_kernels import (
        masked_swe_step,
        swe_coeffs,
        swe_multi_step_masked,
    )

    ndim = grid.ndim
    nfields = ndim + 1  # h + one velocity per axis, all exchanged
    core = tuple(slice(k, -k) for _ in range(ndim))
    cH, cg = swe_coeffs(dt, spacing, H, g)
    padded_local = tuple(ln + 2 * k for ln in grid.local_shape)
    # Flat wire-state arrays per exchanged field (wire.state_arity per
    # slab, 2 slabs per axis).
    per_field = wire.state_arity(wire_mode) * 2 * ndim

    def jnp_k_steps(h, us, Mus):
        for _ in range(k):
            h, us = masked_swe_step(h, us, Mus, cH, cg)
        return h, us

    def local_prepare(hl):
        return tuple(
            padded_face_mask(padded_local, grid, a, k, hl.dtype)
            for a in range(ndim)
        )

    def _exchange(f, wsl, i):
        if not stateful_wire:
            return exchange_halo(f, grid, width=k, wire_mode=wire_mode), ()
        return exchange_halo(
            f, grid, width=k, wire_mode=wire_mode,
            wire_state=tuple(wsl[i * per_field:(i + 1) * per_field]),
        )

    def local_sweep(hl, *rest):
        uls, Mus = rest[:ndim], rest[ndim:2 * ndim]
        wsl = rest[2 * ndim:]
        hp, ws_h = _exchange(hl, wsl, 0)
        ups, ws_us = [], ()
        for i, u in enumerate(uls):
            up, ws_u = _exchange(u, wsl, 1 + i)
            ups.append(up)
            ws_us += ws_u
        ups = tuple(ups)
        if (3 * ndim + 2) * _compute_nbytes(hp) <= _VMEM_BLOCK_BUDGET_BYTES:
            h2, us2 = swe_multi_step_masked(hp, ups, Mus, cH, cg, k)
        else:
            h2, us2 = jnp_k_steps(hp, ups, Mus)
        return (
            (h2[core],) + tuple(u[core] for u in us2) + ws_h + ws_us
        )

    def prepare(h):
        return shard_map(
            local_prepare,
            mesh=grid.mesh,
            in_specs=(grid.spec,),
            out_specs=(grid.spec,) * ndim,
            check_vma=False,
        )(h)

    if stateful_wire:

        def sweep(h, us, Mus_padded, wire_state):
            ws = tuple(wire_state)
            outs = shard_map(
                local_sweep,
                mesh=grid.mesh,
                in_specs=(grid.spec,) * (2 * ndim + 1 + len(ws)),
                out_specs=(grid.spec,) * (ndim + 1 + len(ws)),
                check_vma=False,
            )(h, *us, *Mus_padded, *ws)
            return (
                outs[0], tuple(outs[1:nfields]), tuple(outs[nfields:])
            )

    else:

        def sweep(h, us, Mus_padded):
            outs = shard_map(
                local_sweep,
                mesh=grid.mesh,
                in_specs=(grid.spec,) * (2 * ndim + 1),
                out_specs=(grid.spec,) * (ndim + 1),
                check_vma=False,
            )(h, *us, *Mus_padded)
            return outs[0], tuple(outs[1:])

    return DeepSchedule(
        prepare, sweep, k,
        rebuild=lambda ng: make_swe_deep_sweep(ng, k, dt, spacing, H, g,
                                               wire_mode=wire_mode),
        wire_mode=wire_mode,
        init_wire=(
            (lambda dtype: wire.init_exchange_state(grid, k, wire_mode,
                                                    dtype, fields=nfields))
            if stateful_wire else None
        ),
    )


def make_wave_deep_sweep(grid: GlobalGrid, k: int, dt, spacing,
                         wire_mode: str = "f32") -> DeepSchedule:
    """Deep-halo DeepSchedule for the acoustic-wave workload:
    `prepare(C2)` -> block-padded (M, Cw) — ONE width-k exchange of the
    time-invariant squared wave speed per compiled advance, with the hold
    mask M and the masked coefficient Cw = dt²·c²·M derived in the same
    program — and `sweep(U, Uprev, (M, Cw))` -> (U, Uprev) advanced k
    steps with ONE width-k ghost exchange of the leapfrog state pair (the
    second workload on the flagship multi-chip schedule; same light-cone
    argument as make_deep_sweep, both outputs cropped).

    Local compute: the VMEM-resident masked leapfrog kernel
    (ops.wave_kernels.wave_multi_step_masked) when the padded block fits,
    else an XLA-fused jnp fallback with identical semantics (the wave
    workload is the layering demo — it has no HBM temporal-blocked rung).
    """
    _validate_depth(grid, k, "sweep depth")
    wire.validate_mode(wire_mode)
    stateful_wire = wire.is_stateful(wire_mode)
    from rocm_mpi_tpu.ops.pallas_kernels import (
        _VMEM_BLOCK_BUDGET_BYTES,
        _compute_nbytes,
    )
    from rocm_mpi_tpu.ops.wave_kernels import (
        masked_leapfrog_step,
        wave_multi_step_masked,
    )

    core = tuple(slice(k, -k) for _ in range(grid.ndim))
    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)
    dt2 = float(dt) * float(dt)
    per_field = wire.state_arity(wire_mode) * 2 * grid.ndim

    def jnp_k_steps(U, Uprev, M, Cw):
        for _ in range(k):
            U, Uprev = masked_leapfrog_step(U, Uprev, M, Cw, inv_d2)
        return U, Uprev

    def local_prepare(C2l):
        C2p = exchange_halo(C2l, grid, width=k)
        hold = padded_hold_mask(C2p.shape, grid, k)
        M = jnp.where(hold, jnp.zeros_like(C2p), jnp.ones_like(C2p))
        return M, dt2 * C2p * M

    def local_sweep(Ul, Upl, M, Cw, *wsl):
        if stateful_wire:
            Up_, ws_u = exchange_halo(
                Ul, grid, width=k, wire_mode=wire_mode,
                wire_state=tuple(wsl[:per_field]),
            )
            Upp, ws_p = exchange_halo(
                Upl, grid, width=k, wire_mode=wire_mode,
                wire_state=tuple(wsl[per_field:]),
            )
        else:
            Up_ = exchange_halo(Ul, grid, width=k, wire_mode=wire_mode)
            Upp = exchange_halo(Upl, grid, width=k, wire_mode=wire_mode)
            ws_u = ws_p = ()
        if 2 * _compute_nbytes(Up_) <= _VMEM_BLOCK_BUDGET_BYTES:
            U2, Up2 = wave_multi_step_masked(Up_, Upp, M, Cw, spacing, k)
        else:
            U2, Up2 = jnp_k_steps(Up_, Upp, M, Cw)
        out = (U2[core], Up2[core])
        return out + ws_u + ws_p if stateful_wire else out

    def prepare(C2):
        return shard_map(
            local_prepare,
            mesh=grid.mesh,
            in_specs=(grid.spec,),
            out_specs=(grid.spec, grid.spec),
            check_vma=False,
        )(C2)

    if stateful_wire:

        def sweep(U, Uprev, prepared, wire_state):
            M, Cw = prepared
            ws = tuple(wire_state)
            outs = shard_map(
                local_sweep,
                mesh=grid.mesh,
                in_specs=(grid.spec,) * (4 + len(ws)),
                out_specs=(grid.spec,) * (2 + len(ws)),
                check_vma=False,
            )(U, Uprev, M, Cw, *ws)
            return outs[0], outs[1], tuple(outs[2:])

    else:

        def sweep(U, Uprev, prepared):
            M, Cw = prepared
            return shard_map(
                local_sweep,
                mesh=grid.mesh,
                in_specs=(grid.spec,) * 4,
                out_specs=(grid.spec, grid.spec),
                check_vma=False,
            )(U, Uprev, M, Cw)

    return DeepSchedule(
        prepare, sweep, k,
        rebuild=lambda g: make_wave_deep_sweep(g, k, dt, spacing,
                                               wire_mode=wire_mode),
        wire_mode=wire_mode,
        init_wire=(
            (lambda dtype: wire.init_exchange_state(grid, k, wire_mode,
                                                    dtype, fields=2))
            if stateful_wire else None
        ),
    )
