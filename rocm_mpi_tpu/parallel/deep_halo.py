"""Deep-halo sweeps: width-k ghost exchange every k steps.

The multi-chip form of temporal blocking, and the TPU-first endpoint of the
reference's communication-ladder: where the reference hides a width-1 halo
exchange behind interior compute every step
(/root/reference/scripts/diffusion_2D_perf_hide.jl:94-101, its intended
variant (3)), the deep-halo sweep removes most exchanges altogether —
each device receives a k-wide ghost region once, then advances its block k
steps entirely locally (the ghost light cone keeps the core exact; stale
ghost cells are cropped at sweep end). Communication drops from one
latency-bound message per neighbor per step to one k-times-larger message
per neighbor per k steps — the shape ICI wants: fewer, larger transfers,
k× less exposed latency. Same total exchanged volume, identical math
(fp-reordering aside) to k per-step updates.

Correctness argument (the same light-cone bound as the HBM temporal
blocking in ops.pallas_kernels._tb_kernel): after s local steps, values at
ghost depth ≥ s+1 are stale and roll-wraparound garbage has penetrated
s-1 cells into the k-wide ghost ring; for s ≤ k neither reaches the core.
Dirichlet global-boundary cells are held by a zero update coefficient, and
off-domain ghost cells (domain edge) hold zeros with a zero coefficient —
the zero-ghost convention used framework-wide.

Cp handling: the update coefficient needs neighbor Cp values in the ghost
ring, so each sweep also exchanges Cp's halo. Cp is time-invariant, so this
is redundant work — but it is two small ppermutes per axis amortized over
k steps, and keeping it inside the sweep keeps the carried loop state to
the bare field.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from rocm_mpi_tpu.utils.compat import shard_map

from rocm_mpi_tpu import telemetry
from rocm_mpi_tpu.parallel.halo import exchange_halo
from rocm_mpi_tpu.parallel.mesh import GlobalGrid


def padded_hold_mask(shape, grid: GlobalGrid, width: int):
    """Boolean mask over a width-`width` padded block (inside shard_map):
    True where the cell must NOT update — global Dirichlet boundary cells
    and off-domain ghost cells, located by global index."""
    mask = None
    for ax, name in enumerate(grid.axis_names):
        ln = grid.local_shape[ax]
        n_g = grid.global_shape[ax]
        gidx = (
            lax.axis_index(name) * ln
            + lax.broadcasted_iota(jnp.int32, shape, ax)
            - width
        )
        m = (gidx <= 0) | (gidx >= n_g - 1)
        mask = m if mask is None else (mask | m)
    return mask


def padded_update_coefficient(Cp_padded, grid: GlobalGrid, width: int,
                              lam, dt):
    """Masked dt·λ/Cp for a width-`width` padded block (inside shard_map).

    Zero where the cell must not update: global Dirichlet boundary cells,
    and off-domain ghost cells (where the exchanged `Cp_padded` is itself
    zero — guarded so the division cannot produce inf).
    """
    mask = padded_hold_mask(Cp_padded.shape, grid, width)
    safe = jnp.where(Cp_padded == 0, jnp.ones_like(Cp_padded), Cp_padded)
    return jnp.where(mask, jnp.zeros_like(Cp_padded), (dt * lam) / safe)


def make_deep_sweep(grid: GlobalGrid, k: int, lam, dt, spacing):
    """Build sweep(T, Cp) -> T advanced k steps, one halo exchange total.

    The local k-step kernel is the same unrolled roll-based Pallas program
    as the single-chip VMEM-resident path (ops.pallas_kernels.multi_step_cm)
    — the deep-halo design makes every chip's inner loop identical to the
    fastest single-chip loop, with communication only at sweep boundaries.
    Shards too large for VMEM route to the temporal-blocked HBM sweep
    (multi_step_cm_hbm; k ≤ 16 with a depth-dependent stripe geometry,
    gated on the Mosaic compile envelope — tb_slab_fits): the same
    schedule at every scale — exchange once, advance k steps locally,
    crop.
    """
    if k < 1:
        raise ValueError(f"sweep depth k must be >= 1, got {k}")
    if any(k > ln for ln in grid.local_shape):
        raise ValueError(
            f"sweep depth {k} exceeds a local shard extent "
            f"{grid.local_shape}; ghost slices need width <= shard"
        )
    from rocm_mpi_tpu.ops.pallas_kernels import (
        _TB_MAX_STEPS,
        _VMEM_BLOCK_BUDGET_BYTES,
        _compute_nbytes,
        multi_step_cm,
        multi_step_cm_hbm,
        tb_geometry,
        tb_slab_fits,
    )

    core = tuple(slice(k, -k) for _ in range(grid.ndim))

    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)

    def jnp_k_steps(Tp, Cm):
        # Any-shape/any-k fallback: the same roll+Cm semantics as the
        # Pallas kernels, XLA-fused. Slower (no temporal blocking) but
        # never shape-constrained — the HBM kernel's stripe divisibility,
        # k <= 16 bound, and compile-envelope gate do not always survive
        # run_deep's depth degradation (effective_block_steps), and a
        # crashed sweep is strictly worse than a slower one.
        for _ in range(k):
            lap = None
            for ax in range(Tp.ndim):
                term = (
                    jnp.roll(Tp, -1, ax) + jnp.roll(Tp, 1, ax) - 2.0 * Tp
                ) * inv_d2[ax]
                lap = term if lap is None else lap + term
            Tp = Tp + Cm * lap
        return Tp

    def local_sweep(Tl, Cpl):
        Tp = exchange_halo(Tl, grid, width=k)
        Cpp = exchange_halo(Cpl, grid, width=k)
        Cm = padded_update_coefficient(Cpp, grid, k, lam, dt)
        n0p = Tp.shape[0]
        tb_ok = (
            k <= _TB_MAX_STEPS
            and Tp.ndim in (2, 3)
            and tb_slab_fits(k, Tp.shape, Tp.dtype)
            and n0p % tb_geometry(k)[1] == 0
            and (n0p // tb_geometry(k)[1]) >= 2
        )
        if _compute_nbytes(Tp) <= _VMEM_BLOCK_BUDGET_BYTES:
            route = "vmem"
            Tp = multi_step_cm(Tp, Cm, spacing, k)
        elif tb_ok:
            route = "hbm-tb"
            Tp = multi_step_cm_hbm(Tp, Cm, spacing, k)
        else:
            route = "jnp"
            Tp = jnp_k_steps(Tp, Cm)
        if telemetry.enabled():
            # Trace-time: which local kernel this compiled sweep routed to
            # (the halo.exchange byte annotation fired inside exchange_halo).
            telemetry.annotate("deep.sweep", k=k, route=route,
                               steps_per_exchange=k)
        return Tp[core]

    def sweep(T, Cp):
        return shard_map(
            local_sweep,
            mesh=grid.mesh,
            in_specs=(grid.spec, grid.spec),
            out_specs=grid.spec,
            check_vma=False,
        )(T, Cp)

    return sweep


def padded_face_mask(shape, grid: GlobalGrid, axis: int, width: int, dtype):
    """Face mask for the u_axis field over a width-`width` padded block
    (inside shard_map): exactly 0.0 on the global high wall face (global
    index n_g−1 along `axis`) and on off-domain ghost faces along `axis`,
    1.0 elsewhere. Zeroed wall faces seal the closed basin — off-domain
    ghost values then cannot influence any in-domain cell no matter how
    many local steps a sweep takes (flux across a wall is identically 0),
    which is what lets the SWE deep sweep evolve its ghost ring freely and
    crop it. Off-domain faces along OTHER axes need no zeroing: their
    influence would have to cross that axis's wall to reach the domain."""
    name = grid.axis_names[axis]
    ln = grid.local_shape[axis]
    n_g = grid.global_shape[axis]
    gidx = (
        lax.axis_index(name) * ln
        + lax.broadcasted_iota(jnp.int32, shape, axis)
        - width
    )
    invalid = (gidx >= n_g - 1) | (gidx < 0)
    return jnp.where(
        invalid, jnp.zeros(shape, dtype), jnp.ones(shape, dtype)
    )


def make_swe_deep_sweep(grid: GlobalGrid, k: int, dt, spacing, H, g):
    """Deep-halo sweeps for the shallow-water workload: build
    sweep(h, us) -> (h, us) advanced k steps with ONE width-k ghost
    exchange of the whole ndim+1-field coupled state (same light-cone
    argument as make_deep_sweep: the forward-backward update moves
    information one cell per step in each direction, so width-k ghosts
    keep the core exact for k steps).

    Local compute: the VMEM-resident masked multi-step kernel
    (ops.swe_kernels.swe_multi_step_masked) when the padded state fits,
    else the identical-semantics jnp roll fallback (masked_swe_step — the
    one definition of the update)."""
    if k < 1:
        raise ValueError(f"sweep depth k must be >= 1, got {k}")
    if any(k > ln for ln in grid.local_shape):
        raise ValueError(
            f"sweep depth {k} exceeds a local shard extent "
            f"{grid.local_shape}; ghost slices need width <= shard"
        )
    from rocm_mpi_tpu.ops.pallas_kernels import (
        _VMEM_BLOCK_BUDGET_BYTES,
        _compute_nbytes,
    )
    from rocm_mpi_tpu.ops.swe_kernels import (
        masked_swe_step,
        swe_coeffs,
        swe_multi_step_masked,
    )

    ndim = grid.ndim
    core = tuple(slice(k, -k) for _ in range(ndim))
    cH, cg = swe_coeffs(dt, spacing, H, g)

    def jnp_k_steps(h, us, Mus):
        for _ in range(k):
            h, us = masked_swe_step(h, us, Mus, cH, cg)
        return h, us

    def local_sweep(hl, *uls):
        hp = exchange_halo(hl, grid, width=k)
        ups = tuple(exchange_halo(u, grid, width=k) for u in uls)
        Mus = tuple(
            padded_face_mask(hp.shape, grid, a, k, hp.dtype)
            for a in range(ndim)
        )
        if (3 * ndim + 2) * _compute_nbytes(hp) <= _VMEM_BLOCK_BUDGET_BYTES:
            h2, us2 = swe_multi_step_masked(hp, ups, Mus, cH, cg, k)
        else:
            h2, us2 = jnp_k_steps(hp, ups, Mus)
        return (h2[core],) + tuple(u[core] for u in us2)

    def sweep(h, us):
        outs = shard_map(
            local_sweep,
            mesh=grid.mesh,
            in_specs=(grid.spec,) * (ndim + 1),
            out_specs=(grid.spec,) * (ndim + 1),
            check_vma=False,
        )(h, *us)
        return outs[0], tuple(outs[1:])

    return sweep


def make_wave_deep_sweep(grid: GlobalGrid, k: int, dt, spacing):
    """Deep-halo sweeps for the acoustic-wave workload: build
    sweep(U, Uprev, C2) -> (U, Uprev) advanced k steps with ONE width-k
    ghost exchange — the second workload on the flagship multi-chip
    schedule (same light-cone argument as make_deep_sweep; the leapfrog
    state pair is exchanged together and both outputs cropped).

    Local compute: the VMEM-resident masked leapfrog kernel
    (ops.wave_kernels.wave_multi_step_masked) when the padded block fits,
    else an XLA-fused jnp fallback with identical semantics (the wave
    workload is the layering demo — it has no HBM temporal-blocked rung).
    """
    if k < 1:
        raise ValueError(f"sweep depth k must be >= 1, got {k}")
    if any(k > ln for ln in grid.local_shape):
        raise ValueError(
            f"sweep depth {k} exceeds a local shard extent "
            f"{grid.local_shape}; ghost slices need width <= shard"
        )
    from rocm_mpi_tpu.ops.pallas_kernels import (
        _VMEM_BLOCK_BUDGET_BYTES,
        _compute_nbytes,
    )
    from rocm_mpi_tpu.ops.wave_kernels import (
        masked_leapfrog_step,
        wave_multi_step_masked,
    )

    core = tuple(slice(k, -k) for _ in range(grid.ndim))
    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)
    dt2 = float(dt) * float(dt)

    def jnp_k_steps(U, Uprev, M, Cw):
        for _ in range(k):
            U, Uprev = masked_leapfrog_step(U, Uprev, M, Cw, inv_d2)
        return U, Uprev

    def local_sweep(Ul, Upl, C2l):
        Up_ = exchange_halo(Ul, grid, width=k)
        Upp = exchange_halo(Upl, grid, width=k)
        C2p = exchange_halo(C2l, grid, width=k)
        hold = padded_hold_mask(Up_.shape, grid, k)
        M = jnp.where(
            hold, jnp.zeros_like(Up_), jnp.ones_like(Up_)
        )
        Cw = dt2 * C2p * M
        if 2 * _compute_nbytes(Up_) <= _VMEM_BLOCK_BUDGET_BYTES:
            U2, Up2 = wave_multi_step_masked(Up_, Upp, M, Cw, spacing, k)
        else:
            U2, Up2 = jnp_k_steps(Up_, Upp, M, Cw)
        return U2[core], Up2[core]

    def sweep(U, Uprev, C2):
        return shard_map(
            local_sweep,
            mesh=grid.mesh,
            in_specs=(grid.spec,) * 3,
            out_specs=(grid.spec, grid.spec),
            check_vma=False,
        )(U, Uprev, C2)

    return sweep
