"""Multi-host process wiring (D9) — the launch-layer analog.

Reference: processes are created by Slurm (`srun -n N --mpi=pmix`), wired
into MPI_COMM_WORLD by PMIx, and each rank binds one GPU via the node-local
communicator split (/root/reference/README.md:18,
scripts/rocmaware_test_selectdevice.jl:7-9; SURVEY.md §2.2 D9).

TPU-native: one process per host, `jax.distributed.initialize()` discovers
the pod slice (coordinator/process env comes from the TPU runtime or the
launcher), and every local chip is bound automatically — there is no manual
device selection to do. Cross-host collectives ride DCN, intra-slice ride
ICI. `scripts/run.sh` sets RMT_DISTRIBUTED=1 on multi-host launches, the
runme.sh analog.
"""

from __future__ import annotations

import os

_initialized = False


def process_id() -> int:
    """This process's rank under the launcher contract, WITHOUT forcing
    backend/cluster init: RMT_PROCESS_ID when the launcher set it, else
    jax.process_index() if a backend is already up, else 0. The
    resilience layer's rank-scoped fault clauses key off this."""
    raw = os.environ.get("RMT_PROCESS_ID")
    if raw is not None:
        try:
            return int(raw)
        except ValueError:
            pass
    import sys

    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return jax.process_index()
        except Exception:  # noqa: BLE001 — backend may not be up yet
            pass
    return 0


def _enable_cpu_collectives() -> None:
    """Multi-process CPU runs need gloo collectives selected explicitly
    on jax 0.4.x (`jax_cpu_collectives_implementation` defaults to
    'none' there — cross-process programs then fail with 'Multiprocess
    computations aren't implemented on the CPU backend'); newer jax
    defaults to gloo and drops the knob, hence best-effort."""
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass


def maybe_initialize_distributed() -> bool:
    """Call jax.distributed.initialize() when a multi-host launch is
    requested (RMT_DISTRIBUTED=1, or explicit JAX coordinator env).

    Idempotent; returns True when running in (or just joined) a multi-host
    setup. Single-host runs are a no-op — the reference's single-node case.

    Process wiring comes from either (a) JAX's cluster auto-detection
    (TPU pod runtime, Slurm, Open MPI — the srun/PMIx analog), or (b) the
    framework's own explicit launcher contract, mirroring how PMIx hands
    each rank its identity (README.md:18):

        RMT_COORDINATOR = host:port of process 0's coordinator service
        RMT_NUM_PROCS   = total process count
        RMT_PROCESS_ID  = this process's rank

    All three must be set together; scripts/run.sh exports them on
    multi-host launches.
    """
    global _initialized
    import jax

    if _initialized:
        return True
    env = os.environ
    want = env.get("RMT_DISTRIBUTED") == "1" or (
        "JAX_COORDINATOR_ADDRESS" in env or "RMT_COORDINATOR" in env
    )
    if not want:
        return False
    def int_env(name: str) -> int:
        try:
            val = env[name]
        except KeyError:
            raise RuntimeError(
                f"RMT_COORDINATOR requires {name} to be set too"
            ) from None
        try:
            return int(val)
        except ValueError:
            raise RuntimeError(
                f"{name} must be an integer, got {val!r}"
            ) from None

    kwargs = {}
    if "RMT_COORDINATOR" in env:
        kwargs = dict(
            coordinator_address=env["RMT_COORDINATOR"],
            num_processes=int_env("RMT_NUM_PROCS"),
            process_id=int_env("RMT_PROCESS_ID"),
        )
        if "RMT_INIT_TIMEOUT_S" in env:
            kwargs["initialization_timeout"] = int_env("RMT_INIT_TIMEOUT_S")
    # Resilience drill site: a delay-rank fault here simulates the slow/
    # stalled joiner the launcher's heartbeat reporting must surface.
    from rocm_mpi_tpu.resilience import faults

    faults.fault_point("init")
    _enable_cpu_collectives()
    jax.distributed.initialize(**kwargs)
    _initialized = True
    return True
