"""Multi-host process wiring (D9) — the launch-layer analog.

Reference: processes are created by Slurm (`srun -n N --mpi=pmix`), wired
into MPI_COMM_WORLD by PMIx, and each rank binds one GPU via the node-local
communicator split (/root/reference/README.md:18,
scripts/rocmaware_test_selectdevice.jl:7-9; SURVEY.md §2.2 D9).

TPU-native: one process per host, `jax.distributed.initialize()` discovers
the pod slice (coordinator/process env comes from the TPU runtime or the
launcher), and every local chip is bound automatically — there is no manual
device selection to do. Cross-host collectives ride DCN, intra-slice ride
ICI. `scripts/run.sh` sets RMT_DISTRIBUTED=1 on multi-host launches, the
runme.sh analog.
"""

from __future__ import annotations

import os

_initialized = False


def maybe_initialize_distributed() -> bool:
    """Call jax.distributed.initialize() when a multi-host launch is
    requested (RMT_DISTRIBUTED=1, or explicit JAX coordinator env).

    Idempotent; returns True when running in (or just joined) a multi-host
    setup. Single-host runs are a no-op — the reference's single-node case.
    """
    global _initialized
    import jax

    if _initialized:
        return True
    want = os.environ.get("RMT_DISTRIBUTED") == "1" or (
        "JAX_COORDINATOR_ADDRESS" in os.environ
    )
    if not want:
        return False
    jax.distributed.initialize()
    _initialized = True
    return True
