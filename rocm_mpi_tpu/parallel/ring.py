"""Device-resident ring exchange — the framework's capability smoke test.

TPU-native analog of the reference's ROCm-aware MPI proof
(/root/reference/scripts/rocmaware_test_selectdevice.jl): there, each rank
fills a 4-element GPU buffer with its rank and `MPI.Sendrecv!`s it directly
(device pointers into MPI) around a ring. Here the buffers are
device-resident shards and the exchange is a `lax.ppermute` inside
`shard_map`, which XLA lowers to an ICI collective-permute — data moves
chip-to-chip without staging through the host, the ICI analog of
"ROCm-aware" GPU-direct transport.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from rocm_mpi_tpu.utils.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def ring_exchange(x, axis_name: str, shift: int = 1):
    """Cyclically shift shards along `axis_name` by `shift` (inside shard_map).

    Each device sends its block to rank `(rank + shift) % n` — the
    `Sendrecv!(send, dst=rank+1, …, src=rank-1)` ring of
    rocmaware_test_selectdevice.jl:11-22 as a single XLA collective.
    """
    from rocm_mpi_tpu.utils.compat import axis_size

    from rocm_mpi_tpu import telemetry

    if telemetry.enabled():
        # Trace-time: whole-block collective — every device sends its
        # full shard each call (unlike the halo's edge slices).
        telemetry.annotate(
            "ring.exchange",
            bytes=int(x.size) * x.dtype.itemsize,
            shift=shift,
        )
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def ring_exchange_demo(mesh: Mesh, width: int = 4, dtype=jnp.float32):
    """Run the ring smoke test on `mesh`'s first axis; returns (sent, received).

    `sent[i] == i` on device i; a correct exchange yields
    `received[i] == (i - 1) % n` — the assertion the reference makes by
    printing `recv_msg` on every rank (rocmaware_test_selectdevice.jl:23).
    """
    axis = mesh.axis_names[0]
    n = mesh.devices.shape[0]
    sharding = NamedSharding(mesh, PartitionSpec(axis))

    ranks = jnp.repeat(jnp.arange(n, dtype=dtype), width)  # block i filled with i
    ranks = jax.device_put(ranks, sharding)

    @jax.jit
    def exchange(x):
        return shard_map(
            lambda b: ring_exchange(b, axis, shift=1),
            mesh=mesh,
            in_specs=PartitionSpec(axis),
            out_specs=PartitionSpec(axis),
        )(x)

    received = exchange(ranks)
    return ranks, received
