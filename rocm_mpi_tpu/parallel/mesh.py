"""Global-grid runtime: cartesian device-mesh decomposition (D1/D3/D9).

TPU-native re-design of the capabilities the reference obtains from
ImplicitGlobalGrid.jl (`init_global_grid`, `nx_g`/`ny_g`, `x_g`/`y_g`,
`finalize_global_grid`; call sites at
/root/reference/scripts/diffusion_2D_ap.jl:17-28) and from the MPI process
model (`srun --mpi=pmix`, one rank per GPU, cartesian communicator;
/root/reference/README.md:18, scripts/rocmaware_test_selectdevice.jl:7-9).

Design differences from the reference (deliberate, TPU-first):

* **Non-overlapping shards.** ImplicitGlobalGrid gives each rank a local
  array that *overlaps* its neighbors by 2 cells and refreshes the overlap
  with `update_halo!`. On TPU the idiomatic layout is a single global array
  sharded over a `jax.sharding.Mesh` with *no* persistent ghost storage;
  ghost cells are materialized transiently each step by `halo.exchange_halo`
  (a `lax.ppermute` over ICI) or automatically by GSPMD when the step is
  written as global-array ops. Global size is therefore simply
  ``local_size * dims`` per axis.
* **One process, many devices.** The reference binds one MPI rank per GPU;
  JAX binds all local devices to one process and `jax.distributed` handles
  multi-host. `me`/`nprocs` map to `jax.process_index()`/device count.
* **Cell-centered coordinates.** Cell ``i`` along an axis of global size
  ``n`` and physical length ``l`` has center ``(i + 0.5) * l/n`` — the same
  coordinates the reference computes as ``x_g(ix,dx,T) + dx/2``
  (diffusion_2D_ap.jl:28).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXIS_NAMES = ("gx", "gy", "gz")

# The multi-tenant lane axis (docs/SERVING.md): a space×batch mesh leads
# with this axis, lanes are INDEPENDENT simulations, and no halo
# collective may ever permute over it (graftlint GL05 polices the
# literal spelling; reductions over it — cross-lane diagnostics — are
# legitimate).
BATCH_AXIS = "batch"


def suggest_dims(nprocs: int, ndim: int) -> tuple[int, ...]:
    """Factor `nprocs` into `ndim` near-equal factors, largest first.

    Analog of MPI_Dims_create, which ImplicitGlobalGrid uses to pick the
    process-grid shape when the caller passes dims=0 (the reference's
    `init_global_grid(nx, ny, 1)` call relies on this).
    """
    if nprocs < 1:
        raise ValueError(f"nprocs must be >= 1, got {nprocs}")
    if ndim < 1:
        raise ValueError(f"ndim must be >= 1, got {ndim}")
    dims = [1] * ndim
    remaining = nprocs
    # Greedily peel off the largest factor <= the ideal balanced factor.
    for i in range(ndim - 1):
        ideal = round(remaining ** (1.0 / (ndim - i)))
        f = 1
        for cand in range(min(remaining, max(ideal, 1)), 0, -1):
            if remaining % cand == 0:
                f = cand
                break
        dims[i] = f
        remaining //= f
    dims[ndim - 1] = remaining
    dims.sort(reverse=True)
    return tuple(dims)


def plan_dims(
    global_shape: Sequence[int], max_devices: int
) -> tuple[int, ...]:
    """The largest valid sub-mesh for `global_shape` using at most
    `max_devices` devices: the biggest p <= max_devices whose near-square
    factorization (suggest_dims) divides every grid axis.

    This is the elastic-recovery decomposition planner (docs/RESILIENCE.md
    "Elastic recovery"): when a rank dies, the supervisor re-plans the
    mesh over the survivors, and a checkpoint restored without a template
    (utils.checkpoint.restore_state(like=None)) plans its mesh over
    whatever devices the resumed process has. p=1 always divides, so a
    plan always exists.
    """
    if max_devices < 1:
        raise ValueError(f"max_devices must be >= 1, got {max_devices}")
    ndim = len(global_shape)
    for p in range(int(max_devices), 0, -1):
        dims = suggest_dims(p, ndim)
        if all(n % d == 0 for n, d in zip(global_shape, dims)):
            return dims
    raise AssertionError("unreachable: p=1 divides every shape")


@dataclasses.dataclass(frozen=True)
class GlobalGrid:
    """A global cartesian grid of cells sharded over a device mesh.

    Holds everything the reference's apps get back from
    `init_global_grid(nx, ny, nz)` — `me, dims, nprocs, coords, comm_cart`
    (diffusion_2D_ap.jl:17) — expressed TPU-natively: the `Mesh` *is* the
    cartesian communicator, `dims` is its shape, and per-shard coordinates
    are derived from `lax.axis_index` inside `shard_map`.
    """

    mesh: Mesh
    global_shape: tuple[int, ...]  # cells per axis (nx_g, ny_g[, nz_g])
    lengths: tuple[float, ...]  # physical domain lengths (lx, ly[, lz])

    def __post_init__(self):
        if len(self.global_shape) != len(self.mesh.axis_names):
            raise ValueError(
                f"global_shape {self.global_shape} rank != mesh axes "
                f"{self.mesh.axis_names}"
            )
        if len(self.lengths) != len(self.global_shape):
            raise ValueError("lengths rank must match global_shape rank")
        for n, d, name in zip(self.global_shape, self.dims, self.axis_names):
            if n % d != 0:
                raise ValueError(
                    f"global size {n} along '{name}' not divisible by mesh dim {d}"
                )

    # ---- topology (reference: me/dims/nprocs/coords) --------------------

    @property
    def ndim(self) -> int:
        return len(self.global_shape)

    @property
    def axis_names(self) -> tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    @property
    def dims(self) -> tuple[int, ...]:
        """Process-grid shape (reference `dims`)."""
        return tuple(self.mesh.devices.shape)

    @property
    def nprocs(self) -> int:
        """Total devices in the grid (reference `nprocs`; rank-per-GPU model)."""
        return int(np.prod(self.dims))

    @property
    def me(self) -> int:
        """Host process index (rank-0-gated logging analog of reference `me`)."""
        return jax.process_index()

    def device_coords(self, device) -> tuple[int, ...]:
        """Cartesian coords of `device` in the mesh (reference `coords`)."""
        pos = np.argwhere(self.mesh.devices == device)
        if len(pos) != 1:
            raise ValueError(f"device {device} not in mesh")
        return tuple(int(c) for c in pos[0])

    # ---- sharding -------------------------------------------------------

    @property
    def spec(self) -> PartitionSpec:
        return PartitionSpec(*self.axis_names)

    @property
    def sharding(self) -> NamedSharding:
        """NamedSharding partitioning every grid axis over its mesh axis."""
        return NamedSharding(self.mesh, self.spec)

    @property
    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    @property
    def local_shape(self) -> tuple[int, ...]:
        """Per-device shard shape (the reference's local `nx, ny`)."""
        return tuple(n // d for n, d in zip(self.global_shape, self.dims))

    # ---- global geometry (reference nx_g/ny_g, x_g/y_g, dx/dy) ----------

    @property
    def spacing(self) -> tuple[float, ...]:
        """Cell size per axis: dx = lx / nx_g (diffusion_2D_ap.jl:19)."""
        return tuple(l / n for l, n in zip(self.lengths, self.global_shape))

    def cell_centers(self, axis: int, dtype=jnp.float64) -> jnp.ndarray:
        """Global cell-center coordinates along `axis`.

        Equivalent to the reference's `x_g(ix,dx,T) + dx/2` per-cell
        coordinate (diffusion_2D_ap.jl:28), for the whole global axis.
        """
        n = self.global_shape[axis]
        d = self.spacing[axis]
        return (jnp.arange(n, dtype=dtype) + 0.5) * d

    def coord_mesh(self, dtype=jnp.float64) -> tuple[jnp.ndarray, ...]:
        """Broadcastable global coordinate arrays, one per axis (x_g/y_g analog)."""
        out = []
        for ax in range(self.ndim):
            shape = [1] * self.ndim
            shape[ax] = self.global_shape[ax]
            out.append(self.cell_centers(ax, dtype=dtype).reshape(shape))
        return tuple(out)

    def local_cell_centers(self, axis: int, axis_index, dtype=jnp.float64):
        """Cell centers of one shard along `axis`, for use inside shard_map.

        `axis_index` is typically `lax.axis_index(grid.axis_names[axis])`.
        This is the shard-local x_g/y_g: each device initializes *its* piece
        of the global initial condition, exactly as each reference rank does
        (diffusion_2D_ap.jl:28).
        """
        ln = self.local_shape[axis]
        d = self.spacing[axis]
        start = axis_index * ln
        return (start + jnp.arange(ln, dtype=dtype) + 0.5) * d


def init_global_grid(
    *global_shape: int,
    lengths: Sequence[float] | None = None,
    dims: Sequence[int] | None = None,
    devices: Sequence[jax.Device] | None = None,
    axis_names: Sequence[str] | None = None,
) -> GlobalGrid:
    """Build a GlobalGrid over the available devices.

    TPU-native analog of `init_global_grid(nx, ny, nz)`
    (diffusion_2D_ap.jl:17): constructs the cartesian topology (a Mesh over
    `jax.devices()`), picks the process-grid shape (suggest_dims =
    MPI_Dims_create analog), and records global geometry. Device binding is
    implicit (JAX owns all local devices; under `jax.distributed` the mesh
    spans hosts) — the analog of the reference's rank-per-GPU `device!`
    selection (rocmaware_test_selectdevice.jl:7-9).

    Args:
      *global_shape: global cells per axis, e.g. (504, 504). Trailing size-1
        axes (the reference's `nz=1` idiom) are dropped.
      lengths: physical lengths; default 10.0 per axis (diffusion_2D_ap.jl:11).
      dims: process-grid shape; default near-square factorization of device
        count. Use (1,)*ndim for single-device grids.
      devices: devices to use; default all of `jax.devices()` (prefix that
        fills `prod(dims)`).
      axis_names: mesh axis names; default ("gx","gy","gz")[:ndim].
    """
    shape = tuple(int(n) for n in global_shape)
    while len(shape) > 1 and shape[-1] == 1:
        shape = shape[:-1]
        # Strip explicit dims in lockstep with the (nx, ny, 1) idiom.
        if dims is not None and len(dims) == len(shape) + 1 and dims[-1] == 1:
            dims = tuple(dims)[:-1]
    ndim = len(shape)
    if lengths is None:
        lengths = (10.0,) * ndim
    lengths = tuple(float(l) for l in lengths)
    if devices is None:
        devices = jax.devices()
    if dims is None:
        dims = suggest_dims(len(devices), ndim)
        # Shrink to dims that actually divide the global shape.
        dims = tuple(d if n % d == 0 else math.gcd(n, d) for n, d in zip(shape, dims))
        used = int(np.prod(dims))
        if used < len(devices):
            import warnings

            warnings.warn(
                f"global shape {shape} is not divisible by the natural "
                f"{suggest_dims(len(devices), ndim)} device grid; shrunk to "
                f"dims {dims}, using {used} of {len(devices)} devices. Pass "
                f"a divisible shape (or explicit dims=) to use every device.",
                stacklevel=2,
            )
    dims = tuple(int(d) for d in dims)
    nproc = int(np.prod(dims))
    if nproc > len(devices):
        raise ValueError(f"dims {dims} need {nproc} devices, have {len(devices)}")
    if axis_names is None:
        axis_names = AXIS_NAMES[:ndim]
    dev_grid = np.asarray(devices[:nproc]).reshape(dims)
    mesh = Mesh(dev_grid, tuple(axis_names))
    return GlobalGrid(mesh=mesh, global_shape=shape, lengths=lengths)


def rebuild_for_mesh(
    grid: GlobalGrid,
    dims: Sequence[int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> GlobalGrid:
    """Re-derive `grid` for a NEW decomposition of the SAME global domain.

    Topology is a run-time variable (docs/RESILIENCE.md "Elastic
    recovery"): a run checkpointed on one mesh resumes on another, and
    everything derived from the decomposition — shardings, local shapes,
    halo programs, deep-halo schedules — must be rebuilt from the new
    dims while the global problem (global_shape, lengths, axis names)
    stays fixed. This is that rebuild for the grid itself;
    `parallel.halo.rebuild_for_mesh` / `parallel.deep_halo.rebuild_for_mesh`
    layer the communication programs on top.

    `dims` defaults to the plan_dims sub-mesh over `devices` (default:
    all of jax.devices()). Divisibility is validated by GlobalGrid
    itself, so an invalid explicit dims fails loudly here, not at trace
    time.
    """
    if devices is None:
        devices = jax.devices()
    if dims is None:
        dims = plan_dims(grid.global_shape, len(devices))
    dims = tuple(int(d) for d in dims)
    if len(dims) != grid.ndim:
        raise ValueError(
            f"dims {dims} rank != grid rank {grid.ndim}"
        )
    nproc = int(np.prod(dims))
    if nproc > len(devices):
        raise ValueError(
            f"dims {dims} need {nproc} devices, have {len(devices)}"
        )
    dev_grid = np.asarray(list(devices)[:nproc]).reshape(dims)
    return GlobalGrid(
        mesh=Mesh(dev_grid, grid.axis_names),
        global_shape=grid.global_shape,
        lengths=grid.lengths,
    )


@dataclasses.dataclass(frozen=True)
class BatchedGrid:
    """A space×batch device mesh: `batch` independent simulation lanes of
    one space grid, sharded over a mesh whose LEADING axis is the lane
    axis (docs/SERVING.md).

    The multi-tenant layout (ROADMAP item 1): batched state is
    ``(batch, *space_shape)`` under ``PartitionSpec("batch", gx, …)``,
    so XLA splits lanes over the batch device rows and each lane's
    spatial shards over the space axes. Halo collectives stay strictly
    per-space-axis — inside a `shard_map` over `self.mesh`, the
    per-lane local step is `vmap`ped over the leading lane axis and the
    existing `exchange_halo`/sweep machinery runs against the `space`
    descriptor unchanged (ppermute batching carries the lane dim along;
    lane k's slabs only ever meet lane k's neighbors). Nothing is ever
    permuted over the `batch` axis — lanes are separate tenants
    (graftlint GL05's batch rule is the static police).

    `space` is the per-lane grid DESCRIPTOR: its mesh is one batch row
    of `mesh` (shapes/axis names are what the halo machinery reads; the
    collectives resolve axis names against the surrounding combined-mesh
    shard_map, so the descriptor's device objects never matter)."""

    mesh: Mesh  # axes (BATCH_AXIS, *space axis names)
    space: GlobalGrid  # the per-lane space grid descriptor
    batch: int  # global lane count B

    def __post_init__(self):
        names = tuple(self.mesh.axis_names)
        if not names or names[0] != BATCH_AXIS:
            raise ValueError(
                f"batched mesh must lead with axis {BATCH_AXIS!r}, "
                f"got {names}"
            )
        if names[1:] != self.space.axis_names:
            raise ValueError(
                f"batched mesh space axes {names[1:]} != space grid "
                f"axes {self.space.axis_names}"
            )
        if tuple(self.mesh.devices.shape[1:]) != self.space.dims:
            raise ValueError(
                f"batched mesh space dims {self.mesh.devices.shape[1:]} "
                f"!= space grid dims {self.space.dims}"
            )
        if self.batch < 1:
            raise ValueError(f"batch must be >= 1, got {self.batch}")
        if self.batch % self.batch_dims != 0:
            raise ValueError(
                f"batch {self.batch} not divisible by the {self.batch_dims} "
                f"device rows along {BATCH_AXIS!r}"
            )

    # ---- topology -------------------------------------------------------

    @property
    def batch_dims(self) -> int:
        """Device rows along the lane axis."""
        return int(self.mesh.devices.shape[0])

    @property
    def local_batch(self) -> int:
        """Lanes per batch device row."""
        return self.batch // self.batch_dims

    @property
    def dims(self) -> tuple[int, ...]:
        return tuple(self.mesh.devices.shape)

    @property
    def nprocs(self) -> int:
        return int(np.prod(self.dims))

    @property
    def ndim(self) -> int:
        """Rank of the BATCHED state (1 + space rank)."""
        return 1 + self.space.ndim

    @property
    def axis_names(self) -> tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    @property
    def global_shape(self) -> tuple[int, ...]:
        """Batched state shape: (batch, *space global shape)."""
        return (self.batch,) + self.space.global_shape

    @property
    def local_shape(self) -> tuple[int, ...]:
        return (self.local_batch,) + self.space.local_shape

    # ---- sharding -------------------------------------------------------

    @property
    def spec(self) -> PartitionSpec:
        """P(batch, *space axes) — the batched-state partition spec."""
        return PartitionSpec(*self.axis_names)

    @property
    def sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec)

    @property
    def aux_spec(self) -> PartitionSpec:
        """Spec of an UNBATCHED space-shaped operand inside the combined
        mesh (prepare coefficients shared by every lane)."""
        return PartitionSpec(*self.space.axis_names)

    @property
    def aux_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.aux_spec)

    @property
    def batch_spec(self) -> PartitionSpec:
        """Spec of a per-lane scalar/vector operand, e.g. lane step
        counts shaped (batch,)."""
        return PartitionSpec(BATCH_AXIS)

    @property
    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec)


def init_batched_grid(
    batch: int,
    *global_shape: int,
    lengths: Sequence[float] | None = None,
    space_dims: Sequence[int] | None = None,
    batch_dims: int = 1,
    devices: Sequence[jax.Device] | None = None,
) -> BatchedGrid:
    """Build a BatchedGrid: `batch` lanes of a `global_shape` space grid
    over `batch_dims × space_dims` devices (leading `batch` mesh axis).

    `space_dims` defaults to the largest valid sub-mesh over the devices
    left after the batch rows take theirs (plan_dims); `batch_dims`
    defaults to 1 — the serving layer grows it when the queue is deep
    and the device budget allows (docs/SERVING.md "Elasticity")."""
    if batch < 1:
        raise ValueError(f"batch must be >= 1, got {batch}")
    if batch_dims < 1:
        raise ValueError(f"batch_dims must be >= 1, got {batch_dims}")
    if batch % batch_dims != 0:
        raise ValueError(
            f"batch {batch} not divisible by batch_dims {batch_dims}"
        )
    shape = tuple(int(n) for n in global_shape)
    ndim = len(shape)
    if lengths is None:
        lengths = (10.0,) * ndim
    lengths = tuple(float(l) for l in lengths)
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if batch_dims > len(devices):
        raise ValueError(
            f"batch_dims {batch_dims} needs {batch_dims} devices, "
            f"have {len(devices)}"
        )
    if space_dims is None:
        space_dims = plan_dims(shape, len(devices) // batch_dims)
    space_dims = tuple(int(d) for d in space_dims)
    need = batch_dims * int(np.prod(space_dims))
    if need > len(devices):
        raise ValueError(
            f"batched mesh ({batch_dims}, {space_dims}) needs {need} "
            f"devices, have {len(devices)}"
        )
    dev_grid = np.asarray(devices[:need]).reshape((batch_dims,) + space_dims)
    space = GlobalGrid(
        mesh=Mesh(dev_grid[0], AXIS_NAMES[:ndim]),
        global_shape=shape,
        lengths=lengths,
    )
    return BatchedGrid(
        mesh=Mesh(dev_grid, (BATCH_AXIS,) + space.axis_names),
        space=space,
        batch=int(batch),
    )


def rebuild_batched_for_mesh(
    bgrid: BatchedGrid,
    batch: int | None = None,
    batch_dims: int | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> BatchedGrid:
    """Re-derive a BatchedGrid for a NEW device budget / lane width —
    the serving layer's elastic resize (grow the batch rows when the
    queue is deep, shrink when idle; docs/SERVING.md). The space problem
    (global shape, lengths) stays fixed; everything derived from the
    decomposition — shardings, local lane counts, compiled batched
    programs — must be rebuilt, exactly as the elastic-recovery
    contract demands for the space mesh (rebuild_for_mesh)."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if batch_dims is None:
        batch_dims = bgrid.batch_dims
    if batch is None:
        batch = bgrid.batch
    space_dims = plan_dims(
        bgrid.space.global_shape, max(len(devices) // batch_dims, 1)
    )
    return init_batched_grid(
        batch,
        *bgrid.space.global_shape,
        lengths=bgrid.space.lengths,
        space_dims=space_dims,
        batch_dims=batch_dims,
        devices=devices,
    )
