"""Pallas TPU kernels for the shallow-water workload (third model family —
no reference analog; the reference ships exactly one physics model).

Physics: the linearized shallow-water equations in a closed basin,
discretized on an Arakawa-C-style staggered grid where every field keeps
the SAME array shape (h at cell centers; u_a at the +a face of its cell):

    h' = h  − dt·H·Σ_a ∂a⁻(u_a)        (backward differences)
    u_a' = M_a ∘ (u_a − dt·g·∂a⁺(h'))  (forward differences, updated h)

This forward-backward (symplectic-Euler) pairing of adjoint difference
operators is the classic energy-stable scheme for first-order wave systems.
Unlike the diffusion (one field) and wave (state pair, one exchanged field)
workloads, the SWE state is ndim+1 COUPLED fields whose updates read
neighbors of *different* fields — the case that exercises the framework's
pytree-state halo machinery (parallel.overlap, parallel.deep_halo).

Boundary design — mask-as-data, no `where` in the hot loop: the face mask
M_a is exactly 0.0 on the global high wall (face index n_g−1 along axis a)
and 1.0 elsewhere, so wall-face velocities stay bitwise 0 forever; the low
wall is the zero-ghost convention (u_a[−1] ghosts arrive as zeros,
parallel.halo). Sealed walls give EXACT mass conservation: Σ_core ∂a⁻u_a
telescopes to (wall − wall) = 0, so sum(h) is invariant to fp rounding —
the workload's machine-checkable invariant (tests/test_swe.py), alongside
algebraic time-reversibility (the update has a closed-form inverse).

The roll form below is exact even ON the global array: jnp.roll wraparound
brings exactly the opposite wall face, which the masks hold at 0 — so one
definition (`masked_swe_step`) serves the ap variant, the VMEM-resident
multi-step kernel, and the deep-halo sweep fallback.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax
from rocm_mpi_tpu.utils.compat import pallas as pl
from rocm_mpi_tpu.utils.compat import pallas_tpu as pltpu

from rocm_mpi_tpu.ops.pallas_kernels import (
    _VMEM_BLOCK_BUDGET_BYTES,
    _compute_nbytes,
    _interpret_default,
    _out_struct,
    _supports_compiled,
    _upcast_for_compute,
)


def swe_coeffs(dt, spacing, H, g):
    """Per-axis scalar update coefficients (cH_a, cg_a) = (dt·H/d_a,
    dt·g/d_a) — the only place the physical constants meet the grid."""
    cH = tuple(float(dt) * float(H) / float(d) for d in spacing)
    cg = tuple(float(dt) * float(g) / float(d) for d in spacing)
    return cH, cg


def masked_swe_step(h, us, Mus, cH, cg):
    """One forward-backward SWE step (plain jnp rolls) — THE single
    definition of the update, shared by the ap variant (global arrays:
    wraparound brings the opposite wall face, held 0 by the masks), the
    VMEM-resident Pallas kernel body, and the deep-halo fallback (padded
    blocks: wraparound feeds only the ghost ring, cropped at sweep end;
    off-domain faces are zeroed by the padded masks).

    `us`/`Mus` are length-ndim sequences; returns (h', us')."""
    div = None
    for a, u in enumerate(us):
        d = cH[a] * (u - jnp.roll(u, 1, a))
        div = d if div is None else div + d
    h = h - div
    us = tuple(
        Mus[a] * (u - cg[a] * (jnp.roll(h, -1, a) - h))
        for a, u in enumerate(us)
    )
    return h, us


def _swe_padded_math(hp, ups, Mus, cH, cg):
    """The staggered-index C-grid update on width-1-padded values — THE
    one copy of the coupled slicing arithmetic, shared by the jnp padded
    form and the Pallas kernel body. Returns the (h', u0', …) core tuple.

    h' is computed on the core-plus-high-pad box (one extra cell on the
    high side of every axis) so the forward differences the velocity
    updates need never require a second exchange — one ghost exchange of
    the full state advances the whole coupled step."""
    ndim = hp.ndim
    ext = tuple(slice(1, None) for _ in range(ndim))
    div = None
    for a, up in enumerate(ups):
        hi = [slice(1, None)] * ndim
        lo = [slice(1, None)] * ndim
        lo[a] = slice(0, -1)
        d = cH[a] * (up[tuple(hi)] - up[tuple(lo)])
        div = d if div is None else div + d
    h_ext = hp[ext] - div
    base = tuple(slice(0, -1) for _ in range(ndim))
    h_core = h_ext[base]
    core = tuple(slice(1, -1) for _ in range(ndim))
    outs = [h_core]
    for a, up in enumerate(ups):
        sh = [slice(0, -1)] * ndim
        sh[a] = slice(1, None)
        dh = h_ext[tuple(sh)] - h_core
        outs.append(Mus[a] * (up[core] - cg[a] * dh))
    return tuple(outs)


def swe_step_padded(Sp, Mus, consts, dt, spacing):
    """Candidate SWE update for every core cell of a width-1-padded block
    (pure jnp) — the framework's padded contract (docs/ADDING_A_MODEL.md
    §1) for a PYTREE state: `Sp = (hp, u0p, …)` are all width-1 padded
    (ghosts from exchange_halo), `Mus` are core-shaped face masks,
    `consts = (H, g)`. Returns the (h', u0', …) core tuple
    (_swe_padded_math has the index-arithmetic story)."""
    hp, *ups = Sp
    H, g = consts
    cH, cg = swe_coeffs(dt, spacing, H, g)
    return _swe_padded_math(hp, ups, Mus, cH, cg)


def _swe_kernel_whole(*refs, ndim, cH, cg):
    """Whole-block Pallas twin of swe_step_padded: refs are
    [hp, u0p…, Mu0…, oh, ou0…] (padded state, core masks, core outs).
    The index arithmetic is the shared _swe_padded_math on the
    VMEM-resident values (consts pre-divided into cH/cg by the caller)."""
    n_state = ndim + 1
    pad_in = refs[:n_state]
    mask_in = refs[n_state:n_state + ndim]
    outs = refs[n_state + ndim:]
    vals = _upcast_for_compute(*[r[:] for r in pad_in + mask_in])
    Sp, Mus = vals[:n_state], vals[n_state:]
    hp, *ups = Sp
    res = _swe_padded_math(hp, ups, Mus, cH, cg)
    for o_ref, r in zip(outs, res):
        o_ref[:] = r.astype(o_ref.dtype)


def swe_step_padded_pallas(Sp, Mus, consts, dt, spacing, interpret=None):
    """Pallas whole-block form of the padded SWE step (the perf/hide
    kernel). Falls back to the identical-semantics jnp padded form for
    blocks beyond the VMEM budget and for dtypes Mosaic cannot compile
    (f64 on a real chip) — same policy as the wave workload's kernel
    (wave_step_padded_pallas: the non-flagship models prefer a slower
    correct path over a crash)."""
    hp = Sp[0]
    ndim = hp.ndim
    if interpret is None:
        interpret = _interpret_default()
    # 2·(ndim+1) padded + ndim mask arrays resident at f32 compute width.
    nbytes = (3 * ndim + 2) * _compute_nbytes(Mus[0])
    if (not _supports_compiled(hp.dtype) and not interpret) or (
        nbytes > _VMEM_BLOCK_BUDGET_BYTES
    ):
        return swe_step_padded(Sp, Mus, consts, dt, spacing)
    H, g = consts
    cH, cg = swe_coeffs(dt, spacing, H, g)
    kernel = functools.partial(_swe_kernel_whole, ndim=ndim, cH=cH, cg=cg)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    core_shape = Mus[0].shape
    out_sd = tuple(
        _out_struct(core_shape, hp) for _ in range(ndim + 1)
    )
    outs = pl.pallas_call(
        kernel,
        out_shape=out_sd,
        in_specs=[vmem] * (2 * ndim + 1),
        out_specs=(vmem,) * (ndim + 1),
        interpret=interpret,
    )(*Sp, *Mus)
    return tuple(outs)


def _swe_multi_step_kernel(*refs, ndim, cH, cg, chunk):
    """`chunk` forward-backward steps with the whole state VMEM-resident
    (bf16 storage upcast to f32 for the chunk — one rounding per chunk,
    the storage-only-bf16 policy of the diffusion/wave multi-step
    kernels). refs = [h, u0…, Mu0…, oh, ou0…], all same-shape."""
    n_state = ndim + 1
    ins = refs[:n_state + ndim]
    outs = refs[n_state + ndim:]
    vals = _upcast_for_compute(*[r[:] for r in ins])
    h0, us0, Mus = vals[0], vals[1:n_state], vals[n_state:]

    def body(_, s):
        return masked_swe_step(s[0], s[1], Mus, cH, cg)

    h, us = lax.fori_loop(0, chunk, body, (h0, tuple(us0)), unroll=True)
    outs[0][:] = h.astype(outs[0].dtype)
    for a, u in enumerate(us):
        outs[a + 1][:] = u.astype(outs[a + 1].dtype)


def swe_multi_step_masked(h, us, Mus, cH, cg, n_steps: int, interpret=None):
    """`n_steps` unrolled SWE steps on a VMEM-resident state with
    caller-supplied face masks — the SWE analog of
    ops.pallas_kernels.multi_step_cm / wave_kernels.wave_multi_step_masked,
    and the local compute of SWE deep-halo sweeps: the caller pads the
    blocks and zeroes the masks on wall/off-domain faces; `n_steps` must
    not exceed the ghost width (the light-cone bound). Returns (h, us)."""
    if interpret is None:
        interpret = _interpret_default()
    if not _supports_compiled(h.dtype) and not interpret:
        raise TypeError(f"Mosaic does not support {h.dtype}")
    ndim = h.ndim
    if len(us) != ndim or len(Mus) != ndim:
        raise ValueError(
            f"need ndim={ndim} velocity fields and masks, got "
            f"{len(us)} and {len(Mus)}"
        )
    for arr in (*us, *Mus):
        if arr.shape != h.shape:
            raise ValueError(
                f"all SWE fields share one shape: h {h.shape} vs {arr.shape}"
            )
    # 2·(ndim+1) state + ndim masks resident at f32 compute width.
    nbytes = (3 * ndim + 2) * _compute_nbytes(h)
    if nbytes > _VMEM_BLOCK_BUDGET_BYTES:
        raise ValueError(
            f"state of {nbytes} bytes (f32 compute width) exceeds the "
            f"VMEM-resident budget ({_VMEM_BLOCK_BUDGET_BYTES})"
        )
    kernel = functools.partial(
        _swe_multi_step_kernel, ndim=ndim, cH=tuple(cH), cg=tuple(cg),
        chunk=int(n_steps),
    )
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    out_sd = tuple(_out_struct(h.shape, h) for _ in range(ndim + 1))
    outs = pl.pallas_call(
        kernel,
        out_shape=out_sd,
        in_specs=[vmem] * (2 * ndim + 1),
        out_specs=(vmem,) * (ndim + 1),
        interpret=interpret,
    )(h, *us, *Mus)
    return outs[0], tuple(outs[1:])


def swe_multi_step(
    h, us, Mus, dt, spacing, H, g, n_steps, chunk=None, interpret=None,
    warn_on_cap=True, config=None,
):
    """Advance a *single-shard* SWE state `n_steps` barely leaving VMEM —
    the SWE edition of fused_multi_step / wave_multi_step (same chunk
    policy, resolve_step_chunk; same dynamic-n divisibility caveat: a
    TRACED `n_steps` floors the trip count, so callers must guarantee
    `chunk | n_steps` themselves, as run_vmem_resident does via gcd).
    `Mus` must already hold the wall faces (models.swe.face_masks) — on
    the global array the roll wraparound then reads exactly those zeroed
    opposite wall faces, keeping the closed-basin physics exact.
    `config="auto"` fills an unset `chunk` from the tuning cache (op
    "swe.vmem_loop", static n_steps only — gcd'd, same policy as the
    wave/diffusion editions); a miss keeps the defaults bitwise."""
    from rocm_mpi_tpu.ops.pallas_kernels import resolve_step_chunk

    if interpret is None:
        interpret = _interpret_default()
    if not _supports_compiled(h.dtype) and not interpret:
        raise TypeError(f"Mosaic does not support {h.dtype}")
    if config == "auto" and chunk is None and isinstance(n_steps, int):
        from rocm_mpi_tpu.tuning import resolve as tuning_resolve

        from rocm_mpi_tpu.ops.pallas_kernels import adoptable_vmem_chunk

        tuned = tuning_resolve.resolve("swe.vmem_loop", h.shape, h.dtype)
        if tuned and adoptable_vmem_chunk(tuned.get("chunk")):
            import math

            chunk = math.gcd(n_steps, tuned["chunk"]) or None
    elif config not in (None, "default", "auto"):
        raise ValueError(
            f"config must be None, 'default' or 'auto', got {config!r}"
        )
    nbytes = (3 * h.ndim + 2) * _compute_nbytes(h)
    if nbytes > _VMEM_BLOCK_BUDGET_BYTES:
        raise ValueError(
            f"state of {nbytes} bytes (f32 compute width) exceeds the "
            f"VMEM-resident budget ({_VMEM_BLOCK_BUDGET_BYTES}); use the "
            "per-step path"
        )
    chunk = resolve_step_chunk(n_steps, chunk, _compute_nbytes(h),
                               warn_on_cap)
    cH, cg = swe_coeffs(dt, spacing, H, g)
    return lax.fori_loop(
        0,
        n_steps // chunk,
        lambda _, s: swe_multi_step_masked(
            s[0], s[1], Mus, cH, cg, chunk, interpret=interpret
        ),
        (h, tuple(us)),
    )
