"""Compute ops: pure-jnp stencil helpers and Pallas TPU kernels."""
