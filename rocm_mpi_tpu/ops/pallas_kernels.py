"""Pallas TPU stencil kernels (D7) — the hand-tuned rungs of the ladder.

TPU-native re-design of the reference's hand-written GPU kernels:

* `fused_step_padded` — the fused memory-bound diffusion kernel
  (/root/reference/scripts/diffusion_2D_perf.jl:3-13). Whole-block-in-VMEM
  for shard sizes that fit (the 252²/chip benchmark regime: the entire field
  lives on-chip), row-striped with a 3-slot neighbor-block trick for large
  single-chip grids (the 12288² regime), pipelining HBM→VMEM stripe loads
  against VPU compute.
* `fused_multi_step` — a TPU-only optimization with no reference analog:
  when the whole field fits in VMEM, run the *entire time loop inside one
  kernel*, never spilling T to HBM between steps. The reference pays 3
  whole-array HBM passes per step by construction; on TPU the memory-bound
  assumption dissolves for VMEM-resident fields.

The `gridsize`-is-workitems convention of `@roc` does not carry over: Pallas
grids count *blocks* (SURVEY.md §7 hard-parts note). The reference's
`threads=(32,8)` tuning knob maps to the stripe height `tm` here.

f64 note: Mosaic (the TPU Pallas compiler) does not support f64; the f64
parity path uses these kernels in interpreter mode (tests) or the jnp
step functions (production), per SURVEY.md §7 "f64 on TPU".
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from rocm_mpi_tpu.utils.compat import pallas as pl
from rocm_mpi_tpu.utils.compat import pallas_tpu as pltpu

# Whole-block kernels hold ~5 block-sized buffers in VMEM; stay well under
# the ~16 MB/core budget (pallas_guide.md "Memory Hierarchy").
_VMEM_BLOCK_BUDGET_BYTES = 2 * 1024 * 1024
# Largest in-kernel working slab ((tm+2g) rows) the per-step striped kernel
# may assemble: beyond this the pipeline buffers + lap temporaries blow the
# VMEM compile boundary (measured on v5e, see masked_step's tm selection).
_PS_SLAB_BUDGET_BYTES = 2_500_000


def _supports_compiled(dtype) -> bool:
    return jnp.dtype(dtype).itemsize <= 4


def _upcast_for_compute(*arrays):
    """bf16 is STORAGE-ONLY in this kernel family (r4): operands are upcast
    to f32 on entry and results rounded back once at the kernel boundary.
    bf16 keeps its whole value — halved HBM/VMEM traffic — while the step
    arithmetic runs at f32, so quantization is injected once per kernel
    (per step for the per-step kernels, per chunk/sweep for the multi-step
    ones) instead of compounding through every intermediate. Measured
    motivation: with per-step bf16 rounding the 252² trajectory freezes
    (updates quantize to zero; docs/bf16_error_cpu252_perstep_r4.txt vs
    the flat curve of docs/bf16_error_cpu252_vmem_r4.txt)."""
    if arrays[0].dtype == jnp.bfloat16:
        return tuple(a.astype(jnp.float32) for a in arrays)
    return arrays


def _compute_itemsize(dtype) -> int:
    """In-kernel bytes per element: bf16 state is upcast to f32 inside
    the kernels (_upcast_for_compute), so every VMEM/admission/stripe
    policy must budget at >= f32 width, not storage width. The ONE place
    the storage-only width rule lives."""
    return max(jnp.dtype(dtype).itemsize, 4)


def _compute_nbytes(arr) -> int:
    """In-kernel working-set bytes per field (see _compute_itemsize)."""
    return arr.size * _compute_itemsize(arr.dtype)


def _out_struct(shape, exemplar):
    """ShapeDtypeStruct matching `exemplar`'s dtype and mesh-varying axes.

    Inside shard_map (jax>=0.9 check_vma), pallas_call outputs must declare
    which mesh axes they vary over; propagate the input's vma set
    (version-portably — utils.compat owns the jax-API drift).
    """
    from rocm_mpi_tpu.utils.compat import out_struct_like

    return out_struct_like(shape, exemplar)


def _interpret_default() -> bool:
    """Dispatch policy when the caller passes interpret=None: compiled
    Mosaic on TPU, the Pallas interpreter on CPU (the test harness).
    Any OTHER accelerator backend raises — silently interpreting on a GPU
    would run ≈hours instead of surfacing 'this framework's kernels are
    TPU-native' (VERDICT r3 hygiene note)."""
    backend = jax.default_backend()
    if backend == "tpu":
        return False
    if backend == "cpu":
        return True
    raise RuntimeError(
        f"no default Pallas dispatch for backend {backend!r}: compiled "
        "Mosaic kernels are TPU-only, and the interpreter (the CPU test "
        "path) would silently be hours-slow on an accelerator; pass "
        "interpret= explicitly to override"
    )


def _lap_from_padded(Tp, inv_d2):
    """Σ_ax (hi - 2·c + lo)/dx² from a width-1-padded block (5/7-point)."""
    ndim = Tp.ndim
    core = tuple(slice(1, -1) for _ in range(ndim))
    lap = None
    for ax in range(ndim):
        hi = tuple(slice(2, None) if a == ax else slice(1, -1) for a in range(ndim))
        lo = tuple(slice(None, -2) if a == ax else slice(1, -1) for a in range(ndim))
        term = (Tp[hi] - 2.0 * Tp[core] + Tp[lo]) * inv_d2[ax]
        lap = term if lap is None else lap + term
    return lap


# ---------------------------------------------------------------------------
# Whole-block kernel: core update from a padded block (shard fits in VMEM).
# ---------------------------------------------------------------------------


def _fused_kernel_whole(Tp_ref, Cp_ref, out_ref, *, lam, dt, inv_d2):
    Tp, Cp = _upcast_for_compute(Tp_ref[:], Cp_ref[:])
    core = tuple(slice(1, -1) for _ in range(Tp.ndim))
    out_ref[:] = (
        Tp[core] + (dt * lam) / Cp * _lap_from_padded(Tp, inv_d2)
    ).astype(out_ref.dtype)


def fused_step_padded(Tp, Cp, lam, dt, spacing, interpret=None):
    """Candidate update for every core cell given the padded block `Tp`.

    Pallas counterpart of ops.diffusion.step_fused_padded (same contract:
    caller supplies ghosts via halo.exchange_halo and masks global-boundary
    cells). Dispatches whole-block vs row-striped by VMEM footprint.
    """
    if interpret is None:
        interpret = _interpret_default()
    if not _supports_compiled(Tp.dtype) and not interpret:
        raise TypeError(
            f"Mosaic does not support {Tp.dtype}; use the jnp path or "
            "interpret mode for f64 parity runs"
        )
    # Bake scalars into the kernel as Python floats (captured jnp scalars
    # are rejected by pallas_call; physics constants are static anyway).
    lam, dt = float(lam), float(dt)
    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)
    nbytes = _compute_nbytes(Cp)
    if Tp.ndim in (2, 3) and nbytes > _VMEM_BLOCK_BUDGET_BYTES:
        return _fused_step_striped(Tp, Cp, lam, dt, inv_d2, interpret)
    kernel = functools.partial(
        _fused_kernel_whole, lam=lam, dt=dt, inv_d2=inv_d2
    )
    return pl.pallas_call(
        kernel,
        out_shape=_out_struct(Cp.shape, Cp),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(Tp, Cp)


# ---------------------------------------------------------------------------
# Row-striped kernel for large 2D grids: 3-slot neighbor-block trick.
# Output stripe i (tm rows of the core) reads padded rows [i·tm, i·tm+tm+2),
# assembled from padded row-blocks i and i+1 — overlapping windows built
# from non-overlapping BlockSpecs.
# ---------------------------------------------------------------------------


def _fused_kernel_striped(Ta_ref, Tb_ref, Cp_ref, out_ref, *, lam, dt, inv_d2):
    Ta = Ta_ref[:]  # padded rows [i·tm, i·tm+tm)
    Tb = Tb_ref[:]  # padded rows [i·tm+tm, i·tm+2·tm); last block is partial
    # `ext` is a fully padded block for this output stripe: padded along
    # axis 0 by the stripe overlap, along the rest by Tp's own pad ring.
    ext = jnp.concatenate([Ta, Tb[:2]], axis=0)  # rows [i·tm, i·tm+tm+2)
    ext, Cp = _upcast_for_compute(ext, Cp_ref[:])
    core = tuple(slice(1, -1) for _ in range(ext.ndim))
    out_ref[:] = (
        ext[core] + (dt * lam) / Cp * _lap_from_padded(ext, inv_d2)
    ).astype(out_ref.dtype)


def _stripe_height(row_bytes: int) -> int:
    """Stripe height for the striped kernels: sized so one stripe
    (`row_bytes` bytes per padded row) fits the per-buffer VMEM budget
    (the striped kernel holds ~4 block operands, each double-buffered by
    the Pallas pipeline — hence budget/2 per buffer), rounded down to the
    f32 sublane tile (8). The analog of the reference's `threads=(32,8)`
    tile knob (perf.jl:23), chosen automatically.

    No divisibility constraint on the row count: the grid is
    ceil-divided and Pallas masks partial trailing blocks (out-of-range
    reads feed only dropped rows; out-of-range writes are dropped) —
    pad-to-tile without materializing any padding.
    """
    per_buffer = _VMEM_BLOCK_BUDGET_BYTES // 2
    return max(8, (per_buffer // max(1, row_bytes)) // 8 * 8)


def _striped_call(kernel, Tp, C, interpret):
    """Shared launch of the 3-slot striped kernels over ceil(n1/tm) stripes.

    Output stripe i (tm core rows) reads padded rows [i·tm, i·tm+tm+2),
    assembled in-kernel from padded row-blocks i and i+1 — overlapping
    windows built from non-overlapping BlockSpecs. `C` is the core-shaped
    coefficient operand (Cp or Cm). Partial-stripe bookkeeping:
      - last output stripe may be partial → Pallas drops OOB writes;
      - block i+1 may be partly or wholly OOB on Tp → its index is clamped
        and the garbage rows feed only dropped output rows (when the core
        row count is ≤ tm-2 past the last full stripe, every needed padded
        row is already inside block i; otherwise row n1+1 exists in Tp).
    """
    core = C.shape  # Tp is core + 2 per axis
    n1, rest = core[0], core[1:]
    rest_p = tuple(n + 2 for n in rest)
    # bf16 operands are upcast to f32 in-kernel: size stripes at f32 width.
    row_bytes = _compute_itemsize(C.dtype)
    for n in rest_p:
        row_bytes *= n
    tm = _stripe_height(row_bytes)
    grid = (-(-n1 // tm),)
    zeros = (0,) * len(rest)
    return pl.pallas_call(
        kernel,
        out_shape=_out_struct(core, C),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (tm,) + rest_p, lambda i: (i,) + zeros, memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (tm,) + rest_p,
                lambda i: (i + 1,) + zeros,
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (tm,) + rest, lambda i: (i,) + zeros, memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (tm,) + rest, lambda i: (i,) + zeros, memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(Tp, Tp, C)


def _fused_step_striped(Tp, Cp, lam, dt, inv_d2, interpret):
    kernel = functools.partial(
        _fused_kernel_striped, lam=lam, dt=dt, inv_d2=inv_d2
    )
    return _striped_call(kernel, Tp, Cp, interpret)


# ---------------------------------------------------------------------------
# Cm-masked per-step kernels: the Dirichlet mask and the dt·λ/Cp divide are
# folded into a precomputed coefficient Cm (edge_masked_cm / the sharded
# boundary-masked equivalent), computed ONCE per run instead of per step —
# one kernel per step replaces the reference-parity path's
# kernel + divide + where-mask op chain.
# ---------------------------------------------------------------------------


def _fused_kernel_whole_cm(Tp_ref, Cm_ref, out_ref, *, inv_d2):
    Tp, Cm = _upcast_for_compute(Tp_ref[:], Cm_ref[:])
    core = tuple(slice(1, -1) for _ in range(Tp.ndim))
    out_ref[:] = (
        Tp[core] + Cm * _lap_from_padded(Tp, inv_d2)
    ).astype(out_ref.dtype)


def _fused_kernel_striped_cm(Ta_ref, Tb_ref, Cm_ref, out_ref, *, inv_d2):
    ext = jnp.concatenate([Ta_ref[:], Tb_ref[:2]], axis=0)
    ext, Cm = _upcast_for_compute(ext, Cm_ref[:])
    core = tuple(slice(1, -1) for _ in range(ext.ndim))
    out_ref[:] = (
        ext[core] + Cm * _lap_from_padded(ext, inv_d2)
    ).astype(out_ref.dtype)


def fused_step_cm(Tp, Cm, spacing, interpret=None):
    """Masked per-step core update: new = Tp[core] + Cm · ∇²(Tp).

    `Tp` is the width-1-padded block (ghosts from exchange_halo); `Cm` is
    the core-shaped masked coefficient — (dt·λ)/Cp where the cell updates,
    exactly 0.0 where it is held fixed (global Dirichlet boundary). Because
    the mask is data, the Dirichlet `where` of the unmasked contract
    disappears and one Pallas program serves the whole step (the fused
    memory-bound kernel of diffusion_2D_perf.jl:3-13, with its `ix>1 && …`
    guard carried by Cm instead of control flow). Whole-block in VMEM when
    the shard fits, 3-slot striped otherwise.
    """
    if interpret is None:
        interpret = _interpret_default()
    if not _supports_compiled(Tp.dtype) and not interpret:
        raise TypeError(
            f"Mosaic does not support {Tp.dtype}; use the jnp path or "
            "interpret mode for f64 parity runs"
        )
    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)
    nbytes = _compute_nbytes(Cm)
    if Tp.ndim in (2, 3) and nbytes > _VMEM_BLOCK_BUDGET_BYTES:
        kernel = functools.partial(_fused_kernel_striped_cm, inv_d2=inv_d2)
        return _striped_call(kernel, Tp, Cm, interpret)
    return pl.pallas_call(
        functools.partial(_fused_kernel_whole_cm, inv_d2=inv_d2),
        out_shape=_out_struct(Cm.shape, Cm),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(Tp, Cm)


# ---------------------------------------------------------------------------
# kp rung: three separate kernels with staggered-grid shapes — the
# kernel-programming teaching ladder of the reference
# (/root/reference/scripts/diffusion_2D_kp.jl: Flux! :16-26, Residual!
# :33-40, Update! :47-54), with the same staggered shapes qx=(nx-1,ny-2),
# qy=(nx-2,ny-1), dTdt=(nx-2,ny-2) (scripts/diffusion_2D_ap.jl:22-24).
# Expressed against a width-1-padded block so the same contract serves
# single-device and shard_map use. Whole-array VMEM kernels: the kp rung
# runs 128²-class grids (kp.jl:62); its point is pedagogy and the
# 3-sync-per-step cost the fused rung removes, not scale.
# ---------------------------------------------------------------------------


def _flux_kernel(Tp_ref, qx_ref, qy_ref, *, lam, inv_d):
    # Fourier's law on the staggered grid: q = -λ ∂T (kp.jl Flux!).
    (Tp,) = _upcast_for_compute(Tp_ref[:])
    qx_ref[:] = (-lam * (Tp[1:, 1:-1] - Tp[:-1, 1:-1]) * inv_d[0]).astype(
        qx_ref.dtype
    )
    qy_ref[:] = (-lam * (Tp[1:-1, 1:] - Tp[1:-1, :-1]) * inv_d[1]).astype(
        qy_ref.dtype
    )


def _residual_kernel(qx_ref, qy_ref, Cp_ref, dTdt_ref, *, inv_d):
    # Conservation of energy: ∂T/∂t = 1/cₚ(-∇·q) (kp.jl Residual!).
    qx, qy, Cp = _upcast_for_compute(qx_ref[:], qy_ref[:], Cp_ref[:])
    div = (qx[1:, :] - qx[:-1, :]) * inv_d[0] + (
        qy[:, 1:] - qy[:, :-1]
    ) * inv_d[1]
    dTdt_ref[:] = (-div / Cp).astype(dTdt_ref.dtype)


def _update_kernel(Tp_ref, dTdt_ref, out_ref, *, dt):
    # Temperature update: T_new = T_old + dt·∂T/∂t (kp.jl Update!).
    Tp, dTdt = _upcast_for_compute(Tp_ref[:], dTdt_ref[:])
    out_ref[:] = (Tp[1:-1, 1:-1] + dt * dTdt).astype(out_ref.dtype)


def kp_step_padded(Tp, Cp, lam, dt, spacing, interpret=None):
    """Candidate core update via the 3-kernel ladder (kp variant).

    Same contract as fused_step_padded but as three separate device
    programs per step — reproducing the reference kp rung's structure
    (three launches + three syncs, kp.jl:87-92) to make the fused rung's
    win measurable.
    """
    if Cp.ndim != 2:
        raise ValueError(
            "the kp ladder rung is 2D-only (as is the reference's kp app); "
            "use variants 'perf'/'hide' for 3D grids"
        )
    if interpret is None:
        interpret = _interpret_default()
    if not _supports_compiled(Tp.dtype) and not interpret:
        raise TypeError(f"Mosaic does not support {Tp.dtype}")
    lam, dt = float(lam), float(dt)
    inv_d = tuple(1.0 / float(d) for d in spacing)
    lx, ly = Cp.shape  # core shape; Tp is (lx+2, ly+2)

    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    qx, qy = pl.pallas_call(
        functools.partial(_flux_kernel, lam=lam, inv_d=inv_d),
        out_shape=(
            _out_struct((lx + 1, ly), Tp),
            _out_struct((lx, ly + 1), Tp),
        ),
        in_specs=[vmem],
        out_specs=(vmem, vmem),
        interpret=interpret,
    )(Tp)
    dTdt = pl.pallas_call(
        functools.partial(_residual_kernel, inv_d=inv_d),
        out_shape=_out_struct((lx, ly), Cp),
        in_specs=[vmem, vmem, vmem],
        out_specs=vmem,
        interpret=interpret,
    )(qx, qy, Cp)
    return pl.pallas_call(
        functools.partial(_update_kernel, dt=dt),
        out_shape=_out_struct((lx, ly), Cp),
        in_specs=[vmem, vmem],
        out_specs=vmem,
        interpret=interpret,
    )(Tp, dTdt)


# ---------------------------------------------------------------------------
# Whole-loop-in-VMEM kernel: nt steps without touching HBM (single shard).
# ---------------------------------------------------------------------------


# Equal-spacing body form for _multi_step_kernel: "eqc" (A∘T + c∘s, the
# r3-measured production form) or "conly" (A-free, one fewer VMEM operand
# stream). A module constant, not config plumbing: the choice is a
# measured hardware default, not a user decision — flip it here when the
# chip A/B (scripts/bench_kernel_forms.py, VERDICT r4 next #2) justifies.
EQC_BODY_FORM = "eqc"

# Pad the VMEM-resident loop's field to power-of-two axes (252² → 256²):
# every vreg tile is then full and the ±1 rolls are aligned shifts. The
# pad ring carries Cm = 0, so pad cells never update and the interior is
# bit-identical to the unpadded program (wraparound only ever reaches
# frozen cells — the kernel's own Dirichlet argument). Same contract as
# EQC_BODY_FORM: a measured hardware default, flipped here if the chip
# A/B's pad_eqc/pad_conly rows justify; the CPU bitwise-equivalence test
# (tests/test_pallas_kernels.py) holds either way.
VMEM_PAD_POW2 = False

class KernelChoice(NamedTuple):
    """What a kernel entry point decided at trace time — dispatch route,
    effective chunk/body form, and the pad outcome — as an explicit
    record instead of a post-hoc module-global query flag (the retired
    `last_pad_applied` pattern: a global written at trace time is stale
    the moment a cached program is reused; a record computed by the pure
    planner is valid whenever it is recomputed). `plan_vmem_loop` is the
    planner; bench.py labels its ladder rungs from this, and the
    autotuner keys measured programs by it."""

    op: str  # the tuning-op spelling ("diffusion.vmem_loop", …)
    dispatch: str  # "vmem-loop" | "whole" | "striped" | …
    chunk: int | None = None  # effective steps per kernel launch
    body_form: str | None = None  # resolved eqc/conly (vmem loop)
    pad_requested: bool = False
    # pad outcome, the old last_pad_applied tri-state: True = applied,
    # False = requested but skipped (VMEM budget), None = not requested
    # or nothing to pad (already pow2).
    pad_applied: bool | None = None
    padded_shape: tuple | None = None  # set only when pad_applied


# Deprecation shim state for last_pad_applied(): written by
# fused_multi_step solely so the deprecated accessor keeps answering
# during its sunset. New code uses plan_vmem_loop(...) — pure, and valid
# even when the compiled program came from a cache (this global is not).
_LAST_CHOICE: KernelChoice | None = None


def last_pad_applied() -> bool | None:
    """DEPRECATED: did the most recent fused_multi_step *trace* apply
    the pow2 pad? Stale whenever a cached compiled program is reused —
    compute the decision instead: plan_vmem_loop(...).pad_applied (pure,
    per-config, cache-proof)."""
    import warnings

    warnings.warn(
        "last_pad_applied() is deprecated: the module-global flag is only "
        "valid right after the call that traced the program; use "
        "plan_vmem_loop(shape, dtype, n_steps, ...).pad_applied instead",
        DeprecationWarning,
        stacklevel=2,
    )
    return None if _LAST_CHOICE is None else _LAST_CHOICE.pad_applied


def _next_pow2(n: int) -> int:
    return 1 << (int(n) - 1).bit_length()


def adoptable_vmem_chunk(v) -> bool:
    """May a tuning-cache chunk steer a VMEM multi-step kernel? Only a
    power of two >= 4: the kernels switch to a different fp body below
    chunk 4, and a pow2 preference guarantees gcd(n, v) lands in the
    SAME body-form class as the default gcd(n, DEFAULT_STEP_CHUNK) for
    every n — the algebra that keeps config="auto" bitwise-equal to the
    defaults no matter what step counts the caller brings. (The search
    space only emits 16/64/256; this guards hand-edited entries.)"""
    return (
        isinstance(v, int) and not isinstance(v, bool)
        and v >= 4 and (v & (v - 1)) == 0
    )


def plan_vmem_loop(shape, dtype, n_steps, chunk=None, body_form=None,
                   pad_pow2=None, config=None,
                   warn_on_cap=False) -> KernelChoice:
    """The VMEM-resident loop's trace-time decisions as a pure function
    of its inputs — the planning half of fused_multi_step, split out so
    callers (bench.py's ladder labels, the autotuner's program keys) can
    know what a given config WILL do without running it, and so
    `config="auto"` resolution has one seam.

    `config`: None/"default" keeps the passed/None-default knobs;
    "auto" consults the tuning cache (tuning/resolve.py, op
    "diffusion.vmem_loop") for any knob the caller left None, falling
    back to the module-constant hardware defaults on a miss. Resolved
    values end up in the returned record — explicit data, never mutated
    module state (GL02)."""
    shape = tuple(int(d) for d in shape)
    if config == "auto":
        from rocm_mpi_tpu.tuning import resolve as tuning_resolve

        tuned = tuning_resolve.resolve("diffusion.vmem_loop", shape, dtype)
        if tuned:
            if chunk is None and adoptable_vmem_chunk(tuned.get("chunk")):
                # A tuned chunk is a PREFERENCE the divisibility
                # contract still governs: gcd against a static n_steps
                # (mirroring the default policy); with a traced n the
                # caller's guarantee covers only the default chunk, so
                # auto stays hands-off there.
                if isinstance(n_steps, int):
                    chunk = math.gcd(n_steps, tuned["chunk"]) or None
            if body_form is None:
                body_form = tuned.get("body_form")
            if pad_pow2 is None:
                pad_pow2 = tuned.get("pad_pow2")
    elif config not in (None, "default"):
        raise ValueError(
            f"config must be None, 'default' or 'auto', got {config!r}"
        )
    if body_form is None:
        body_form = EQC_BODY_FORM
    if body_form not in ("eqc", "conly"):
        raise ValueError(
            f"body_form must be 'eqc' or 'conly', got {body_form!r}"
        )
    if pad_pow2 is None:
        pad_pow2 = VMEM_PAD_POW2
    nbytes = math.prod(shape) * _compute_itemsize(dtype)
    pad_applied: bool | None = None
    padded_shape = None
    if pad_pow2:
        padded = tuple(_next_pow2(d) for d in shape)
        pad_bytes = math.prod(padded) * _compute_itemsize(dtype)
        if padded == shape:
            pad_applied = None  # already pow2: nothing requested to do
        elif pad_bytes <= _VMEM_BLOCK_BUDGET_BYTES:
            pad_applied = True
            padded_shape = padded
            nbytes = pad_bytes  # the unroll cap must see the padded size
        else:
            pad_applied = False
    eff_chunk = resolve_step_chunk(n_steps, chunk, nbytes, warn_on_cap)
    return KernelChoice(
        op="diffusion.vmem_loop", dispatch="vmem-loop", chunk=eff_chunk,
        body_form=body_form, pad_requested=bool(pad_pow2),
        pad_applied=pad_applied, padded_shape=padded_shape,
    )


def _multi_step_kernel(T_ref, Cm_ref, out_ref, *, inv_d2, chunk,
                       body_form=None):
    """`chunk` steps of T += Cm · ∇²T, fully VMEM-resident.

    Tuned for the latency-bound small-field regime (the 252²/chip benchmark
    geometry): neighbors come from `jnp.roll` (single vreg lane/sublane
    rotate — measured ~2.5× faster on-chip than the pad+shifted-slice
    formulation, whose unaligned lane slices Mosaic lowers to multi-op
    shuffles), the Dirichlet boundary is enforced by `Cm` being zero outside
    the interior (so roll's wraparound neighbors are multiplied by exactly
    0.0 and edge cells stay fixed — bitwise identical to the masked-update
    formulation), and the step loop is fully unrolled (a non-unrolled
    in-kernel fori_loop costs ~2.5× in scalar-core loop overhead).

    For chunk ≥ 4 on small fields the update is algebraically refactored to
    T' = A∘T + Σ_ax c_ax∘(roll(T,-1,ax)+roll(T,+1,ax)) with A = 1−2Σc_ax
    and c_ax = Cm·inv_d2[ax] hoisted into a once-per-launch prologue —
    one fewer VPU op per axis per step, measured 8 % faster at 252² f32
    (425→390 ns/step, docs/perstep_bounds_r3.txt protocol). When the
    spacing is equal on every axis (true of the benchmark geometry) the
    per-axis coefficients collapse to ONE array c = Cm·inv with
    A = 1−2·ndim·c and the roll pairs sum before the single multiply —
    one fewer VPU multiply per step again (within-run A/B:
    scripts/bench_kernel_forms.py). The Dirichlet hold stays exact in both
    forms: Cm==0 ⇒ c==0, A==1.0 ⇒ T'==T bitwise. Short chunks keep the
    direct form (the prologue would not amortize), and so do fields beyond
    _AC_FORM_MAX_BYTES: the prologue keeps up to ndim+1 extra field-sized
    arrays live across the unrolled loop, which near the 2 MB admission
    budget would blow the VMEM footprint the old form was validated under.
    """
    ndim = len(T_ref.shape)
    # bf16 is storage-only: budget the prologue at the f32 compute width.
    nbytes = _compute_itemsize(T_ref.dtype)
    for d in T_ref.shape:
        nbytes *= d
    T_in, Cm = _upcast_for_compute(T_ref[:], Cm_ref[:])

    if chunk >= 4 and nbytes <= _AC_FORM_MAX_BYTES:
        if all(inv == inv_d2[0] for inv in inv_d2):
            # Equal-spacing specialization: the per-axis coefficients
            # collapse to ONE array, c = Cm·inv, A = 1 − 2·ndim·c, and the
            # roll pairs sum BEFORE the multiply —
            # T' = A∘T + c∘Σ_ax(roll pair): one fewer VPU multiply per
            # step than the general A/c form. Same Dirichlet argument:
            # Cm==0 ⇒ c==0, A==1 ⇒ T'==T bitwise.
            c = Cm * inv_d2[0]
            # Two algebraically-identical final expressions over ONE
            # shared neighbor sum; the branch resolves at trace time.
            # "conly" (T' = T + c∘(s − 2·ndim·T)) reads one fewer VMEM
            # operand stream per step than "eqc" (no A array; 2·ndim is a
            # scalar) at the same VPU op count; the Dirichlet hold is
            # exact either way (c==0 ⇒ T'==T bitwise). Whether the saved
            # stream matters is the pending chip A/B's question
            # (scripts/bench_kernel_forms.py); CPU equivalence of both
            # forms is pinned in tests/test_pallas_kernels.py.
            if body_form is None:
                body_form = EQC_BODY_FORM
            if body_form not in ("eqc", "conly"):
                raise ValueError(
                    f"body_form must be 'eqc' or 'conly', got "
                    f"{body_form!r}"
                )
            conly = body_form == "conly"
            coef = (
                jnp.asarray(2.0 * ndim, c.dtype)
                if conly
                else 1.0 - (2.0 * ndim) * c
            )

            def body(_, T):
                s = None
                for ax in range(ndim):
                    r = jnp.roll(T, -1, ax) + jnp.roll(T, 1, ax)
                    s = r if s is None else s + r
                if conly:
                    return T + c * (s - coef * T)
                return coef * T + c * s

        else:
            cs = [Cm * inv for inv in inv_d2]
            A = 1.0 - 2.0 * functools.reduce(lambda a, b: a + b, cs)

            def body(_, T):
                acc = A * T
                for ax in range(ndim):
                    acc = acc + cs[ax] * (
                        jnp.roll(T, -1, ax) + jnp.roll(T, 1, ax)
                    )
                return acc

    else:

        def body(_, T):
            lap = None
            for ax in range(ndim):
                term = (
                    jnp.roll(T, -1, ax) + jnp.roll(T, 1, ax) - 2.0 * T
                ) * inv_d2[ax]
                lap = term if lap is None else lap + term
            return T + Cm * lap

    out_ref[:] = lax.fori_loop(0, chunk, body, T_in, unroll=True).astype(
        out_ref.dtype
    )


DEFAULT_STEP_CHUNK = 256
# The A/c refactoring of _multi_step_kernel (see its docstring) holds
# ndim+1 extra field-sized coefficient arrays VMEM-resident; allow it only
# well below the whole-block admission budget (validated at the 252²-class).
_AC_FORM_MAX_BYTES = 512 * 1024


def resolve_step_chunk(n_steps, chunk, nbytes, warn_on_cap=True):
    """The one chunk policy of the VMEM-resident multi-step kernels
    (fused_multi_step and ops.wave_kernels.wave_multi_step): default
    gcd(n_steps, DEFAULT_STEP_CHUNK) for static step counts; an explicit
    chunk must divide a static n_steps; and fields beyond the 256 KB
    unroll-friendly class cap the chunk at gcd(chunk, 16) — Mosaic compile
    time grows superlinearly in unrolled-steps × field size (252² compiles
    chunk=256 in tens of seconds; 512² at chunk=64 exceeded 9 minutes,
    measured) — warning when that degrades an explicitly requested chunk.
    """
    n_static = isinstance(n_steps, int)
    explicit = chunk is not None
    if chunk is None:
        chunk = (
            math.gcd(n_steps, DEFAULT_STEP_CHUNK)
            if n_static
            else DEFAULT_STEP_CHUNK
        )
    if n_static and n_steps % chunk != 0:
        raise ValueError(f"chunk {chunk} must divide n_steps {n_steps}")
    if nbytes > 256 * 1024:
        capped = math.gcd(chunk, 16) or 1
        if explicit and warn_on_cap and capped != chunk:
            import warnings

            warnings.warn(
                f"chunk degraded: {chunk} requested but the {nbytes}-byte "
                f"field exceeds the 256 KB unroll-friendly class; running "
                f"chunk={capped} (longer unrolls stall the Mosaic compiler).",
                stacklevel=3,
            )
        chunk = capped
    return chunk


def fused_multi_step(T, Cp, lam, dt, spacing, n_steps, chunk=None, interpret=None,
                     warn_on_cap=True, body_form=None, pad_pow2=None,
                     config=None):
    """Advance a *single-shard* field `n_steps` barely leaving VMEM.

    `body_form` ('eqc'/'conly') and `pad_pow2` are explicit TRACE-TIME
    switches for the kernel-form A/B (bench.py's stage-2.5 ladder passes
    them per rung); None defaults to the module constants EQC_BODY_FORM /
    VMEM_PAD_POW2 — the measured hardware defaults. Explicit kwargs, not
    global mutation: a cached/reused jitted advance would silently ignore
    a mutated module global, but a changed kwarg changes the trace
    (ADVICE r5 #1). `config="auto"` fills any knob left None from the
    persistent tuning cache instead (plan_vmem_loop → tuning/resolve.py;
    a cache miss keeps the hand-picked defaults, bitwise-identically).

    TPU-only optimization (no reference analog — the GPU version must round-
    trip HBM every step): the kernel runs `chunk` steps per invocation with
    the field VMEM-resident, and an outer XLA loop repeats it — one HBM
    round-trip every `chunk` steps instead of 3 whole-array passes per step.
    `chunk` is static (Mosaic compile time scales with it; a dynamic
    in-kernel trip count stalls the compiler) and must divide `n_steps`;
    default gcd(n_steps, 256), and on fields larger than the 252²-class
    (256 KB) the effective chunk is capped at gcd(chunk, 16) — larger
    unrolls over that many vregs stall the Mosaic compiler for minutes.
    The outer trip count is dynamic, so one compiled program serves every
    `n_steps` with the same chunk. Global
    boundary = block boundary (Dirichlet).

    bf16 fields are storage-only (r4): the kernel computes the whole
    chunk in f32 and rounds back once per chunk, so bf16 keeps its
    traffic savings without per-step quantization drift
    (_upcast_for_compute; error curve in BASELINE.md). Admission and
    chunk policy therefore budget at f32 width.
    """
    if interpret is None:
        interpret = _interpret_default()
    if not _supports_compiled(T.dtype) and not interpret:
        raise TypeError(f"Mosaic does not support {T.dtype}")
    nbytes = _compute_nbytes(T)
    if nbytes > _VMEM_BLOCK_BUDGET_BYTES:
        raise ValueError(
            f"field of {nbytes} bytes (f32 compute width) exceeds the "
            f"VMEM-resident budget ({_VMEM_BLOCK_BUDGET_BYTES}); use the "
            "per-step path"
        )
    lam, dt = float(lam), float(dt)
    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)
    # Masked update coefficient, computed ONCE per advance call (not per
    # step) — for the single-shard use the block edge IS the global
    # boundary (the reference's interior-only guard, perf.jl:7).
    Cm = _edge_masked_cm(T, Cp, lam, dt)
    orig_shape = T.shape
    choice = plan_vmem_loop(
        T.shape, T.dtype, n_steps, chunk=chunk, body_form=body_form,
        pad_pow2=pad_pow2, config=config, warn_on_cap=warn_on_cap,
    )
    global _LAST_CHOICE
    _LAST_CHOICE = choice  # deprecation shim only (last_pad_applied)
    if choice.pad_applied:
        widths = tuple(
            (0, p - d) for p, d in zip(choice.padded_shape, T.shape)
        )
        T = jnp.pad(T, widths)  # pad values are frozen (Cm pads to 0)
        Cm = jnp.pad(Cm, widths)
    elif choice.pad_applied is False:
        # Requested but skipped: without a loud record, a bench row at
        # a larger geometry would carry a 'pad256' label for a program
        # that actually ran unpadded (ADVICE r5 #4).
        import warnings

        warnings.warn(
            f"pad_pow2 requested but SKIPPED: the padded field would "
            f"exceed the VMEM budget ({_VMEM_BLOCK_BUDGET_BYTES}); the "
            "program runs unpadded — do not label this measurement 'pad'",
            stacklevel=2,
        )
    kernel = functools.partial(_multi_step_kernel, inv_d2=inv_d2,
                               chunk=choice.chunk,
                               body_form=choice.body_form)
    run_chunk = pl.pallas_call(
        kernel,
        out_shape=_out_struct(T.shape, T),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )
    # For traced n_steps divisibility can't be checked at trace time: the
    # trip count floors, so a non-multiple silently rounds DOWN to the
    # nearest chunk — callers with dynamic n must guarantee divisibility
    # (run_vmem_resident does, via gcd).
    out = lax.fori_loop(
        0, n_steps // choice.chunk, lambda _, x: run_chunk(x, Cm), T
    )
    if out.shape != orig_shape:
        out = out[tuple(slice(0, d) for d in orig_shape)]
    return out


def multi_step_cm(T, Cm, spacing, n_steps: int, interpret=None):
    """`n_steps` unrolled roll-based steps on a VMEM-resident block with a
    caller-supplied masked update coefficient `Cm` (same contract as the
    coefficient `fused_multi_step` builds internally: dt·λ/Cp where the
    cell updates, exactly 0.0 where it is held fixed).

    This is the local compute of the deep-halo sweep
    (parallel.deep_halo): the caller pads the block and zeroes `Cm` on
    ghost/Dirichlet cells; `n_steps` must not exceed the ghost width.
    """
    if interpret is None:
        interpret = _interpret_default()
    if not _supports_compiled(T.dtype) and not interpret:
        raise TypeError(f"Mosaic does not support {T.dtype}")
    if T.shape != Cm.shape:
        raise ValueError(f"shape mismatch: T {T.shape} vs Cm {Cm.shape}")
    nbytes = _compute_nbytes(T)
    if nbytes > _VMEM_BLOCK_BUDGET_BYTES:
        raise ValueError(
            f"padded block of {nbytes} bytes (f32 compute width) exceeds "
            f"the VMEM-resident budget ({_VMEM_BLOCK_BUDGET_BYTES}); for "
            "HBM-resident blocks use multi_step_cm_hbm (the deep-halo "
            "sweep routes there automatically) or the per-step variants"
        )
    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)
    kernel = functools.partial(
        _multi_step_kernel, inv_d2=inv_d2, chunk=int(n_steps)
    )
    return pl.pallas_call(
        kernel,
        out_shape=_out_struct(T.shape, T),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(T, Cm)


# ---------------------------------------------------------------------------
# Temporal blocking for HBM-resident fields: k steps per memory sweep.
# ---------------------------------------------------------------------------


def edge_mask(shape):
    """Boolean mask: True on the global Dirichlet edge of an unsharded
    block (every axis's first/last cell). The one edge-detection used by
    both mask-as-data contracts (diffusion's edge_masked_cm, the wave
    workload's interior_mask)."""
    mask = None
    for ax in range(len(shape)):
        idx = lax.broadcasted_iota(jnp.int32, shape, ax)
        m = (idx == 0) | (idx == shape[ax] - 1)
        mask = m if mask is None else (mask | m)
    return mask


def edge_masked_cm(T, Cp, lam, dt):
    """(dt·λ)/Cp on the interior, exactly 0.0 on the global Dirichlet edge.

    The masked update coefficient of the Cm-contract kernels
    (fused_step_cm / masked_step / multi_step_cm): cells with Cm == 0.0
    stay bit-identically fixed (old + 0.0·lap == old), carrying the
    reference's interior-only guard (perf.jl:7) as data. Unsharded form —
    the block edge IS the global boundary; the sharded form masks via
    parallel.halo.global_boundary_mask instead.
    """
    return jnp.where(edge_mask(T.shape), jnp.zeros_like(Cp), (dt * lam) / Cp)


_edge_masked_cm = edge_masked_cm  # internal alias (pre-r3 name)


def _tb_kernel(Tu_ref, Tc_ref, Td_ref, Cu_ref, Cc_ref, Cd_ref, o_ref, *,
               inv_d2, k, g, tm):
    """Advance one axis-0 stripe by `k` steps from a (g+tm+g)-row slab.

    Stripe i's output rows [i·tm, (i+1)·tm) after k steps depend on input
    rows [i·tm−k, (i+1)·tm+k); with k ≤ g the slab of the core stripe plus
    one g-row ghost block per side covers that light cone. Ghost rows feed
    transient values whose own errors (from the slab edge's roll wraparound)
    propagate one row per step and never reach the core in k ≤ g steps.
    At the domain's first/last stripe the clamped ghost blocks are replaced
    by zeros — the same zero-ghost convention as the VMEM-resident kernel
    (those values only ever multiply into cells the zero `Cm` edge ring
    keeps Dirichlet-fixed).
    """
    i = pl.program_id(0)
    n_i = pl.num_programs(0)
    zg = jnp.zeros_like(Tu_ref[:])
    T = jnp.concatenate(
        [jnp.where(i == 0, zg, Tu_ref[:]), Tc_ref[:],
         jnp.where(i == n_i - 1, zg, Td_ref[:])], 0)
    Cm = jnp.concatenate(
        [jnp.where(i == 0, zg, Cu_ref[:]), Cc_ref[:],
         jnp.where(i == n_i - 1, zg, Cd_ref[:])], 0)
    T, Cm = _upcast_for_compute(T, Cm)  # bf16 storage, f32 sweep arithmetic
    ndim = T.ndim
    for _ in range(k):
        lap = None
        for ax in range(ndim):
            term = (
                jnp.roll(T, -1, ax) + jnp.roll(T, 1, ax) - 2.0 * T
            ) * inv_d2[ax]
            lap = term if lap is None else lap + term
        T = T + Cm * lap
    o_ref[:] = T[g:g + tm].astype(o_ref.dtype)


def _stripe_ghost_specs(tm, g, n0, rest):
    """(core, gup, gdn) BlockSpecs shared by the ghost-block stripe
    pipelines (_tb_kernel and _per_step_kernel): core stripe i (tm rows)
    plus the clamped g-row ghost blocks above/below it. The domain-edge
    clamps re-read an interior block; the kernels zero those via the
    i==0 / i==n-1 selects."""
    r = tm // g
    zeros = (0,) * len(rest)
    core = pl.BlockSpec(
        (tm,) + rest, lambda i: (i,) + zeros, memory_space=pltpu.VMEM
    )
    gup = pl.BlockSpec(
        (g,) + rest,
        lambda i: (jnp.maximum(i * r - 1, 0),) + zeros,
        memory_space=pltpu.VMEM,
    )
    gdn = pl.BlockSpec(
        (g,) + rest,
        lambda i: (jnp.minimum((i + 1) * r, n0 // g - 1),) + zeros,
        memory_space=pltpu.VMEM,
    )
    return core, gup, gdn


DEFAULT_TB_STEPS = 8  # HBM temporal blocking: bounded by the ghost rows
# Deep-halo sweep depth: single-chip optimum at 252² re-measured with the
# A/c kernel form (r3: k=8 1.02 µs, k=16 0.889, k=32 0.848 — the prologue
# amortizes further with depth); on a pod slice larger k also divides the
# message count. HBM-resident shards cap at DEFAULT_TB_STEPS regardless.
DEFAULT_DEEP_STEPS = 32
_TB_G = 8  # tb-sweep ghost-block rows (the TPU sublane tile) = max k/sweep
_TB_TM = 16  # stripe height; with _TB_G ghosts, tuned to the VMEM limit
assert _TB_TM % _TB_G == 0  # _stripe_ghost_specs' index maps require it
_TB_MAX_STEPS = 16  # deepest supported sweep (the (g=16, tm=32) geometry)


def tb_geometry(k: int) -> tuple[int, int]:
    """(ghost rows g, stripe height tm) for a k-step temporal-blocked
    sweep. k <= 8 keeps the chip-validated production geometry (8, 16);
    deeper sweeps (k <= 16) use (16, 32) — half the HBM passes per step
    (5 per 16 steps vs 5 per 8), at a (tm+2g)=64-row slab whose Mosaic
    compile envelope at very wide rows is measured by
    scripts/bench_tb_stripes.py's (32,16,16) case before any default
    changes. Both satisfy tm % g == 0 (_stripe_ghost_specs) and k <= g
    (the light-cone bound of _tb_kernel)."""
    if 1 <= k <= _TB_G:
        return _TB_G, _TB_TM
    if _TB_G < k <= _TB_MAX_STEPS:
        return 16, 32
    raise ValueError(
        f"temporal-blocked sweeps support 1 <= k <= {_TB_MAX_STEPS}, "
        f"got {k}"
    )


def tb_slab_fits(k: int, shape, dtype) -> bool:
    """True when a k-deep sweep's in-kernel slab — (tm+2g) rows at the f32
    compute width — fits the measured Mosaic compile envelope
    (_PS_SLAB_BUDGET_BYTES). The deep (16, 32) geometry's 64-row slab
    exceeds it for f32 rows wider than ~9.7k columns (the flagship 12288²
    included), so callers must gate on this instead of crashing the
    compile: fused_multi_step_hbm/multi_step_cm_hbm raise with a clear
    message, and the deep-halo routing falls back to the jnp path."""
    g, tm = tb_geometry(k)
    row = _compute_itemsize(dtype)
    for n in shape[1:]:
        row *= n
    return (tm + 2 * g) * row <= _PS_SLAB_BUDGET_BYTES


def hbm_class_edge(itemsize: int = 4, k: int = DEFAULT_TB_STEPS) -> int:
    """Smallest square-shard edge whose k-padded block exceeds the
    VMEM-resident budget — i.e. the smallest shard a k-deep sweep routes
    to the temporal-blocked HBM kernel (multi_step_cm_hbm) instead of the
    VMEM loop. The ONE sizing used by the routing-coverage checks
    (__graft_entry__ dryrun, tests/test_overlap.py), so a budget or
    geometry retune cannot leave them asserting a stale routing claim:
    the edge iterates in tb_geometry(k) stripe-height units, which (with
    2k divisible by that tm for the supported depths) keeps the k-padded
    row count stripe-divisible by construction.
    """
    g, tm = tb_geometry(k)
    if (2 * k) % tm != 0:
        raise ValueError(
            f"hbm_class_edge needs 2k divisible by the stripe height "
            f"(k={k}, tm={tm}) so the padded row count stays "
            "stripe-divisible; pass k=8 or k=16"
        )
    n = tm  # n % tm == 0 and 2k % tm == 0 ⇒ (n + 2k) % tm == 0
    while (n + 2 * k) ** 2 * itemsize <= _VMEM_BLOCK_BUDGET_BYTES:
        n += tm
    return n


def fused_multi_step_hbm(T, Cp, lam, dt, spacing, n_steps, block_steps=None,
                         interpret=None):
    """Advance a *single-shard* HBM-resident field `n_steps` via temporal
    blocking: each memory sweep advances the whole field `block_steps`
    steps. Per sweep, each stripe loads tm+2g rows per tm output rows —
    with the (g, tm) geometry picked per depth by tb_geometry: k <= 8 at
    (8, 16) is 2 reads of T, 2 of Cm, 1 write = 5 whole-array passes per
    k steps (~0.6 passes/step at k=8); k <= 16 at (16, 32) is the same 5
    passes per 16 steps (~0.3/step) — instead of the 3 passes *per step*
    the per-step path (and the reference's fused GPU kernel,
    perf.jl:3-13) pays by construction. The TPU grid executes
    stripes sequentially, so sweep s+1 only starts after sweep s wrote its
    stripes; correctness needs no inter-stripe synchronization beyond the
    light-cone ghost blocks (see _tb_kernel). bf16 fields are
    storage-only (r4): slabs upcast to f32 in-kernel and round back once
    per sweep — bf16 HBM traffic, f32 sweep arithmetic.

    Requires n_steps % block_steps == 0 (static check when n_steps is a
    Python int; for traced n_steps the trip count floors), axis-0 length
    divisible by the depth's stripe height (tb_geometry: 16 for k <= 8,
    32 beyond), and — for the deeper geometry — rows narrow enough for
    the slab to fit the Mosaic compile envelope (tb_slab_fits). Measured on one v5e chip at 12288²
    f32: ~2 ms/step — effective T_eff ~900 GB/s, above the chip's raw HBM
    bandwidth, which a 3-passes-per-step design can never reach (current
    measured numbers: BASELINE.md's results table).
    """
    if interpret is None:
        interpret = _interpret_default()
    if not _supports_compiled(T.dtype) and not interpret:
        raise TypeError(f"Mosaic does not support {T.dtype}")
    k = DEFAULT_TB_STEPS if block_steps is None else block_steps
    if not 1 <= k <= _TB_MAX_STEPS:
        raise ValueError(
            f"block_steps must be in [1, {_TB_MAX_STEPS}], got {k}"
        )
    g, tm = tb_geometry(k)  # ghost rows (>= k) and stripe height
    if not tb_slab_fits(k, T.shape, T.dtype):
        raise ValueError(
            f"a k={k} sweep's (tm+2g)={tm + 2 * g}-row slab exceeds the "
            f"Mosaic compile envelope ({_PS_SLAB_BUDGET_BYTES} B at f32 "
            "compute width) for rows this wide; use k <= "
            f"{_TB_G} or a narrower field"
        )
    n0 = T.shape[0]
    # n0 % tm == 0 with tm a multiple of g also gives the ghost-block
    # alignment the stripe specs need.
    if n0 % tm != 0 or (n0 // tm) < 2:
        raise ValueError(
            f"axis-0 length {n0} must be a multiple of {tm} (>= 2 stripes)"
        )
    if isinstance(n_steps, int) and n_steps % k != 0:
        raise ValueError(f"n_steps {n_steps} must be a multiple of {k}")
    lam, dt = float(lam), float(dt)
    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)
    Cm = _edge_masked_cm(T, Cp, lam, dt)
    sweep = _make_tb_sweep(T, inv_d2, k, g, tm, interpret)
    return lax.fori_loop(0, n_steps // k, lambda _, x: sweep(x, Cm), T)


def _make_tb_sweep(T, inv_d2, k, g, tm, interpret):
    """Build sweep(T, Cm) -> T advanced k steps, one temporal-blocked
    memory pass (the pallas_call shared by fused_multi_step_hbm and
    multi_step_cm_hbm). Caller guarantees the shape constraints."""
    core, gup, gdn = _stripe_ghost_specs(tm, g, T.shape[0], T.shape[1:])
    kernel = functools.partial(_tb_kernel, inv_d2=inv_d2, k=k, g=g, tm=tm)
    call = pl.pallas_call(
        kernel,
        out_shape=_out_struct(T.shape, T),
        grid=(T.shape[0] // tm,),
        in_specs=[gup, core, gdn, gup, core, gdn],
        out_specs=core,
        interpret=interpret,
    )
    return lambda T, Cm: call(T, T, T, Cm, Cm, Cm)


def multi_step_cm_hbm(T, Cm, spacing, n_steps: int, interpret=None):
    """One temporal-blocked sweep of `n_steps` steps on an *HBM-resident*
    block with a caller-supplied masked coefficient — the large-shard form
    of multi_step_cm (same contract: Cm is dt·λ/Cp where the cell updates,
    exactly 0.0 where held; the caller crops sweep-edge staleness).

    This is the local compute of deep-halo sweeps on shards too big for
    VMEM (parallel.deep_halo): the k-wide exchanged ghost ring bounds the
    block-edge staleness exactly as the VMEM kernel's roll wraparound
    does, and the in-sweep stripe ghosts (g rows) bound the stripe-level
    staleness, so `n_steps` ≤ g and ≤ ghost width keeps the crop exact.
    Requires axis-0 length divisible by the depth's stripe height
    (tb_geometry) and, for the deeper geometry, rows that fit the Mosaic
    compile envelope (tb_slab_fits — the deep-halo router pre-checks and
    falls back to the jnp path instead of tripping this).
    """
    if interpret is None:
        interpret = _interpret_default()
    if not _supports_compiled(T.dtype) and not interpret:
        raise TypeError(f"Mosaic does not support {T.dtype}")
    if T.shape != Cm.shape:
        raise ValueError(f"shape mismatch: T {T.shape} vs Cm {Cm.shape}")
    if not 1 <= n_steps <= _TB_MAX_STEPS:
        raise ValueError(
            f"n_steps must be in [1, {_TB_MAX_STEPS}] per HBM sweep, got "
            f"{n_steps} (the g-row stripe ghosts bound the in-sweep "
            "light cone)"
        )
    g, tm = tb_geometry(int(n_steps))
    if not tb_slab_fits(int(n_steps), T.shape, T.dtype):
        raise ValueError(
            f"a k={n_steps} sweep's (tm+2g)={tm + 2 * g}-row slab exceeds "
            f"the Mosaic compile envelope for rows this wide; use k <= "
            f"{_TB_G} or a narrower block (the deep-halo router falls "
            "back to the jnp path automatically)"
        )
    n0 = T.shape[0]
    if n0 % tm != 0 or (n0 // tm) < 2:
        raise ValueError(
            f"axis-0 length {n0} must be a multiple of {tm} (>= 2 stripes)"
        )
    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)
    return _make_tb_sweep(T, inv_d2, int(n_steps), g, tm, interpret)(T, Cm)


# ---------------------------------------------------------------------------
# Unsharded per-step sweep: one kernel per step for HBM-resident fields —
# the reference-parity rung (one whole-field pass per step, perf.jl:47-52)
# without the pad/divide/where op chain around it.
# ---------------------------------------------------------------------------


def _per_step_kernel(Tu_ref, Tc_ref, Td_ref, Cm_ref, o_ref, *, inv_d2, g, tm):
    """Advance one axis-0 stripe by ONE step from a (g+tm+g)-row slab.

    The k=1 specialization of the temporal-blocking structure (_tb_kernel):
    because only the immediately adjacent row feeds a 1-step update, the
    coefficient needs no ghost blocks — Cm is read core-only, cutting a
    whole array pass per step versus the k-step slab. Domain-edge ghost
    blocks are zeroed; their values only multiply into cells the zero-Cm
    edge ring holds fixed. Requires the row count divisible by the stripe
    height: a partial trailing stripe would feed Pallas-masked (undefined)
    rows into the last valid row's neighborhood, where NaN·0.0 could leak
    through the Cm guard — masked_step falls back to the padded-contract
    kernel for such shapes.
    """
    i = pl.program_id(0)
    n_i = pl.num_programs(0)
    zg = jnp.zeros_like(Tu_ref[:])
    T = jnp.concatenate(
        [jnp.where(i == 0, zg, Tu_ref[:]), Tc_ref[:],
         jnp.where(i == n_i - 1, zg, Td_ref[:])], 0)
    T, Tc, Cm = _upcast_for_compute(T, Tc_ref[:], Cm_ref[:])
    lap = None
    for ax in range(T.ndim):
        term = (
            jnp.roll(T, -1, ax) + jnp.roll(T, 1, ax) - 2.0 * T
        ) * inv_d2[ax]
        lap = term if lap is None else lap + term
    o_ref[:] = (Tc + Cm * lap[g:g + tm]).astype(o_ref.dtype)


def _masked_step_striped(T, Cm, inv_d2, interpret, tm, g):
    n0, rest = T.shape[0], T.shape[1:]
    core, gup, gdn = _stripe_ghost_specs(tm, g, n0, rest)
    kernel = functools.partial(_per_step_kernel, inv_d2=inv_d2, g=g, tm=tm)
    return pl.pallas_call(
        kernel,
        out_shape=_out_struct(T.shape, T),
        grid=(n0 // tm,),
        in_specs=[gup, core, gdn, core],
        out_specs=core,
        interpret=interpret,
    )(T, T, T, Cm)


def masked_step(T, Cm, spacing, interpret=None, tm=None, config=None):
    """Unsharded per-step update with the mask folded into `Cm`: one Pallas
    program per step.

    The reference-parity per-step schedule (one whole-field sweep per step,
    perf.jl:47-52) for a single-device grid: `Cm` (edge_masked_cm) carries
    both (dt·λ)/Cp and the Dirichlet guard, computed once per run — so each
    step is exactly one kernel, with no ghost-pad copy, no per-step divide,
    and no where-mask pass. Dispatch: VMEM-resident roll kernel
    (multi_step_cm, n=1) for fields that fit; the ghost-block striped sweep
    for HBM-resident fields with stripe-divisible rows; zero-ghost pad +
    the padded-contract striped kernel for everything else.

    `tm` overrides the stripe height (tuning knob — the threads=(32,8)
    analog); must be a multiple of 8. `config="auto"` consults the tuning
    cache (op "diffusion.masked_step") for a tm the caller left unset; a
    cached tm that no longer satisfies this shape's stripe constraints is
    ignored silently (the automatic height picks instead) — an auto
    resolve must never be louder than the default path.
    """
    if T.shape != Cm.shape:
        raise ValueError(f"shape mismatch: T {T.shape} vs Cm {Cm.shape}")
    if interpret is None:
        interpret = _interpret_default()
    if not _supports_compiled(T.dtype) and not interpret:
        raise TypeError(f"Mosaic does not support {T.dtype}")
    nbytes = _compute_nbytes(T)
    if nbytes <= _VMEM_BLOCK_BUDGET_BYTES:
        return multi_step_cm(T, Cm, spacing, 1, interpret=interpret)
    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)
    g = 8
    n0 = T.shape[0]
    tm_explicit = tm is not None
    if config == "auto" and tm is None:
        from rocm_mpi_tpu.tuning import resolve as tuning_resolve

        tuned = tuning_resolve.resolve(
            "diffusion.masked_step", T.shape, T.dtype
        )
        if tuned and tuned.get("tm"):
            cand = int(tuned["tm"])
            slab_unit = (
                math.prod(T.shape[1:]) * _compute_itemsize(T.dtype)
            )
            if (
                cand % g == 0
                and n0 % cand == 0
                and (cand + 2 * g) * slab_unit <= _PS_SLAB_BUDGET_BYTES
            ):
                tm = cand
    elif config not in (None, "default", "auto"):
        raise ValueError(
            f"config must be None, 'default' or 'auto', got {config!r}"
        )
    if tm is None:
        row_bytes = T.dtype.itemsize
        for n in T.shape[1:]:
            row_bytes *= n
        base = _stripe_height(row_bytes)
        # Taller stripes amortize the per-stripe DMA overhead (measured on
        # v5e at 12288² f32: tm=32 ≈ 254 GB/s T_eff vs tm=16 ≈ 241): take
        # the tallest multiple of g up to 2× the budget height that divides
        # the row count AND whose in-kernel slab (tm+2g rows, concatenated
        # + ~3 lap temporaries, computed at ≥f32 width even for bf16
        # inputs) stays under the measured Mosaic compile boundary
        # (~2.4 MB f32-equivalent slab: f32 12288²/tm=48, 8192²/tm=64 and
        # bf16 12288²/tm=64 all fail to compile beyond it).
        # No candidate fitting → None → the pad fallback (very wide rows,
        # where even the base slab would blow the compile boundary).
        slab_unit = (row_bytes // T.dtype.itemsize) * max(
            T.dtype.itemsize, 4
        )
        tm = next(
            (
                c
                for c in range(2 * base, g - 1, -g)
                if n0 % c == 0
                and (c + 2 * g) * slab_unit <= _PS_SLAB_BUDGET_BYTES
            ),
            None,
        )
    strip_ok = (
        tm is not None
        and T.ndim in (2, 3)
        and tm % g == 0
        and n0 % tm == 0
        and n0 % g == 0
    )
    if strip_ok:
        return _masked_step_striped(T, Cm, inv_d2, interpret, tm, g)
    if tm_explicit:
        import warnings

        warnings.warn(
            f"masked_step tm={tm} ignored: the striped path needs a 2D/3D "
            f"field with tm and the row count ({n0}) divisible by {g} and "
            "n0 % tm == 0; running the pad + padded-contract fallback "
            "instead.",
            stacklevel=2,
        )
    # General-shape fallback: zero ghost ring + the padded-contract striped
    # kernel (edge Cm = 0.0 makes the ghost values irrelevant).
    Tp = jnp.pad(T, [(1, 1)] * T.ndim)
    return fused_step_cm(Tp, Cm, spacing, interpret=interpret)
