"""Pure-jnp stencil slicing helpers — the array-programming vocabulary.

These are the view helpers the reference's array-programming app defines
(`d_xa/d_xi/d_ya/d_yi/inn`, /root/reference/scripts/diffusion_2D_ap.jl:3-7),
generalized to N dimensions. In JAX they are functional (return new arrays);
XLA fuses the slices into the consuming elementwise kernels, so — unlike the
Julia broadcasts, which launch one GPU kernel each — a whole update chain
compiles to a single fused device program.

Naming (reference convention):
  d_<axis>a(A): forward difference along <axis>, all other axes full.
  d_<axis>i(A): forward difference along <axis>, all other axes inner (1:-1).
  inn(A): interior of A (1:-1 on every axis).
"""

from __future__ import annotations

import jax.numpy as jnp


def _slc(ndim: int, axis: int, s: slice, other: slice) -> tuple[slice, ...]:
    return tuple(s if ax == axis else other for ax in range(ndim))


def d_a(A: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Forward difference along `axis`, full extent on other axes (d_xa/d_ya)."""
    hi = _slc(A.ndim, axis, slice(1, None), slice(None))
    lo = _slc(A.ndim, axis, slice(None, -1), slice(None))
    return A[hi] - A[lo]


def d_i(A: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Forward difference along `axis`, inner extent on other axes (d_xi/d_yi)."""
    hi = _slc(A.ndim, axis, slice(1, None), slice(1, -1))
    lo = _slc(A.ndim, axis, slice(None, -1), slice(1, -1))
    return A[hi] - A[lo]


def inn(A: jnp.ndarray) -> jnp.ndarray:
    """Interior of A: drop one boundary cell on every axis."""
    return A[tuple(slice(1, -1) for _ in range(A.ndim))]


# 2D aliases matching the reference names exactly (diffusion_2D_ap.jl:3-7).
def d_xa(A):
    return d_a(A, 0)


def d_ya(A):
    return d_a(A, 1)


def d_xi(A):
    return d_i(A, 0)


def d_yi(A):
    return d_i(A, 1)
