"""Pallas TPU kernels for the acoustic-wave workload (framework-generality
demo — no reference analog; the reference ships exactly one physics model).

The leapfrog update U⁺ = 2U − U⁻ + dt²·c²·∇²U is a 3-operand stencil: the
same padded-block contract as the diffusion kernels
(ops.pallas_kernels.fused_step_padded), with a second state array read
core-only. Note the Dirichlet guard CANNOT ride a zeroed coefficient alone
(c²==0 gives U⁺ = 2U − U⁻ ≠ U): the per-step path masks explicitly in the
caller (the diffusion 'shard' variant structure), and the VMEM-resident
multi-step kernel rewrites the update as

    U⁺ = U + M∘(U − U⁻) + Cw∘∇²U,   M = interior mask, Cw = dt²·c²·M

which holds edge cells bitwise (M==0 and Cw==0 ⇒ U⁺==U) — the wave
edition of the diffusion kernels' mask-as-data contract.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax
from rocm_mpi_tpu.utils.compat import pallas as pl
from rocm_mpi_tpu.utils.compat import pallas_tpu as pltpu

from rocm_mpi_tpu.ops.pallas_kernels import (
    _VMEM_BLOCK_BUDGET_BYTES,
    _compute_nbytes,
    _interpret_default,
    _lap_from_padded,
    _out_struct,
    _supports_compiled,
    _upcast_for_compute,
)


def wave_step_padded(Up, Uprev, C2, dt, spacing):
    """Candidate leapfrog update for every core cell of the padded block
    (pure jnp). `Up` is width-1-padded displacement; `Uprev`/`C2` are
    core-shaped. Same contract as ops.diffusion.step_fused_padded: the
    caller supplies ghosts and masks global-boundary cells. The one
    stencil definition — the Pallas kernel below computes the same
    expression in VMEM, and the VMEM-overflow fallback calls this.
    """
    inv_d2 = tuple(1.0 / (d * d) for d in spacing)
    core = tuple(slice(1, -1) for _ in range(C2.ndim))
    return 2.0 * Up[core] - Uprev + (dt * dt) * C2 * _lap_from_padded(
        Up, inv_d2
    )


def wave_step_padded_geom(Up, Uprev, C2, dt2, inv_d2):
    """`wave_step_padded` with the geometry PRECOMPUTED as operands:
    `dt2` = dt² and `inv_d2` = per-axis 1/spacing², both computed on the
    HOST in f64 then cast — the same rounding the python-float path
    above hits at its weak-typed casts. The ladder lane kernel: a
    laddered batch carries per-lane dt²/inv-spacing² so one compiled
    program serves lanes of different original shapes bitwise-equal to
    their standalone runs (ops.diffusion.step_fused_padded_geom has the
    ulp rationale)."""
    core = tuple(slice(1, -1) for _ in range(C2.ndim))
    return 2.0 * Up[core] - Uprev + dt2 * C2 * _lap_from_padded(
        Up, inv_d2
    )


def _wave_kernel_whole(Up_ref, Uprev_ref, C2_ref, out_ref, *, dt2, inv_d2):
    Up, Uprev, C2 = _upcast_for_compute(Up_ref[:], Uprev_ref[:], C2_ref[:])
    core = tuple(slice(1, -1) for _ in range(Up.ndim))
    out_ref[:] = (
        2.0 * Up[core] - Uprev + dt2 * C2 * _lap_from_padded(Up, inv_d2)
    ).astype(out_ref.dtype)


def wave_step_padded_pallas(Up, Uprev, C2, dt, spacing, interpret=None):
    """Candidate leapfrog update for every core cell of a padded block.

    `Up` is the width-1-padded displacement (ghosts from exchange_halo);
    `Uprev` and `C2` (squared wave speed) are core-shaped. Whole-block VMEM
    kernel; falls back to the IDENTICAL-semantics jnp padded form in two
    cases (ADVICE r3): blocks beyond the VMEM budget, and dtypes Mosaic
    cannot compile (f64 on a real TPU — unlike the diffusion kernels,
    which raise there; the wave workload is the layering demo, not the
    tuned flagship, so a chip benchmark of wave f64 times the jnp path).
    """
    if interpret is None:
        interpret = _interpret_default()
    nbytes = _compute_nbytes(C2)
    if (not _supports_compiled(Up.dtype) and not interpret) or (
        nbytes > _VMEM_BLOCK_BUDGET_BYTES
    ):
        return wave_step_padded(Up, Uprev, C2, dt, spacing)
    dt2 = float(dt) * float(dt)
    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)
    kernel = functools.partial(_wave_kernel_whole, dt2=dt2, inv_d2=inv_d2)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        out_shape=_out_struct(C2.shape, C2),
        in_specs=[vmem, vmem, vmem],
        out_specs=vmem,
        interpret=interpret,
    )(Up, Uprev, C2)


def wave_step_padded_masked(Up, Uprev, M, Cw, spacing):
    """Masked-contract candidate leapfrog update (pure jnp): `Up` is the
    width-1-padded displacement; `Uprev`, the interior mask `M` (1.0 on
    updating cells, exactly 0.0 on global Dirichlet cells) and the masked
    coefficient `Cw = dt²·c²·M` are core-shaped data operands prepared
    once per program (models.wave `_mask_prepare`).

    The hold is a branch-free select, M·cand + (1−M)·U: on updating cells
    (M==1) `cand = 2U − U⁻ + Cw·∇²U` is the SAME left-associated fp
    expression as `wave_step_padded`, so results are bitwise identical
    there; on held cells the result is U bitwise. No caller-side
    whole-shard `where` — the wave edition of the diffusion Cm contract
    (the leapfrog needs M itself because c²==0 alone gives 2U − U⁻ ≠ U,
    see the module docstring).
    """
    inv_d2 = tuple(1.0 / (d * d) for d in spacing)
    core = tuple(slice(1, -1) for _ in range(M.ndim))
    Uc = Up[core]
    cand = 2.0 * Uc - Uprev + Cw * _lap_from_padded(Up, inv_d2)
    return M * cand + (1.0 - M) * Uc


def _wave_kernel_whole_masked(Up_ref, Uprev_ref, M_ref, Cw_ref, out_ref, *,
                              inv_d2):
    Up, Uprev, M, Cw = _upcast_for_compute(
        Up_ref[:], Uprev_ref[:], M_ref[:], Cw_ref[:]
    )
    core = tuple(slice(1, -1) for _ in range(M.ndim))
    Uc = Up[core]
    cand = 2.0 * Uc - Uprev + Cw * _lap_from_padded(Up, inv_d2)
    out_ref[:] = (M * cand + (1.0 - M) * Uc).astype(out_ref.dtype)


def wave_step_padded_masked_pallas(Up, Uprev, M, Cw, spacing,
                                   interpret=None):
    """Pallas whole-block form of the masked-contract leapfrog update
    (the hide rung's region kernel). Falls back to the identical-semantics
    jnp form for blocks beyond the VMEM budget and for dtypes Mosaic
    cannot compile (f64 on a real chip) — the same policy as
    wave_step_padded_pallas."""
    if interpret is None:
        interpret = _interpret_default()
    nbytes = _compute_nbytes(M)
    if (not _supports_compiled(Up.dtype) and not interpret) or (
        nbytes > _VMEM_BLOCK_BUDGET_BYTES
    ):
        return wave_step_padded_masked(Up, Uprev, M, Cw, spacing)
    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)
    kernel = functools.partial(_wave_kernel_whole_masked, inv_d2=inv_d2)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        out_shape=_out_struct(M.shape, M),
        in_specs=[vmem, vmem, vmem, vmem],
        out_specs=vmem,
        interpret=interpret,
    )(Up, Uprev, M, Cw)


# ---------------------------------------------------------------------------
# Whole-loop-in-VMEM leapfrog: the wave edition of the diffusion flagship's
# fused_multi_step schedule (one HBM round-trip per `chunk` steps).
# ---------------------------------------------------------------------------


def masked_leapfrog_step(U, Uprev, M, Cw, inv_d2):
    """One roll-based masked leapfrog step (plain jnp ops): the ONE
    definition of the update used by the Pallas kernel body below and by
    the deep-halo jnp fallback (parallel.deep_halo.make_wave_deep_sweep).
    Roll wraparound only ever feeds edge cells, which M==0 / Cw==0 hold
    bitwise fixed. Returns the advanced (U, U_prev) pair.
    """
    lap = None
    for ax in range(U.ndim):
        term = (
            jnp.roll(U, -1, ax) + jnp.roll(U, 1, ax) - 2.0 * U
        ) * inv_d2[ax]
        lap = term if lap is None else lap + term
    return U + M * (U - Uprev) + Cw * lap, U


def _wave_multi_step_kernel(
    U_ref, Uprev_ref, M_ref, Cw_ref, oU_ref, oUprev_ref, *, inv_d2, chunk
):
    """`chunk` leapfrog steps with the state pair VMEM-resident (bf16
    storage upcast to f32 for the whole chunk — one rounding per chunk).

    Equal-spacing A-form (r4, the wave edition of the diffusion kernel's
    prologue-hoisted refactoring): with one shared inv = inv_d2[ax] the
    update U⁺ = U + M∘(U−U⁻) + Cw∘∇²U distributes to

        U⁺ = A∘U + c∘S − M∘U⁻,   c = Cw·inv,  A = 1 + M − 2·ndim·c,
        S  = Σ_ax (roll(U,-1,ax) + roll(U,+1,ax))

    — A and c hoisted into a once-per-launch prologue, ~3 fewer VPU ops
    per step than the direct form. The Dirichlet hold stays bitwise:
    held cells have M==0, Cw==0 ⇒ c==0, A==1 ⇒ U⁺ = U − 0·U⁻ = U.
    Short chunks keep the direct form (the prologue would not amortize);
    unequal spacing keeps it too (per-axis coefficients would need
    ndim+1 extra arrays for a smaller saving).
    """
    U0, Uprev0, M, Cw = _upcast_for_compute(
        U_ref[:], Uprev_ref[:], M_ref[:], Cw_ref[:]
    )
    if chunk >= 4 and all(inv == inv_d2[0] for inv in inv_d2):
        ndim = U0.ndim
        c = Cw * inv_d2[0]
        A = 1.0 + M - (2.0 * ndim) * c

        def body(_, s):
            U, Uprev = s
            S = None
            for ax in range(ndim):
                r = jnp.roll(U, -1, ax) + jnp.roll(U, 1, ax)
                S = r if S is None else S + r
            return A * U + c * S - M * Uprev, U

    else:

        def body(_, s):
            return masked_leapfrog_step(s[0], s[1], M, Cw, inv_d2)

    U, Uprev = lax.fori_loop(0, chunk, body, (U0, Uprev0), unroll=True)
    oU_ref[:] = U.astype(oU_ref.dtype)
    oUprev_ref[:] = Uprev.astype(oUprev_ref.dtype)


def interior_mask(shape, dtype):
    """1.0 on interior cells, exactly 0.0 on the global Dirichlet edge
    (the shared edge detection of ops.pallas_kernels.edge_mask)."""
    from rocm_mpi_tpu.ops.pallas_kernels import edge_mask

    return jnp.where(
        edge_mask(shape), jnp.zeros(shape, dtype), jnp.ones(shape, dtype)
    )


def wave_multi_step_masked(U, Uprev, M, Cw, spacing, n_steps: int,
                           interpret=None):
    """`n_steps` unrolled leapfrog steps on a VMEM-resident state pair with
    caller-supplied interior mask `M` and masked coefficient `Cw` (dt²·c²
    where the cell updates, exactly 0.0 where held) — the wave analog of
    ops.pallas_kernels.multi_step_cm, and the local compute of wave deep-
    halo sweeps (parallel.deep_halo.make_wave_deep_sweep): the caller pads
    the blocks and zeroes M/Cw on ghost/Dirichlet cells; `n_steps` must
    not exceed the ghost width. Returns the advanced (U, U_prev) pair.
    """
    if interpret is None:
        interpret = _interpret_default()
    if not _supports_compiled(U.dtype) and not interpret:
        raise TypeError(f"Mosaic does not support {U.dtype}")
    if not (U.shape == Uprev.shape == M.shape == Cw.shape):
        raise ValueError(
            f"shape mismatch: U {U.shape}, Uprev {Uprev.shape}, "
            f"M {M.shape}, Cw {Cw.shape}"
        )
    nbytes = _compute_nbytes(U)
    if nbytes > _VMEM_BLOCK_BUDGET_BYTES // 2:
        raise ValueError(
            f"block of {nbytes} bytes (f32 compute width) exceeds the "
            f"wave VMEM-resident budget ({_VMEM_BLOCK_BUDGET_BYTES // 2})"
        )
    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)
    kernel = functools.partial(
        _wave_multi_step_kernel, inv_d2=inv_d2, chunk=int(n_steps)
    )
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        out_shape=(_out_struct(U.shape, U), _out_struct(U.shape, U)),
        in_specs=[vmem, vmem, vmem, vmem],
        out_specs=(vmem, vmem),
        interpret=interpret,
    )(U, Uprev, M, Cw)


def wave_multi_step(
    U, Uprev, C2, dt, spacing, n_steps, chunk=None, interpret=None,
    warn_on_cap=True, config=None,
):
    """Advance a *single-shard* leapfrog state `n_steps` barely leaving
    VMEM — the wave edition of ops.pallas_kernels.fused_multi_step (same
    schedule, chunk, and compile-time constraints; see its docstring).
    Returns the advanced (U, U_prev) pair. `chunk` must divide `n_steps`
    when both are static; the outer trip count is dynamic — and for a
    TRACED `n_steps` divisibility cannot be checked at trace time: the
    trip count floors, silently dropping any `n_steps % chunk` remainder
    (ADVICE r3). Callers with dynamic step counts must guarantee
    divisibility themselves, as run_vmem_resident does via gcd. The kernel
    holds 4 field-sized arrays (U, U⁻, M, Cw), so admission is gated on
    half the diffusion kernel's VMEM budget. `config="auto"` fills an
    unset `chunk` from the tuning cache (op "wave.vmem_loop"); a miss
    keeps the default chunk policy, bitwise-identically.
    """
    from rocm_mpi_tpu.ops.pallas_kernels import resolve_step_chunk

    if interpret is None:
        interpret = _interpret_default()
    if not _supports_compiled(U.dtype) and not interpret:
        raise TypeError(f"Mosaic does not support {U.dtype}")
    if config == "auto" and chunk is None and isinstance(n_steps, int):
        # Static step counts only: a tuned chunk is a PREFERENCE the
        # divisibility contract still governs (gcd, mirroring the
        # default policy); with a traced n the caller's own guarantee
        # covers only the default chunk, so auto stays hands-off.
        from rocm_mpi_tpu.tuning import resolve as tuning_resolve

        from rocm_mpi_tpu.ops.pallas_kernels import adoptable_vmem_chunk

        tuned = tuning_resolve.resolve("wave.vmem_loop", U.shape, U.dtype)
        if tuned and adoptable_vmem_chunk(tuned.get("chunk")):
            import math

            chunk = math.gcd(n_steps, tuned["chunk"]) or None
    elif config not in (None, "default", "auto"):
        raise ValueError(
            f"config must be None, 'default' or 'auto', got {config!r}"
        )
    nbytes = _compute_nbytes(U)
    if nbytes > _VMEM_BLOCK_BUDGET_BYTES // 2:
        raise ValueError(
            f"field of {nbytes} bytes (f32 compute width) exceeds the "
            f"wave VMEM-resident budget ({_VMEM_BLOCK_BUDGET_BYTES // 2}); "
            "use the per-step path"
        )
    chunk = resolve_step_chunk(n_steps, chunk, nbytes, warn_on_cap)
    M = interior_mask(U.shape, U.dtype)
    Cw = (float(dt) * float(dt)) * C2 * M
    return lax.fori_loop(
        0,
        n_steps // chunk,
        lambda _, s: wave_multi_step_masked(
            s[0], s[1], M, Cw, spacing, chunk, interpret=interpret
        ),
        (U, Uprev),
    )
