"""Pallas TPU kernel for the acoustic-wave workload (framework-generality
demo — no reference analog; the reference ships exactly one physics model).

The leapfrog update U⁺ = 2U − U⁻ + dt²·c²·∇²U is a 3-operand stencil: the
same padded-block contract as the diffusion kernels
(ops.pallas_kernels.fused_step_padded), with a second state array read
core-only. Note the Dirichlet guard CANNOT ride a zeroed coefficient here
(c²==0 gives U⁺ = 2U − U⁻ ≠ U), so the caller masks boundary cells
explicitly — the same structure as the diffusion 'shard' variant
(models.diffusion._make_shard_step).
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from rocm_mpi_tpu.ops.pallas_kernels import (
    _VMEM_BLOCK_BUDGET_BYTES,
    _interpret_default,
    _lap_from_padded,
    _out_struct,
    _supports_compiled,
)


def wave_step_padded(Up, Uprev, C2, dt, spacing):
    """Candidate leapfrog update for every core cell of the padded block
    (pure jnp). `Up` is width-1-padded displacement; `Uprev`/`C2` are
    core-shaped. Same contract as ops.diffusion.step_fused_padded: the
    caller supplies ghosts and masks global-boundary cells. The one
    stencil definition — the Pallas kernel below computes the same
    expression in VMEM, and the VMEM-overflow fallback calls this.
    """
    inv_d2 = tuple(1.0 / (d * d) for d in spacing)
    core = tuple(slice(1, -1) for _ in range(C2.ndim))
    return 2.0 * Up[core] - Uprev + (dt * dt) * C2 * _lap_from_padded(
        Up, inv_d2
    )


def _wave_kernel_whole(Up_ref, Uprev_ref, C2_ref, out_ref, *, dt2, inv_d2):
    Up = Up_ref[:]
    core = tuple(slice(1, -1) for _ in range(Up.ndim))
    out_ref[:] = (
        2.0 * Up[core]
        - Uprev_ref[:]
        + dt2 * C2_ref[:] * _lap_from_padded(Up, inv_d2)
    )


def wave_step_padded_pallas(Up, Uprev, C2, dt, spacing, interpret=None):
    """Candidate leapfrog update for every core cell of a padded block.

    `Up` is the width-1-padded displacement (ghosts from exchange_halo);
    `Uprev` and `C2` (squared wave speed) are core-shaped. Whole-block VMEM
    kernel; blocks beyond the VMEM budget fall back to the jnp padded form
    (the wave workload is the layering demo, not the tuned flagship — the
    diffusion kernels carry the striped/temporal-blocked machinery).
    """
    if interpret is None:
        interpret = _interpret_default()
    nbytes = C2.size * C2.dtype.itemsize
    if (not _supports_compiled(Up.dtype) and not interpret) or (
        nbytes > _VMEM_BLOCK_BUDGET_BYTES
    ):
        return wave_step_padded(Up, Uprev, C2, dt, spacing)
    dt2 = float(dt) * float(dt)
    inv_d2 = tuple(1.0 / (float(d) * float(d)) for d in spacing)
    kernel = functools.partial(_wave_kernel_whole, dt2=dt2, inv_d2=inv_d2)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        out_shape=_out_struct(C2.shape, C2),
        in_specs=[vmem, vmem, vmem],
        out_specs=vmem,
        interpret=interpret,
    )(Up, Uprev, C2)
