"""Heat-diffusion step functions (pure jnp) and the analytic golden solution.

Physics: Fourier's law + conservation of energy,
    q = -λ ∇T ;  ∂T/∂t = 1/cₚ (-∇·q)
exactly as the reference's array-programming update
(/root/reference/scripts/diffusion_2D_ap.jl:38-41). Boundary condition:
global-domain edge cells are *never updated* (the reference updates
`T[2:end-1,2:end-1]` only) — Dirichlet with the initial boundary values
held fixed.

Two step formulations, both functional (return the new field):

* `step_flux_form` — the 3-stage staggered-grid update (flux arrays qx/qy of
  shapes (nx-1,ny-2)/(nx-2,ny-1), then divergence; ap.jl:22-24,38-41).
* `step_fused` — the single-pass 5-point (2·ndim+1-point) stencil that the
  reference's fused perf kernel computes inline (scripts/diffusion_2D_perf.jl:3-13),
  recomputing fluxes to trade FLOPs for memory traffic.

The two are algebraically identical; tests assert fp-level agreement.

NOTE on a reference quirk: the fused kernel *multiplies* by Cp
(`dt*(Cp[ix,iy]*(…))`, perf.jl:8) where the ap/kp variants *divide*
(`1.0./inn(Cp)`, ap.jl:40). With the shipped Cp = Cp0 = 1.0 the two
coincide. This framework uses the physically-correct 1/cₚ everywhere.
"""

from __future__ import annotations

import jax.numpy as jnp

from rocm_mpi_tpu.ops.stencil import d_a, d_i, inn


def step_flux_form(T, Cp, lam, dt, spacing):
    """One explicit step in staggered flux form (ap variant, any ndim).

    Mirrors diffusion_2D_ap.jl:38-41: per axis a flux q_ax = -λ d_i(T)/d_ax
    on the staggered grid, then dTdt = 1/cₚ Σ_ax (-d_a(q_ax)/d_ax), then an
    interior-only update.
    """
    ndim = T.ndim
    dTdt = jnp.zeros_like(inn(T))
    for ax in range(ndim):
        d = spacing[ax]
        q = -lam * d_i(T, ax) / d  # Fourier's law on the staggered grid
        dTdt = dTdt - d_a(q, ax) / d
    dTdt = dTdt / inn(Cp)
    interior = tuple(slice(1, -1) for _ in range(ndim))
    return T.at[interior].add(dt * dTdt)


def step_fused(T, Cp, lam, dt, spacing):
    """One explicit step as a single fused stencil (perf variant, any ndim).

    The jnp expression of the reference's fused memory-bound kernel
    (diffusion_2D_perf.jl:3-13): read the 2·ndim+1-point neighborhood of T,
    write the interior of the output; edge cells pass through unchanged
    (the kernel's `ix>1 && ix<nx && …` guard). Delegates to
    `step_fused_padded`, viewing T's own boundary ring as the padding.
    """
    interior = tuple(slice(1, -1) for _ in range(T.ndim))
    return T.at[interior].set(
        step_fused_padded(T, Cp[interior], lam, dt, spacing)
    )


def step_fused_padded(Tp, Cp, lam, dt, spacing):
    """Candidate fused update for *every* cell of a block, given its
    width-1-padded neighborhood `Tp` (shape = Cp.shape + 2 per axis).

    The per-shard form of `step_fused` used under shard_map: ghosts arrive
    from `parallel.halo.exchange_halo`, and the caller masks out
    global-boundary cells (Dirichlet). Equivalent of the reference's fused
    kernel body computed at interior offsets (diffusion_2D_perf.jl:3-13).
    """
    ndim = Cp.ndim
    core = tuple(slice(1, -1) for _ in range(ndim))
    lap = jnp.zeros_like(Cp)
    for ax in range(ndim):
        d2 = spacing[ax] * spacing[ax]
        hi = tuple(slice(2, None) if a == ax else slice(1, -1) for a in range(ndim))
        lo = tuple(slice(None, -2) if a == ax else slice(1, -1) for a in range(ndim))
        lap = lap + (Tp[hi] - 2.0 * Tp[core] + Tp[lo]) / d2
    return Tp[core] + dt * lam / Cp * lap


def step_fused_padded_geom(Tp, Cp, dt_lam, inv_d2):
    """`step_fused_padded` with the geometry PRECOMPUTED as operands:
    `dt_lam` = dt·λ (host-multiplied in the compute dtype, exactly the
    trace-time constant fold above) and `inv_d2` = per-axis 1/spacing²
    as the CORRECTLY-ROUNDED reciprocal of the in-dtype spacing². This
    is the ladder lane kernel: a laddered batch carries per-lane dt·λ
    and 1/spacing², so one compiled program serves lanes whose ORIGINAL
    shapes — hence dt and spacing — differ, bitwise-equal to each
    lane's standalone run.

    The reciprocal MULTIPLY (not a divide) is load-bearing for that
    bitwise pin: XLA strength-reduces `x / const` into `x * (1/const)`
    with the reciprocal rounded once, but a division by a traced
    OPERAND stays a true divide — same algebra, different rounding. A
    multiply, by contrast, is the identical instruction whether the
    scalar arrives folded or as an operand, so the host precomputes
    exactly the reciprocal XLA would have folded (serving adapters'
    ladder_geom: f32(1 / f64(f32(s·s)))) and both paths agree to the
    bit. Computing dt·λ or the reciprocal traced instead would also
    drift a ulp from the f64-then-cast standalone constants.

    `inv_d2` is a TUPLE of per-axis scalars, not an indexed (ndim,)
    vector: a vector gather inside a fori_loop body fuses differently
    from the folded-constant form (measured: 1-ulp drift on CPU) while
    separate scalar operands compile to the identical multiplies —
    models' batched_ladder_advance_fn threads them as distinct
    shard_map/vmap operands for exactly this reason.
    """
    ndim = Cp.ndim
    core = tuple(slice(1, -1) for _ in range(ndim))
    lap = jnp.zeros_like(Cp)
    for ax in range(ndim):
        hi = tuple(slice(2, None) if a == ax else slice(1, -1) for a in range(ndim))
        lo = tuple(slice(None, -2) if a == ax else slice(1, -1) for a in range(ndim))
        lap = lap + (Tp[hi] - 2.0 * Tp[core] + Tp[lo]) * inv_d2[ax]
    return Tp[core] + dt_lam / Cp * lap


def step_cm_padded(Tp, Cm, spacing):
    """Candidate fused update under the Cm contract (pure jnp): `Tp` is
    the width-1-padded block, `Cm` the PREPARED masked coefficient —
    (dt·λ)/Cp on updating cells, exactly 0.0 on held (global Dirichlet)
    cells (models.diffusion `_cm_prepare`). Held cells therefore come back
    bit-unchanged (Tp[core] + 0·lap), so callers need no trailing
    whole-shard `where` — the jnp twin of ops.pallas_kernels.fused_step_cm,
    and bitwise-identical to `step_fused_padded` on updating cells (the
    same left-associated (dt·λ)/Cp·lap product, just computed once per
    program instead of once per step).
    """
    ndim = Cm.ndim
    core = tuple(slice(1, -1) for _ in range(ndim))
    lap = jnp.zeros_like(Cm)
    for ax in range(ndim):
        d2 = spacing[ax] * spacing[ax]
        hi = tuple(slice(2, None) if a == ax else slice(1, -1) for a in range(ndim))
        lo = tuple(slice(None, -2) if a == ax else slice(1, -1) for a in range(ndim))
        lap = lap + (Tp[hi] - 2.0 * Tp[core] + Tp[lo]) / d2
    return Tp[core] + Cm * lap


def gaussian_ic(coords, lengths, dtype=None):
    """Initial condition: unit Gaussian at the domain center.

    T₀ = exp(-Σ_ax (x_ax - l_ax/2)²), the reference IC with cell-centered
    coordinates (diffusion_2D_ap.jl:28: exp(-(x_g+dx/2-lx/2)² - …)).

    `coords` are broadcastable per-axis cell-center arrays
    (GlobalGrid.coord_mesh).
    """
    r2 = sum((c - l / 2.0) ** 2 for c, l in zip(coords, lengths))
    T = jnp.exp(-r2)
    return T.astype(dtype) if dtype is not None else T


def analytic_solution(coords, lengths, diffusivity, t):
    """Exact solution of the free-space heat equation for `gaussian_ic`.

    With T₀ = exp(-r²) (i.e. 1/(4a₀) = 1/4, a₀=1) and D = λ/cₚ, the
    solution at time t is
        T(x,t) = (1 + 4Dt)^(-d/2) · exp(-r² / (1 + 4Dt)).
    Valid while the field is negligible at the domain boundary (the Dirichlet
    edges then don't matter) — the golden-test regime. This is the
    quantitative version of the reference's visual acceptance check
    ("smooth centered Gaussian", docs/Temp_4_252_252.png; SURVEY.md §4.2).
    """
    d = len(coords)
    s = 1.0 + 4.0 * diffusivity * t
    r2 = sum((c - l / 2.0) ** 2 for c, l in zip(coords, lengths))
    return s ** (-d / 2.0) * jnp.exp(-r2 / s)
