"""Utilities: timers/metrics, visualization, logging, profiling."""
