"""jax version compatibility shims (installed: 0.4.37; code targets newer).

One module owns every "where does this live / what is it called in this
jax" question, so version drift is fixed in exactly one place:

* `shard_map` — newer jax exports it at the top level and calls the
  replication-check kwarg `check_vma`; 0.4.x has it under
  `jax.experimental.shard_map` with the kwarg named `check_rep`. The
  wrapper resolves the import once and renames the kwarg to whatever the
  resolved implementation actually accepts (either direction, so the
  call sites stay written against the modern API).

`utils.backend.set_cpu_device_count` is the same idea for the
virtual-CPU-device knob.

The jax.experimental modules the framework uses (`pallas`, its `tpu`
sublayer, `multihost_utils`) resolve HERE too, lazily via module
`__getattr__` (PEP 562) so importing compat for shard_map alone does not
pay the Pallas import: graftlint rule GL03 forbids `jax.experimental`
anywhere else in the tree, which makes this module's `__all__` the one
stable allowlist a version bump has to revisit.
"""

from __future__ import annotations

import inspect

__all__ = [
    "axis_size",
    "cost_analysis_dict",
    "multihost_utils",
    "out_struct_like",
    "pallas",
    "pallas_tpu",
    "shard_map",
]

try:  # newer jax: top-level export
    from jax import shard_map as _shard_map_impl
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map_impl

_SHARD_MAP_PARAMS = frozenset(
    inspect.signature(_shard_map_impl).parameters
)


def shard_map(*args, **kwargs):
    """`jax.shard_map` with the replication-check kwarg renamed to match
    the installed implementation (`check_vma` <-> `check_rep`)."""
    for ours, theirs in (("check_vma", "check_rep"), ("check_rep", "check_vma")):
        if ours in kwargs and ours not in _SHARD_MAP_PARAMS:
            kwargs[theirs] = kwargs.pop(ours)
    return _shard_map_impl(*args, **kwargs)


def axis_size(axis_name) -> int:
    """`lax.axis_size` (newer jax) for 0.4.x too: `psum(1, name)` of the
    static literal 1 constant-folds to the mesh axis size at trace time —
    a Python int, usable to build ppermute permutations."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def cost_analysis_dict(compiled) -> dict:
    """`compiled.cost_analysis()` as a flat dict: 0.4.x returns a
    one-dict-per-partition LIST (take the first), newer jax the dict
    itself; both normalize to {} when analysis is unavailable."""
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost or {})


def _resolve_lazy(name: str):
    """The jax.experimental residents, resolved on first attribute access.

    Newer jax is probed first where a module has (or grows) a top-level
    home, the 0.4.x spelling second — the same both-directions policy as
    the shard_map shim, so neither an upgrade nor the pinned image breaks
    the import site.
    """
    if name == "pallas":
        try:
            from jax import pallas  # newer jax, if/when it graduates
        except ImportError:
            from jax.experimental import pallas
        return pallas
    if name == "pallas_tpu":
        try:
            from jax.pallas import tpu  # type: ignore[import-not-found]
        except ImportError:
            from jax.experimental.pallas import tpu
        return tpu
    if name == "multihost_utils":
        try:
            from jax import multihost_utils  # newer jax
        except ImportError:
            from jax.experimental import multihost_utils
        return multihost_utils
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __getattr__(name: str):  # PEP 562: lazy jax.experimental resolution
    value = _resolve_lazy(name)
    globals()[name] = value  # cache: resolve once per process
    return value


def out_struct_like(shape, exemplar):
    """ShapeDtypeStruct matching `exemplar`'s dtype and (where the
    installed jax tracks it) mesh-varying axes: under jax>=0.9 check_vma,
    pallas_call outputs inside shard_map must declare which mesh axes
    they vary over, so propagate the input's vma set; 0.4.x has no vma
    tracking and takes the plain struct."""
    import jax

    if hasattr(jax, "typeof"):
        return jax.ShapeDtypeStruct(
            shape, exemplar.dtype, vma=jax.typeof(exemplar).vma
        )
    return jax.ShapeDtypeStruct(shape, exemplar.dtype)
