"""jax version compatibility shims (installed: 0.4.37; code targets newer).

One module owns every "where does this live / what is it called in this
jax" question, so version drift is fixed in exactly one place:

* `shard_map` — newer jax exports it at the top level and calls the
  replication-check kwarg `check_vma`; 0.4.x has it under
  `jax.experimental.shard_map` with the kwarg named `check_rep`. The
  wrapper resolves the import once and renames the kwarg to whatever the
  resolved implementation actually accepts (either direction, so the
  call sites stay written against the modern API).

`utils.backend.set_cpu_device_count` is the same idea for the
virtual-CPU-device knob.

The jax.experimental modules the framework uses (`pallas`, its `tpu`
sublayer, `multihost_utils`) resolve HERE too, lazily via module
`__getattr__` (PEP 562) so importing compat for shard_map alone does not
pay the Pallas import: graftlint rule GL03 forbids `jax.experimental`
anywhere else in the tree, which makes this module's `__all__` the one
stable allowlist a version bump has to revisit.
"""

from __future__ import annotations

import inspect

__all__ = [
    "axis_size",
    "cost_analysis_dict",
    "install_compile_listener",
    "multihost_utils",
    "out_struct_like",
    "pallas",
    "pallas_tpu",
    "shard_map",
]

try:  # newer jax: top-level export
    from jax import shard_map as _shard_map_impl
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map_impl

_SHARD_MAP_PARAMS = frozenset(
    inspect.signature(_shard_map_impl).parameters
)


def shard_map(*args, **kwargs):
    """`jax.shard_map` with the replication-check kwarg renamed to match
    the installed implementation (`check_vma` <-> `check_rep`)."""
    for ours, theirs in (("check_vma", "check_rep"), ("check_rep", "check_vma")):
        if ours in kwargs and ours not in _SHARD_MAP_PARAMS:
            kwargs[theirs] = kwargs.pop(ours)
    return _shard_map_impl(*args, **kwargs)


def axis_size(axis_name) -> int:
    """`lax.axis_size` (newer jax) for 0.4.x too: `psum(1, name)` of the
    static literal 1 constant-folds to the mesh axis size at trace time —
    a Python int, usable to build ppermute permutations."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def cost_analysis_dict(compiled) -> dict:
    """`compiled.cost_analysis()` as a flat dict: 0.4.x returns a
    one-dict-per-partition LIST (take the first), newer jax the dict
    itself; both normalize to {} when analysis is unavailable."""
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost or {})


BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_COMPILE_LISTENER_MODE: str | None = None


def install_compile_listener(on_interval, on_event=None) -> str | None:
    """Tap jax's compile pipeline for the compile tracker
    (telemetry/compiles.py). Where the hook lands is pure version drift,
    so it lives HERE, the one chokepoint a jax bump revisits:

    * preferred ("named"): wrap `jax._src.dispatch.log_elapsed_time`,
      the context manager every trace/lower/backend-compile interval in
      0.4.x runs under — its `fun_name` is the per-program identity the
      public monitoring API does not carry. Call sites resolve it as a
      module attribute at call time, so the install-once rebind below is
      effective without reimporting anything.
    * fallback ("events"): `jax.monitoring`'s duration listener — same
      events, `name=None` (per-program attribution degrades to totals,
      the tracker still counts).

    `on_interval(event, name, dur_s)` receives every completed interval
    (event is e.g. BACKEND_COMPILE_EVENT); `on_event(event)` receives
    point events (persistent-cache hit/miss). Both are wrapped so a
    listener exception can never break a compile. Installs at most once
    per process; returns the active mode ("named"/"events"/None).
    """
    global _COMPILE_LISTENER_MODE
    if _COMPILE_LISTENER_MODE is not None:
        return _COMPILE_LISTENER_MODE
    import contextlib
    import time

    def _safe_interval(event, name, dur_s):
        try:
            on_interval(event, name, dur_s)
        except Exception:  # noqa: BLE001 — never break a compile
            pass

    mode = None
    try:
        from jax._src import dispatch as _dispatch

        _orig = _dispatch.log_elapsed_time

        @contextlib.contextmanager
        def _tapped_log_elapsed_time(*args, **kwargs):
            # Signature-transparent on purpose: the pinned jax calls
            # (fmt, fun_name=…, event=…), but a bumped jax that adds or
            # renames a parameter must cost ATTRIBUTION, not the run —
            # a TypeError here would propagate out of every jit trace.
            fun_name = kwargs.get("fun_name")
            event = kwargs.get("event")
            if len(args) > 1 and fun_name is None:
                fun_name = args[1]
            if len(args) > 2 and event is None:
                event = args[2]
            t0 = time.monotonic()
            with _orig(*args, **kwargs):
                yield
            # Only a COMPLETED interval counts (an aborted compile is an
            # error, not a compile); jax's own listeners already fired
            # inside _orig's exit.
            _safe_interval(event, fun_name, time.monotonic() - t0)

        # The install-once seam this function exists for — not a
        # trace-time knob (GL02's hazard); cached programs are
        # unaffected, only future compiles pass through the tap.
        # (carried a GL02 inline suppression until the
        # --strict-suppressions audit proved it dead: the purity rule
        # only flags module-state writes reachable from traced bodies,
        # and this install-once seam never was)
        _dispatch.log_elapsed_time = _tapped_log_elapsed_time
        mode = "named"
    except Exception:  # noqa: BLE001 — private-module drift: fall back
        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                lambda event, duration, **kw: _safe_interval(
                    event, None, duration
                )
            )
            mode = "events"
        except Exception:  # noqa: BLE001
            return None
    if on_event is not None:
        try:
            import jax.monitoring

            def _safe_event(event, **kw):
                try:
                    on_event(event)
                except Exception:  # noqa: BLE001
                    pass

            jax.monitoring.register_event_listener(_safe_event)
        except Exception:  # noqa: BLE001 — hit/miss counts degrade to 0
            pass
    _COMPILE_LISTENER_MODE = mode
    return mode


def _resolve_lazy(name: str):
    """The jax.experimental residents, resolved on first attribute access.

    Newer jax is probed first where a module has (or grows) a top-level
    home, the 0.4.x spelling second — the same both-directions policy as
    the shard_map shim, so neither an upgrade nor the pinned image breaks
    the import site.
    """
    if name == "pallas":
        try:
            from jax import pallas  # newer jax, if/when it graduates
        except ImportError:
            from jax.experimental import pallas
        return pallas
    if name == "pallas_tpu":
        try:
            from jax.pallas import tpu  # type: ignore[import-not-found]
        except ImportError:
            from jax.experimental.pallas import tpu
        return tpu
    if name == "multihost_utils":
        try:
            from jax import multihost_utils  # newer jax
        except ImportError:
            from jax.experimental import multihost_utils
        return multihost_utils
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __getattr__(name: str):  # PEP 562: lazy jax.experimental resolution
    value = _resolve_lazy(name)
    globals()[name] = value  # cache: resolve once per process
    return value


def out_struct_like(shape, exemplar):
    """ShapeDtypeStruct matching `exemplar`'s dtype and (where the
    installed jax tracks it) mesh-varying axes: under jax>=0.9 check_vma,
    pallas_call outputs inside shard_map must declare which mesh axes
    they vary over, so propagate the input's vma set; 0.4.x has no vma
    tracking and takes the plain struct."""
    import jax

    if hasattr(jax, "typeof"):
        return jax.ShapeDtypeStruct(
            shape, exemplar.dtype, vma=jax.typeof(exemplar).vma
        )
    return jax.ShapeDtypeStruct(shape, exemplar.dtype)
