"""Process-0-gated logging (the reference's `me==0 && println` idiom,
/root/reference/scripts/diffusion_2D_ap.jl:36,44)."""

from __future__ import annotations

import jax


def is_main() -> bool:
    return jax.process_index() == 0


def log0(*args, **kwargs):
    """Print only on process 0 (rank-0 gating)."""
    if is_main():
        print(*args, **kwargs, flush=True)
