"""Backend-selection workarounds for this image's pre-pinned platform.

The environment pre-imports jax at interpreter startup with the chip
platform pinned, so a JAX_PLATFORMS env var set afterwards (e.g. cpu for
local testing) is silently ignored unless re-applied through jax.config
before first backend use. Every entry point that honors the env var
(bench.py, scripts/bench_*.py, tests/conftest.py's direct config calls)
routes through here so the quirk is encoded exactly once.
"""

from __future__ import annotations

import os


def enable_persistent_cache() -> None:
    """Point this process at the repo's persistent XLA compilation cache
    (.jax_cache/, overridable via JAX_COMPILATION_CACHE_DIR).

    On the tunneled chip a first Mosaic compile costs tens of seconds and
    the tunnel flaps, so every measurement entry point opts in: a re-run
    after a killed attempt then skips compiles the dead process already
    paid for. Accelerator-only by default for the same reason as
    bench._setup_compilation_cache — XLA:CPU AOT entries embed the compile
    machine's CPU feature set and can SIGILL on mismatch — EXCEPT under
    RMT_CPU_CACHE=1, the test harness's machine-local opt-in
    (tests/conftest.py): there the cache dir lives untracked on the one
    machine that wrote it, mismatch cannot occur, and the per-commit
    suite's subprocess children (apps, bench contract, dryrun) stop
    re-paying identical XLA:CPU compiles on every run. Best-effort: an
    older jax without the knobs must not break a measurement run.
    """
    import jax

    cpu_cache = os.environ.get("RMT_CPU_CACHE", "").strip().lower() not in (
        "", "0", "false", "no",
    )
    try:
        if jax.default_backend() in ("cpu",) and not cpu_cache:
            return
    except Exception:  # noqa: BLE001 — backend probe itself may fail
        return
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        ".jax_cache",
    )
    for knob, val in (
        ("jax_compilation_cache_dir", cache_dir),
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", 0),
    ):
        try:
            jax.config.update(knob, val)
        except Exception:  # noqa: BLE001
            pass


def set_cpu_device_count(n: int) -> None:
    """Request `n` virtual CPU devices, portably across jax versions.

    Newer jax has the `jax_num_cpu_devices` config knob; 0.4.37 (this
    image) does not, so the fallback appends XLA's
    `--xla_force_host_platform_device_count=N` to XLA_FLAGS — which the
    CPU client reads at backend creation, so it still works after
    `import jax` as long as no backend has initialized yet. One shim,
    all five call sites (tests/conftest, tests/distributed_worker,
    apps/_common, apps/ici_ring_test, __graft_entry__) — the quirk must
    not be re-solved per entry point.

    Best-effort once a backend is up: the config path raises (newer jax)
    but the XLA_FLAGS path is silently inert after initialization, so
    callers that REQUIRE the count must assert `len(jax.devices())`
    afterwards (tests/conftest.py does).
    """
    import jax

    n = int(n)
    if n < 1:
        raise ValueError(f"device count must be >= 1, got {n}")
    try:
        jax.config.update("jax_num_cpu_devices", n)
        return
    except AttributeError:
        pass  # jax 0.4.x: no knob — fall back to the XLA flag
    flag = f"--xla_force_host_platform_device_count={n}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        # Replace any prior count (ours or inherited) — last write wins
        # in XLA's parser is not guaranteed, so scrub first.
        flags = " ".join(
            f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f
        )
    os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()


def require_accelerator(script: str) -> None:
    """Exit 2 when jax resolved to the CPU fallback.

    Chip measurement scripts call this so a mid-queue tunnel drop (jax
    silently falls back to CPU when the accelerator plugin fails init)
    exits nonzero — the queue then records an INCOMPLETE artifact and
    retries later, instead of promoting interpret-mode timings as the
    completed chip measurement. One policy, one exit code, one message.
    """
    import sys

    import jax

    if jax.devices()[0].platform == "cpu":
        print(
            f"{script}: CPU fallback — refusing to measure (an accelerator "
            "backend is required; interpret-mode numbers must never land "
            "in a chip-labeled artifact)",
            file=sys.stderr,
            flush=True,
        )
        raise SystemExit(2)


def apply_platform_override() -> None:
    """Re-apply a JAX_PLATFORMS env override via jax.config (no-op when
    the var is unset or the backend is already initialized)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        try:
            jax.config.update("jax_platforms", plat)
        except (RuntimeError, ValueError):
            pass  # backend already initialized; keep whatever it picked
