"""Backend-selection workarounds for this image's pre-pinned platform.

The environment pre-imports jax at interpreter startup with the chip
platform pinned, so a JAX_PLATFORMS env var set afterwards (e.g. cpu for
local testing) is silently ignored unless re-applied through jax.config
before first backend use. Every entry point that honors the env var
(bench.py, scripts/bench_*.py, tests/conftest.py's direct config calls)
routes through here so the quirk is encoded exactly once.
"""

from __future__ import annotations

import os


def apply_platform_override() -> None:
    """Re-apply a JAX_PLATFORMS env override via jax.config (no-op when
    the var is unset or the backend is already initialized)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        try:
            jax.config.update("jax_platforms", plat)
        except (RuntimeError, ValueError):
            pass  # backend already initialized; keep whatever it picked
