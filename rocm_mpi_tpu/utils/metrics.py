"""Walltime timers and the performance metrics (D5, SURVEY.md §5.5).

The reference's one metric is effective memory throughput
    T_eff = A_eff / wtime_it,  A_eff = (2+1)/1e9 · nx·ny · sizeof(dtype) GB
(read T + write T2 + read Cp = 3 whole-array passes), with
    wtime_it = wtime / (nt - warmup)
excluding 10 warmup iterations (/root/reference/scripts/diffusion_2D_perf.jl:55-58,
tic/toc at :48,53). The driver's headline metric Gpts/s = nx·ny/wtime_it/1e9
is the same measurement, hardware-agnostically normalized per grid point.

TPU note: `tic`/`toc` bracket device work with a *value fetch* — the analog
of the reference's `wait(signal)` sync before `toc` — because JAX dispatch
is async AND, on the tunneled-chip transport this framework targets,
`block_until_ready` (both the module function and the array method) returns
before remote execution finishes; only materializing a value on the host
actually waits. Measured: a 2.5 s computation "synced" with
block_until_ready times at 0.000 s, with a scalar fetch at 2.49 s. The
fetch costs one tiny transfer round-trip, which the caller amortizes by
timing windows of many steps.

Since the telemetry subsystem (rocm_mpi_tpu/telemetry/, docs/TELEMETRY.md)
this module is the compatibility surface: the structured-event API
(`record_event`/`events`/`clear_events`) is a thin shim over
`telemetry.events`, and a *labeled* Timer feeds its interval into the
telemetry stream. New code should prefer `telemetry.span(...)` directly —
bare `tic()`/`toc()` remains supported for the models' measurement loops
but is deprecated in apps, where raw timing is also lint-gated (graftlint
GL06 flags `time.perf_counter()`/`time.time()` outside this module and
telemetry/).
"""

from __future__ import annotations

import math
import time

import jax

from rocm_mpi_tpu.telemetry import events as _tel


def force(x):
    """Truly wait for `x`: block_until_ready, then fetch one scalar.

    The fetch is an O(1) single-element slice (not a whole-array pull) and
    is skipped for non-fully-addressable global arrays (multi-host runs),
    where cross-host fetches are invalid — there, block_until_ready is the
    real runtime's sync and the fetch workaround is neither possible nor
    needed (the no-op behavior is a quirk of the single-host tunnel).
    """
    x = jax.block_until_ready(x)
    if hasattr(x, "ndim") and getattr(x, "is_fully_addressable", False):
        jax.device_get(x[(0,) * x.ndim])
    return x


class Timer:
    """tic/toc walltime timer (ImplicitGlobalGrid tic()/toc() analog),
    also usable as a context manager:

        with Timer() as timer:
            state = advance(state, n)   # sync yourself, or...
            timer.toc(state)            # ...toc explicitly with sync args
        wtime = timer.elapsed

    __exit__ calls toc() only when the body didn't — an explicit
    toc(*sync) inside the block keeps the device-fetch sync semantics and
    wins over the exit stamp. A `label` routes the measured interval into
    the telemetry stream as a span record (phase attribution for code
    that already times with Timer), with `attrs` carried along; unlabeled
    timers stay telemetry-silent, exactly as before.
    """

    def __init__(self, label: str | None = None, **attrs):
        self._t0 = None
        self._t0_wall = None
        self.elapsed = None
        self.label = label
        self.attrs = attrs

    def tic(self, *sync):
        """Start timing. Pass device arrays to sync on first."""
        for x in sync:
            force(x)
        self.elapsed = None
        self._t0_wall = time.time()
        self._t0 = time.perf_counter()

    def toc(self, *sync) -> float:
        """Stop timing (after syncing on `sync`); returns elapsed seconds."""
        for x in sync:
            force(x)
        if self._t0 is None:
            raise RuntimeError("toc() before tic()")
        self.elapsed = time.perf_counter() - self._t0
        if self.label is not None and _tel.enabled():
            from rocm_mpi_tpu.telemetry.spans import span_record

            span_record(self.label, self._t0_wall, self.elapsed,
                        **self.attrs)
        return self.elapsed

    def __enter__(self):
        self.tic()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self.elapsed is None and self._t0 is not None:
            if exc_type is None:
                self.toc()
            else:
                # A failing body still gets its interval recorded (no
                # sync — there may be nothing coherent to sync on): the
                # hours a run burned before the supervisor gave up must
                # show in the stream, error-flagged like a failed span.
                self.elapsed = time.perf_counter() - self._t0
                if self.label is not None and _tel.enabled():
                    from rocm_mpi_tpu.telemetry.spans import span_record

                    span_record(self.label, self._t0_wall, self.elapsed,
                                error=exc_type.__name__, **self.attrs)
        return False


def wtime_per_it(wtime: float, nt: int, warmup: int = 10) -> float:
    """wtime_it = wtime/(nt - warmup) (perf.jl:56)."""
    if nt <= warmup:
        raise ValueError(f"nt={nt} must exceed warmup={warmup}")
    return wtime / (nt - warmup)


def a_eff_gb(shape, itemsize: int, n_passes: int = 3) -> float:
    """A_eff in GB: n_passes whole-array memory passes per step (perf.jl:55)."""
    return n_passes / 1e9 * math.prod(shape) * itemsize


def t_eff_gbs(shape, itemsize: int, wtime_it: float, n_passes: int = 3) -> float:
    """Effective memory throughput T_eff [GB/s] (perf.jl:57)."""
    return a_eff_gb(shape, itemsize, n_passes) / wtime_it


def gpts_per_s(shape, wtime_it: float) -> float:
    """Grid points processed per second [Gpts/s] — the driver's metric."""
    return math.prod(shape) / wtime_it / 1e9


# ---------------------------------------------------------------------------
# Structured run events — a compatibility shim over telemetry.events.
#
# The PR-1 resilience layer introduced this API; the telemetry subsystem
# now owns the storage (versioned records, per-rank JSONL writers,
# RMT_EVENT_LOG legacy tee — rocm_mpi_tpu/telemetry/events.py). The
# RunEvent view below preserves every pre-telemetry caller (tests,
# supervisor post-mortems) while new fields — the satellite fixes —
# ride along: `t_mono` (monotonic, orders events within a rank; the old
# wall-only stamp couldn't) and `v` (the event-schema version the old
# lines lacked).
# ---------------------------------------------------------------------------

import dataclasses  # noqa: E402  (grouped with the shim it serves)
import json  # noqa: E402


@dataclasses.dataclass(frozen=True)
class RunEvent:
    """One structured resilience event (retry, restore, give-up...)."""

    kind: str            # e.g. "attempt-failed", "backoff", "restored"
    t: float             # wall time at emission (comparable across ranks)
    attempt: int | None = None
    step: int | None = None
    wait_s: float | None = None
    error: str | None = None
    t_mono: float | None = None  # monotonic stamp (ordering within a rank)
    v: int = _tel.SCHEMA_VERSION

    def to_json(self) -> str:
        return json.dumps(
            {k: v for k, v in dataclasses.asdict(self).items()
             if v is not None}
        )


def _as_run_event(rec: dict) -> RunEvent:
    return RunEvent(
        kind=rec["name"], t=rec["t"], attempt=rec.get("attempt"),
        step=rec.get("step"), wait_s=rec.get("wait_s"),
        error=rec.get("error"), t_mono=rec.get("t_mono"),
        v=rec.get("v", _tel.SCHEMA_VERSION),
    )


def record_event(kind: str, *, attempt=None, step=None, wait_s=None,
                 error=None) -> RunEvent:
    """Append a structured event (telemetry stream + RMT_EVENT_LOG tee)."""
    rec = _tel.record_event(kind, attempt=attempt, step=step,
                            wait_s=wait_s, error=error)
    return _as_run_event(rec)


def events(kind: str | None = None) -> list[RunEvent]:
    """The in-process event trail (optionally filtered by kind)."""
    return [
        _as_run_event(r)
        for r in _tel.records(kind="event", name=kind)
    ]


def clear_events() -> None:
    """Deprecated alias for `telemetry.clear_events()` — the one public
    reset for the event trail (events dropped, buffered spans/gauges and
    the trace-annotation dedup state preserved). The two spellings used
    to live side by side with the behavior defined only here; the
    telemetry side now owns it (the flight recorder's reset path goes
    through the same function), and this shim just forwards."""
    import warnings

    warnings.warn(
        "utils.metrics.clear_events() is deprecated; call "
        "telemetry.clear_events()",
        DeprecationWarning,
        stacklevel=2,
    )
    _tel.clear_events()
