"""Walltime timers and the performance metrics (D5, SURVEY.md §5.5).

The reference's one metric is effective memory throughput
    T_eff = A_eff / wtime_it,  A_eff = (2+1)/1e9 · nx·ny · sizeof(dtype) GB
(read T + write T2 + read Cp = 3 whole-array passes), with
    wtime_it = wtime / (nt - warmup)
excluding 10 warmup iterations (/root/reference/scripts/diffusion_2D_perf.jl:55-58,
tic/toc at :48,53). The driver's headline metric Gpts/s = nx·ny/wtime_it/1e9
is the same measurement, hardware-agnostically normalized per grid point.

TPU note: `tic`/`toc` bracket device work with a *value fetch* — the analog
of the reference's `wait(signal)` sync before `toc` — because JAX dispatch
is async AND, on the tunneled-chip transport this framework targets,
`block_until_ready` (both the module function and the array method) returns
before remote execution finishes; only materializing a value on the host
actually waits. Measured: a 2.5 s computation "synced" with
block_until_ready times at 0.000 s, with a scalar fetch at 2.49 s. The
fetch costs one tiny transfer round-trip, which the caller amortizes by
timing windows of many steps.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time

import jax


def force(x):
    """Truly wait for `x`: block_until_ready, then fetch one scalar.

    The fetch is an O(1) single-element slice (not a whole-array pull) and
    is skipped for non-fully-addressable global arrays (multi-host runs),
    where cross-host fetches are invalid — there, block_until_ready is the
    real runtime's sync and the fetch workaround is neither possible nor
    needed (the no-op behavior is a quirk of the single-host tunnel).
    """
    x = jax.block_until_ready(x)
    if hasattr(x, "ndim") and getattr(x, "is_fully_addressable", False):
        jax.device_get(x[(0,) * x.ndim])
    return x


class Timer:
    """tic/toc walltime timer (ImplicitGlobalGrid tic()/toc() analog)."""

    def __init__(self):
        self._t0 = None
        self.elapsed = None

    def tic(self, *sync):
        """Start timing. Pass device arrays to sync on first."""
        for x in sync:
            force(x)
        self._t0 = time.perf_counter()

    def toc(self, *sync) -> float:
        """Stop timing (after syncing on `sync`); returns elapsed seconds."""
        for x in sync:
            force(x)
        if self._t0 is None:
            raise RuntimeError("toc() before tic()")
        self.elapsed = time.perf_counter() - self._t0
        return self.elapsed


def wtime_per_it(wtime: float, nt: int, warmup: int = 10) -> float:
    """wtime_it = wtime/(nt - warmup) (perf.jl:56)."""
    if nt <= warmup:
        raise ValueError(f"nt={nt} must exceed warmup={warmup}")
    return wtime / (nt - warmup)


def a_eff_gb(shape, itemsize: int, n_passes: int = 3) -> float:
    """A_eff in GB: n_passes whole-array memory passes per step (perf.jl:55)."""
    return n_passes / 1e9 * math.prod(shape) * itemsize


def t_eff_gbs(shape, itemsize: int, wtime_it: float, n_passes: int = 3) -> float:
    """Effective memory throughput T_eff [GB/s] (perf.jl:57)."""
    return a_eff_gb(shape, itemsize, n_passes) / wtime_it


def gpts_per_s(shape, wtime_it: float) -> float:
    """Grid points processed per second [Gpts/s] — the driver's metric."""
    return math.prod(shape) / wtime_it / 1e9


# ---------------------------------------------------------------------------
# Structured run events (resilience layer, docs/RESILIENCE.md §2).
#
# The supervisor's retry/backoff decisions must leave a machine-readable
# trail — "the run recovered twice" is an operational fact the same way
# T_eff is a performance fact. Events accumulate in-process (the tests'
# and supervisor-caller's view) and, when RMT_EVENT_LOG names a path,
# append as JSON lines (the post-mortem view: the file survives the
# process the way the chip watcher's log survived the outage rounds).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RunEvent:
    """One structured resilience event (retry, restore, give-up...)."""

    kind: str            # e.g. "attempt-failed", "backoff", "restored"
    t: float             # time.time() at emission
    attempt: int | None = None
    step: int | None = None
    wait_s: float | None = None
    error: str | None = None

    def to_json(self) -> str:
        return json.dumps(
            {k: v for k, v in dataclasses.asdict(self).items()
             if v is not None}
        )


_EVENTS: list[RunEvent] = []


def record_event(kind: str, *, attempt=None, step=None, wait_s=None,
                 error=None) -> RunEvent:
    """Append a structured event; best-effort tee to RMT_EVENT_LOG."""
    ev = RunEvent(
        kind=kind, t=time.time(), attempt=attempt, step=step,
        wait_s=wait_s, error=error,
    )
    _EVENTS.append(ev)
    path = os.environ.get("RMT_EVENT_LOG")
    if path:
        try:
            with open(path, "a") as fh:
                fh.write(ev.to_json() + "\n")
        except OSError:
            pass  # the event log must never be what kills a run
    return ev


def events(kind: str | None = None) -> list[RunEvent]:
    """The in-process event trail (optionally filtered by kind)."""
    if kind is None:
        return list(_EVENTS)
    return [e for e in _EVENTS if e.kind == kind]


def clear_events() -> None:
    _EVENTS.clear()
