"""Headless heatmap rendering (D10, reference L5).

The reference renders the gathered global temperature field with Plots.jl/GR
in headless mode and saves `../output/Temp_<variant>_<nprocs>_<nxg>_<nyg>.png`
(/root/reference/scripts/diffusion_2D_ap.jl:30,47). Here: matplotlib Agg on
process 0, same filename scheme, same transpose-for-display convention
(`heatmap(transpose(T_v))` — axis 0 of the field is x, which matplotlib
plots vertically unless transposed).
"""

from __future__ import annotations

import pathlib

import numpy as np


def artifact_name(variant: str, nprocs: int, global_shape) -> str:
    """Temp_<variant>_<nprocs>_<nx_g>_<ny_g>.png (ap.jl:47)."""
    dims = "_".join(str(n) for n in global_shape)
    return f"Temp_{variant}_{nprocs}_{dims}.png"


def save_heatmap(field, path, title: str | None = None) -> pathlib.Path:
    """Render `field` (2D, or 3D mid-slice) to `path` as a PNG heatmap."""
    import matplotlib

    matplotlib.use("Agg")  # headless (GKSwstype="nul" analog, ap.jl:30)
    import matplotlib.pyplot as plt

    field = np.asarray(field)
    if field.ndim == 3:
        field = field[:, :, field.shape[2] // 2]
    if field.ndim != 2:
        raise ValueError(f"expected 2D/3D field, got shape {field.shape}")

    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fig, ax = plt.subplots(figsize=(6, 5))
    im = ax.imshow(field.T, origin="lower", cmap="inferno")
    fig.colorbar(im, ax=ax)
    if title:
        ax.set_title(title)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path
