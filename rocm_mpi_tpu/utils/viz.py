"""Headless heatmap rendering (D10, reference L5).

The reference renders the gathered global temperature field with Plots.jl/GR
in headless mode and saves `../output/Temp_<variant>_<nprocs>_<nxg>_<nyg>.png`
(/root/reference/scripts/diffusion_2D_ap.jl:30,47). Here: matplotlib Agg on
process 0, same filename scheme, same transpose-for-display convention
(`heatmap(transpose(T_v))` — axis 0 of the field is x, which matplotlib
plots vertically unless transposed).
"""

from __future__ import annotations

import pathlib

import numpy as np


def artifact_name(variant: str, nprocs: int, global_shape) -> str:
    """Temp_<variant>_<nprocs>_<nx_g>_<ny_g>.png (ap.jl:47)."""
    dims = "_".join(str(n) for n in global_shape)
    return f"Temp_{variant}_{nprocs}_{dims}.png"


def save_heatmap(field, path, title: str | None = None) -> pathlib.Path:
    """Render `field` (2D, or 3D mid-slice) to `path` as a PNG heatmap."""
    import matplotlib

    matplotlib.use("Agg")  # headless (GKSwstype="nul" analog, ap.jl:30)
    import matplotlib.pyplot as plt

    field = np.asarray(field)
    if field.ndim == 3:
        field = field[:, :, field.shape[2] // 2]
    if field.ndim != 2:
        raise ValueError(f"expected 2D/3D field, got shape {field.shape}")

    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fig, ax = plt.subplots(figsize=(6, 5))
    im = ax.imshow(field.T, origin="lower", cmap="inferno")
    fig.colorbar(im, ax=ax)
    if title:
        ax.set_title(title)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def save_shard_panels(field, dims, path, title: str | None = None,
                      signed: bool = False):
    """Render each shard of a 2D field as its own panel — the halo-exchange
    PoC artifact (the reference's docs/poc_rocmaware.png shows one GKS
    window per rank, README.md:5-7). A working exchange shows the blob
    spilling smoothly across panel edges; a broken one shows clipped or
    seamed blobs.

    `signed=True` scales the colormap symmetrically around 0 — required
    for fields that oscillate (the SWE surface height): the default
    non-negative scale would clip every trough to flat colormap-bottom,
    hiding exactly the seams the artifact exists to expose.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    field = np.asarray(field)
    if field.ndim != 2 or len(dims) != 2:
        raise ValueError("shard panels are 2D-only")
    lx, ly = field.shape[0] // dims[0], field.shape[1] // dims[1]
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    vmax = (np.abs(field).max() if signed else field.max()) or 1.0
    vmin = -vmax if signed else 0.0
    # Panel rows follow display convention: axis 1 (y) is vertical,
    # top row = highest y shard, so panels tile like the field itself.
    fig, axes = plt.subplots(
        dims[1], dims[0],
        figsize=(3 * dims[0], 2.6 * dims[1]), squeeze=False,
    )
    for cx in range(dims[0]):
        for cy in range(dims[1]):
            shard = field[cx * lx:(cx + 1) * lx, cy * ly:(cy + 1) * ly]
            ax = axes[dims[1] - 1 - cy][cx]
            ax.imshow(shard.T, origin="lower",
                      cmap="RdBu_r" if signed else "inferno",
                      vmin=vmin, vmax=vmax)
            ax.set_title(f"device ({cx},{cy})", fontsize=8)
            ax.set_xticks([]), ax.set_yticks([])
    if title:
        fig.suptitle(title)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def save_shard_panels_artifact(field, grid, label, out_dir,
                               signed: bool = False):
    """The app drivers' one entry point for the PoC panels: builds the
    shared filename scheme (poc_<label>_<nprocs>.png) and title, so the
    diffusion and SWE apps cannot drift on either. Returns the path."""
    path = pathlib.Path(out_dir) / f"poc_{label}_{grid.nprocs}.png"
    return save_shard_panels(
        field, grid.dims, path,
        title=f"per-device shards — {label} mesh={grid.dims}",
        signed=signed,
    )
