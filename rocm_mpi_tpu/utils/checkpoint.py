"""Checkpoint/resume (SURVEY.md §5.4, upgraded beyond matched scope).

The reference persists nothing but a final PNG (its §5.4 row is "none");
round 3 matched that with `--save-field`. This module adds the real
subsystem a long run needs: periodic sharded checkpoints via orbax (the
TPU-ecosystem checkpoint library), with resume-from-latest — so a
multi-hour run survives preemption, the exact failure mode the flapping
chip tunnel demonstrates (BASELINE.md outage log).

Design: the timed loop stays ONE jitted `advance(state..., n)` program —
checkpointing never reaches inside it. `run_segmented` splits the step
budget at checkpoint boundaries, calls the model's own advance between
saves, and a resumed run continues from the latest saved step with the
SAME compiled program (the segment lengths differ only in the traced `n`).
State arrays keep their NamedSharding: orbax saves/restores per-shard, so
a sharded run checkpoints without gathering to one host.
"""

from __future__ import annotations

import pathlib


def _manager(directory, keep: int = 3):
    import orbax.checkpoint as ocp

    path = pathlib.Path(directory).resolve()
    path.mkdir(parents=True, exist_ok=True)
    return ocp.CheckpointManager(
        path, options=ocp.CheckpointManagerOptions(max_to_keep=keep)
    )


def save_state(directory, step: int, state, keep: int = 3) -> None:
    """Save `state` (any pytree of jax arrays — sharded arrays keep their
    sharding) labeled by absolute step count."""
    import orbax.checkpoint as ocp

    mgr = _manager(directory, keep)
    mgr.save(step, args=ocp.args.StandardSave(state))
    mgr.wait_until_finished()
    mgr.close()


def latest_step(directory) -> int | None:
    """The newest checkpointed step in `directory`, or None."""
    path = pathlib.Path(directory)
    if not path.is_dir():
        return None
    mgr = _manager(path)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore_state(directory, step: int, like):
    """Restore the pytree saved at `step`, placed/sharded like the
    abstract template `like` (pass the freshly-initialized state — shapes,
    dtypes, and shardings are taken from it, so a restored run lands
    exactly where the initializer would have put it)."""
    import jax
    import orbax.checkpoint as ocp

    mgr = _manager(directory)
    template = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding),
        like,
    )
    out = mgr.restore(step, args=ocp.args.StandardRestore(template))
    mgr.close()
    return out


def run_segmented(
    advance,
    state,
    nt: int,
    directory,
    every: int,
    start_step: int = 0,
    keep: int = 3,
):
    """Advance `state` by `nt - start_step` steps, checkpointing every
    `every` steps (and at the end). `advance(state, n) -> state` must
    accept a traced step count — the framework's standard advance
    contract — so every segment reuses one compiled program. Returns the
    final state.

    Resume idiom (what the apps' --resume flag does):

        start = latest_step(dir) or 0
        state = restore_state(dir, start, init_state) if start else init_state
        state = run_segmented(advance, state, nt, dir, every, start)
    """
    import orbax.checkpoint as ocp

    if every < 1:
        raise ValueError(f"checkpoint interval must be >= 1, got {every}")
    if not 0 <= start_step <= nt:
        raise ValueError(f"need 0 <= start_step <= nt, got {start_step}, {nt}")
    # ONE manager for the whole run: orbax saves asynchronously, so each
    # segment's write overlaps the next segment's compute; the single
    # wait_until_finished at the end is the only forced sync.
    mgr = _manager(directory, keep)
    try:
        step = start_step
        while step < nt:
            n = min(every, nt - step)
            state = advance(state, n)
            step += n
            mgr.save(step, args=ocp.args.StandardSave(state))
        mgr.wait_until_finished()
    finally:
        mgr.close()
    return state
