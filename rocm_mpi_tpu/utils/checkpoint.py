"""Checkpoint/resume with integrity manifests (SURVEY.md §5.4 upgraded;
resilience layer: docs/RESILIENCE.md).

The reference persists nothing but a final PNG (its §5.4 row is "none");
round 3 matched that with `--save-field`. This module is the real
subsystem a long run needs: periodic sharded checkpoints via orbax (the
TPU-ecosystem checkpoint library), resume-from-latest, and — the PR-1
resilience upgrade — a per-save INTEGRITY MANIFEST so a resumed run can
tell a good checkpoint from a truncated or corrupt one and fall back to
the previous kept step instead of restarting (or worse, silently
continuing) from garbage.

Design: the timed loop stays ONE jitted `advance(state..., n)` program —
checkpointing never reaches inside it. `run_segmented` splits the step
budget at checkpoint boundaries, calls the model's own advance between
saves, and a resumed run continues from the latest saved step with the
SAME compiled program (the segment lengths differ only in the traced `n`).
State arrays keep their NamedSharding: orbax saves/restores per-shard, so
a sharded run checkpoints without gathering to one host.

Two donation hazards this module owns (measured on the installed
jax 0.4.37 CPU stack, pinned by tests/test_resilience.py):

* SAVE: orbax saves asynchronously, but the framework's advance donates
  its state argument — an in-flight async save reads the very buffer the
  next segment's advance reuses, and the checkpoint lands full of
  garbage (every mid-run save corrupted, measured). `run_segmented`
  therefore waits for each save to complete before advancing; the wait
  is also what makes the manifest sound (it hashes the files the save
  actually wrote).
* RESTORE: orbax-restored arrays can alias buffers XLA does not own
  exclusively; donating them straight into the jitted advance produces
  garbage. `restore_state` returns a defensive on-device copy, so its
  output is always donation-safe.

Manifest format (manifest-<step>.json next to orbax's step dir):
    {"step": int,
     "v": 2,
     "treedef": str(jax.tree_util.tree_structure(state)),
     "leaves": [{"shape": [...], "dtype": "...", "crc32": int|null}, ...],
     "files": {"<relpath under the step dir>": size_bytes, ...},
     "meta": {"mesh": {"dims": [...], "axes": [...]},
              "specs": [[axis|[axes]|null per array dim] | null, ...],
              "extra": {...caller fingerprint...}} | null}
crc32 is over the leaf's row-major host bytes; null for non-fully-
addressable (multi-host) leaves, where no single process sees the data.
Validation (latest_valid_step / verify_step) re-walks the step dir and
compares the file inventory — a truncated or missing file changes a size
— and restore_state(verify=True) re-hashes the restored leaves.

Topology portability (v2, docs/RESILIENCE.md "Elastic recovery"): the
`meta` block records the decomposition the state was saved under —
global shapes/dtypes already live in `leaves`, `meta` adds the mesh
(dims + axis names) and one partition spec per leaf. That makes the
checkpoint self-describing: `restore_state(dir, step, like=None)`
rebuilds the restore template from disk alone, planning the mesh for
whatever devices the RESUMED process has (resilience.reshard) — a run
checkpointed on (4,2) resumes on (2,2), (2,1), or (4,4), with shard
slabs re-sliced by orbax/tensorstore against the new shardings. A
caller-provided `like` that contradicts the manifest (leaf count,
global shape, dtype) raises TopologyMismatch — a clear refusal instead
of an orbax shape error. v1 manifests (pre-metadata) keep restoring
with a caller template, with a warning; a v2 manifest whose metadata
fails validation is treated as corrupt (latest_valid_step skips it).

Storage-fault plane (docs/RESILIENCE.md §7): checkpoint storage is the
one dependency this framework cannot supervise away — it flakes
(transient EIO on a network filesystem), it crawls (a throttled volume
turning every save into a multi-second stall), and it fills (ENOSPC).
Every save here runs under a `StoragePolicy`:

* transient `OSError`s get bounded retry + exponential backoff, each
  attempt visible as a `ckpt.retry` telemetry event;
* `ENOSPC` first prunes the keep-list (oldest kept steps beyond the
  newest one are deleted, `ckpt.enospc-prune`) and retries;
* a save that completes but exceeds `slow_save_timeout_s` trips the
  slow-write watchdog;
* when retries are exhausted (or the watchdog trips), `run_segmented`
  enters DEGRADED mode instead of crashing: compute continues, each
  boundary makes one cheap probe attempt (success exits degraded mode
  with `ckpt.recovered`; failure emits `ckpt.degraded` and skips), so a
  storage outage costs checkpoints — bounded by the last pre-outage
  valid step — never the run. The standalone `save_state` keeps the
  loud contract (retries, then raise); degraded mode is the segmented
  loop's, where "keep computing" is a meaningful alternative.

The same loop is preemption-aware (resilience.preempt): at every
segment boundary it polls for a SIGTERM grace deadline and either lands
one final save (if the measured p90 save wall fits the remaining grace)
or skips it — never starting a save the scheduler would SIGKILL
mid-write — then exits RC_PREEMPTED, which every supervisor upstack
classifies as resumable.
"""

from __future__ import annotations

import collections
import dataclasses
import errno
import json
import os
import pathlib
import shutil
import time
import zlib

from rocm_mpi_tpu.telemetry import enabled as _telemetry_enabled
from rocm_mpi_tpu.telemetry import flight as _flight
from rocm_mpi_tpu.telemetry import record_event as _record_event
from rocm_mpi_tpu.telemetry import span


def _drain(state) -> None:
    """Telemetry-enabled runs only: wait out in-flight compute on `state`
    before a checkpoint span opens — jax dispatch is async, so without
    the drain the save span would absorb whatever the donating advance
    left running and report compute time as checkpoint I/O."""
    if not _telemetry_enabled():
        return
    import jax

    from rocm_mpi_tpu.utils.metrics import force

    jax.tree_util.tree_map(force, state)


MANIFEST_VERSION = 2  # v2 = topology metadata (meta block); v1 = none


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed integrity validation (manifest mismatch)."""


class TopologyMismatch(ValueError):
    """The caller's restore template contradicts the checkpoint manifest
    (leaf count / global shape / dtype), or a template-less restore was
    asked of a checkpoint with no topology metadata. A ValueError on
    purpose: this is a configuration error that reproduces identically —
    the supervisor must surface it, never retry it."""


# ---------------------------------------------------------------------------
# Storage-fault plane (docs/RESILIENCE.md §7)
# ---------------------------------------------------------------------------

_FALSY = ("0", "off", "false", "no", "")

DEFAULT_SAVE_RETRIES = 2
DEFAULT_SAVE_BACKOFF_S = 0.25
DEFAULT_BACKOFF_FACTOR = 2.0
DEFAULT_RESTORE_RETRIES = 2

# Recent save walls (monotonic-diff seconds), feeding save_wall_p90():
# the preemption deadline call needs to know what a save COSTS before
# betting the remaining grace on one.
_SAVE_WALLS: collections.deque = collections.deque(maxlen=32)


def save_wall_p90() -> float | None:
    """Interpolating p90 of the recent save walls this process measured
    (None with no history) — the preemption emergency-save budget."""
    if not _SAVE_WALLS:
        return None
    vals = sorted(_SAVE_WALLS)
    if len(vals) == 1:
        return vals[0]
    pos = 0.9 * (len(vals) - 1)
    lo = int(pos)
    frac = pos - lo
    hi = min(lo + 1, len(vals) - 1)
    return vals[lo] * (1 - frac) + vals[hi] * frac


@dataclasses.dataclass
class StoragePolicy:
    """How a save responds to a misbehaving filesystem. The defaults
    harden every caller (bounded retries, degrade instead of crash in
    the segmented loop); `from_env` lets a launcher forward the policy
    to ranks without new plumbing (RMT_CKPT_* vars)."""

    retries: int = DEFAULT_SAVE_RETRIES
    backoff_s: float = DEFAULT_SAVE_BACKOFF_S
    backoff_factor: float = DEFAULT_BACKOFF_FACTOR
    slow_save_timeout_s: float | None = None
    degrade: bool = True  # run_segmented only: skip-save-and-continue
    probe_every: int = 1  # degraded mode: attempt every Nth boundary
    sleep: object = time.sleep  # injectable for tests

    @classmethod
    def from_env(cls) -> "StoragePolicy":
        def _num(name, cast, default):
            raw = os.environ.get(name, "").strip()
            if not raw:
                return default
            try:
                return cast(raw)
            except ValueError:
                return default

        return cls(
            retries=_num("RMT_CKPT_RETRIES", int, DEFAULT_SAVE_RETRIES),
            backoff_s=_num("RMT_CKPT_BACKOFF_S", float,
                           DEFAULT_SAVE_BACKOFF_S),
            slow_save_timeout_s=_num("RMT_CKPT_SLOW_S", float, None),
            degrade=os.environ.get("RMT_CKPT_DEGRADE", "1").lower()
            not in _FALSY,
            probe_every=max(_num("RMT_CKPT_PROBE_EVERY", int, 1), 1),
        )


class _StorageState:
    """Cross-save bookkeeping for one run_segmented loop: whether the
    run is in degraded (skip-save-and-continue) mode, how many saves the
    outage has cost, and the last step known durable on disk."""

    def __init__(self, last_durable=None):
        self.degraded = False
        self.skipped = 0
        self.boundaries_degraded = 0
        self.last_durable = last_durable


def _clean_partial_save(directory, step) -> None:
    """Remove a step dir a failed save attempt may have left: a torn
    step without a manifest is invisible to latest_valid_step, but it
    would make the retry's orbax save collide with the leftovers."""
    step_dir = _step_dir(directory, step)
    if step_dir.exists() and not _manifest_path(directory, step).is_file():
        shutil.rmtree(step_dir, ignore_errors=True)


def _prune_for_space(directory) -> list:
    """ENOSPC response: delete every kept checkpoint step EXCEPT the
    newest valid one (plus its manifest) to make room for the incoming
    save — an old checkpoint is worth strictly less than landing a new
    one, but the newest valid step must survive in case the retry fails
    too. Returns the pruned step numbers."""
    root = pathlib.Path(directory)
    if not root.is_dir():
        return []
    step_dirs = sorted(
        (d for d in root.iterdir() if d.is_dir() and d.name.isdigit()),
        key=lambda d: int(d.name),
    )
    keep_newest = None
    for d in reversed(step_dirs):
        ok, _ = _verify_step(directory, int(d.name))
        if ok:
            keep_newest = int(d.name)
            break
    pruned = []
    for d in step_dirs:
        step = int(d.name)
        if step == keep_newest:
            continue
        shutil.rmtree(d, ignore_errors=True)
        _manifest_path(directory, step).unlink(missing_ok=True)
        pruned.append(step)
    return pruned


def _save_once(mgr, directory, step, state) -> float:
    """One save ATTEMPT: fault point, orbax save-and-wait, manifest,
    stale-manifest prune. Returns the measured wall (seconds); raises
    OSError on an injected/real storage failure. The wall is recorded
    into the p90 history only for completed saves."""
    import orbax.checkpoint as ocp

    from rocm_mpi_tpu.resilience import faults

    t0 = time.monotonic()
    faults.fault_point("save", step=step, directory=directory)
    mgr.save(step, args=ocp.args.StandardSave(state))
    mgr.wait_until_finished()
    write_manifest(directory, step, state)
    _prune_stale_manifests(directory)
    wall = time.monotonic() - t0
    _SAVE_WALLS.append(wall)
    return wall


def _retrying_save(mgr, directory, step, state, policy: StoragePolicy,
                   log=None) -> float:
    """Save with the policy's bounded retry + backoff and ENOSPC
    pruning. Returns the final attempt's wall; raises the last OSError
    when every attempt failed (the caller decides whether that means
    degrade or crash). Every decision is a telemetry event."""
    attempt = 0
    pruned = False
    while True:
        try:
            return _save_once(mgr, directory, step, state)
        except OSError as exc:
            _clean_partial_save(directory, step)
            err = f"{type(exc).__name__}: {exc}"
            if getattr(exc, "errno", None) == errno.ENOSPC and not pruned:
                pruned = True
                freed = _prune_for_space(directory)
                _record_event("ckpt.enospc-prune", step=int(step),
                              pruned_steps=freed)
                if log is not None:
                    log(f"checkpoint step {step}: ENOSPC — pruned kept "
                        f"step(s) {freed} to make room, retrying")
                if freed:
                    continue  # space freed: retry without burning an attempt
            if attempt >= policy.retries:
                raise
            wait = policy.backoff_s * policy.backoff_factor**attempt
            _record_event("ckpt.retry", step=int(step), attempt=attempt,
                          wait_s=wait, error=err)
            if log is not None:
                log(f"checkpoint step {step}: save attempt {attempt} "
                    f"failed ({err}); retrying in {wait:.2f}s")
            policy.sleep(wait)
            attempt += 1


def _guarded_save(mgr, directory, step, state, policy: StoragePolicy,
                  st: _StorageState, log=None) -> bool:
    """The segmented loop's save: `_retrying_save` plus degraded-mode
    bookkeeping. Returns whether `step` is durable on disk.

    Degraded mode (entered when retries are exhausted, or when the
    slow-write watchdog trips): each boundary makes at most ONE cheap
    probe attempt (every `probe_every`th boundary) — a success that is
    also fast exits degraded mode (`ckpt.recovered`); anything else
    emits `ckpt.degraded` and the run keeps computing. The degraded
    decision is driven purely by (deterministic, injectable) save
    outcomes, so SPMD drills keep every rank's decision uniform."""
    if st.degraded:
        st.boundaries_degraded += 1
        if policy.probe_every > 1 and (
            st.boundaries_degraded % policy.probe_every
        ):
            st.skipped += 1
            _record_event("ckpt.degraded", step=int(step), reason="skip",
                          skipped=st.skipped,
                          last_valid_step=st.last_durable)
            _flight.progress(ckpt_skipped=1)
            return False
        try:
            wall = _save_once(mgr, directory, step, state)
        except OSError as exc:
            _clean_partial_save(directory, step)
            st.skipped += 1
            _record_event("ckpt.degraded", step=int(step),
                          reason="probe-failed",
                          error=f"{type(exc).__name__}: {exc}",
                          skipped=st.skipped,
                          last_valid_step=st.last_durable)
            _flight.progress(ckpt_skipped=1)
            if log is not None:
                log(f"checkpoint step {step}: storage still degraded "
                    f"({exc}); continuing without a save")
            return False
        st.last_durable = int(step)
        if policy.slow_save_timeout_s is not None \
                and wall > policy.slow_save_timeout_s:
            _record_event("ckpt.degraded", step=int(step), reason="io-slow",
                          wall_s=wall, skipped=st.skipped,
                          last_valid_step=st.last_durable)
            return True  # durable, but the storage is still crawling
        st.degraded = False
        _record_event("ckpt.recovered", step=int(step), skipped=st.skipped)
        # The monitor's degraded-storage indicator compares these two
        # cumulative counters on the heartbeat (telemetry.health): the
        # recovery bump is what clears the badge. Flushed NOW — a
        # counter-only bump doesn't force a heartbeat write, and a run
        # whose last boundary is the recovery would otherwise exit with
        # the stale DEGRADED badge on disk forever.
        _flight.progress(ckpt_recovered=1)
        _flight.flush()
        if log is not None:
            log(f"checkpoint step {step}: storage recovered after "
                f"{st.skipped} skipped save(s)")
        st.skipped = 0
        st.boundaries_degraded = 0
        return True

    try:
        wall = _retrying_save(mgr, directory, step, state, policy, log=log)
    except OSError as exc:
        if not policy.degrade:
            raise
        st.degraded = True
        st.skipped += 1
        _record_event("ckpt.degraded", step=int(step), reason="io-error",
                      error=f"{type(exc).__name__}: {exc}",
                      skipped=st.skipped, last_valid_step=st.last_durable)
        _flight.progress(ckpt_degraded=1, ckpt_skipped=1)
        _flight.flush()  # per-incident: the badge must land even if the
        # run's last boundary is the one that degraded
        if log is not None:
            log(f"checkpoint step {step}: save failed after "
                f"{policy.retries + 1} attempt(s) ({exc}); entering "
                f"DEGRADED mode — compute continues, loss bounded by "
                f"step {st.last_durable}")
        return False
    st.last_durable = int(step)
    if policy.slow_save_timeout_s is not None \
            and wall > policy.slow_save_timeout_s:
        st.degraded = True
        _record_event("ckpt.degraded", step=int(step), reason="io-slow",
                      wall_s=wall, timeout_s=policy.slow_save_timeout_s,
                      last_valid_step=st.last_durable)
        _flight.progress(ckpt_degraded=1)
        _flight.flush()
        if log is not None:
            log(f"checkpoint step {step}: save took {wall:.2f}s (> "
                f"{policy.slow_save_timeout_s:.2f}s watchdog); entering "
                "DEGRADED mode")
    return True


def _manager(directory, keep: int = 3):
    import orbax.checkpoint as ocp

    path = pathlib.Path(directory).resolve()
    path.mkdir(parents=True, exist_ok=True)
    return ocp.CheckpointManager(
        path, options=ocp.CheckpointManagerOptions(max_to_keep=keep)
    )


def _manifest_path(directory, step: int) -> pathlib.Path:
    return pathlib.Path(directory) / f"manifest-{int(step)}.json"


def _step_dir(directory, step: int) -> pathlib.Path:
    """Orbax CheckpointManager lays out saves as <directory>/<step>/."""
    return pathlib.Path(directory) / str(int(step))


def _leaf_entries(state):
    """Per-leaf (shape, dtype, crc32) records; crc32 None where no single
    process holds the whole array (multi-host shards)."""
    import jax
    import numpy as np

    entries = []
    for leaf in jax.tree_util.tree_leaves(state):
        if getattr(leaf, "is_fully_addressable", True):
            arr = np.asarray(leaf)
            entries.append(
                {
                    "shape": list(arr.shape),
                    "dtype": str(arr.dtype),
                    "crc32": zlib.crc32(np.ascontiguousarray(arr).tobytes()),
                }
            )
        else:
            entries.append(
                {
                    "shape": list(leaf.shape),
                    "dtype": str(leaf.dtype),
                    "crc32": None,
                }
            )
    return entries


def _file_inventory(step_dir: pathlib.Path) -> dict:
    return {
        str(p.relative_to(step_dir)): p.stat().st_size
        for p in sorted(step_dir.rglob("*"))
        if p.is_file()
    }


def write_manifest(directory, step: int, state, extra_meta=None) -> None:
    """Record the integrity manifest for a COMPLETED save at `step`.

    Must run after the save is durable (run_segmented waits first): the
    file inventory hashes what orbax actually wrote. Process-0-only on
    multi-host runs — one writer, one manifest.

    v2: the manifest also records the state's topology (mesh dims/axes +
    per-leaf partition specs, resilience.reshard.state_meta) so a resume
    can rebuild the restore template — on a DIFFERENT mesh — from disk
    alone. `extra_meta` (a JSON-able dict: physics/config fingerprint)
    rides along under meta.extra. Metadata is best-effort: a state whose
    shardings defy description saves a meta-less (v1-compatible)
    manifest with a warning rather than failing the save.
    """
    import jax

    if jax.process_index() != 0:
        return
    try:
        from rocm_mpi_tpu.resilience.reshard import state_meta

        meta = state_meta(state)
    except Exception as exc:  # noqa: BLE001 — durability over description
        import warnings

        warnings.warn(
            f"checkpoint step {step}: could not record topology metadata "
            f"({exc!r}); the save is valid but will only restore with a "
            "caller-provided template",
            stacklevel=2,
        )
        meta = None
    if meta is not None and extra_meta:
        meta["extra"] = dict(extra_meta)
    manifest = {
        "step": int(step),
        "v": MANIFEST_VERSION,
        "treedef": str(jax.tree_util.tree_structure(state)),
        "leaves": _leaf_entries(state),
        "files": _file_inventory(_step_dir(directory, step)),
        "meta": meta,
    }
    path = _manifest_path(directory, step)
    tmp = path.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(manifest, indent=1))
    tmp.replace(path)  # atomic: a crash mid-write cannot half-publish


def _prune_stale_manifests(directory) -> None:
    """Drop manifests whose step dir orbax already garbage-collected
    (max_to_keep): a manifest must never outlive — or vouch for — a
    checkpoint that is gone."""
    root = pathlib.Path(directory)
    for path in root.glob("manifest-*.json"):
        step = path.stem.rpartition("-")[2]
        if step.isdigit() and not (root / step).is_dir():
            try:
                path.unlink()
            except OSError:
                pass


def read_manifest(directory, step: int) -> dict | None:
    path = _manifest_path(directory, step)
    if not path.is_file():
        return None
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None  # unreadable/truncated manifest = no manifest


def validate_manifest_meta(manifest: dict) -> list[str]:
    """Structural validation of a manifest's topology metadata. Returns
    problem strings (empty = ok, including the meta-less v1 case — the
    legacy policy is latest_valid_step's business, not a schema error).
    stdlib-only on purpose: the telemetry schema gate
    (regress.check_schema via scripts/lint.sh) runs this on committed
    manifest artifacts without importing jax."""
    meta = manifest.get("meta")
    if meta is None:
        return []
    problems: list[str] = []
    if not isinstance(meta, dict):
        return ["meta: not a mapping"]
    mesh = meta.get("mesh")
    if not isinstance(mesh, dict):
        problems.append("meta.mesh: missing or not a mapping")
        mesh = {}
    dims = mesh.get("dims")
    axes = mesh.get("axes")
    if not (
        isinstance(dims, list)
        and dims
        and all(isinstance(d, int) and d >= 1 for d in dims)
    ):
        problems.append(f"meta.mesh.dims: want positive ints, got {dims!r}")
        dims = []
    if not (
        isinstance(axes, list)
        and all(isinstance(a, str) for a in axes)
        and len(axes) == len(dims)
    ):
        problems.append(
            f"meta.mesh.axes: want {len(dims)} axis name(s), got {axes!r}"
        )
        axes = []
    leaves = manifest.get("leaves", [])
    specs = meta.get("specs")
    if not isinstance(specs, list) or len(specs) != len(leaves):
        problems.append(
            f"meta.specs: want one spec per leaf ({len(leaves)}), got "
            f"{len(specs) if isinstance(specs, list) else specs!r}"
        )
        specs = []
    by_axis = dict(zip(axes, dims))
    for i, (rec, spec) in enumerate(zip(leaves, specs)):
        if spec is None:
            continue
        shape = rec.get("shape", [])
        if not isinstance(spec, list) or len(spec) != len(shape):
            problems.append(
                f"meta.specs[{i}]: want {len(shape)} entr(ies), got {spec!r}"
            )
            continue
        for d, (size, entry) in enumerate(zip(shape, spec)):
            if entry is None:
                continue
            names = entry if isinstance(entry, list) else [entry]
            factor = 1
            for name in names:
                if name not in by_axis:
                    problems.append(
                        f"meta.specs[{i}][{d}]: unknown mesh axis {name!r}"
                    )
                    break
                factor *= by_axis[name]
            else:
                if isinstance(size, int) and size % factor:
                    problems.append(
                        f"meta.specs[{i}][{d}]: global size {size} not "
                        f"divisible by mesh factor {factor}"
                    )
    return problems


def verify_step(directory, step: int) -> tuple[bool, str]:
    """Validate the checkpoint at `step` against its manifest WITHOUT
    restoring it: the step dir must exist and its file inventory must
    match the manifest byte-for-byte in names and sizes (a truncated,
    missing, or extra file all change the inventory). Returns
    (ok, reason). A step with no manifest reports ok=False with reason
    'no manifest' — latest_valid_step decides the legacy policy.
    """
    with span("checkpoint.validate", step=int(step)):
        return _verify_step(directory, step)


def _verify_step(directory, step: int) -> tuple[bool, str]:
    step_dir = _step_dir(directory, step)
    if not step_dir.is_dir():
        return False, f"step dir {step_dir} missing"
    manifest = read_manifest(directory, step)
    if manifest is None:
        return False, "no manifest"
    if manifest.get("step") != int(step):
        return False, f"manifest step field {manifest.get('step')} != {step}"
    want = manifest.get("files", {})
    have = _file_inventory(step_dir)
    if want != have:
        missing = sorted(set(want) - set(have))
        extra = sorted(set(have) - set(want))
        resized = sorted(
            k for k in set(want) & set(have) if want[k] != have[k]
        )
        return False, (
            f"file inventory mismatch (missing={missing[:3]}, "
            f"extra={extra[:3]}, resized={resized[:3]})"
        )
    meta_problems = validate_manifest_meta(manifest)
    if meta_problems:
        # Garbage topology metadata is corruption like any other: a
        # template-less resume would plan a mesh from it. Fall back to
        # the previous kept step (latest_valid_step skips this one).
        return False, (
            f"topology metadata failed validation ({meta_problems[0]}"
            + (f", +{len(meta_problems) - 1} more" if len(meta_problems) > 1
               else "")
            + ")"
        )
    return True, "ok"


def latest_step(directory) -> int | None:
    """The newest checkpointed step in `directory` (no validation), or
    None. Prefer latest_valid_step for resume decisions."""
    path = pathlib.Path(directory)
    if not path.is_dir():
        return None
    mgr = _manager(path)
    step = mgr.latest_step()
    mgr.close()
    return step


def all_steps(directory) -> list:
    path = pathlib.Path(directory)
    if not path.is_dir():
        return []
    mgr = _manager(path)
    steps = sorted(mgr.all_steps())
    mgr.close()
    return steps


def latest_valid_step(directory, log=None) -> int | None:
    """The newest checkpointed step that passes integrity validation,
    falling back through older kept steps past corrupt/truncated ones.

    Policy for manifest-less steps: when the directory has NO manifests
    at all it predates the integrity layer — every step is trusted
    (legacy behavior, = latest_step). When any manifest exists, a step
    without one is an incomplete save (the manifest is written after the
    save completes) and is skipped.

    `log` (callable, e.g. log0) receives one line per rejected step, so
    a fallback is never silent.
    """
    steps = all_steps(directory)
    if not steps:
        return None
    legacy = not any(
        _manifest_path(directory, s).is_file() for s in steps
    )
    for step in reversed(steps):
        ok, reason = verify_step(directory, step)
        if ok or (legacy and reason == "no manifest"):
            return step
        if log is not None:
            log(
                f"checkpoint step {step} failed validation ({reason}); "
                "falling back to the previous kept step"
            )
    return None


def save_state(directory, step: int, state, keep: int = 3,
               storage: StoragePolicy | None = None) -> None:
    """Save `state` (any pytree of jax arrays — sharded arrays keep their
    sharding) labeled by absolute step count, then record its manifest.

    Runs under the storage-fault policy (default StoragePolicy.from_env):
    transient OSErrors retry with backoff, ENOSPC prunes the keep-list
    first. This one-shot API stays LOUD — exhausted retries re-raise;
    degraded skip-save-and-continue belongs to run_segmented, where
    there is a run to keep alive."""
    policy = storage or StoragePolicy.from_env()
    _drain(state)
    with span("checkpoint.save", step=int(step)):
        mgr = _manager(directory, keep)
        try:
            _retrying_save(mgr, directory, step, state, policy)
        finally:
            mgr.close()


def restore_state(directory, step: int, like=None, verify: bool = True,
                  devices=None):
    """Restore the pytree saved at `step`.

    `like` is the abstract template (pass the freshly-initialized state —
    shapes, dtypes, and shardings are taken from it, so a restored run
    lands exactly where the initializer would have put it). Since v2
    manifests it is OPTIONAL: with `like=None` the restore template is
    rebuilt from the manifest's topology metadata alone, sharded over a
    mesh planned for the current `devices` (default jax.devices(),
    resilience.reshard.template_from_meta) — possibly a DIFFERENT mesh
    than the save's; orbax re-slices the shard slabs against the new
    shardings. The metadata path returns a TUPLE of leaves in tree
    order (the framework's state convention). A template-less restore of
    a pre-metadata (v1) checkpoint raises TopologyMismatch; a `like`
    that contradicts the manifest (leaf count / global shape / dtype)
    raises TopologyMismatch too — a different MESH in `like` is not a
    mismatch, it is the elastic-resume path.

    verify=True re-hashes every fully-addressable restored leaf against
    the manifest's crc32 (when a manifest exists) and raises
    CheckpointCorruptionError on mismatch — bit rot between save and
    restore cannot silently continue the run.

    The returned pytree is a defensive on-device copy: orbax-restored
    arrays can alias buffers XLA does not own exclusively, and donating
    such an array into a jitted advance produced garbage on this stack
    (measured; tests/test_resilience.py pins the safe behavior).
    """
    with span("checkpoint.restore", step=int(step)):
        return _restore_body(directory, step, like, verify, devices)


def _check_like_against_manifest(like, manifest, step) -> None:
    """TopologyMismatch when `like` contradicts the manifest's GLOBAL
    facts (leaf count, global shape, dtype). Shardings are deliberately
    not compared: restoring onto a different mesh is the point."""
    import jax

    leaves = jax.tree_util.tree_leaves(like)
    want = manifest.get("leaves", [])
    if len(want) != len(leaves):
        raise TopologyMismatch(
            f"step {step}: template has {len(leaves)} leaves, manifest "
            f"records {len(want)} — was this checkpoint written by a "
            "different workload/state layout?"
        )
    for i, (leaf, rec) in enumerate(zip(leaves, want)):
        shape = tuple(int(n) for n in rec.get("shape", []))
        if tuple(leaf.shape) != shape:
            raise TopologyMismatch(
                f"step {step} leaf {i}: template global shape "
                f"{tuple(leaf.shape)} != checkpointed {shape} — the mesh "
                "may change on resume, the global domain may not"
            )
        if str(leaf.dtype) != rec.get("dtype"):
            raise TopologyMismatch(
                f"step {step} leaf {i}: template dtype {leaf.dtype} != "
                f"checkpointed {rec.get('dtype')}"
            )


def _restore_body(directory, step, like, verify, devices=None):
    import warnings

    import jax
    import jax.numpy as jnp
    import numpy as np
    import orbax.checkpoint as ocp

    manifest = read_manifest(directory, step)
    as_tuple = False
    if like is None:
        if manifest is None or not manifest.get("meta"):
            raise TopologyMismatch(
                f"step {step}: template-less restore needs a manifest "
                "with topology metadata (v2); this checkpoint predates "
                "it — pass `like` (the freshly-initialized state)"
            )
        meta_problems = validate_manifest_meta(manifest)
        if meta_problems:
            raise CheckpointCorruptionError(
                f"step {step}: topology metadata failed validation: "
                f"{meta_problems[0]}"
            )
        from rocm_mpi_tpu.resilience.reshard import template_from_meta

        template = template_from_meta(manifest, devices=devices)
        as_tuple = True
    else:
        if manifest is not None:
            _check_like_against_manifest(like, manifest, step)
            if not manifest.get("meta"):
                warnings.warn(
                    f"checkpoint step {step} has a v1 (pre-topology-"
                    "metadata) manifest: restoring with the caller "
                    "template; same-mesh resume only — re-save to "
                    "upgrade it for elastic recovery",
                    stacklevel=3,
                )
        template = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(
                a.shape, a.dtype, sharding=a.sharding
            ),
            like,
        )
    # Bounded retry on transient OSError: a restore reads many files
    # through the same flaky storage the saves write (the "restore"
    # fault point drills it). Corruption/topology refusals are NOT
    # OSErrors and surface immediately.
    from rocm_mpi_tpu.resilience import faults

    mgr = _manager(directory)
    attempt = 0
    try:
        while True:
            try:
                faults.fault_point("restore", step=int(step),
                                   directory=directory)
                out = mgr.restore(
                    step, args=ocp.args.StandardRestore(template)
                )
                break
            except OSError as exc:
                if attempt >= DEFAULT_RESTORE_RETRIES:
                    raise
                wait = DEFAULT_SAVE_BACKOFF_S * DEFAULT_BACKOFF_FACTOR**attempt
                _record_event("ckpt.retry", step=int(step), attempt=attempt,
                              wait_s=wait, op="restore",
                              error=f"{type(exc).__name__}: {exc}")
                time.sleep(wait)
                attempt += 1
    finally:
        mgr.close()
    if as_tuple:
        out = tuple(out)
    if verify:
        if manifest is not None:
            leaves = jax.tree_util.tree_leaves(out)
            want = manifest.get("leaves", [])
            if len(want) != len(leaves):
                raise CheckpointCorruptionError(
                    f"step {step}: manifest records {len(want)} leaves, "
                    f"restored {len(leaves)}"
                )
            for i, (leaf, rec) in enumerate(zip(leaves, want)):
                if rec.get("crc32") is None:
                    continue
                if not getattr(leaf, "is_fully_addressable", True):
                    continue
                arr = np.asarray(leaf)
                crc = zlib.crc32(np.ascontiguousarray(arr).tobytes())
                if crc != rec["crc32"]:
                    raise CheckpointCorruptionError(
                        f"step {step} leaf {i}: crc32 {crc} != manifest "
                        f"{rec['crc32']} — restored data is corrupt"
                    )
    return jax.tree_util.tree_map(jnp.copy, out)


def run_segmented(
    advance,
    state,
    nt: int,
    directory,
    every: int,
    start_step: int = 0,
    keep: int = 3,
    storage: StoragePolicy | None = None,
):
    """Advance `state` by `nt - start_step` steps, checkpointing every
    `every` steps (and at the end). `advance(state, n) -> state` must
    accept a traced step count — the framework's standard advance
    contract — so every segment reuses one compiled program. Returns the
    final state.

    Each save COMPLETES (wait_until_finished) before the next segment
    runs: the framework's advance donates its state buffer, and on this
    stack an in-flight async save reads the donated-and-reused buffer —
    every mid-run checkpoint was measured corrupt under the old
    overlapped design. The completed save is then manifested, which is
    what latest_valid_step validates on resume.

    Saves run under `storage` (default StoragePolicy.from_env): bounded
    retry/backoff on OSError, ENOSPC keep-list pruning, the slow-write
    watchdog, and degraded skip-save-and-continue mode — a storage
    outage costs checkpoints (loss bounded by the last valid step),
    never the run (module docstring; docs/RESILIENCE.md §7).

    Preemption (resilience.preempt): each boundary polls the SIGTERM
    grace deadline. When preempted, the boundary save happens only if
    the measured p90 save wall fits the remaining grace — else it is
    skipped outright (a save SIGKILLed mid-write is a torn artifact) —
    and the loop raises `Preempted` (SystemExit RC_PREEMPTED), which
    supervisors classify as resumable.

    Fault-injection hook: resilience.faults.fault_point("segment", ...)
    fires after every completed save, so crash-at-step-k and
    truncate-latest faults exercise this exact loop (tests/
    test_resilience.py); the opt-in "save" site fires inside every save
    attempt (storage kinds: io-error / io-slow / enospc).

    Resume idiom (what the apps' --resume flag does):

        start = latest_valid_step(dir) or 0
        state = restore_state(dir, start, init_state) if start else init_state
        state = run_segmented(advance, state, nt, dir, every, start)
    """
    from rocm_mpi_tpu.resilience import faults
    from rocm_mpi_tpu.resilience import preempt as _preempt

    if every < 1:
        raise ValueError(f"checkpoint interval must be >= 1, got {every}")
    if not 0 <= start_step <= nt:
        raise ValueError(f"need 0 <= start_step <= nt, got {start_step}, {nt}")
    policy = storage or StoragePolicy.from_env()
    st = _StorageState(last_durable=start_step if start_step else None)
    mgr = _manager(directory, keep)
    try:
        step = start_step
        while step < nt:
            n = min(every, nt - step)
            state = advance(state, n)
            step += n
            _drain(state)
            # Opt-in pre-save fault site (at=segment-pre): after the
            # segment's collectives, BEFORE the progress bump and the
            # save barrier — a rank stalled here lags the counters its
            # peers are about to publish, which is what lets the
            # watchdog name it (a post-save stall freezes every peer
            # inside the next segment's collective at the same count).
            faults.fault_point("segment-pre", step=step,
                               directory=directory)
            # Health-plane progress bump (no-op unless the flight
            # recorder is armed), BEFORE the save's blocking collective:
            # a rank wedged in the save barrier must already have
            # published the step it reached, or the watchdog's
            # stalled-vs-median signature cannot name the victim
            # (telemetry.flight module docstring has the ordering
            # contract).
            _flight.progress(step=step)
            if _preempt.requested():
                if _preempt.note_noticed():
                    _record_event("preempt.noticed", step=step,
                                  remaining_grace_s=(
                                      _preempt.remaining_grace_s()))
                rem = _preempt.remaining_grace_s()
                p90 = save_wall_p90()
                if _preempt.budget_allows_save(p90):
                    # The emergency save IS the boundary save, just
                    # deadline-shaped: one attempt, no backoff — a
                    # retry schedule has no place inside a grace window.
                    _record_event("preempt.save", step=step,
                                  remaining_grace_s=rem,
                                  save_wall_p90_s=p90)
                    try:
                        with span("checkpoint.save", step=step):
                            _save_once(mgr, directory, step, state)
                    except OSError as exc:
                        _clean_partial_save(directory, step)
                        _record_event(
                            "preempt.save-failed", step=step,
                            error=f"{type(exc).__name__}: {exc}",
                            last_valid_step=st.last_durable)
                        raise _preempt.Preempted(st.last_durable,
                                                 saved=False) from None
                    raise _preempt.Preempted(step, saved=True)
                _record_event("preempt.skip-save", step=step,
                              remaining_grace_s=rem, save_wall_p90_s=p90,
                              last_valid_step=st.last_durable)
                raise _preempt.Preempted(st.last_durable, saved=False)
            with span("checkpoint.save", step=step):
                durable = _guarded_save(mgr, directory, step, state,
                                        policy, st)
            faults.fault_point("segment", step=step, directory=directory)
            if _preempt.requested():
                # The notice landed while we were inside the save (or
                # the post-save fault point): the boundary just
                # published is the resume point — exit now instead of
                # betting another whole segment against the deadline.
                if _preempt.note_noticed():
                    _record_event("preempt.noticed", step=step,
                                  remaining_grace_s=(
                                      _preempt.remaining_grace_s()))
                _record_event("preempt.stop", step=step,
                              saved=bool(durable),
                              last_valid_step=st.last_durable)
                raise _preempt.Preempted(
                    step if durable else st.last_durable,
                    saved=bool(durable))
    finally:
        mgr.close()
    return state
