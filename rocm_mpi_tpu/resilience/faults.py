"""Deterministic fault injection (docs/RESILIENCE.md §3).

Three rounds of accelerator outage (165 failed probes over ~11.5 h,
docs/chip_watcher_r5.log) made failure this framework's most common
input — so failure must be INJECTABLE, deterministically, at the exact
points the resilience layer defends, or its recovery paths are dead code
until the next real outage tests them in production.

A fault plan is a comma-separated spec, from the `--inject-fault` app
flag or the RMT_INJECT_FAULT env var (the launcher forwards it to every
rank):

    crash@step=K            raise InjectedCrash at the step-K fault point
    crash@segment=N         raise at the Nth completed segment (1-based)
    kill@step=K             os._exit(RC_INJECTED_KILL) at step K — the
                            no-cleanup SIGKILL analog (mid-collective
                            peers are left hanging; the launcher's
                            first-failure reporting is the defense)
    die@step=K              os._exit(0) at step K — the rank VANISHES
                            with a clean exit code: no crash, no
                            post-mortem, no nonzero rc for the
                            launcher's first-failure scan to see. The
                            preempted-pod / evicted-container analog,
                            distinct from `kill` (nonzero rc) and
                            `stall` (still alive). Only the launcher's
                            vanish detection (spawn_ranks
                            vanish_grace_s) and the elastic supervisor
                            (docs/RESILIENCE.md "Elastic recovery")
                            handle it
    truncate-latest         after the next completed save, truncate the
                            largest file of the newest checkpoint step
    delay=S@step=K          sleep S seconds at step K (flapping-tunnel
                            stall analog; exercises heartbeat reporting)
    stall@step=K            block FOREVER in a time.monotonic busy-wait
                            at step K — the wedged-in-a-collective
                            analog. Unlike `delay` it never resumes, so
                            it is the only kind that exercises the
                            health-plane watchdog's full detect → dump →
                            kill path (parallel/launcher.py): the
                            stalled rank stops bumping its flight
                            recorder while its peers advance and then
                            wedge behind it

Any clause may be rank-scoped with `rank=R`:

    kill@step=4,rank=1      only process R injects (other ranks run clean)

and site-scoped with `at=SITE` (SITE = an instrumented fault-point name
below). An unscoped clause fires at the FIRST site that matches its
step — the legacy semantics; `at=` pins it to one site when the same
step count passes several. The elastic stall drill needs this:

    stall@step=8,rank=1,at=segment-pre

wedges rank 1 after the segment's collectives but BEFORE its progress
bump and the save barrier, so its peers bump PAST it and the watchdog's
stalled-vs-median signature names the right victim (an unscoped stall
at the post-save "segment" site freezes every peer inside the next
segment's collective at the same counter — the coordinated-slowness
shape the watchdog deliberately never flags).

Every trigger is exact-match ("crash at step K", not "at or after"):
a supervisor retry that re-runs past the same step must NOT re-fire the
fault, so `fault_point` arms each clause at most MAX_FIRES times per
process (default once). Determinism is the whole point: no randomness,
no wall-clock dependence (delays excepted, by definition).

Instrumented fault points:
    "segment"  — utils/checkpoint.run_segmented, after each completed
                 save (step = absolute step count, directory = ckpt dir)
    "segment-pre" — utils/checkpoint.run_segmented, after a segment's
                 advance but BEFORE the flight-recorder step bump and
                 the save (same step count the following save will
                 carry). OPT-IN: only `at=segment-pre` clauses fire
                 here — unscoped step clauses keep firing at the
                 post-save "segment" site exactly as before this site
                 existed, so legacy specs are unchanged
    "init"     — parallel/distributed.maybe_initialize_distributed,
                 before jax.distributed.initialize (step = None)
    "window"   — apps/weak_scaling.telemetry_windowed_run, at each
                 window boundary AFTER the halo heartbeat probe and
                 BEFORE the flight-recorder step bump (step = steps
                 completed so far) — the ordering the health-plane
                 watchdog drill relies on (docs/TELEMETRY.md)
    "step"     — parallel/halo.HostStagedStepper.run, before each
                 host-staged step (step = 1-based step index)
"""

from __future__ import annotations

import os
import time

RC_INJECTED_KILL = 43  # distinctive rc: a killed rank is diagnosable
RC_INJECTED_DIE = 0  # the point of `die`: the exit code says nothing
ENV_VAR = "RMT_INJECT_FAULT"

# Sites that only fire for clauses explicitly scoped there (at=SITE):
# they share step numbering with an adjacent legacy site, and an
# unscoped clause must keep firing at the legacy one.
OPTIN_SITES = frozenset({"segment-pre"})


class InjectedCrash(RuntimeError):
    """The injected failure run_supervised retries around."""


class FaultClause:
    __slots__ = ("kind", "step", "segment", "rank", "delay_s", "site",
                 "fires")

    def __init__(self, kind, step=None, segment=None, rank=None,
                 delay_s=0.0, site=None):
        self.kind = kind
        self.step = step
        self.segment = segment
        self.rank = rank
        self.delay_s = delay_s
        self.site = site
        self.fires = 0

    def __repr__(self):
        parts = [self.kind]
        if self.step is not None:
            parts.append(f"step={self.step}")
        if self.segment is not None:
            parts.append(f"segment={self.segment}")
        if self.rank is not None:
            parts.append(f"rank={self.rank}")
        if self.site is not None:
            parts.append(f"at={self.site}")
        if self.delay_s:
            parts.append(f"delay={self.delay_s}")
        return f"FaultClause({', '.join(parts)})"


def _parse_clause(raw: str) -> FaultClause:
    head, *mods = [p.strip() for p in raw.split(",")]
    kind, _, trigger = head.partition("@")
    kind = kind.strip()
    delay_s = 0.0
    if kind.startswith("delay="):
        delay_s = float(kind[len("delay="):])
        kind = "delay"
    if kind not in ("crash", "kill", "die", "truncate-latest", "delay",
                    "stall"):
        raise ValueError(f"unknown fault kind {kind!r} in {raw!r}")
    clause = FaultClause(kind, delay_s=delay_s)
    triggers = [t for t in [trigger.strip()] + mods if t]
    for t in triggers:
        key, _, val = t.partition("=")
        key = key.strip()
        if key == "step":
            clause.step = int(val)
        elif key == "segment":
            clause.segment = int(val)
        elif key == "rank":
            clause.rank = int(val)
        elif key == "at":
            clause.site = val.strip()
        else:
            raise ValueError(f"unknown fault trigger {t!r} in {raw!r}")
    if kind in ("crash", "kill", "die", "delay", "stall") \
            and clause.step is None and clause.segment is None:
        raise ValueError(
            f"{kind} fault needs a step=K or segment=N trigger: {raw!r}"
        )
    return clause


class FaultPlan:
    """Parsed, armed fault clauses; fault_point() consults the installed
    plan. MAX_FIRES guards the retry path: a recovered-and-re-run step
    must not re-fire its fault."""

    MAX_FIRES = 1

    def __init__(self, clauses):
        self.clauses = list(clauses)
        self._segments_seen = 0

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        # Clause separator is ';' so ',' stays free for modifiers.
        clauses = [
            _parse_clause(part)
            for part in spec.split(";")
            if part.strip()
        ]
        return cls(clauses)

    def __bool__(self):
        return bool(self.clauses)


_PLAN: FaultPlan | None = None
_ENV_CONSUMED = False  # the env spec installs at most once per process


def _rank() -> int:
    """This process's rank — parallel.distributed.process_id, which never
    forces backend init (fault bookkeeping must not be what initializes
    a backend). Lazy import: distributed's init path calls fault_point."""
    from rocm_mpi_tpu.parallel.distributed import process_id

    return process_id()


def install(spec: str | None) -> FaultPlan | None:
    """Install (or with None/'' clear) the process-wide fault plan. An
    explicit install wins over — and permanently supersedes — the env
    spec (a cleared plan stays cleared)."""
    global _PLAN, _ENV_CONSUMED
    _ENV_CONSUMED = True
    _PLAN = FaultPlan.parse(spec) if spec else None
    return _PLAN


def install_from_env() -> FaultPlan | None:
    """Install the plan from RMT_INJECT_FAULT, at most once per process;
    cheap when the var is unset (the common case pays one getenv)."""
    global _ENV_CONSUMED
    if _ENV_CONSUMED:
        return _PLAN
    spec = os.environ.get(ENV_VAR, "").strip()
    if spec:
        install(spec)
    else:
        _ENV_CONSUMED = True
    return _PLAN


def active_plan() -> FaultPlan | None:
    return _PLAN


def _truncate_latest(directory) -> None:
    """Truncate the largest file of the NEWEST checkpoint step dir —
    the torn-write the integrity manifest must catch. Pure pathlib (no
    checkpoint-module import: checkpoint imports us)."""
    import pathlib

    root = pathlib.Path(directory)
    step_dirs = sorted(
        (d for d in root.iterdir() if d.is_dir() and d.name.isdigit()),
        key=lambda d: int(d.name),
    )
    if not step_dirs:
        return
    files = sorted(
        (f for f in step_dirs[-1].rglob("*") if f.is_file()),
        key=lambda f: f.stat().st_size,
    )
    if not files:
        return
    target = files[-1]
    size = target.stat().st_size
    with target.open("r+b") as fh:
        fh.truncate(max(size // 2, 0))


def fault_point(name: str, step=None, directory=None) -> None:
    """Instrumentation hook: a no-op without an installed/env plan.

    `name` identifies the instrumented site; `step` the absolute step
    count where meaningful; `directory` the checkpoint dir (needed by
    truncate-latest).
    """
    plan = install_from_env()
    if not plan:
        return
    if name == "segment":
        plan._segments_seen += 1
    rank = _rank()
    for clause in plan.clauses:
        if clause.fires >= plan.MAX_FIRES:
            continue
        if clause.rank is not None and clause.rank != rank:
            continue
        if clause.site is not None:
            if clause.site != name:
                continue
        elif name in OPTIN_SITES:
            # Opt-in sites never match unscoped clauses: a legacy spec's
            # step trigger must keep firing where it always fired.
            continue
        hit = False
        if clause.step is not None:
            hit = step is not None and int(step) == clause.step
        elif clause.segment is not None:
            hit = name == "segment" and plan._segments_seen == clause.segment
        elif clause.kind == "truncate-latest":
            hit = name == "segment" and directory is not None
        if not hit:
            continue
        clause.fires += 1
        if clause.kind == "delay":
            time.sleep(clause.delay_s)
        elif clause.kind == "stall":
            # The wedged rank: a pure-Python monotonic busy-wait that
            # never exits. Deliberately NOT a sleep — the interpreter
            # keeps executing bytecode, so daemon threads (telemetry
            # drains) stay live and the process looks exactly like a
            # rank spinning inside a stuck collective: alive by wall
            # clock, dead by progress. Only the watchdog's kill (or the
            # launcher timeout) ends it.
            while True:  # pragma: no branch — exit is the kill signal
                time.monotonic()
        elif clause.kind == "truncate-latest":
            if directory is not None:
                _truncate_latest(directory)
        elif clause.kind == "kill":
            os._exit(RC_INJECTED_KILL)  # noqa: SLF001 — the point: no cleanup
        elif clause.kind == "die":
            # The vanished rank: a CLEAN exit mid-run. No exception, no
            # post-mortem, rc 0 — everything downstream must infer death
            # from the peers it orphaned, which is exactly the path the
            # elastic drills need to exercise deterministically.
            os._exit(RC_INJECTED_DIE)  # noqa: SLF001 — no cleanup either
        elif clause.kind == "crash":
            raise InjectedCrash(
                f"injected crash at fault point {name!r} "
                f"(step={step}, rank={rank})"
            )
