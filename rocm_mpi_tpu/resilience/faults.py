"""Deterministic fault injection (docs/RESILIENCE.md §3).

Three rounds of accelerator outage (165 failed probes over ~11.5 h,
docs/chip_watcher_r5.log) made failure this framework's most common
input — so failure must be INJECTABLE, deterministically, at the exact
points the resilience layer defends, or its recovery paths are dead code
until the next real outage tests them in production.

A fault plan is a comma-separated spec, from the `--inject-fault` app
flag or the RMT_INJECT_FAULT env var (the launcher forwards it to every
rank):

    crash@step=K            raise InjectedCrash at the step-K fault point
    crash@segment=N         raise at the Nth completed segment (1-based)
    kill@step=K             os._exit(RC_INJECTED_KILL) at step K — the
                            no-cleanup SIGKILL analog (mid-collective
                            peers are left hanging; the launcher's
                            first-failure reporting is the defense)
    die@step=K              os._exit(0) at step K — the rank VANISHES
                            with a clean exit code: no crash, no
                            post-mortem, no nonzero rc for the
                            launcher's first-failure scan to see. The
                            preempted-pod / evicted-container analog,
                            distinct from `kill` (nonzero rc) and
                            `stall` (still alive). Only the launcher's
                            vanish detection (spawn_ranks
                            vanish_grace_s) and the elastic supervisor
                            (docs/RESILIENCE.md "Elastic recovery")
                            handle it
    truncate-latest         after the next completed save, truncate the
                            largest file of the newest checkpoint step
    delay=S@step=K          sleep S seconds at step K (flapping-tunnel
                            stall analog; exercises heartbeat reporting)
    stall@step=K            block FOREVER in a time.monotonic busy-wait
                            at step K — the wedged-in-a-collective
                            analog. Unlike `delay` it never resumes, so
                            it is the only kind that exercises the
                            health-plane watchdog's full detect → dump →
                            kill path (parallel/launcher.py): the
                            stalled rank stops bumping its flight
                            recorder while its peers advance and then
                            wedge behind it
    io-error@step=K         raise OSError(EIO) at the step-K save
                            attempt — the flaky-storage analog the
                            checkpoint retry/backoff and degraded mode
                            defend (docs/RESILIENCE.md §7). Fires at the
                            "save" site by default (see below)
    io-slow=S@step=K        sleep S seconds inside the step-K save
                            attempt (default 2.0 s when the duration is
                            omitted) — trips the slow-write watchdog
                            (StoragePolicy.slow_save_timeout_s) without
                            failing the save
    enospc@step=K           raise OSError(ENOSPC) at the step-K save
                            attempt — exercises the keep-list pruning
                            path before the save gives up

Serving-plane kinds (docs/SERVING.md "SLOs and admission"; consumed by
serving/service.py and apps/soak.py through `serving_fault`, never by
the raising `fault_point` below — the caller interprets the clause):

    lane-nan@request=N      poison the lane carrying the Nth SUBMITTED
                            request (1-based ticket ordinal) with NaN
                            initial state — the numerical-poison drill:
                            the per-lane finiteness reduction must fail
                            ONLY that ticket, and `times=` large enough
                            to outlast the retry budget drives it into
                            quarantine
    batch-error@step=N      the Nth EXECUTED batch raises a transient
                            batch-level error before dispatch — the
                            retry-budget/backoff drill (times=1 makes
                            the first retry succeed; consecutive clauses
                            open the circuit breaker)
    slow-batch=S@step=N     sleep S seconds inside the Nth executed
                            batch (default 0.5 s) — the straggler-batch
                            analog that makes co-batched tenants miss
                            deadlines they'd otherwise clear
    queue-flood=M@step=N    at the Nth DRAIN boundary the driver
                            (apps/soak.py) submits M synthetic requests
                            at once (default 16) — the admission-
                            control drill: a bounded queue must reject
                            the overflow fast with a retry-after hint

Fleet-plane kinds (docs/SERVING.md "The fleet"; consumed by the fleet
router's drive loop through `replica_fault`, never by the raising
`fault_point` — `rank=` names the REPLICA id, not a process rank):

    replica-kill@step=K,rank=R   at the Kth fleet drive tick, replica
                            R dies without cleanup (the SIGKILL /
                            rc-75 / watchdog-verdict analog): its
                            queue counters are gone, and only the
                            router's ticket journal can prove what it
                            owed — the replay-reconciliation drill
    replica-stall@step=K,rank=R  at the Kth drive tick replica R stops
                            making progress but stays up — the
                            wedged-replica analog: the router's health
                            view must DEMOTE it (no new routes) and
                            re-route its pending tickets exactly as
                            for a kill, while its frozen state stays
                            readable

The infrastructure kinds compose with serving through the opt-in
`serve-batch` site: `kill@step=2,rank=1,at=serve-batch` kills rank 1
before the 2nd batch's collectives (step = the service's global batch
ordinal; the flight-recorder step bump happens AFTER this fault point,
so a stalled rank is named BY PROGRESS exactly as in the segment-pre
drill).

Storage kinds re-fire per ATTEMPT: the save retry loop re-runs the
"save" fault point, so a clause with `times=N` (see below) can defeat N
attempts — `io-error@step=8,times=3` exhausts a 2-retry save and drives
the run into degraded mode, while the default times=1 makes the FIRST
retry succeed (the transient-flap drill). An outage spanning several
saves is several clauses: `io-error@step=8,times=3;io-error@step=12,
times=3`. NOTE the SPMD hazard: a save is collective — storage clauses
in multi-rank drills should stay UNSCOPED (every rank injects the same
decision at the same step) so no rank enters a save barrier its peers
skipped; rank= scoping of storage kinds is for single-rank drills.

Any clause may be re-armed with `times=N` (fire up to N times instead
of the default once) and rank-scoped with `rank=R`:

    kill@step=4,rank=1      only process R injects (other ranks run clean)

and site-scoped with `at=SITE` (SITE = an instrumented fault-point name
below). An unscoped clause fires at the FIRST site that matches its
step — the legacy semantics; `at=` pins it to one site when the same
step count passes several. The elastic stall drill needs this:

    stall@step=8,rank=1,at=segment-pre

wedges rank 1 after the segment's collectives but BEFORE its progress
bump and the save barrier, so its peers bump PAST it and the watchdog's
stalled-vs-median signature names the right victim (an unscoped stall
at the post-save "segment" site freezes every peer inside the next
segment's collective at the same counter — the coordinated-slowness
shape the watchdog deliberately never flags).

Every trigger is exact-match ("crash at step K", not "at or after"):
a supervisor retry that re-runs past the same step must NOT re-fire the
fault, so `fault_point` arms each clause at most MAX_FIRES times per
process (default once). Determinism is the whole point: no randomness,
no wall-clock dependence (delays excepted, by definition).

Instrumented fault points:
    "segment"  — utils/checkpoint.run_segmented, after each completed
                 save (step = absolute step count, directory = ckpt dir)
    "segment-pre" — utils/checkpoint.run_segmented, after a segment's
                 advance but BEFORE the flight-recorder step bump and
                 the save (same step count the following save will
                 carry). OPT-IN: only `at=segment-pre` clauses fire
                 here — unscoped step clauses keep firing at the
                 post-save "segment" site exactly as before this site
                 existed, so legacy specs are unchanged
    "init"     — parallel/distributed.maybe_initialize_distributed,
                 before jax.distributed.initialize (step = None)
    "window"   — apps/weak_scaling.telemetry_windowed_run, at each
                 window boundary AFTER the halo heartbeat probe and
                 BEFORE the flight-recorder step bump (step = steps
                 completed so far) — the ordering the health-plane
                 watchdog drill relies on (docs/TELEMETRY.md)
    "step"     — parallel/halo.HostStagedStepper.run, before each
                 host-staged step (step = 1-based step index)
    "save"     — utils/checkpoint, inside every save ATTEMPT (retries
                 re-fire it) before orbax writes anything, so an
                 injected failure never leaves a partial step dir
                 (step = the step being saved). OPT-IN like
                 segment-pre — it shares step numbering with the
                 adjacent segment sites, and an unscoped legacy clause
                 must keep firing where it always fired; the storage
                 kinds (io-error / io-slow / enospc) default to
                 `at=save` when no site is given
    "restore"  — utils/checkpoint.restore_state, before each restore
                 attempt (step = the step being restored). OPT-IN for
                 the same reason
    "serve-batch" — serving/service.SimulationService._prepare_batch,
                 before each batch's lane assembly, flight step bump,
                 and collectives (step = the service's global batch
                 ordinal). OPT-IN: its step numbering is batches, not
                 simulation steps — an unscoped legacy clause must
                 never fire here
"""

from __future__ import annotations

import errno
import os
import time

RC_INJECTED_KILL = 43  # distinctive rc: a killed rank is diagnosable
RC_INJECTED_DIE = 0  # the point of `die`: the exit code says nothing
ENV_VAR = "RMT_INJECT_FAULT"

# Sites that only fire for clauses explicitly scoped there (at=SITE):
# they share step numbering with an adjacent legacy site, and an
# unscoped clause must keep firing at the legacy one.
OPTIN_SITES = frozenset({"segment-pre", "save", "restore", "serve-batch"})

# Storage-fault kinds: they only make sense at an IO attempt, so a
# clause with no at= clause is pinned to the "save" site at parse time.
IO_KINDS = frozenset({"io-error", "io-slow", "enospc"})
IO_SLOW_DEFAULT_S = 2.0

# Serving-plane kinds (module docstring): matched ONLY by
# `serving_fault` — the raising `fault_point` below skips them, so a
# `batch-error@step=2` can never collide with the halo "step" site's
# step numbering. The caller interprets the returned clause (`delay_s`
# carries the slow-batch seconds / queue-flood size).
SERVING_KINDS = frozenset(
    {"lane-nan", "batch-error", "queue-flood", "slow-batch"}
)
SLOW_BATCH_DEFAULT_S = 0.5
QUEUE_FLOOD_DEFAULT_N = 16

# Fleet-plane kinds (module docstring): matched ONLY by
# `replica_fault` — their `rank=` modifier names a REPLICA id, not a
# process rank, so neither `fault_point` nor `serving_fault` may ever
# interpret them.
REPLICA_KINDS = frozenset({"replica-kill", "replica-stall"})


class InjectedCrash(RuntimeError):
    """The injected failure run_supervised retries around."""


class FaultClause:
    __slots__ = ("kind", "step", "segment", "rank", "delay_s", "site",
                 "times", "fires", "request")

    def __init__(self, kind, step=None, segment=None, rank=None,
                 delay_s=0.0, site=None, times=None, request=None):
        self.kind = kind
        self.step = step
        self.segment = segment
        self.rank = rank
        self.delay_s = delay_s
        self.site = site
        self.times = times  # None = the plan's MAX_FIRES default
        self.request = request  # lane-nan's ticket-ordinal trigger
        self.fires = 0

    def __repr__(self):
        parts = [self.kind]
        if self.step is not None:
            parts.append(f"step={self.step}")
        if self.segment is not None:
            parts.append(f"segment={self.segment}")
        if self.request is not None:
            parts.append(f"request={self.request}")
        if self.rank is not None:
            parts.append(f"rank={self.rank}")
        if self.site is not None:
            parts.append(f"at={self.site}")
        if self.times is not None:
            parts.append(f"times={self.times}")
        if self.delay_s:
            parts.append(f"delay={self.delay_s}")
        return f"FaultClause({', '.join(parts)})"


def _parse_clause(raw: str) -> FaultClause:
    head, *mods = [p.strip() for p in raw.split(",")]
    kind, _, trigger = head.partition("@")
    kind = kind.strip()
    delay_s = 0.0
    if kind.startswith("delay="):
        delay_s = float(kind[len("delay="):])
        kind = "delay"
    elif kind.startswith("io-slow="):
        delay_s = float(kind[len("io-slow="):])
        kind = "io-slow"
    elif kind == "io-slow":
        delay_s = IO_SLOW_DEFAULT_S
    elif kind.startswith("slow-batch="):
        delay_s = float(kind[len("slow-batch="):])
        kind = "slow-batch"
    elif kind == "slow-batch":
        delay_s = SLOW_BATCH_DEFAULT_S
    elif kind.startswith("queue-flood="):
        # delay_s doubles as the flood SIZE for queue-flood (the one
        # value-bearing serving kind; apps/soak.py casts it back).
        delay_s = float(kind[len("queue-flood="):])
        kind = "queue-flood"
    elif kind == "queue-flood":
        delay_s = float(QUEUE_FLOOD_DEFAULT_N)
    if kind not in ("crash", "kill", "die", "truncate-latest", "delay",
                    "stall") and kind not in IO_KINDS \
            and kind not in SERVING_KINDS \
            and kind not in REPLICA_KINDS:
        raise ValueError(f"unknown fault kind {kind!r} in {raw!r}")
    clause = FaultClause(kind, delay_s=delay_s)
    triggers = [t for t in [trigger.strip()] + mods if t]
    for t in triggers:
        key, _, val = t.partition("=")
        key = key.strip()
        if key == "step":
            clause.step = int(val)
        elif key == "segment":
            clause.segment = int(val)
        elif key == "rank":
            clause.rank = int(val)
        elif key == "request":
            clause.request = int(val)
        elif key == "at":
            clause.site = val.strip()
        elif key == "times":
            clause.times = int(val)
            if clause.times < 1:
                raise ValueError(f"times must be >= 1 in {raw!r}")
        else:
            raise ValueError(f"unknown fault trigger {t!r} in {raw!r}")
    if clause.request is not None and kind != "lane-nan":
        raise ValueError(
            f"request=N only triggers lane-nan clauses: {raw!r}"
        )
    if kind in IO_KINDS and clause.site is None:
        # Storage faults strike IO attempts; without an explicit at=
        # they pin to the save site (the one every drill wants).
        clause.site = "save"
    if (kind in ("crash", "kill", "die", "delay", "stall")
            or kind in IO_KINDS) \
            and clause.step is None and clause.segment is None:
        raise ValueError(
            f"{kind} fault needs a step=K or segment=N trigger: {raw!r}"
        )
    if kind == "lane-nan" and clause.request is None:
        raise ValueError(
            f"lane-nan needs a request=N trigger (the 1-based ticket "
            f"ordinal): {raw!r}"
        )
    if kind in ("batch-error", "slow-batch", "queue-flood") \
            and clause.step is None:
        raise ValueError(
            f"{kind} needs a step=N trigger (batch/drain ordinal): "
            f"{raw!r}"
        )
    if kind in REPLICA_KINDS and clause.step is None:
        raise ValueError(
            f"{kind} needs a step=K trigger (the fleet drive tick): "
            f"{raw!r}"
        )
    return clause


class FaultPlan:
    """Parsed, armed fault clauses; fault_point() consults the installed
    plan. MAX_FIRES guards the retry path: a recovered-and-re-run step
    must not re-fire its fault."""

    MAX_FIRES = 1

    def __init__(self, clauses):
        self.clauses = list(clauses)
        self._segments_seen = 0

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        # Clause separator is ';' so ',' stays free for modifiers.
        clauses = [
            _parse_clause(part)
            for part in spec.split(";")
            if part.strip()
        ]
        return cls(clauses)

    def __bool__(self):
        return bool(self.clauses)


_PLAN: FaultPlan | None = None
_ENV_CONSUMED = False  # the env spec installs at most once per process


def _rank() -> int:
    """This process's rank — parallel.distributed.process_id, which never
    forces backend init (fault bookkeeping must not be what initializes
    a backend). Lazy import: distributed's init path calls fault_point."""
    from rocm_mpi_tpu.parallel.distributed import process_id

    return process_id()


def install(spec: str | None) -> FaultPlan | None:
    """Install (or with None/'' clear) the process-wide fault plan. An
    explicit install wins over — and permanently supersedes — the env
    spec (a cleared plan stays cleared)."""
    global _PLAN, _ENV_CONSUMED
    _ENV_CONSUMED = True
    _PLAN = FaultPlan.parse(spec) if spec else None
    return _PLAN


def install_from_env() -> FaultPlan | None:
    """Install the plan from RMT_INJECT_FAULT, at most once per process;
    cheap when the var is unset (the common case pays one getenv)."""
    global _ENV_CONSUMED
    if _ENV_CONSUMED:
        return _PLAN
    spec = os.environ.get(ENV_VAR, "").strip()
    if spec:
        install(spec)
    else:
        _ENV_CONSUMED = True
    return _PLAN


def active_plan() -> FaultPlan | None:
    return _PLAN


def _truncate_latest(directory) -> None:
    """Truncate the largest file of the NEWEST checkpoint step dir —
    the torn-write the integrity manifest must catch. Pure pathlib (no
    checkpoint-module import: checkpoint imports us)."""
    import pathlib

    root = pathlib.Path(directory)
    step_dirs = sorted(
        (d for d in root.iterdir() if d.is_dir() and d.name.isdigit()),
        key=lambda d: int(d.name),
    )
    if not step_dirs:
        return
    files = sorted(
        (f for f in step_dirs[-1].rglob("*") if f.is_file()),
        key=lambda f: f.stat().st_size,
    )
    if not files:
        return
    target = files[-1]
    size = target.stat().st_size
    with target.open("r+b") as fh:
        fh.truncate(max(size // 2, 0))


def serving_fault(kind: str, step=None, request=None):
    """Match-and-consume for the serving-plane kinds (module
    docstring): returns the firing `FaultClause` or None. The CALLER
    interprets the clause — the service raises for batch-error, sleeps
    `clause.delay_s` for slow-batch, poisons the lane for lane-nan;
    apps/soak.py submits `int(clause.delay_s)` requests for
    queue-flood. `step` is the batch/drain ordinal; `request` the
    1-based ticket ordinal (lane-nan only). times=/rank= re-arm and
    scope exactly like every other clause."""
    if kind not in SERVING_KINDS:
        raise ValueError(f"not a serving fault kind: {kind!r}")
    plan = install_from_env()
    if not plan:
        return None
    rank = _rank()
    for clause in plan.clauses:
        if clause.kind != kind:
            continue
        if clause.fires >= (clause.times or plan.MAX_FIRES):
            continue
        if clause.rank is not None and clause.rank != rank:
            continue
        if clause.request is not None:
            hit = request is not None and int(request) == clause.request
        else:
            hit = step is not None and clause.step is not None \
                and int(step) == clause.step
        if not hit:
            continue
        clause.fires += 1
        return clause
    return None


def replica_fault(kind: str, step=None, replica=None):
    """Match-and-consume for the fleet-plane kinds (module docstring):
    returns the firing `FaultClause` or None. `step` is the router's
    drive-tick ordinal; `replica` the replica id a clause's `rank=`
    modifier scopes to (an unscoped clause matches any replica — the
    first drive tick to ask, wins). The CALLER interprets the clause:
    the router marks the replica dead for replica-kill, demotes it for
    replica-stall, and runs journal-replay reconciliation for both.
    Deliberately NOT `serving_fault`: there `rank=` means the calling
    process's rank, and a fleet drill scoping `rank=1` must kill
    replica 1, not depend on which process hosts the router."""
    if kind not in REPLICA_KINDS:
        raise ValueError(f"not a replica fault kind: {kind!r}")
    plan = install_from_env()
    if not plan:
        return None
    for clause in plan.clauses:
        if clause.kind != kind:
            continue
        if clause.fires >= (clause.times or plan.MAX_FIRES):
            continue
        if clause.rank is not None and (
            replica is None or clause.rank != int(replica)
        ):
            continue
        if step is None or clause.step is None \
                or int(step) != clause.step:
            continue
        clause.fires += 1
        return clause
    return None


def fault_point(name: str, step=None, directory=None) -> None:
    """Instrumentation hook: a no-op without an installed/env plan.

    `name` identifies the instrumented site; `step` the absolute step
    count where meaningful; `directory` the checkpoint dir (needed by
    truncate-latest).
    """
    plan = install_from_env()
    if not plan:
        return
    if name == "segment":
        plan._segments_seen += 1
    rank = _rank()
    for clause in plan.clauses:
        if clause.kind in SERVING_KINDS or clause.kind in REPLICA_KINDS:
            # Serving kinds are matched only by serving_fault() and
            # replica kinds only by replica_fault(): their step
            # numbering is batches/drains/drive-ticks, not simulation
            # steps — and a replica clause's rank= is a replica id.
            continue
        if clause.fires >= (clause.times or plan.MAX_FIRES):
            continue
        if clause.rank is not None and clause.rank != rank:
            continue
        if clause.site is not None:
            if clause.site != name:
                continue
        elif name in OPTIN_SITES:
            # Opt-in sites never match unscoped clauses: a legacy spec's
            # step trigger must keep firing where it always fired.
            continue
        hit = False
        if clause.step is not None:
            hit = step is not None and int(step) == clause.step
        elif clause.segment is not None:
            hit = name == "segment" and plan._segments_seen == clause.segment
        elif clause.kind == "truncate-latest":
            hit = name == "segment" and directory is not None
        if not hit:
            continue
        clause.fires += 1
        if clause.kind == "delay":
            time.sleep(clause.delay_s)
        elif clause.kind == "io-error":
            raise OSError(
                errno.EIO,
                f"injected io-error at fault point {name!r} "
                f"(step={step}, rank={rank})",
            )
        elif clause.kind == "io-slow":
            # Inside the save attempt's measured wall: the slow-write
            # watchdog (StoragePolicy.slow_save_timeout_s) sees it.
            time.sleep(clause.delay_s)
        elif clause.kind == "enospc":
            raise OSError(
                errno.ENOSPC,
                f"injected enospc at fault point {name!r} "
                f"(step={step}, rank={rank})",
            )
        elif clause.kind == "stall":
            # The wedged rank: a pure-Python monotonic busy-wait that
            # never exits. Deliberately NOT a sleep — the interpreter
            # keeps executing bytecode, so daemon threads (telemetry
            # drains) stay live and the process looks exactly like a
            # rank spinning inside a stuck collective: alive by wall
            # clock, dead by progress. Only the watchdog's kill (or the
            # launcher timeout) ends it.
            while True:  # pragma: no branch — exit is the kill signal
                time.monotonic()
        elif clause.kind == "truncate-latest":
            if directory is not None:
                _truncate_latest(directory)
        elif clause.kind == "kill":
            os._exit(RC_INJECTED_KILL)  # noqa: SLF001 — the point: no cleanup
        elif clause.kind == "die":
            # The vanished rank: a CLEAN exit mid-run. No exception, no
            # post-mortem, rc 0 — everything downstream must infer death
            # from the peers it orphaned, which is exactly the path the
            # elastic drills need to exercise deterministically.
            os._exit(RC_INJECTED_DIE)  # noqa: SLF001 — no cleanup either
        elif clause.kind == "crash":
            raise InjectedCrash(
                f"injected crash at fault point {name!r} "
                f"(step={step}, rank={rank})"
            )
