"""Topology resharding: move a global state between device meshes
(docs/RESILIENCE.md "Elastic recovery").

The paper's workflow fixes the Cartesian grid for the life of a run;
production does not get that luxury — a device dies, a pod slice shrinks,
a resumed run lands on a different machine. This module makes the
decomposition a run-time variable for STATE: given a pytree of global
sharded arrays (or a checkpoint manifest's topology metadata), it plans a
valid mesh for whatever devices exist now and moves the data there.

Three layers, smallest first:

* `gather_slabs` / `scatter_slabs` — the slab path: pull every leaf's
  global content to host memory (per-shard slabs assembled by the
  runtime), then place it shard-by-shard under new shardings. This is
  the explicit form of what a cross-mesh checkpoint restore does through
  orbax/tensorstore, usable on LIVE state (no checkpoint round-trip).
* `reshard_state` — gather + scatter against a target grid/shardings;
  the result is freshly placed device memory, so it is donation-safe by
  construction (the same contract `checkpoint.restore_state` gives).
* `state_meta` / `template_from_meta` — the manifest glue: record a
  state's topology (mesh dims/axes + per-leaf partition specs) at save
  time, and rebuild an orbax restore template for the CURRENT device set
  from that record alone — no caller-provided `like` pytree needed
  (`restore_state(dir, step, like=None)`).

Donation hazard (GL01): `reshard_state`'s gather READS its input leaves.
Never reshard a state that has already been donated into a jitted
advance — gather first, step after — and never re-read the pre-reshard
state once a donating program consumed it. The analyzer's GL01 rule
polices the pattern (tests/analysis_fixtures/gl01_pos.py pins the
reshard-after-donate shape).
"""

from __future__ import annotations

from typing import Sequence

from rocm_mpi_tpu.parallel.mesh import suggest_dims


def _spec_entry_to_json(entry):
    if entry is None:
        return None
    if isinstance(entry, (tuple, list)):
        return [str(e) for e in entry]
    return str(entry)


def _spec_entry_from_json(entry):
    if entry is None:
        return None
    if isinstance(entry, list):
        return tuple(entry)
    return entry


def sharding_spec(leaf) -> list | None:
    """The leaf's partition spec as JSON-serializable entries (one per
    array axis; axis name, list of names, or None), or None when the leaf
    has no NamedSharding (single-device / replicated placement)."""
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    entries = [_spec_entry_to_json(e) for e in spec]
    # Pad to the array rank: PartitionSpec omits trailing None entries.
    entries += [None] * (leaf.ndim - len(entries))
    return entries


def state_meta(state) -> dict | None:
    """The topology metadata block a checkpoint manifest records for
    `state`: the mesh (dims + axis names, from the first NamedSharding
    leaf) and one partition spec per leaf. None when no leaf carries a
    NamedSharding — there is no topology to record, and the manifest
    stays restorable the pre-metadata way (caller-provided `like`)."""
    import jax

    mesh = None
    specs = []
    for leaf in jax.tree_util.tree_leaves(state):
        sharding = getattr(leaf, "sharding", None)
        leaf_mesh = getattr(sharding, "mesh", None)
        if leaf_mesh is not None and mesh is None:
            mesh = leaf_mesh
        specs.append(sharding_spec(leaf))
    if mesh is None:
        return None
    return {
        "mesh": {
            "dims": [int(d) for d in mesh.devices.shape],
            "axes": [str(a) for a in mesh.axis_names],
        },
        "specs": specs,
    }


def plan_mesh_dims(
    meta: dict, leaf_shapes: Sequence[Sequence[int]], max_devices: int
) -> tuple[int, ...]:
    """The largest valid mesh dims for the CURRENT device budget given a
    manifest's topology metadata: the biggest p <= max_devices whose
    near-square factorization divides every sharded axis of every leaf
    (per that leaf's recorded partition spec). p=1 always works."""
    axes = [str(a) for a in meta["mesh"]["axes"]]
    specs = meta.get("specs") or [None] * len(leaf_shapes)
    ndim = len(axes)

    def divides(dims) -> bool:
        by_axis = dict(zip(axes, dims))
        for shape, spec in zip(leaf_shapes, specs):
            if spec is None:
                continue
            for size, entry in zip(shape, spec):
                entry = _spec_entry_from_json(entry)
                if entry is None:
                    continue
                names = entry if isinstance(entry, tuple) else (entry,)
                factor = 1
                for name in names:
                    factor *= by_axis.get(name, 1)
                if size % factor:
                    return False
        return True

    for p in range(int(max_devices), 0, -1):
        dims = suggest_dims(p, ndim)
        if divides(dims):
            return dims
    raise AssertionError("unreachable: p=1 divides every shape")


def template_from_meta(manifest: dict, devices=None) -> list:
    """Rebuild the orbax restore template from a v2 manifest ALONE: one
    jax.ShapeDtypeStruct per recorded leaf, sharded over a mesh planned
    for the current `devices` (default jax.devices()).

    Policy: when the saved mesh still fits the device budget exactly
    (prod(saved dims) == len(devices)) it is reused — a same-topology
    resume stays bit-for-bit the legacy restore. Otherwise the mesh is
    re-planned as the largest valid sub-mesh for the current budget
    (plan_mesh_dims), which is how a run checkpointed on (4,2) resumes
    on 4, 2, or 1 devices. Returns a LIST of leaves in tree order — the
    metadata path restores leaf structure, not an arbitrary treedef; the
    framework's states are tuples of arrays, so callers tuple() it.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    meta = manifest.get("meta")
    if not meta:
        raise ValueError("manifest has no topology metadata (v1 manifest)")
    if devices is None:
        devices = jax.devices()
    leaves = manifest.get("leaves", [])
    shapes = [tuple(int(n) for n in rec["shape"]) for rec in leaves]
    saved_dims = tuple(int(d) for d in meta["mesh"]["dims"])
    if int(np.prod(saved_dims)) == len(devices):
        dims = saved_dims
    else:
        dims = plan_mesh_dims(meta, shapes, len(devices))
    axes = tuple(str(a) for a in meta["mesh"]["axes"])
    grid = np.asarray(list(devices)[: int(np.prod(dims))]).reshape(dims)
    mesh = jax.sharding.Mesh(grid, axes)
    specs = meta.get("specs") or [None] * len(leaves)
    template = []
    for rec, spec in zip(leaves, specs):
        if spec is None:
            pspec = PartitionSpec()
        else:
            pspec = PartitionSpec(
                *(_spec_entry_from_json(e) for e in spec)
            )
        template.append(
            jax.ShapeDtypeStruct(
                tuple(int(n) for n in rec["shape"]),
                jnp.dtype(rec["dtype"]),
                sharding=NamedSharding(mesh, pspec),
            )
        )
    return template


# ---------------------------------------------------------------------------
# The slab path: gather to host, scatter under new shardings
# ---------------------------------------------------------------------------


def gather_slabs(state) -> list:
    """Every leaf's GLOBAL content as host numpy arrays, in tree order.

    Requires each leaf fully addressable (every shard visible to this
    process — single-process meshes, or post-allgather state). Multi-host
    live resharding goes through the checkpoint round-trip instead: save
    on the old mesh, restore on the new (orbax reads the slabs from
    disk, which every process can address).
    """
    import jax
    import numpy as np

    slabs = []
    for i, leaf in enumerate(jax.tree_util.tree_leaves(state)):
        if not getattr(leaf, "is_fully_addressable", True):
            raise ValueError(
                f"leaf {i} is not fully addressable from this process; "
                "live cross-process resharding must round-trip through a "
                "checkpoint (save on the old mesh, restore on the new)"
            )
        slabs.append(np.asarray(jax.device_get(leaf)))
    return slabs


def scatter_slabs(slabs, shardings):
    """Place host slabs under `shardings` (one per slab, or one shared
    sharding): the scatter half of the slab path. Returns a tuple of
    device arrays — freshly placed, so donation-safe."""
    import jax

    if not isinstance(shardings, (tuple, list)):
        shardings = [shardings] * len(slabs)
    if len(shardings) != len(slabs):
        raise ValueError(
            f"{len(slabs)} slab(s) but {len(shardings)} sharding(s)"
        )
    return tuple(
        jax.device_put(slab, sh) for slab, sh in zip(slabs, shardings)
    )


def reshard_state(state, target):
    """Move `state` (a pytree of fully-addressable global arrays) onto a
    new decomposition. `target` is a GlobalGrid (every leaf gets its
    grid-sharding), a single Sharding, or a flat sequence of Shardings in
    leaf order. Returns the resharded state with `state`'s tree
    structure. The gather READS every input leaf — reshard BEFORE
    donating the state into an advance, never after (module docstring).
    """
    import jax

    sharding = getattr(target, "sharding", target)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    out = scatter_slabs(gather_slabs(leaves), sharding)
    return jax.tree_util.tree_unflatten(treedef, out)
