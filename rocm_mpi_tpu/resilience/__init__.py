"""Fault tolerance: supervised runs, checkpoint integrity, fault
injection (docs/RESILIENCE.md).

The layer spans three levels, matching where failure actually strikes:

* `supervisor.run_supervised` — process-level retry/backoff around the
  segmented checkpointed advance (crash → restore latest VALID step);
* `utils.checkpoint` — per-save integrity manifests +
  `latest_valid_step` fallback (torn/corrupt checkpoints are skipped,
  never restored), plus the storage-fault plane: per-save retry/backoff,
  ENOSPC keep-list pruning, the slow-write watchdog, and degraded
  skip-save-and-continue mode (docs/RESILIENCE.md §7);
* `faults` — deterministic fault injection (crash/kill/die/truncate/
  delay/stall at exact steps, plus the storage kinds io-error/io-slow/
  enospc at save attempts), wired through `run_segmented`, the
  launcher, and the apps' `--inject-fault` flag, so every recovery path
  above is exercised by tests (tests/test_resilience.py), not just by
  outages;
* `preempt` — scheduler-eviction awareness: the SIGTERM grace-deadline
  handler, the emergency-save budget call, and the RC_PREEMPTED exit
  every supervisor upstack classifies as resumable (docs/RESILIENCE.md
  §7);
* `elastic.run_elastic` — launcher-level TOPOLOGY supervision: when a
  rank dies for good (watchdog kill, vanish, nonzero rc), shrink to the
  largest valid sub-mesh and resume from the latest valid step instead
  of aborting; when recovered devices rejoin the budget, preempt-and-
  grow back onto the largest valid larger mesh (docs/RESILIENCE.md
  "Elastic recovery" and §7);
* `policy.ElasticPolicy` — the pluggable shrink/grow/give-up decision
  table with grow hysteresis, injectable by the future serving layer;
* `reshard` — the topology-portability substrate: checkpoint manifest
  metadata (mesh dims + per-leaf partition specs), restore-template
  planning for the current device set, and the host gather/scatter slab
  path for live state.
"""

from rocm_mpi_tpu.resilience.elastic import (  # noqa: F401
    ElasticExhausted,
    ElasticReport,
    run_elastic,
)
from rocm_mpi_tpu.resilience.faults import (  # noqa: F401
    FaultPlan,
    InjectedCrash,
    fault_point,
    install,
    install_from_env,
)
from rocm_mpi_tpu.resilience.policy import ElasticPolicy  # noqa: F401
from rocm_mpi_tpu.resilience.preempt import (  # noqa: F401
    RC_PREEMPTED,
    Preempted,
)
from rocm_mpi_tpu.resilience.supervisor import (  # noqa: F401
    default_retryable,
    run_supervised,
)
