"""Elastic topology policy: the shrink/grow/give-up decisions, pluggable
(docs/RESILIENCE.md §7).

PR 6 hard-coded the elastic supervisor's one decision — shrink to the
largest valid sub-mesh of the survivors, give up at `min_ranks`. Growth
makes the decision space real: when recovered devices rejoin the budget
mid-run, SHOULD the run pay a checkpoint-and-relaunch to use them? That
is a policy question (a run 2 segments from completion should not; a
serving layer may want to steal the devices for another tenant
instead), so the decisions live in this object and `run_elastic` only
executes them. The future serving layer (ROADMAP item 1) injects its
own subclass; the default encodes the single-tenant answer: always
shrink to survive, grow whenever the budget allows and hysteresis
agrees, give up below `min_ranks`.

Hysteresis: topology changes are expensive (a checkpoint, a relaunch, a
recompile), so `min_grow_interval_steps` refuses a grow until the run
has advanced that many steps past the LAST topology change — a flapping
device that joins and dies every few seconds must not convert the run
into a relaunch loop. Growth happens only at segment boundaries by
construction: the grow path preempts the running ranks (SIGTERM,
resilience.preempt), and the preemption check lives at the segmented
loop's boundaries — there is no other place a rank can exit with a
durable, resumable step.

Shrink takes precedence over grow: a launch that FAILED (dead rank,
watchdog verdict, vanish) re-plans for the survivors even when the
nominal budget says more devices exist — the budget's claim is exactly
what the dead rank just disproved. Growth is only considered from a
healthy state: a completed-preempted launch, or the live rejoin probe.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class ElasticPolicy:
    """Decision table for `resilience.elastic.run_elastic`.

    `min_ranks` — below this, a failure raises ElasticExhausted.
    `grow` — master switch for elastic growth (the rejoin probe and the
        post-preemption re-plan both consult it).
    `min_grow_interval_steps` — hysteresis: steps that must pass after a
        topology change before a grow is considered. 0 = any new
        segment boundary. When the current step is unknowable (no
        checkpoint_dir), a nonzero interval refuses the grow —
        hysteresis that cannot be evaluated must fail closed.
    `grow_poll_s` — rejoin-probe cadence while a launch is live.
    `max_preempt_resumes` — bound on preempted-relaunch cycles (an
        external SIGTERM storm must not loop forever).
    """

    min_ranks: int = 1
    grow: bool = True
    min_grow_interval_steps: int = 0
    grow_poll_s: float = 1.0
    max_preempt_resumes: int = 8

    def give_up(self, nprocs: int) -> bool:
        """A launch failed at `nprocs`: is there anywhere left to go?"""
        return nprocs <= self.min_ranks

    def shrink_target(self, nprocs: int, dead_count: int,
                      plan_ranks) -> int:
        """Rank count after a failure that killed `dead_count` ranks:
        the largest valid mesh over the SURVIVORS (never n-1 — a launch
        that lost two pods must not re-plan for a budget including one
        of them), floored at min_ranks. `plan_ranks(budget) -> int`
        maps a device budget to the largest valid mesh's rank count
        (identity when no global shape constrains it)."""
        budget = nprocs - max(dead_count, 1)
        return max(plan_ranks(max(budget, 1)), self.min_ranks)

    def wants_grow(self, nprocs: int, budget: int, *,
                   step: int | None = None,
                   last_change_step: int | None = None) -> bool:
        """Should the run grow onto `budget` devices? True only when
        growth is on, the budget actually exceeds the running rank
        count, and the hysteresis interval has provably passed."""
        if not self.grow or budget <= nprocs:
            return False
        if self.min_grow_interval_steps <= 0:
            return True
        if step is None:
            return False  # interval unknowable: fail closed
        since = last_change_step if last_change_step is not None else 0
        return step - since >= self.min_grow_interval_steps

    def grow_target(self, nprocs: int, budget: int, plan_ranks) -> int:
        """Rank count a grow relaunches on: the largest valid mesh
        within `budget`. May equal `nprocs` (budget grew but no bigger
        mesh tiles the grid) — the caller treats that as no grow."""
        return max(plan_ranks(max(budget, 1)), nprocs)
