"""Elastic topology policy: the shrink/grow/give-up decisions, pluggable
(docs/RESILIENCE.md §7).

PR 6 hard-coded the elastic supervisor's one decision — shrink to the
largest valid sub-mesh of the survivors, give up at `min_ranks`. Growth
makes the decision space real: when recovered devices rejoin the budget
mid-run, SHOULD the run pay a checkpoint-and-relaunch to use them? That
is a policy question (a run 2 segments from completion should not; a
serving layer may want to steal the devices for another tenant
instead), so the decisions live in this object and `run_elastic` only
executes them. The future serving layer (ROADMAP item 1) injects its
own subclass; the default encodes the single-tenant answer: always
shrink to survive, grow whenever the budget allows and hysteresis
agrees, give up below `min_ranks`.

Hysteresis: topology changes are expensive (a checkpoint, a relaunch, a
recompile), so `min_grow_interval_steps` refuses a grow until the run
has advanced that many steps past the LAST topology change — a flapping
device that joins and dies every few seconds must not convert the run
into a relaunch loop. Growth happens only at segment boundaries by
construction: the grow path preempts the running ranks (SIGTERM,
resilience.preempt), and the preemption check lives at the segmented
loop's boundaries — there is no other place a rank can exit with a
durable, resumable step.

Shrink takes precedence over grow: a launch that FAILED (dead rank,
watchdog verdict, vanish) re-plans for the survivors even when the
nominal budget says more devices exist — the budget's claim is exactly
what the dead rank just disproved. Growth is only considered from a
healthy state: a completed-preempted launch, or the live rejoin probe.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class ElasticPolicy:
    """Decision table for `resilience.elastic.run_elastic`.

    `min_ranks` — below this, a failure raises ElasticExhausted.
    `grow` — master switch for elastic growth (the rejoin probe and the
        post-preemption re-plan both consult it).
    `min_grow_interval_steps` — hysteresis: steps that must pass after a
        topology change before a grow is considered. 0 = any new
        segment boundary. When the current step is unknowable (no
        checkpoint_dir), a nonzero interval refuses the grow —
        hysteresis that cannot be evaluated must fail closed.
    `grow_poll_s` — rejoin-probe cadence while a launch is live.
    `max_preempt_resumes` — bound on preempted-relaunch cycles (an
        external SIGTERM storm must not loop forever).
    """

    min_ranks: int = 1
    grow: bool = True
    min_grow_interval_steps: int = 0
    grow_poll_s: float = 1.0
    max_preempt_resumes: int = 8

    def give_up(self, nprocs: int) -> bool:
        """A launch failed at `nprocs`: is there anywhere left to go?"""
        return nprocs <= self.min_ranks

    def shrink_target(self, nprocs: int, dead_count: int,
                      plan_ranks) -> int:
        """Rank count after a failure that killed `dead_count` ranks:
        the largest valid mesh over the SURVIVORS (never n-1 — a launch
        that lost two pods must not re-plan for a budget including one
        of them), floored at min_ranks. `plan_ranks(budget) -> int`
        maps a device budget to the largest valid mesh's rank count
        (identity when no global shape constrains it)."""
        budget = nprocs - max(dead_count, 1)
        return max(plan_ranks(max(budget, 1)), self.min_ranks)

    def wants_grow(self, nprocs: int, budget: int, *,
                   step: int | None = None,
                   last_change_step: int | None = None) -> bool:
        """Should the run grow onto `budget` devices? True only when
        growth is on, the budget actually exceeds the running rank
        count, and the hysteresis interval has provably passed."""
        if not self.grow or budget <= nprocs:
            return False
        if self.min_grow_interval_steps <= 0:
            return True
        if step is None:
            return False  # interval unknowable: fail closed
        since = last_change_step if last_change_step is not None else 0
        return step - since >= self.min_grow_interval_steps

    def grow_target(self, nprocs: int, budget: int, plan_ranks) -> int:
        """Rank count a grow relaunches on: the largest valid mesh
        within `budget`. May equal `nprocs` (budget grew but no bigger
        mesh tiles the grid) — the caller treats that as no grow."""
        return max(plan_ranks(max(budget, 1)), nprocs)


@dataclasses.dataclass
class RequestRetryPolicy:
    """The request plane's retry decision table (docs/SERVING.md "SLOs
    and admission"; consumed by serving.service).

    A transient batch-level failure (compile hiccup, storage flap on a
    session save, an injected `batch-error`) or a numerical failure
    (NaN/Inf lane) requeues the request a BOUNDED number of times with
    exponential backoff, instead of either dying on first fault or
    looping forever; a request that exhausts `budget` is quarantined —
    never requeued again — with its full record banked for offline
    repro. Per-request validation errors (unknown physics, a session
    past the requested nt) never retry: the request itself is wrong.

    `budget` — retries per request (0 = quarantine on first fault).
    `backoff_base_s` — first-retry delay; doubles per retry.
    `backoff_cap_s` — backoff ceiling (an eviction storm must not push
        a request's next try into next week).
    """

    budget: int = 2
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0

    def __post_init__(self):
        if self.budget < 0:
            raise ValueError(f"budget must be >= 0, got {self.budget}")
        if self.backoff_base_s < 0 or self.backoff_cap_s < 0:
            raise ValueError("backoff must be >= 0 seconds")

    def backoff_s(self, retries: int) -> float:
        """Delay before retry number `retries` (1-based)."""
        if retries < 1:
            return 0.0
        return min(
            self.backoff_base_s * 2.0 ** (retries - 1),
            self.backoff_cap_s,
        )


@dataclasses.dataclass
class CircuitPolicy:
    """Per-program-class (BinKey) circuit breaker thresholds
    (docs/SERVING.md "SLOs and admission"; consumed by
    serving.service).

    `k` consecutive batch failures in ONE program class open the
    breaker: requests in that class reject fast with `circuit-open`
    instead of burning lanes, batch retries, and the retry budgets of
    every co-batched tenant — one failing shape class can no longer
    starve every other tenant's throughput. After `cooldown_drains`
    drain passes the breaker goes half-open: exactly one probe request
    is re-admitted; success closes the breaker, failure re-opens it.
    `k <= 0` disables the breaker entirely.
    """

    k: int = 3
    cooldown_drains: int = 2

    def __post_init__(self):
        if self.cooldown_drains < 1:
            raise ValueError(
                f"cooldown_drains must be >= 1, got {self.cooldown_drains}"
            )

    @property
    def enabled(self) -> bool:
        return self.k > 0
