"""Elastic launch supervision: shrink the mesh on rank death instead of
aborting the run (docs/RESILIENCE.md "Elastic recovery").

`run_supervised` (supervisor.py) retries a run on the SAME topology —
the right answer when the failure was transient. When a device is gone
(watchdog-killed wedged rank, preempted pod, vanished container), the
same topology no longer exists, and before this module the only outcome
was an aborted run. `run_elastic` is the launcher-level supervisor that
treats topology as a run-time variable:

    report = run_elastic(argv, nprocs=4, checkpoint_dir=d,
                         global_shape=(64, 64), health_dir=h)

launches `nprocs` ranks of `argv` under the spawn_ranks contract and,
when a launch fails — a rank killed (`kill`), wedged and put down by the
PR-5 progress watchdog (`stall`), or vanished with a clean rc
(`die`, caught by the launcher's vanish_grace_s detection) — it:

 1. plans the LARGEST VALID SUB-MESH for the survivors
    (parallel.mesh.plan_dims against the global shape: the biggest
    p <= n-1 whose near-square factorization divides every grid axis);
 2. emits a structured `elastic.shrink` event — old/new mesh dims, dead
    ranks, reason, the resume step — to the run's `elastic.jsonl`
    sidecar (telemetry.health owns the record format; the monitor CLI
    shows the mesh + a SHRUNK badge from it) and, when the supervising
    process itself collects telemetry, as telemetry events/gauges;
 3. respawns on the smaller rank count. The ranks themselves resume
    from the latest VALID checkpoint step exactly as any --resume run
    does — the v2 manifest topology metadata + orbax re-slicing
    (utils.checkpoint.restore_state) land the old mesh's shard slabs on
    the new decomposition bit-exactly.

The injected fault spec (when drilling) is forwarded to the FIRST launch
only: the fault already happened; a respawn must not re-arm it.

Shrinking stops at `min_ranks`; a failure there raises ElasticExhausted
after an `elastic.gave-up` event — like run_supervised, the elastic
layer never converts persistent failure into silence. Clean launches
never shrink: success is every rank exiting 0 with no watchdog verdict
and no vanish.
"""

from __future__ import annotations

import dataclasses
import math
import pathlib


class ElasticExhausted(RuntimeError):
    """The run kept failing all the way down to `min_ranks`."""


@dataclasses.dataclass
class ElasticReport:
    """What the elastic supervisor did: one entry per launch, the
    elastic.* event records (also in the sidecar), and the last launch's
    RankResults (`.results`)."""

    launches: list = dataclasses.field(default_factory=list)
    events: list = dataclasses.field(default_factory=list)
    shrinks: int = 0
    final_nprocs: int | None = None
    results: object = None

    def note_event(self, rec: dict) -> None:
        self.events.append(rec)


def _judge(results) -> tuple[bool, list[int], str]:
    """(ok, dead_ranks, reason) for one finished launch. Dead ranks are
    the CAUSE (watchdog-flagged / vanished / first nonzero rc), not the
    peers the launcher reaped after them."""
    report = results.report
    if report.watchdog_verdicts:
        ranks = sorted({v["rank"] for v in report.watchdog_verdicts})
        return False, ranks, "watchdog-stall"
    if report.vanished is not None:
        return False, [report.vanished], "vanished (clean rc mid-run)"
    if report.first_failure is not None:
        rank, rc, _ = report.first_failure
        return False, [rank], f"rank {rank} rc={rc}"
    bad = [i for i, (p, _) in enumerate(results) if p.returncode != 0]
    if bad:
        return False, bad[:1], f"rank {bad[0]} rc={results[bad[0]][0].returncode}"
    return True, [], "ok"


def run_elastic(
    argv,
    nprocs: int,
    *,
    checkpoint_dir=None,
    global_shape=None,
    min_ranks: int = 1,
    inject_fault: str | None = None,
    sidecar_dir=None,
    launch=None,
    log=None,
    **spawn_kwargs,
) -> ElasticReport:
    """Launch `argv` on `nprocs` ranks, shrinking the mesh and resuming
    on failure; returns the ElasticReport (`.results` is the last
    launch). `argv` may be a callable `(nprocs, attempt) -> argv` when
    ranks need per-launch arguments.

    `global_shape` drives the sub-mesh planning (plan_dims); without it
    the shrink is a plain n-1. `checkpoint_dir` is only read here to
    stamp the resume step on events — the ranks own the actual restore.
    `sidecar_dir` (default: health_dir, then telemetry_dir, then
    checkpoint_dir) receives `elastic.jsonl`. `launch` is injectable for
    tests (default parallel.launcher.spawn_ranks); remaining kwargs pass
    through to it — `vanish_grace_s` defaults ON here (10 s) because
    vanish detection is the only way a `die`-class death is seen at all.
    """
    from rocm_mpi_tpu import telemetry
    from rocm_mpi_tpu.telemetry import health as _health

    if nprocs < 1 or min_ranks < 1 or min_ranks > nprocs:
        raise ValueError(
            f"need 1 <= min_ranks <= nprocs, got {min_ranks}, {nprocs}"
        )
    if launch is None:
        from rocm_mpi_tpu.parallel.launcher import spawn_ranks

        launch = spawn_ranks
    spawn_kwargs.setdefault("vanish_grace_s", 10.0)
    log = log or (lambda *_: None)
    sidecar = (
        sidecar_dir
        or spawn_kwargs.get("health_dir")
        or spawn_kwargs.get("telemetry_dir")
        or checkpoint_dir
    )
    report = ElasticReport()

    def event(name: str, **attrs) -> None:
        if sidecar is not None:
            rec = _health.append_elastic_event(sidecar, name, **attrs)
        else:
            rec = {"name": name, **attrs}
        report.note_event(rec)
        # The supervising process may itself collect telemetry (tests,
        # a driving notebook): mirror the decision there too. No-ops
        # when collection is off.
        telemetry.record_event(name)
        if name in ("elastic.launch", "elastic.shrink"):
            telemetry.gauge("elastic.ranks", attrs.get("new_nprocs",
                                                       attrs.get("nprocs")))

    def resume_step():
        if checkpoint_dir is None:
            return None
        from rocm_mpi_tpu.utils import checkpoint as ckpt

        return ckpt.latest_valid_step(checkpoint_dir, log=log)

    def mesh_for(n: int):
        if global_shape is None:
            return None
        from rocm_mpi_tpu.parallel.mesh import plan_dims

        return list(plan_dims(global_shape, n))

    def next_nprocs(n: int, dead_count: int) -> int:
        # The survivors are what's left after EVERY dead rank, not n-1:
        # a launch that lost two pods must not re-plan for a device
        # budget that includes one of them.
        budget = n - max(dead_count, 1)
        mesh = mesh_for(budget)
        if mesh is None:
            return budget
        return int(math.prod(mesh))

    if sidecar is not None:
        # elastic.jsonl is THIS run's record: a reused directory must not
        # show last run's shrinks as this run's (same hygiene the
        # launcher applies to stale heartbeat sidecars).
        stale = pathlib.Path(sidecar) / _health.ELASTIC_FILE
        stale.unlink(missing_ok=True)

    n = nprocs
    attempt = 0
    start = resume_step()
    while True:
        mesh = mesh_for(n)
        event("elastic.launch", attempt=attempt, nprocs=n, mesh=mesh,
              resume_step=start)
        log(f"elastic: launch {attempt} on {n} rank(s)"
            + (f", mesh {tuple(mesh)}" if mesh else "")
            + (f", resuming step {start}" if start else ""))
        this_argv = argv(n, attempt) if callable(argv) else argv
        results = launch(
            this_argv,
            nprocs=n,
            inject_fault=inject_fault if attempt == 0 else None,
            **spawn_kwargs,
        )
        ok, dead, reason = _judge(results)
        report.launches.append({
            "attempt": attempt,
            "nprocs": n,
            "mesh": mesh,
            "resume_step": start,
            "ok": ok,
            "dead_ranks": dead,
            "reason": reason,
            "returncodes": [p.returncode for p, _ in results],
        })
        report.results = results
        if ok:
            report.final_nprocs = n
            event("elastic.complete", nprocs=n, mesh=mesh,
                  shrinks=report.shrinks)
            log(f"elastic: run complete on {n} rank(s) after "
                f"{report.shrinks} shrink(s)")
            return report
        if n <= min_ranks:
            event("elastic.gave-up", nprocs=n, reason=reason,
                  dead_ranks=dead)
            log(f"elastic: giving up — failed at min_ranks={min_ranks} "
                f"({reason})")
            raise ElasticExhausted(
                f"run failed at the minimum rank count {min_ranks}: "
                f"{reason}"
            )
        new_n = max(next_nprocs(n, len(dead)), min_ranks)
        new_mesh = mesh_for(new_n)
        # Re-resolve AFTER the failed launch (its ranks saved steps) —
        # then carry the value: nothing runs between this shrink and
        # the next launch, so re-walking every manifest again at the
        # loop top would be pure repeated validation I/O.
        start = resume_step()
        event("elastic.shrink", old_nprocs=n, new_nprocs=new_n,
              old_mesh=mesh, new_mesh=new_mesh, dead_ranks=dead,
              reason=reason, resume_step=start)
        log(f"elastic: shrinking {n} → {new_n} rank(s) "
            f"({reason}; dead {dead}), resuming from step {start}")
        report.shrinks += 1
        n = new_n
        attempt += 1
