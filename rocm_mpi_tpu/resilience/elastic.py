"""Elastic launch supervision: shrink the mesh on rank death, grow it
back when devices rejoin, and treat scheduler preemption as a resumable
state (docs/RESILIENCE.md "Elastic recovery" and §7).

`run_supervised` (supervisor.py) retries a run on the SAME topology —
the right answer when the failure was transient. When a device is gone
(watchdog-killed wedged rank, preempted pod, vanished container), the
same topology no longer exists, and before this module the only outcome
was an aborted run. `run_elastic` is the launcher-level supervisor that
treats topology as a run-time variable:

    report = run_elastic(argv, nprocs=4, checkpoint_dir=d,
                         global_shape=(64, 64), health_dir=h)

launches `nprocs` ranks of `argv` under the spawn_ranks contract and,
when a launch fails — a rank killed (`kill`), wedged and put down by the
PR-5 progress watchdog (`stall`), or vanished with a clean rc
(`die`, caught by the launcher's vanish_grace_s detection) — it:

 1. plans the LARGEST VALID SUB-MESH for the survivors
    (parallel.mesh.plan_dims against the global shape: the biggest
    p <= n-1 whose near-square factorization divides every grid axis);
 2. emits a structured `elastic.shrink` event — old/new mesh dims, dead
    ranks, reason, the resume step — to the run's `elastic.jsonl`
    sidecar (telemetry.health owns the record format; the monitor CLI
    shows the mesh + a SHRUNK badge from it) and, when the supervising
    process itself collects telemetry, as telemetry events/gauges;
 3. respawns on the smaller rank count. The ranks themselves resume
    from the latest VALID checkpoint step exactly as any --resume run
    does — the v2 manifest topology metadata + orbax re-slicing
    (utils.checkpoint.restore_state) land the old mesh's shard slabs on
    the new decomposition bit-exactly.

GROWTH — the other half (this PR): pass `device_budget` (a callable
returning the rank budget currently available, or a constant int) and
the supervisor runs a REJOIN PROBE: between launches, and periodically
while a reduced-mesh launch is live, it re-plans against the current
budget. When more ranks are available than the running mesh uses — and
the `ElasticPolicy` hysteresis agrees — it preempts the running ranks
(SIGTERM through resilience.preempt; each rank lands one final save at
its next segment boundary and exits RC_PREEMPTED), emits
`elastic.grow`, and relaunches on the largest valid larger mesh,
resuming through the same cross-mesh restore that powers shrinking.
Growth therefore only ever happens at segment boundaries, from a
durable step — the bitwise-continuation contract holds in both
directions. Shrink takes precedence over grow: a launch that FAILED
re-plans for its survivors no matter what the budget claims.

PREEMPTION of the whole job: a launch whose only nonzero exits are
RC_PREEMPTED is judged "preempted", never a failure — if the parent
itself holds a SIGTERM notice (the launcher's forwarder stamped it),
run_elastic stops relaunching, emits `elastic.preempted`, and RETURNS
the report (`report.preempted`): the job is resumable by the next
invocation, exactly like a rank-level resume. Without a parent notice a
preempted launch is relaunched (grown when the budget probe says so) —
bounded by `policy.max_preempt_resumes`.

All decisions live in the pluggable `ElasticPolicy`
(resilience/policy.py); the defaults reproduce the PR-6 behavior
exactly when no budget is armed. The injected fault spec (when
drilling) is forwarded to the FIRST launch only: the fault already
happened; a respawn must not re-arm it.

Shrinking stops at `policy.min_ranks`; a failure there raises
ElasticExhausted after an `elastic.gave-up` event — like
run_supervised, the elastic layer never converts persistent failure
into silence. Clean launches never change topology: success is every
rank exiting 0 with no watchdog verdict and no vanish.
"""

from __future__ import annotations

import dataclasses
import math
import pathlib
import signal as _signal
import threading

from rocm_mpi_tpu.resilience import preempt as _preempt
from rocm_mpi_tpu.resilience.policy import ElasticPolicy


class ElasticExhausted(RuntimeError):
    """The run kept failing all the way down to `min_ranks`."""


@dataclasses.dataclass
class ElasticReport:
    """What the elastic supervisor did: one entry per launch, the
    elastic.* event records (also in the sidecar), and the last launch's
    RankResults (`.results`)."""

    launches: list = dataclasses.field(default_factory=list)
    events: list = dataclasses.field(default_factory=list)
    shrinks: int = 0
    grows: int = 0
    resumes: int = 0  # preempted relaunches that changed nothing
    preempted: bool = False  # the whole job was evicted; resumable
    final_nprocs: int | None = None
    results: object = None

    def note_event(self, rec: dict) -> None:
        self.events.append(rec)


def _judge(results) -> tuple[str, list[int], str]:
    """(status, dead_ranks, reason) for one finished launch; status is
    "ok" | "failed" | "preempted". Dead ranks are the CAUSE
    (watchdog-flagged / vanished / first nonzero rc), not the peers the
    launcher reaped after them. A launch where every deliberate nonzero
    exit is RC_PREEMPTED is a scheduler eviction, not a failure — those
    ranks exited on purpose from a durable step (resilience.preempt).
    Peers with negative rcs alongside an RC_PREEMPTED exit are the
    documented boundary-skew casualties: a rank that noticed the notice
    one segment later than its preempted peer strands in a collective
    the peer already left, and the launcher's peer-grace/watchdog kill
    reaps it (SIGKILL → negative rc). That reap — watchdog verdict and
    all — is part of the preemption contract's bounded fallback (the
    resume falls back to the last durable step), so it must not
    downgrade the eviction into a failure and trigger a shrink: the
    devices are not dead, the scheduler took them.

    A rc-0 vanish verdict alongside RC_PREEMPTED exits ALSO yields to
    "preempted" — deliberately. The ambiguous rc-0 exit is either a
    rank that legitimately finished while a slower peer got preempted
    past the vanish grace (eviction near completion: a shrink would
    wrongly discard healthy topology) or a genuine die-class death that
    happened to coincide with an eviction; the preempted relaunch
    self-corrects the latter in one launch (the dead device fails it,
    and THAT launch judges "failed" and shrinks), while the flipped
    precedence would mis-shrink the former with nothing to correct
    it."""
    report = results.report
    rcs = [p.returncode for p, _ in results]
    nonzero = [(i, rc) for i, rc in enumerate(rcs) if rc != 0]
    preempted = [i for i, rc in nonzero if rc == _preempt.RC_PREEMPTED]
    casualties = [(i, rc) for i, rc in nonzero
                  if rc != _preempt.RC_PREEMPTED]
    if preempted and all(rc < 0 for _, rc in casualties):
        extra = (f", {len(casualties)} peer(s) reaped at the boundary "
                 "skew" if casualties else "")
        return "preempted", [], (
            f"{len(preempted)} rank(s) exited preempted "
            f"(rc={_preempt.RC_PREEMPTED}){extra}"
        )
    if report.watchdog_verdicts:
        ranks = sorted({v["rank"] for v in report.watchdog_verdicts})
        return "failed", ranks, "watchdog-stall"
    if report.vanished is not None:
        return "failed", [report.vanished], "vanished (clean rc mid-run)"
    if report.first_failure is not None:
        rank, rc, _ = report.first_failure
        return "failed", [rank], f"rank {rank} rc={rc}"
    if nonzero:
        i, rc = nonzero[0]
        return "failed", [i], f"rank {i} rc={rc}"
    return "ok", [], "ok"


class _GrowWatcher:
    """The live rejoin probe: while a launch runs, poll the device
    budget; when the policy wants a grow, preempt the ranks (SIGTERM —
    they land one final save at the next segment boundary and exit
    RC_PREEMPTED) and remember the target for the post-launch decision.

    Before preempting it additionally requires a step durably saved
    PAST the launch's resume point: a rank that has not completed a new
    segment has nothing fresher to grow from (and may not have armed
    its preemption handler yet) — growth waits for the next boundary by
    construction."""

    def __init__(self, policy, budget_fn, plan_ranks, resume_step_fn, log):
        self.policy = policy
        self.budget_fn = budget_fn
        self.plan_ranks = plan_ranks
        self.resume_step_fn = resume_step_fn
        self.log = log
        self.target: int | None = None
        self._stop = threading.Event()
        self._thread = None

    def on_spawn(self, nprocs: int, last_change_step):
        def _cb(procs):
            self._thread = threading.Thread(
                target=self._watch, args=(procs, nprocs, last_change_step),
                daemon=True,
            )
            self._thread.start()

        return _cb

    def arm(self):
        self.target = None
        self._stop = threading.Event()

    def disarm(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _watch(self, procs, nprocs, last_change_step):
        while not self._stop.wait(self.policy.grow_poll_s):
            try:
                budget = int(self.budget_fn())
            except Exception:  # noqa: BLE001 — a flaky probe is no budget
                continue
            if budget <= nprocs:
                # The common steady state (budget == running ranks).
                # Checked BEFORE resume_step_fn: that call validates the
                # newest checkpoint (orbax open + CRC) and must not run
                # every poll of a run that can never grow.
                continue
            try:
                step = self.resume_step_fn()
            except Exception:  # noqa: BLE001
                step = None
            since = last_change_step if last_change_step is not None else 0
            if step is None or step <= since:
                continue  # nothing durably newer to grow from yet
            if not self.policy.wants_grow(nprocs, budget, step=step,
                                          last_change_step=since):
                continue
            target = self.policy.grow_target(nprocs, budget,
                                             self.plan_ranks)
            if target <= nprocs:
                continue
            self.target = target
            self.log(
                f"elastic: rejoin probe sees budget {budget} > {nprocs} "
                f"rank(s) at step {step} — preempting for growth to "
                f"{target} rank(s) at the next segment boundary"
            )
            for p in procs:
                try:
                    if p.poll() is None:
                        p.send_signal(_signal.SIGTERM)
                except (OSError, AttributeError):
                    pass
            return


def run_elastic(
    argv,
    nprocs: int,
    *,
    checkpoint_dir=None,
    global_shape=None,
    min_ranks: int = 1,
    inject_fault: str | None = None,
    sidecar_dir=None,
    launch=None,
    log=None,
    policy: ElasticPolicy | None = None,
    device_budget=None,
    **spawn_kwargs,
) -> ElasticReport:
    """Launch `argv` on `nprocs` ranks, shrinking/growing the mesh and
    resuming per the policy; returns the ElasticReport (`.results` is
    the last launch). `argv` may be a callable `(nprocs, attempt) ->
    argv` when ranks need per-launch arguments.

    `global_shape` drives the sub-mesh planning (plan_dims); without it
    the shrink is a plain n-1 (and a grow a plain budget). `checkpoint_dir`
    is read here to stamp resume steps on events and to feed the grow
    hysteresis — the ranks own the actual restore. `sidecar_dir`
    (default: health_dir, then telemetry_dir, then checkpoint_dir)
    receives `elastic.jsonl`. `policy` defaults to
    ElasticPolicy(min_ranks=min_ranks) — PR-6 behavior exactly.
    `device_budget` (callable -> int, or a constant int) arms the
    rejoin probe and elastic growth; None (default) disables growth.
    `launch` is injectable for tests (default
    parallel.launcher.spawn_ranks); remaining kwargs pass through to
    it — `vanish_grace_s` defaults ON here (10 s) because vanish
    detection is the only way a `die`-class death is seen at all, and
    when growth is armed `preempt_grace_s` defaults ON too (the grow
    path preempts ranks, so they must know their grace).
    """
    from rocm_mpi_tpu import telemetry
    from rocm_mpi_tpu.telemetry import health as _health

    if nprocs < 1 or min_ranks < 1 or min_ranks > nprocs:
        raise ValueError(
            f"need 1 <= min_ranks <= nprocs, got {min_ranks}, {nprocs}"
        )
    if policy is None:
        policy = ElasticPolicy(min_ranks=min_ranks)
    if launch is None:
        from rocm_mpi_tpu.parallel.launcher import spawn_ranks

        launch = spawn_ranks
    spawn_kwargs.setdefault("vanish_grace_s", 10.0)
    log = log or (lambda *_: None)
    sidecar = (
        sidecar_dir
        or spawn_kwargs.get("health_dir")
        or spawn_kwargs.get("telemetry_dir")
        or checkpoint_dir
    )
    budget_fn = None
    if device_budget is not None:
        budget_fn = (
            device_budget if callable(device_budget)
            else (lambda b=int(device_budget): b)
        )
        # Ranks about to be preempted for growth must have the handler
        # armed, or the SIGTERM just kills them (judged a failure).
        spawn_kwargs.setdefault("preempt_grace_s",
                                _preempt.DEFAULT_GRACE_S)
    report = ElasticReport()

    def event(name: str, **attrs) -> None:
        if sidecar is not None:
            rec = _health.append_elastic_event(sidecar, name, **attrs)
        else:
            rec = {"name": name, **attrs}
        report.note_event(rec)
        # The supervising process may itself collect telemetry (tests,
        # a driving notebook): mirror the decision there too. No-ops
        # when collection is off.
        telemetry.record_event(name)
        if name in ("elastic.launch", "elastic.shrink", "elastic.grow"):
            telemetry.gauge("elastic.ranks", attrs.get("new_nprocs",
                                                       attrs.get("nprocs")))

    def resume_step():
        if checkpoint_dir is None:
            return None
        from rocm_mpi_tpu.utils import checkpoint as ckpt

        return ckpt.latest_valid_step(checkpoint_dir, log=log)

    def mesh_for(n: int):
        if global_shape is None:
            return None
        from rocm_mpi_tpu.parallel.mesh import plan_dims

        return list(plan_dims(global_shape, n))

    def plan_ranks(budget: int) -> int:
        mesh = mesh_for(budget)
        if mesh is None:
            return budget
        return int(math.prod(mesh))

    watcher = None
    if budget_fn is not None and policy.grow:
        watcher = _GrowWatcher(policy, budget_fn, plan_ranks,
                               resume_step, log)

    if sidecar is not None:
        # elastic.jsonl is THIS run's record: a reused directory must not
        # show last run's shrinks as this run's (same hygiene the
        # launcher applies to stale heartbeat sidecars).
        stale = pathlib.Path(sidecar) / _health.ELASTIC_FILE
        stale.unlink(missing_ok=True)

    n = nprocs
    attempt = 0
    start = resume_step()
    # Hysteresis anchor: the step at the last topology change (the
    # launch's own resume point until one happens).
    last_change_step = start
    while True:
        mesh = mesh_for(n)
        event("elastic.launch", attempt=attempt, nprocs=n, mesh=mesh,
              resume_step=start)
        log(f"elastic: launch {attempt} on {n} rank(s)"
            + (f", mesh {tuple(mesh)}" if mesh else "")
            + (f", resuming step {start}" if start else ""))
        this_argv = argv(n, attempt) if callable(argv) else argv
        launch_kwargs = dict(spawn_kwargs)
        if watcher is not None:
            watcher.arm()
            watcher_cb = watcher.on_spawn(n, last_change_step)
            caller_cb = launch_kwargs.get("on_spawn")
            if caller_cb is None:
                launch_kwargs["on_spawn"] = watcher_cb
            else:
                # A caller-supplied on_spawn rides along with the grow
                # watcher's — spawn_ranks documents the hook, so arming
                # growth must not silently eat it.
                def _chained(procs, _u=caller_cb, _w=watcher_cb):
                    _u(procs)
                    _w(procs)

                launch_kwargs["on_spawn"] = _chained
        try:
            results = launch(
                this_argv,
                nprocs=n,
                inject_fault=inject_fault if attempt == 0 else None,
                **launch_kwargs,
            )
        finally:
            if watcher is not None:
                watcher.disarm()
        status, dead, reason = _judge(results)
        report.launches.append({
            "attempt": attempt,
            "nprocs": n,
            "mesh": mesh,
            "resume_step": start,
            "status": status,
            "ok": status == "ok",
            "dead_ranks": dead,
            "reason": reason,
            "returncodes": [p.returncode for p, _ in results],
        })
        report.results = results
        if status == "ok":
            report.final_nprocs = n
            event("elastic.complete", nprocs=n, mesh=mesh,
                  shrinks=report.shrinks, grows=report.grows)
            log(f"elastic: run complete on {n} rank(s) after "
                f"{report.shrinks} shrink(s) and {report.grows} grow(s)")
            return report

        if status == "preempted":
            # Re-resolve AFTER the launch: the ranks exited from a
            # durable boundary (or skipped to the previous one).
            start = resume_step()
            if _preempt.requested():
                # The PARENT holds the eviction notice (the launcher's
                # forwarder stamped it): the whole job is being taken.
                # Stop relaunching; the next invocation resumes.
                report.preempted = True
                report.final_nprocs = n
                event("elastic.preempted", nprocs=n, mesh=mesh,
                      resume_step=start, reason=reason)
                log(f"elastic: job preempted on {n} rank(s); resumable "
                    f"from step {start}")
                # The notice is CONSUMED by returning it in the report:
                # preempt's request state is module-global, and a
                # long-lived driver (the serving layer) that calls
                # run_elastic again in this process must not have its
                # next grow-preemption misread as a second whole-job
                # eviction.
                _preempt.reset()
                return report
            grow_to = None
            if watcher is not None and watcher.target is not None:
                grow_to = watcher.target
            elif budget_fn is not None:
                try:
                    budget = int(budget_fn())
                except Exception:  # noqa: BLE001
                    budget = n
                if policy.wants_grow(n, budget, step=start,
                                     last_change_step=last_change_step):
                    candidate = policy.grow_target(n, budget, plan_ranks)
                    if candidate > n:
                        grow_to = candidate
            if grow_to is not None and grow_to > n:
                new_mesh = mesh_for(grow_to)
                event("elastic.grow", old_nprocs=n, new_nprocs=grow_to,
                      old_mesh=mesh, new_mesh=new_mesh,
                      resume_step=start, reason="device-budget")
                log(f"elastic: growing {n} → {grow_to} rank(s) "
                    f"(device budget), resuming from step {start}")
                report.grows += 1
                last_change_step = start
                n = grow_to
            else:
                report.resumes += 1
                if report.resumes > policy.max_preempt_resumes:
                    event("elastic.gave-up", nprocs=n, reason=(
                        f"{report.resumes} preempted relaunches "
                        f"(max {policy.max_preempt_resumes})"))
                    raise ElasticExhausted(
                        f"preempted {report.resumes} times without "
                        "completing — giving up"
                    )
                event("elastic.resume", nprocs=n, mesh=mesh,
                      resume_step=start, reason=reason)
                log(f"elastic: ranks preempted; relaunching on {n} "
                    f"rank(s) from step {start}")
            attempt += 1
            continue

        # status == "failed": shrink (precedence over any grow signal —
        # the budget's optimism is exactly what the dead rank disproved).
        if policy.give_up(n):
            event("elastic.gave-up", nprocs=n, reason=reason,
                  dead_ranks=dead)
            log(f"elastic: giving up — failed at min_ranks="
                f"{policy.min_ranks} ({reason})")
            raise ElasticExhausted(
                f"run failed at the minimum rank count {policy.min_ranks}: "
                f"{reason}"
            )
        new_n = policy.shrink_target(n, len(dead), plan_ranks)
        new_mesh = mesh_for(new_n)
        # Re-resolve AFTER the failed launch (its ranks saved steps) —
        # then carry the value: nothing runs between this shrink and
        # the next launch, so re-walking every manifest again at the
        # loop top would be pure repeated validation I/O.
        start = resume_step()
        event("elastic.shrink", old_nprocs=n, new_nprocs=new_n,
              old_mesh=mesh, new_mesh=new_mesh, dead_ranks=dead,
              reason=reason, resume_step=start)
        log(f"elastic: shrinking {n} → {new_n} rank(s) "
            f"({reason}; dead {dead}), resuming from step {start}")
        report.shrinks += 1
        last_change_step = start
        n = new_n
        attempt += 1
