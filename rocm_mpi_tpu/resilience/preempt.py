"""Preemption-aware shutdown: SIGTERM with a grace deadline
(docs/RESILIENCE.md §7).

Production schedulers do not kill a pod outright — they send SIGTERM
and give it a grace window (Kubernetes `terminationGracePeriodSeconds`,
Slurm `--signal=TERM@grace`, Borg eviction notices), then SIGKILL. A
rank that ignores the notice loses everything since its last completed
save; a rank that panics and STARTS a save it cannot finish leaves a
torn step dir for the next resume to trip over. This module is the
deadline-aware middle path:

 1. `install()` registers a SIGTERM handler (this module lives in
    `resilience/`, one of the two GL07 signal-hygiene owners — handler
    installation anywhere else is a lint finding). The handler is
    async-signal-minimal: it stamps the request time and the grace
    deadline into module state and returns. It deliberately does NOT
    touch telemetry — the events layer takes a lock, and a signal
    arriving while the main thread holds that very lock would deadlock
    the interpreter. The first boundary that *notices* the request
    emits the `preempt.noticed` event instead.
 2. The segmented checkpoint loop (utils.checkpoint.run_segmented)
    polls `requested()` at every segment boundary — the only place the
    state is whole and quiescent — and makes the deadline call:
    save if the telemetry-measured p90 save wall (times a safety
    factor) fits the remaining grace, else SKIP the save entirely and
    rely on the last valid step. A save that would be SIGKILLed
    mid-write is worse than no save: it burns the grace AND leaves a
    torn artifact.
 3. Either way the rank exits `RC_PREEMPTED` (75, EX_TEMPFAIL: "try
    again later") via the `Preempted` SystemExit subclass — a rc the
    supervisors upstack classify as RESUMABLE: `run_supervised` never
    retries a SystemExit, and `resilience.elastic._judge` reports a
    launch whose only nonzero rcs are RC_PREEMPTED as "preempted", to
    be relaunched/resumed (or grown — the elastic rejoin probe delivers
    SIGTERM on purpose), never shrunk or given up on.

Multi-rank note: every rank decides save-vs-skip from its own deadline
and save history. The launcher forwards one SIGTERM to all ranks in the
same pass (`install_forwarder`), so in practice the inputs — and hence
the collective save-or-skip decision — agree; a pathological skew would
strand savers in the collective save barrier, where the launcher's
peer-grace kill reaps them and the resume falls back one segment. That
bounded fallback is the contract, not a hang.

stdlib-only; `requested()` is one module-global read on the hot path.
"""

from __future__ import annotations

import os
import signal
import time

RC_PREEMPTED = 75  # EX_TEMPFAIL: resumable interruption, not a failure
ENV_GRACE = "RMT_PREEMPT_GRACE_S"
DEFAULT_GRACE_S = 30.0

# The emergency-save budget call: the p90 save wall must fit the
# remaining grace with this much headroom (saves have tails), and with
# no history at all only a comfortably long grace may gamble on a save.
SAFETY_FACTOR = 1.5
NO_HISTORY_FLOOR_S = 10.0

_ARMED = False
_GRACE_S: float | None = None
_REQUESTED_MONO: float | None = None
_DEADLINE_MONO: float | None = None
_NOTICED = False
_PREV_HANDLER = None


class Preempted(SystemExit):
    """The preemption exit: code RC_PREEMPTED so every supervisor
    upstack can tell 'resumable, scheduler took the machine' from a
    failure. `step` is the last DURABLE step (the one a resume will
    restore); `saved` says whether the emergency save landed."""

    def __init__(self, step=None, saved: bool = False):
        super().__init__(RC_PREEMPTED)
        self.step = step
        self.saved = saved


def _handler(signum, frame) -> None:
    # Async-signal-minimal on purpose: stamp state, return. No locks, no
    # telemetry, no I/O — the interrupted main thread may hold any of
    # those locks (module docstring).
    global _REQUESTED_MONO, _DEADLINE_MONO
    if _REQUESTED_MONO is None:
        _REQUESTED_MONO = time.monotonic()
        _DEADLINE_MONO = _REQUESTED_MONO + (_GRACE_S or 0.0)


def install(grace_s: float | None = None) -> bool:
    """Register the SIGTERM grace-deadline handler. `grace_s` is the
    scheduler's promised window between SIGTERM and SIGKILL (default:
    RMT_PREEMPT_GRACE_S, else 30 s). Returns whether the handler is
    armed (False on platforms without SIGTERM or off the main thread —
    preemption awareness degrades to the legacy die-on-TERM, never to
    an error)."""
    global _ARMED, _GRACE_S, _PREV_HANDLER
    if not hasattr(signal, "SIGTERM"):
        return False
    if grace_s is None:
        raw = os.environ.get(ENV_GRACE, "").strip()
        try:
            grace_s = float(raw) if raw else DEFAULT_GRACE_S
        except ValueError:
            grace_s = DEFAULT_GRACE_S
    _GRACE_S = max(float(grace_s), 0.0)
    try:
        prev = signal.signal(signal.SIGTERM, _handler)
    except (ValueError, OSError):  # not the main thread / exotic platform
        return False
    if not _ARMED:
        _PREV_HANDLER = prev
    _ARMED = True
    return True


def install_from_env() -> bool:
    """Arm the handler when the launcher contract says so
    (RMT_PREEMPT_GRACE_S set — spawn_ranks forwards it); cheap no-op
    otherwise. Workers call this once at startup."""
    raw = os.environ.get(ENV_GRACE, "").strip()
    if not raw:
        return False
    try:
        grace = float(raw)
    except ValueError:
        return False
    return install(grace)


def uninstall() -> None:
    """Restore the pre-install SIGTERM disposition and clear the
    request state (tests; also the forwarder's restore path)."""
    global _ARMED, _PREV_HANDLER
    if _ARMED and hasattr(signal, "SIGTERM"):
        try:
            signal.signal(signal.SIGTERM, _PREV_HANDLER or signal.SIG_DFL)
        except (ValueError, OSError):
            pass
    _ARMED = False
    _PREV_HANDLER = None
    reset()


def reset() -> None:
    """Clear a pending request (tests, and a supervisor that consumed
    the preemption and is deliberately carrying on)."""
    global _REQUESTED_MONO, _DEADLINE_MONO, _NOTICED
    _REQUESTED_MONO = None
    _DEADLINE_MONO = None
    _NOTICED = False


def request(grace_s: float | None = None) -> None:
    """Raise the preemption flag WITHOUT a signal — the drill hook (and
    the only path on platforms with no SIGTERM). Same semantics as the
    handler: first request wins, deadline = now + grace."""
    global _GRACE_S
    if grace_s is not None:
        _GRACE_S = max(float(grace_s), 0.0)
    elif _GRACE_S is None:
        _GRACE_S = DEFAULT_GRACE_S
    _handler(None, None)


def requested() -> bool:
    """Has a preemption notice arrived? One module-global read."""
    return _REQUESTED_MONO is not None


def remaining_grace_s() -> float | None:
    """Seconds left before the scheduler's SIGKILL (negative once the
    deadline passed); None while no preemption is pending."""
    if _DEADLINE_MONO is None:
        return None
    return _DEADLINE_MONO - time.monotonic()


def budget_allows_save(save_wall_p90_s: float | None) -> bool:
    """The emergency-save decision: does the measured p90 save wall
    (with SAFETY_FACTOR headroom) fit the remaining grace? With no
    save history only a grace above NO_HISTORY_FLOOR_S gambles on a
    save. True when no preemption is pending (a normal save)."""
    rem = remaining_grace_s()
    if rem is None:
        return True
    if save_wall_p90_s is None:
        return rem >= NO_HISTORY_FLOOR_S
    return rem >= save_wall_p90_s * SAFETY_FACTOR


def note_noticed() -> bool:
    """First-notice latch: True exactly once per request, so the
    boundary that first observes the preemption can emit the
    `preempt.noticed` telemetry event the handler itself must not."""
    global _NOTICED
    if not requested() or _NOTICED:
        return False
    _NOTICED = True
    return True


def install_forwarder(procs) -> object:
    """Parent-side preemption forwarding (the launcher seam): when the
    LAUNCHER gets the scheduler's SIGTERM, every live rank must see it
    too — they hold the state. Registers a SIGTERM handler that stamps
    the parent's own request state (so run_elastic knows the whole job
    is being evicted, not one rank) and relays SIGTERM to every live
    proc in `procs`. Returns a zero-arg restore callable; spawn_ranks
    calls it on every exit path. Signal-handler installation lives HERE
    (resilience/ is a GL07 owner) — the launcher only calls this."""
    if not hasattr(signal, "SIGTERM"):
        return lambda: None

    def _forward(signum, frame):
        _handler(signum, frame)
        for p in procs:
            try:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            except OSError:
                pass

    try:
        prev = signal.signal(signal.SIGTERM, _forward)
    except (ValueError, OSError):
        return lambda: None

    def restore():
        try:
            signal.signal(signal.SIGTERM, prev)
        except (ValueError, OSError):
            pass

    return restore
