"""Supervised segmented runs: retry/backoff around checkpointed advance
(docs/RESILIENCE.md §1).

The round-5 outage record (165 failed probes over ~11.5 h,
docs/chip_watcher_r5.log) is the operating reality: the backend flaps,
and a long run's expected failure count is > 0. `run_supervised` is the
process-level answer — the same discipline the bash chip watcher applies
from outside, moved inside the run where it can resume from the latest
VALID checkpoint instead of restarting from step 0:

    state = run_supervised(advance, init_state, nt, directory, every)

is `utils/checkpoint.run_segmented` wrapped in a supervision loop:

  * a crash (any exception the policy classifies as retryable — backend
    errors, injected faults, OOM-class runtime errors) re-resolves
    `latest_valid_step` — NOT merely latest: a crash mid-save leaves a
    torn checkpoint, which validation skips, falling back to the
    previous kept step;
  * the restart waits exponential-backoff long (base * factor**attempt,
    capped), exactly like the bench parent's child-retry policy;
  * attempts are bounded; exhaustion re-raises the last failure — a
    supervisor must never convert a persistent failure into silence;
  * every decision emits a structured telemetry event
    ("attempt-failed" / "backoff" / "restored" / "recovered" /
    "gave-up") — versioned, monotonic-stamped, written to the rank's
    telemetry stream when collection is on (docs/TELEMETRY.md), and
    still visible through the legacy `utils.metrics.events()` view —
    so the retry history is machine-readable next to the run's
    performance metrics.

The advance contract is unchanged (`advance(state, n) -> state`, traced
n) — supervision composes around the compiled program, never inside it.

Scope: this supervisor retries on the SAME topology — right when the
failure was transient (backend flap, IO hiccup). When the topology
itself died (watchdog-killed rank, preempted pod, vanished container),
retrying the same mesh can only fail again; that case belongs to the
launcher-level ELASTIC supervisor (resilience.elastic.run_elastic),
which shrinks to the largest valid sub-mesh and resumes from the latest
valid step through the v2 manifests' topology metadata
(docs/RESILIENCE.md "Elastic recovery").
"""

from __future__ import annotations

import time

from rocm_mpi_tpu import telemetry
from rocm_mpi_tpu.utils import checkpoint as ckpt


def default_retryable(exc: BaseException) -> bool:
    """Crash classification: retry runtime/backend/injected failures;
    never retry programming errors (TypeError, ValueError...) — those
    reproduce identically and must surface immediately."""
    from rocm_mpi_tpu.resilience.faults import InjectedCrash

    if isinstance(exc, InjectedCrash):
        return True
    if isinstance(exc, (KeyboardInterrupt, SystemExit)):
        return False
    # XlaRuntimeError subclasses RuntimeError in every jax this targets;
    # OSError covers checkpoint-IO flaps (the tunnel drops mid-write).
    return isinstance(exc, (RuntimeError, OSError))


def run_supervised(
    advance,
    init_state,
    nt: int,
    directory,
    every: int,
    *,
    max_retries: int = 3,
    backoff_s: float = 0.5,
    backoff_factor: float = 2.0,
    backoff_max_s: float = 60.0,
    resume: bool = True,
    retryable=default_retryable,
    sleep=time.sleep,
    log=None,
):
    """Run `nt` steps of `advance` with checkpointing every `every` steps
    under crash supervision; returns the final state.

    `init_state` is BOTH the cold-start state and the restore template
    (shapes/dtypes/shardings) — the same dual role the apps' --resume
    path gives it. With resume=True an existing valid checkpoint in
    `directory` is continued even on the first attempt, so a re-invoked
    process (the watcher's retry, a preempted pod) supervises seamlessly
    into the same run.

    `max_retries` bounds RESTARTS (attempts = max_retries + 1);
    exhaustion re-raises the last exception after a "gave-up" event.
    `sleep` is injectable so tests assert the exponential schedule
    without waiting it out.
    """
    if max_retries < 0:
        raise ValueError(f"max_retries must be >= 0, got {max_retries}")
    log = log or (lambda *_: None)

    import jax
    import jax.numpy as jnp

    # `init_state` itself is NEVER handed to the advance: the framework's
    # advances donate their state argument, so a cold restart after a
    # pre-first-checkpoint crash would otherwise feed already-donated
    # buffers back in and die on a (non-retryable) deleted-buffer error —
    # exactly when supervision matters most. Each cold start gets a fresh
    # copy; the pristine original stays valid as the restore template
    # (shapes/dtypes/shardings survive regardless).
    def cold_state():
        return jax.tree_util.tree_map(jnp.copy, init_state)

    def resolve_start():
        """(start_step, state) from the latest VALID checkpoint."""
        start = ckpt.latest_valid_step(directory, log=log)
        if start is None:
            return 0, cold_state()
        state = ckpt.restore_state(directory, start, init_state)
        telemetry.record_event("restored", step=start)
        log(f"supervisor: restored step {start} from {directory}")
        return start, state

    attempt = 0
    recovered = False
    while True:
        try:
            if resume or attempt > 0:
                start, state = resolve_start()
            else:
                start, state = 0, cold_state()
            if start >= nt:
                log(f"supervisor: checkpoint already at step {start} >= "
                    f"nt={nt}; nothing to run")
                final = state
            else:
                final = ckpt.run_segmented(
                    advance, state, nt, directory, every, start_step=start
                )
            if recovered:
                telemetry.record_event("recovered", attempt=attempt, step=nt)
            return final
        except BaseException as exc:  # noqa: BLE001 — classified below
            if not retryable(exc):
                raise
            err = f"{type(exc).__name__}: {exc}"
            telemetry.record_event(
                "attempt-failed", attempt=attempt, error=err
            )
            log(f"supervisor: attempt {attempt} failed — {err}")
            if attempt >= max_retries:
                telemetry.record_event(
                    "gave-up", attempt=attempt, error=err
                )
                log(f"supervisor: giving up after {attempt + 1} attempts")
                raise
            wait = min(backoff_s * backoff_factor**attempt, backoff_max_s)
            telemetry.record_event("backoff", attempt=attempt, wait_s=wait)
            log(f"supervisor: retrying in {wait:.2f}s")
            sleep(wait)
            attempt += 1
            recovered = True
