"""The compiled HBM-traffic gate CLI (docs/PERF.md).

    python -m rocm_mpi_tpu.perf [--local N] [--devices N] [--deep-k K]
                                [--budgets PATH] [--json]
                                [--include-waste-fixture]
                                [--include-wire-fixture] [--no-wire]

CPU-only by construction: it pins the CPU backend, builds a small
virtual-device mesh, lowers + compiles each distributed step driver, and
gates the modeled bytes-per-invocation (and exact collective wire bytes)
against the committed budgets in rocm_mpi_tpu/perf/budgets.json. It then
runs the wire-bytes ladder (docs/PERF.md "Wire precision"): one deep
sweep compiled per wire mode, its exact collective send bytes held to
the mode's closed-form ideal AND the committed ladder fraction of the
full-precision wire (--no-wire skips it; --include-wire-fixture audits
the doctored over-ladder regression row, which must fail).

Exit codes: 0 every audited variant within budget; 1 any variant over
budget (or over the wire ideal); 2 usage/internal error. Runs in tier-1
and scripts/lint.sh — no accelerator, no timing, no flakiness.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m rocm_mpi_tpu.perf",
        description=__doc__.splitlines()[0],
    )
    p.add_argument("--local", type=int, default=None,
                   help="per-device shard edge (default: budgets geometry)")
    p.add_argument("--devices", type=int, default=None,
                   help="virtual CPU devices (default: budgets geometry)")
    p.add_argument("--deep-k", type=int, default=None,
                   help="deep sweep depth (default: budgets geometry)")
    p.add_argument("--budgets", default=None, metavar="PATH",
                   help="budgets file (default: rocm_mpi_tpu/perf/budgets.json)")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON line per row on stdout (table goes "
                   "to stderr)")
    p.add_argument("--include-waste-fixture", action="store_true",
                   help="also audit the known-waste concatenate-splice "
                   "fixture (regression-tests the gate itself; EXPECTED "
                   "to fail, so the exit code goes 1)")
    p.add_argument("--include-wire-fixture", action="store_true",
                   help="also audit the doctored over-ladder wire row "
                   "(a full-precision program claiming the bf16 ladder "
                   "row; regression-tests the wire-bytes ladder — "
                   "EXPECTED to fail, so the exit code goes 1)")
    p.add_argument("--no-wire", action="store_true",
                   help="skip the wire-bytes ladder (docs/PERF.md 'Wire "
                   "precision'); the ladder runs by default")
    p.add_argument("--include-batch-fixture", action="store_true",
                   help="also audit the doctored over-padded batched row "
                   "(a 4-wide batched program carrying one live lane; "
                   "regression-tests the batched-step audit, docs/"
                   "SERVING.md — EXPECTED to fail, so the exit code "
                   "goes 1)")
    p.add_argument("--no-batch", action="store_true",
                   help="skip the batched-step audit (docs/SERVING.md); "
                   "it runs by default")
    args = p.parse_args(argv)

    # CPU pinning BEFORE any backend use: the gate must neither need nor
    # touch an accelerator (a flaky chip tunnel cannot hang it).
    import jax

    from rocm_mpi_tpu.utils.backend import set_cpu_device_count

    from rocm_mpi_tpu.perf import traffic

    try:
        budgets = traffic.load_budgets(args.budgets)
    except (OSError, ValueError) as e:
        print(f"perf: cannot load budgets: {e}", file=sys.stderr)
        return 2
    geo = budgets.get("geometry", {})
    local = args.local or int(geo.get("local", traffic.DEFAULT_LOCAL))
    deep_k = args.deep_k or int(geo.get("deep_k", traffic.DEFAULT_DEEP_K))
    dims = tuple(int(d) for d in geo.get("dims", (2, 1)))
    if args.devices:
        from rocm_mpi_tpu.parallel.mesh import suggest_dims

        dims = suggest_dims(args.devices, 2)

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import math

    set_cpu_device_count(max(2, math.prod(dims)))

    rows = traffic.audit_variants(
        local=local, dims=dims, deep_k=deep_k, budgets=budgets,
        include_waste_fixture=args.include_waste_fixture,
    )
    if not args.no_batch:
        # The multi-tenant batched-step audit (docs/SERVING.md): the
        # B-lane program's bytes per invocation vs B × the single-lane
        # ideal; rows render/gate alongside the per-variant audit.
        serving_geo = budgets.get("serving", {})
        rows += traffic.audit_batched(
            local=local, dims=dims,
            batch=int(serving_geo.get("batch", traffic.DEFAULT_BATCH)),
            budgets=budgets,
            include_batch_fixture=args.include_batch_fixture,
        )
    wire_rows = []
    if not args.no_wire:
        wire_geo = budgets.get("wire", {})
        wire_rows = traffic.audit_wire_modes(
            local=int(wire_geo.get("local", traffic.DEFAULT_WIRE_LOCAL)),
            dims=dims,
            deep_k=int(wire_geo.get("deep_k",
                                    traffic.DEFAULT_WIRE_DEEP_K)),
            budgets=budgets,
            include_wire_fixture=args.include_wire_fixture,
        )
    table = traffic.render_table(rows)
    if wire_rows:
        table += "\n\n" + traffic.render_wire_table(wire_rows)
    if args.json:
        print(table, file=sys.stderr)
        for r in rows:
            print(json.dumps({
                "metric": f"traffic {r.variant}", "steps": r.steps,
                "bytes": r.measured_bytes, "ideal": r.ideal_bytes,
                "ratio": round(r.ratio, 4), "wire": r.wire_bytes,
                "wire_ideal": r.wire_ideal, "budget": r.budget,
                "ok": r.ok,
            }))
        for w in wire_rows:
            print(json.dumps({
                "metric": f"wire {w.mode}", "bytes": w.wire_bytes,
                "full_ideal": w.full_ideal, "mode_ideal": w.mode_ideal,
                "fraction": round(w.fraction, 4), "ladder": w.ladder,
                "ok": w.ok,
            }))
    else:
        print(table)
    bad = [r for r in rows if not r.ok]
    bad_wire = [w for w in wire_rows if not w.ok]
    if bad or bad_wire:
        msgs = [
            f"{r.variant} ({r.ratio:.2f}x vs "
            f"{r.budget if r.budget is not None else '—'}"
            f"{'' if r.wire_ok else ', wire over ideal'})"
            for r in bad
        ] + [
            f"wire {w.mode} ({w.fraction:.3f} of the f32 wire vs ladder "
            f"{w.ladder if w.ladder is not None else '—'})"
            for w in bad_wire
        ]
        print("perf: TRAFFIC GATE FAILED — " + ", ".join(msgs),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
