"""Compiled HBM-traffic audit: is every distributed step driver
traffic-minimal, *provably*?

The reference's whole performance ladder is judged by one number — T_eff,
effective memory throughput against the ideal of exactly (2+1)
array-traversals per step: read T, write T2, read Cp (BASELINE.md;
/root/reference/scripts/diffusion_2D_perf.jl:55-58). A distributed step
can silently drift away from that bound through staging copies the
schedule never needed — concatenate splices, defensive buffer copies,
re-exchanged loop invariants — and wall-clock timing on a loaded CI box
cannot catch the drift. This module catches it statically:

1. lower + compile each step driver's per-invocation program on the CPU
   backend (the HLO *structure* — staging copies, collective shapes,
   materialized intermediates — is what the audit cares about, and it is
   visible without any accelerator);
2. walk the optimized entry HLO and model its memory traffic per op
   (`hlo_bytes_accessed`): every op reads its operands and writes its
   result, EXCEPT the ops XLA executes without touching the full buffer
   (in-place `dynamic-update-slice` costs two update-sized accesses;
   `slice` reads only what it emits). The raw
   `compiled.cost_analysis()["bytes accessed"]` (via the
   `utils/compat.cost_analysis_dict` chokepoint) is recorded alongside,
   but it charges every in-place ghost write a whole-buffer round trip,
   which would drown the very staging signal the gate watches for — both
   numbers appear in the report;
3. compare against the variant's analytic A_eff ideal (`ideal_*_bytes`:
   the traversal count a traffic-minimal schedule needs, docs/PERF.md)
   and gate the ratio against the committed budget
   (rocm_mpi_tpu/perf/budgets.json).

The audit runs per-shard: programs are compiled over a small multi-device
CPU mesh (the acceptance geometry is 2 virtual ranks) and the modeled
bytes are the per-partition program's. Results are emitted as
`telemetry.annotate("step.traffic", ...)` facts when telemetry is on.
"""

from __future__ import annotations

import dataclasses
import json
import math
import pathlib
import re

DEFAULT_LOCAL = 64
DEFAULT_DEEP_K = 8
BUDGETS_PATH = pathlib.Path(__file__).with_name("budgets.json")

# ---------------------------------------------------------------------------
# The per-op traffic model over optimized HLO text
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}
_SHAPE_RE = re.compile(
    r"\b(pred|s8|u8|s16|u16|bf16|f16|s32|u32|f32|s64|u64|f64|c64|c128)"
    r"\[([0-9,]*)\]"
)
_OP_RE = re.compile(r"^(?:ROOT\s+)?%[\w.\-]+\s*=\s*(.*?)\s([\w\-]+)\(")

# Ops that move no tensor bytes of their own (parameters/constants are
# charged where they are consumed, as operand reads).
_FREE_OPS = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "partition-id", "replica-id", "custom-call",
})


def _tokens_bytes(text: str) -> list[int]:
    return [
        _DTYPE_BYTES[m.group(1)] * math.prod(
            int(d) for d in m.group(2).split(",") if d
        )
        for m in _SHAPE_RE.finditer(text)
    ]


def hlo_wire_bytes(hlo_text: str) -> int:
    """Bytes this partition's program SENDS over collectives per
    invocation: the summed operand bytes of its `collective-permute` ops.
    Unlike the modeled total, this figure is exact and lowering-stable —
    a schedule that re-grows an exchange (the old per-sweep coefficient
    re-exchange) moves it by whole slabs, so the gate holds it to the
    analytic wire ideal with almost no tolerance."""
    total = 0
    in_entry = False
    for raw in hlo_text.splitlines():
        line = raw.strip()
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry and line.startswith("}"):
            break
        if not in_entry:
            continue
        m = _OP_RE.match(line)
        if m and m.group(2) == "collective-permute":
            body = line.split(", metadata=")[0]
            result = sum(_tokens_bytes(m.group(1)))
            total += sum(_tokens_bytes(body)) - result
    return total


def hlo_bytes_accessed(hlo_text: str) -> int:
    """Modeled memory traffic (bytes) of one invocation of the optimized
    entry computation.

    Per-op rules (the module docstring has the why):
      * default: sum(operand bytes) + result bytes — producers write
        memory, consumers read it back;
      * `fusion`: result bytes + per-operand min(operand, result) bytes —
        fusions stream their boundary I/O (subcomputations live in
        registers), and a fusion that emits a slab never streams more of
        an operand than it emits (a kLoop fusion slicing one column out
        of the padded buffer reads a column, not the buffer);
      * `dynamic-update-slice`: 2 × update bytes (XLA updates in place);
      * `slice` / `dynamic-slice`: 2 × result bytes (reads only the
        window it emits);
      * `collective-permute`: operand + result (send + receive);
      * parameters, constants, tuple plumbing: free (charged at use).
    """
    total = 0
    in_entry = False
    for raw in hlo_text.splitlines():
        line = raw.strip()
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry and line.startswith("}"):
            break
        if not in_entry or "=" not in line:
            continue
        m = _OP_RE.match(line)
        if not m:
            continue
        result_text, op = m.group(1), m.group(2)
        if op in _FREE_OPS:
            continue
        # Strip trailing metadata: shapes never appear inside it, but the
        # op_name strings could in principle — cut at ", metadata=".
        body = line.split(", metadata=")[0]
        result_bytes = sum(_tokens_bytes(result_text))
        operand_bytes = sum(_tokens_bytes(body)) - result_bytes
        if op == "dynamic-update-slice":
            toks = _tokens_bytes(body[m.end():])
            update = toks[1] if len(toks) > 1 else result_bytes
            total += 2 * update
        elif op in ("slice", "dynamic-slice"):
            total += 2 * result_bytes
        elif op == "fusion":
            total += result_bytes + sum(
                min(t, result_bytes) for t in _tokens_bytes(body[m.end():])
            )
        else:
            total += operand_bytes + result_bytes
    return total


# ---------------------------------------------------------------------------
# Analytic A_eff ideals (docs/PERF.md)
# ---------------------------------------------------------------------------


def _prod(xs) -> int:
    return math.prod(int(x) for x in xs)


def ideal_exchanged_step_bytes(local_shape, itemsize: int,
                               width: int = 1) -> int:
    """Per-shard ideal of ONE exchanged step (shard and overlap
    schedules): the (2+1)-traversal bound — read T, write T2, read C —
    plus the irreducible exchange machinery: one padded staging buffer
    (written once, read once by the stencil in place of a raw T read)
    and the ghost slices over the wire (read + send + receive + write,
    all slab-sized)."""
    from rocm_mpi_tpu.parallel.halo import exchange_nbytes

    n = _prod(local_shape) * itemsize
    npad = _prod(ln + 2 * width for ln in local_shape) * itemsize
    halo = exchange_nbytes(local_shape, itemsize, width)
    # read T + write Tp + read Tp + read C + write out  +  4 slab passes
    return 3 * n + 2 * npad + 4 * halo


def ideal_deep_sweep_bytes(local_shape, itemsize: int, k: int) -> int:
    """Per-shard ideal of one deep-halo sweep (k steps, one width-k
    exchange, jnp local form): the exchange staging as above, then k
    local steps each bounded by (2+1) traversals of the PADDED block
    (read Tp, read Cm, write the advanced inner box)."""
    from rocm_mpi_tpu.parallel.halo import exchange_nbytes

    n = _prod(local_shape) * itemsize
    npad = _prod(ln + 2 * k for ln in local_shape) * itemsize
    halo = exchange_nbytes(local_shape, itemsize, k)
    return n + npad + 4 * halo + k * 3 * npad


def ideal_wire_bytes(local_shape, itemsize: int, width: int,
                     wire_mode: str = "f32") -> int:
    """Per-mode closed-form wire ideal of one exchange — the wire-bytes
    ladder's row anchor (halo.exchange_nbytes at the mode's on-wire
    itemsize; parallel/wire.py owns the per-mode tables)."""
    from rocm_mpi_tpu.parallel.halo import exchange_nbytes

    return exchange_nbytes(local_shape, itemsize, width,
                           wire_mode=wire_mode)


# ---------------------------------------------------------------------------
# The audit
# ---------------------------------------------------------------------------


WIRE_TOLERANCE = 1.02  # exact metric; tolerance covers rounding only


@dataclasses.dataclass(frozen=True)
class TrafficRow:
    """One audited step program."""

    variant: str
    steps: int  # steps one program invocation advances
    measured_bytes: int  # modeled traffic per invocation (per shard)
    ideal_bytes: int  # analytic A_eff ideal per invocation
    wire_bytes: int  # exact collective send bytes per invocation
    wire_ideal: int  # analytic exchange_nbytes for the schedule
    cost_analysis_bytes: float  # raw XLA cost-analysis figure (context)
    budget: float | None  # committed max measured/ideal ratio

    @property
    def ratio(self) -> float:
        return self.measured_bytes / self.ideal_bytes

    @property
    def wire_ratio(self) -> float:
        return self.wire_bytes / self.wire_ideal if self.wire_ideal else 0.0

    @property
    def wire_ok(self) -> bool:
        return self.wire_ratio <= WIRE_TOLERANCE

    @property
    def ok(self) -> bool:
        return (
            self.budget is None or self.ratio <= self.budget
        ) and self.wire_ok


def _modeled_bytes(jitted, *args) -> tuple[int, int, float]:
    from rocm_mpi_tpu.utils.compat import cost_analysis_dict

    compiled = jitted.lower(*args).compile()
    raw = cost_analysis_dict(compiled).get("bytes accessed", float("nan"))
    text = compiled.as_text()
    return hlo_bytes_accessed(text), hlo_wire_bytes(text), float(raw)


def load_budgets(path=None) -> dict:
    doc = json.loads(pathlib.Path(path or BUDGETS_PATH).read_text())
    if not isinstance(doc, dict) or "budgets" not in doc:
        raise ValueError(f"unrecognized budgets file {path or BUDGETS_PATH}")
    return doc


def _legacy_overlap_step(model):
    """The pre-rework overlap splice, kept as the gate's KNOWN-WASTE
    fixture: per-axis concatenate halo staging, a concatenate tree
    re-assembling the shard from its region updates, and a trailing
    whole-shard Dirichlet `where` over a mask rebuilt in the step. The
    regression test asserts the gate FAILS this program — proof the audit
    detects the staging-copy class it exists for, not just that budgets
    are loose."""
    import jax
    import jax.numpy as jnp

    from rocm_mpi_tpu.ops.diffusion import step_fused_padded
    from rocm_mpi_tpu.parallel.halo import (
        global_boundary_mask,
        neighbor_shift,
    )
    from rocm_mpi_tpu.parallel.overlap import effective_b_width
    from rocm_mpi_tpu.utils.compat import shard_map

    cfg, grid = model.config, model.grid
    local, ndim = grid.local_shape, grid.ndim
    bw = effective_b_width(local, cfg.b_width)
    dt = cfg.jax_dtype(cfg.dt)

    def concat_exchange(u):
        for ax in range(ndim):
            name = grid.axis_names[ax]
            lo = tuple(
                slice(0, 1) if a == ax else slice(None) for a in range(ndim)
            )
            hi = tuple(
                slice(-1, None) if a == ax else slice(None)
                for a in range(ndim)
            )
            ghost_lo = neighbor_shift(u[hi], name, +1)
            ghost_hi = neighbor_shift(u[lo], name, -1)
            u = jnp.concatenate([ghost_lo, u, ghost_hi], axis=ax)
        return u

    def local_step(Tl, Cpl):
        Tp = concat_exchange(Tl)

        def region(bounds):
            pad_idx = tuple(slice(lo, hi + 2) for lo, hi in bounds)
            core_idx = tuple(slice(lo, hi) for lo, hi in bounds)
            return step_fused_padded(
                Tp[pad_idx], Cpl[core_idx], cfg.lam, dt, cfg.spacing
            )

        def build(axis, prefix):
            if axis == ndim:
                return region(prefix)
            n, b = local[axis], bw[axis]
            rest = [(0, local[a]) for a in range(axis + 1, ndim)]
            parts = [region(prefix + [(0, b)] + rest)]
            if n - 2 * b > 0:
                parts.append(build(axis + 1, prefix + [(b, n - b)]))
            parts.append(region(prefix + [(n - b, n)] + rest))
            return jnp.concatenate(parts, axis=axis)

        new = build(0, [])
        return jnp.where(global_boundary_mask(grid), Tl, new)

    def step(T, C):
        return shard_map(
            local_step,
            mesh=grid.mesh,
            in_specs=(grid.spec, grid.spec),
            out_specs=grid.spec,
            check_vma=False,
        )(T, C)

    # Donated like the audited drivers — the fixture's waste is its
    # concatenate staging, which no aliasing can remove.
    return jax.jit(step, donate_argnums=0)


def audit_variants(local: int = DEFAULT_LOCAL, dims=(2, 1),
                   deep_k: int = DEFAULT_DEEP_K, budgets: dict | None = None,
                   include_waste_fixture: bool = False) -> list[TrafficRow]:
    """Compile + audit the distributed diffusion step drivers on the
    current (CPU) backend: the fused shard step, the overlap step, and
    one deep-k sweep (jnp local form — the shapes the CPU backend
    actually lowers; the Pallas forms are TPU-measured, not CPU-modeled).
    f64 keeps every audited program on the pure-XLA path."""
    import jax

    from rocm_mpi_tpu import telemetry
    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion
    from rocm_mpi_tpu.parallel.deep_halo import make_deep_sweep

    if budgets is None:
        budgets = load_budgets()
    budget_of = budgets.get("budgets", {})

    dims = tuple(int(d) for d in dims)
    cfg = DiffusionConfig(
        global_shape=tuple(local * d for d in dims),
        lengths=(10.0,) * len(dims),
        nt=8, warmup=0, dtype="f64", dims=dims,
        # A REAL overlap decomposition at the audit's shard size: the
        # default (32,4) frame swallows a 64² shard whole (no interior),
        # which would audit a slab-only program no production overlap
        # run executes.
        b_width=(local // 8, local // 8),
    )
    model = HeatDiffusion(cfg)
    itemsize = jax.numpy.dtype(cfg.jax_dtype).itemsize
    local_shape = model.grid.local_shape
    T, Cp = model.init_state()
    shard_ideal = ideal_exchanged_step_bytes(local_shape, itemsize)

    from rocm_mpi_tpu.parallel.halo import exchange_nbytes

    wire_step = exchange_nbytes(local_shape, itemsize, 1)

    rows: list[TrafficRow] = []

    def audit(variant, budget_key, jitted, args, steps, ideal, wire_ideal):
        measured, wire, raw = _modeled_bytes(jitted, *args)
        rows.append(TrafficRow(
            variant=variant, steps=steps, measured_bytes=measured,
            ideal_bytes=ideal, wire_bytes=wire, wire_ideal=wire_ideal,
            cost_analysis_bytes=raw, budget=budget_of.get(budget_key),
        ))

    # donate=True everywhere: the audited programs carry the drivers'
    # steady-state aliasing (their loop carries donate the field), which
    # is what lets XLA run the ghost-write chain in place. Auditing an
    # undonated step would charge every variant a defensive whole-shard
    # copy no driver ever executes.
    for variant, model_variant in (("shard", "shard"), ("overlap", "hide")):
        step, prepare = model.prepared_step_fn(model_variant, donate=True)
        C = prepare(Cp)
        audit(variant, variant, step, (T, C), 1, shard_ideal, wire_step)

    k = min(deep_k, min(local_shape))
    sched = make_deep_sweep(
        model.grid, k, cfg.lam, cfg.jax_dtype(cfg.dt), cfg.spacing,
        local_form="jnp",
    )
    Cm = jax.jit(sched.prepare)(Cp)
    audit(
        f"deep{k}", "deep",
        jax.jit(sched.sweep, donate_argnums=0), (T, Cm), k,
        ideal_deep_sweep_bytes(local_shape, itemsize, k),
        exchange_nbytes(local_shape, itemsize, k),
    )

    if include_waste_fixture:
        # Gated against the SHARD budget: the fixture is a fused shard
        # step rebuilt with the pre-rework concatenate staging — a
        # traffic regression the gate must reject no matter how its
        # wire bytes look.
        audit("concat-splice(fixture)", "shard",
              _legacy_overlap_step(model), (T, Cp), 1, shard_ideal,
              wire_step)

    if telemetry.enabled():
        for r in rows:
            telemetry.annotate(
                "step.traffic", variant=r.variant, steps=r.steps,
                bytes=int(r.measured_bytes), ideal=int(r.ideal_bytes),
                ratio=round(r.ratio, 4), wire=int(r.wire_bytes),
                wire_ideal=int(r.wire_ideal),
                budget=r.budget if r.budget is not None else -1.0,
            )
    return rows


# ---------------------------------------------------------------------------
# The batched-step audit (multi-tenant serving, docs/SERVING.md)
# ---------------------------------------------------------------------------

DEFAULT_BATCH = 2
DEFAULT_BATCH_FIXTURE_WIDTH = 4


def ideal_batched_step_bytes(local_shape, itemsize: int, lanes: int,
                             width: int = 1) -> int:
    """Per-shard ideal of ONE B-lane batched step: exactly `lanes` ×
    the single-lane exchanged-step ideal — batching amortizes the
    PROGRAM, not the bytes, so a batched program that moves more than
    B× the single-lane bytes (per live lane) is shipping padding
    (the bin scheduler's split rule exists to prevent exactly that)."""
    return lanes * ideal_exchanged_step_bytes(local_shape, itemsize, width)


def audit_batched(local: int = DEFAULT_LOCAL, dims=(2, 1),
                  batch: int = DEFAULT_BATCH,
                  budgets: dict | None = None,
                  include_batch_fixture: bool = False) -> list[TrafficRow]:
    """Compile + audit the B-lane batched diffusion step (the serving
    layer's program class: shard_map over the space×batch mesh, the
    per-lane body vmapped — models.diffusion.batched_step_fn) on the
    current (CPU) backend: modeled bytes/invocation must stay within
    BATCH_TOLERANCE × B × the single-lane ideal, and the collective
    wire bytes must be EXACTLY B × the single-lane exchange (a batched
    exchange that ships more is permuting padding).

    `include_batch_fixture` appends the doctored over-padded row: a
    width-{DEFAULT_BATCH_FIXTURE_WIDTH} program carrying ONE live lane,
    audited against the single live lane's ideal — the padding-inflation
    class the bin scheduler's occupancy floor exists to split away. It
    must fail (the gate exits 1)."""
    import jax
    import numpy as np

    from rocm_mpi_tpu import telemetry
    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion
    from rocm_mpi_tpu.parallel.halo import exchange_nbytes

    if budgets is None:
        budgets = load_budgets()
    serving = budgets.get("serving", {})
    tolerance = serving.get("batch_tolerance")
    hide_tolerance = serving.get("hide_tolerance")

    dims = tuple(int(d) for d in dims)
    cfg = DiffusionConfig(
        global_shape=tuple(local * d for d in dims),
        lengths=(10.0,) * len(dims),
        nt=8, warmup=0, dtype="f64", dims=dims,
    )
    model = HeatDiffusion(cfg)
    # The batched-hide twin: same problem, a REAL overlap decomposition
    # at the audit's shard size (audit_variants has the why — the
    # default frame would swallow the shard whole).
    model_hide = HeatDiffusion(dataclasses.replace(
        cfg, b_width=(local // 8, local // 8)
    ))
    itemsize = jax.numpy.dtype(cfg.jax_dtype).itemsize
    local_shape = model.grid.local_shape
    wire1 = exchange_nbytes(local_shape, itemsize, 1)
    T0, Cp = model.init_state()
    T0n, Cpn = np.asarray(T0), np.asarray(Cp)

    def measure(width: int, variant: str = "shard", m=None):
        m = model if m is None else m
        bgrid = m.make_batched_grid(width, batch_dims=1)
        step = m.batched_step_fn(bgrid, variant=variant, donate=True)
        Tb = jax.device_put(np.stack([T0n] * width), bgrid.sharding)
        Cb = m.batched_prepare_fn(bgrid, variant)(
            jax.device_put(Cpn, bgrid.aux_sharding)
        )
        return _modeled_bytes(step, Tb, Cb)

    rows: list[TrafficRow] = []
    measured, wire, raw = measure(batch)
    rows.append(TrafficRow(
        variant=f"batched{batch}", steps=1,
        measured_bytes=measured,
        ideal_bytes=ideal_batched_step_bytes(local_shape, itemsize, batch),
        wire_bytes=wire, wire_ideal=batch * wire1,
        cost_analysis_bytes=raw, budget=tolerance,
    ))

    # The batched-hide program (docs/SERVING.md "The pipeline"): the
    # lane-batched comm/compute overlap the serving layer compiles for
    # variant "hide" bins. Its wire bytes must still be EXACTLY B× one
    # lane's exchange (an over-wire batched hide is permuting padding),
    # and its modeled bytes gate against the committed hide tolerance —
    # an un-overlapped or padding-bloated pipeline program fails here,
    # in the lint stage, before it ever serves traffic.
    measured, wire, raw = measure(batch, variant="hide", m=model_hide)
    rows.append(TrafficRow(
        variant=f"batched-hide{batch}", steps=1,
        measured_bytes=measured,
        ideal_bytes=ideal_batched_step_bytes(local_shape, itemsize, batch),
        wire_bytes=wire, wire_ideal=batch * wire1,
        cost_analysis_bytes=raw, budget=hide_tolerance,
    ))

    # The ladder row (docs/SERVING.md "Continuous batching"): the
    # rung-shaped ladder program carrying lanes whose ORIGINAL domains
    # sit 2 cells shy of the rung per axis — the padding class the
    # shape-padding ladder deliberately admits to consolidate program
    # classes. Audited against the ORIGINAL domains' live-cell ideal,
    # so the ratio prices the padded cells the rung ships on top of the
    # batched program's slack; the budget is batch_tolerance × (1 +
    # padded_flops_tolerance) — a rung whose embedding inflates FLOPs
    # past the committed tolerance fails here, the same split rule
    # serving/bins.ladder_shape enforces at admission. The row runs the
    # f32 program class because that is the only one the service admits
    # to the ladder (lossless f32 wire is an eligibility rule). Measured
    # 2.04 on the gate geometry (126×62 → 128×64 rung, 4.9% cell
    # inflation) vs the 3.0 budget.
    pf_tol = serving.get("padded_flops_tolerance")
    if pf_tol is not None:
        from rocm_mpi_tpu.serving.bins import ladder_shape

        orig = tuple(s - 2 for s in cfg.global_shape)
        rung = ladder_shape(orig, tolerance=float(pf_tol))
        cfg_l = dataclasses.replace(cfg, global_shape=rung, dtype="f32")
        model_l = HeatDiffusion(cfg_l)
        ty = cfg_l.jax_dtype
        item_l = jax.numpy.dtype(ty).itemsize
        bgrid = model_l.make_batched_grid(batch, batch_dims=1)
        step = jax.jit(model_l.batched_ladder_step_fn(bgrid),
                       donate_argnums=0)
        Tb = jax.device_put(
            np.zeros((batch,) + rung, ty), bgrid.sharding)
        Cb = jax.device_put(np.ones(rung, ty), bgrid.aux_sharding)
        hold = jax.device_put(
            np.zeros((batch,) + rung, bool), bgrid.sharding)
        dtlam = jax.device_put(np.ones(batch, ty), bgrid.batch_sharding)
        invd2 = tuple(
            jax.device_put(np.ones(batch, ty), bgrid.batch_sharding)
            for _ in range(len(rung)))
        measured, wire, raw = _modeled_bytes(
            step, Tb, Cb, hold, dtlam, *invd2)
        orig_local = tuple(o // d for o, d in zip(orig, dims))
        rows.append(TrafficRow(
            variant=f"ladder{batch}", steps=1,
            measured_bytes=measured,
            ideal_bytes=ideal_batched_step_bytes(
                orig_local, item_l, batch),
            wire_bytes=wire,
            wire_ideal=batch * exchange_nbytes(
                model_l.grid.local_shape, item_l, 1),
            cost_analysis_bytes=raw,
            budget=(None if tolerance is None
                    else float(tolerance) * (1.0 + float(pf_tol))),
        ))

    if include_batch_fixture:
        # The doctored row: a 4-wide program with ONE live lane — the
        # machine executes 4 lanes of bytes for 1 lane of work. Audited
        # per LIVE lane it lands ~4× over; the gate must exit 1.
        w = DEFAULT_BATCH_FIXTURE_WIDTH
        measured, wire, raw = measure(w)
        rows.append(TrafficRow(
            variant=f"batched-pad{w}/1(fixture)", steps=1,
            measured_bytes=measured,
            ideal_bytes=ideal_batched_step_bytes(local_shape, itemsize, 1),
            wire_bytes=wire, wire_ideal=w * wire1,
            cost_analysis_bytes=raw, budget=tolerance,
        ))

    if telemetry.enabled():
        for r in rows:
            telemetry.annotate(
                "step.traffic", variant=r.variant, steps=r.steps,
                bytes=int(r.measured_bytes), ideal=int(r.ideal_bytes),
                ratio=round(r.ratio, 4), wire=int(r.wire_bytes),
                wire_ideal=int(r.wire_ideal),
                budget=r.budget if r.budget is not None else -1.0,
            )
    return rows


# ---------------------------------------------------------------------------
# The wire-bytes ladder (per-mode reduced-precision exchange audit)
# ---------------------------------------------------------------------------

DEFAULT_WIRE_LOCAL = 64
DEFAULT_WIRE_DEEP_K = 4


@dataclasses.dataclass(frozen=True)
class WireRow:
    """One wire mode's audited deep-sweep program: its EXACT collective
    send bytes from the optimized HLO, held against two anchors — the
    mode's own closed-form ideal (the program must not ship more than
    the codec's accounting, WIRE_TOLERANCE) and the committed ladder
    row (the fraction of the full-precision wire this mode is allowed
    to ship; rocm_mpi_tpu/perf/budgets.json "wire")."""

    mode: str
    wire_bytes: int  # measured send bytes per sweep (per shard)
    full_ideal: int  # full-precision (f32) closed-form wire bytes
    mode_ideal: int  # this mode's closed-form wire bytes
    ladder: float | None  # committed max wire_bytes/full_ideal fraction
    fixture: bool = False  # the doctored over-ladder regression row

    @property
    def fraction(self) -> float:
        return self.wire_bytes / self.full_ideal if self.full_ideal else 0.0

    @property
    def ok(self) -> bool:
        under_ladder = (
            self.ladder is None or self.fraction <= self.ladder
        )
        exact = self.wire_bytes <= WIRE_TOLERANCE * self.mode_ideal
        return under_ladder and exact


def audit_wire_modes(local: int = DEFAULT_WIRE_LOCAL, dims=(2, 1),
                     deep_k: int = DEFAULT_WIRE_DEEP_K,
                     budgets: dict | None = None,
                     include_wire_fixture: bool = False) -> list[WireRow]:
    """Compile the deep-halo sweep (jnp local form, f32 state — the one
    schedule every wire mode supports, stateful modes included) once per
    wire mode on the current (CPU) backend and measure its EXACT
    collective send bytes from the optimized HLO. Each row must land
    within WIRE_TOLERANCE of the mode's closed-form ideal AND under the
    committed ladder fraction of the full-precision wire — the proof
    that a bf16 exchange really ships half the bytes (and the int8/delta
    modes strictly less), not just that a flag flipped.

    `include_wire_fixture` appends the doctored regression row: a
    program that SHIPS full-precision slabs audited against the bf16
    ladder row — the drift class the ladder exists to catch (a codec
    edit that silently stops downcasting). It must fail."""
    import jax
    import jax.numpy as jnp

    from rocm_mpi_tpu import telemetry
    from rocm_mpi_tpu.config import DiffusionConfig
    from rocm_mpi_tpu.models import HeatDiffusion
    from rocm_mpi_tpu.parallel import wire
    from rocm_mpi_tpu.parallel.deep_halo import make_deep_sweep

    if budgets is None:
        budgets = load_budgets()
    wire_cfg = budgets.get("wire", {})
    ladder_of = wire_cfg.get("ladder", dict(wire.DEFAULT_LADDER))

    dims = tuple(int(d) for d in dims)
    cfg = DiffusionConfig(
        global_shape=tuple(local * d for d in dims),
        lengths=(10.0,) * len(dims),
        nt=8, warmup=0, dtype="f32", dims=dims,
    )
    model = HeatDiffusion(cfg)
    local_shape = model.grid.local_shape
    k = min(int(deep_k), min(local_shape))
    itemsize = 4  # f32 state — the production wire-plane dtype
    full_ideal = ideal_wire_bytes(local_shape, itemsize, k, "f32")
    T, Cp = model.init_state()
    dt = cfg.jax_dtype(cfg.dt)

    def measure(mode: str) -> int:
        sched = make_deep_sweep(model.grid, k, cfg.lam, dt, cfg.spacing,
                                local_form="jnp", wire_mode=mode)
        Cm = jax.jit(sched.prepare)(Cp)
        jitted = jax.jit(sched.sweep, donate_argnums=0)
        args = (T, Cm) if sched.init_wire is None else (
            T, Cm, sched.init_wire(jnp.float32)
        )
        text = jitted.lower(*args).compile().as_text()
        return hlo_wire_bytes(text)

    rows: list[WireRow] = []
    for mode in wire.WIRE_MODES:
        rows.append(WireRow(
            mode=mode,
            wire_bytes=measure(mode),
            full_ideal=full_ideal,
            mode_ideal=ideal_wire_bytes(local_shape, itemsize, k, mode),
            ladder=ladder_of.get(mode),
        ))

    if include_wire_fixture:
        # The doctored row: a full-precision sweep claiming the bf16
        # ladder row. fraction 1.0 > 0.55 — the gate must exit 1.
        rows.append(WireRow(
            mode="bf16(fixture)",
            wire_bytes=measure("f32"),
            full_ideal=full_ideal,
            mode_ideal=ideal_wire_bytes(local_shape, itemsize, k, "bf16"),
            ladder=ladder_of.get("bf16"),
            fixture=True,
        ))

    if telemetry.enabled():
        for r in rows:
            telemetry.annotate(
                "wire.ladder", mode=r.mode, bytes=int(r.wire_bytes),
                full_ideal=int(r.full_ideal),
                mode_ideal=int(r.mode_ideal),
                fraction=round(r.fraction, 4),
                ladder=r.ladder if r.ladder is not None else -1.0,
            )
    return rows


def render_wire_table(rows: list[WireRow]) -> str:
    head = (
        f"{'wire mode':16s} {'wire/sweep':>10s} {'f32 ideal':>10s} "
        f"{'mode ideal':>10s} {'frac':>6s} {'ladder':>6s} status"
    )
    lines = [head, "-" * len(head)]
    for r in rows:
        ladder = f"{r.ladder:.2f}" if r.ladder is not None else "   —"
        if r.ok:
            status = "ok"
        elif r.ladder is not None and r.fraction > r.ladder:
            status = "OVER LADDER"
        else:
            status = "OVER MODE IDEAL"
        lines.append(
            f"{r.mode:16s} {r.wire_bytes:10d} {r.full_ideal:10d} "
            f"{r.mode_ideal:10d} {r.fraction:6.3f} {ladder:>6s} {status}"
        )
    return "\n".join(lines)


def render_table(rows: list[TrafficRow]) -> str:
    head = (
        f"{'variant':24s} {'steps':>5s} {'bytes/invoc':>12s} "
        f"{'ideal':>12s} {'ratio':>6s} {'budget':>6s} "
        f"{'wire':>8s} {'wire0':>8s} {'xla-ca':>12s} status"
    )
    lines = [head, "-" * len(head)]
    for r in rows:
        budget = f"{r.budget:.2f}" if r.budget is not None else "   —"
        if r.ok:
            status = "ok"
        elif not r.wire_ok:
            status = "WIRE OVER IDEAL"
        else:
            status = "OVER BUDGET"
        lines.append(
            f"{r.variant:24s} {r.steps:5d} {r.measured_bytes:12d} "
            f"{r.ideal_bytes:12d} {r.ratio:6.2f} {budget:>6s} "
            f"{r.wire_bytes:8d} {r.wire_ideal:8d} "
            f"{r.cost_analysis_bytes:12.0f} {status}"
        )
    return "\n".join(lines)
