"""Static performance accounting: the compiled HBM-traffic gate.

`traffic.py` audits the distributed step drivers' compiled programs
against their analytic A_eff ideals; `python -m rocm_mpi_tpu.perf` is the
CPU-only CI gate (docs/PERF.md)."""

from rocm_mpi_tpu.perf.traffic import (  # noqa: F401
    TrafficRow,
    audit_variants,
    hlo_bytes_accessed,
    load_budgets,
    render_table,
)
