"""The service driver: batches queued requests onto the space×batch
mesh (docs/SERVING.md).

One `SimulationService` owns a `RequestQueue`, a per-bin model/program
cache, and the serving accounting. The drain loop pops pending tickets,
groups them by `bins.bin_key`, packs each group into power-of-two lane
widths (`bins.plan_batches` — the occupancy floor splits an
under-occupied wide batch into a narrower program class instead of
shipping padding), and executes every batch through the workload's
`batched_advance_fn`. Compiled programs are cached by
(bin key | width | batch rows): since the persistent compile cache is
unsound on this stack, this cache IS the compile amortizer, and the
PR-5 `compiles.steady_state == 0` gate is the steady-state contract —
once a drain pass needs no new program, the service marks steady and
any further recompile is a gated regression.

Resilience integration: requests with a `session` id get their final
state saved through the PR-6 manifest machinery
(``sessions/<id>/`` — `resume=True` continues from the latest valid
step, restored template-less across whatever mesh the service now
runs); a SIGTERM preemption notice (resilience.preempt, rc 75) stops
dispatch at the next batch boundary and requeues every unserved ticket;
and the service is the first real `ElasticPolicy` consumer — the queue
depth drives batch-row growth within the device budget (policy
hysteresis included), idle drains shrink back.

Determinism: every scheduling decision (grouping, widths, lane order)
is a pure function of the submitted trace — in a multi-controller
service every rank plans identical batches, so the batched collectives
can never diverge (the GL08 hazard class). Sessions and result
fetching are single-controller (the drill pins program counts).

The drain hot path is PIPELINED (docs/SERVING.md "The pipeline"):
each batch runs four explicit stages — assemble (host lane state) →
dispatch (device transfer + the batched advance + a non-blocking host
copy, all JAX async) → fetch (ONE blocking wait on the whole batch,
then the finiteness verdicts) → resolve (session saves, ticket
resolution, accounting). At `ServeConfig.pipeline_depth >= 2`
(default 2, double-buffered) batch N+1's assemble/dispatch overlaps
batch N's device compute and batch N's fetch/resolve runs while N+1
computes; depth 1 is the serial drain. Results are bitwise-equal at
any depth, every batch resolves inside its own drain pass, and the
`serve.device_bubble` gauge reports the fraction of drain wall the
device sat idle.
"""

from __future__ import annotations

import dataclasses
import os
import pathlib
import time
from typing import Callable

from rocm_mpi_tpu.resilience.policy import CircuitPolicy, RequestRetryPolicy
from rocm_mpi_tpu.serving import bins as _bins
from rocm_mpi_tpu.serving.bins import BinKey, BinStats
from rocm_mpi_tpu.serving.queue import (
    Request,
    RequestQueue,
    Ticket,
    append_quarantine,
    quarantine_record,
)

# Physics fields each workload's config accepts from a request (anything
# else fails the request loudly — a typo'd constant must not silently
# serve default physics).
PHYSICS_FIELDS = {
    "diffusion": ("lam", "cp0"),
    "wave": ("c0", "cfl"),
    "swe": ("H0", "g", "cfl"),
}


def load_serving_budgets(path=None) -> dict:
    """The committed serving row (perf/budgets.json "serving"):
    occupancy floor + batch tolerance the scheduler and the traffic
    audit share. Absent block falls back to the bins defaults."""
    from rocm_mpi_tpu.perf.traffic import load_budgets

    try:
        doc = load_budgets(path)
    except (OSError, ValueError):
        return {}
    serving = doc.get("serving")
    return serving if isinstance(serving, dict) else {}


@dataclasses.dataclass
class ServeConfig:
    """Service knobs (docs/SERVING.md "Service driver")."""

    max_width: int = _bins.DEFAULT_MAX_WIDTH
    occupancy_floor: float | None = None  # None -> budgets "serving" row
    batch_dims: int = 1  # device rows along the lane axis
    sessions_dir: str | None = None  # checkpoint multiplex root
    fetch_results: bool | None = None  # None: auto (off multi-controller)
    # Elasticity (the ElasticPolicy consumer): policy=None disables.
    policy: object | None = None  # resilience.policy.ElasticPolicy
    # Lane-ROW budget: how many device rows the batch axis may spread
    # over (each row carries one space mesh). Default: all devices.
    device_budget: Callable[[], int] | None = None
    grow_queue_depth: int = 8  # depth that makes the policy consider a grow
    idle_shrink_drains: int = 3  # empty drains before shrinking back
    # The request-plane SLO knobs (docs/SERVING.md "SLOs and
    # admission"): admission bound (None = unbounded, the PR-13
    # behavior), the retry budget/backoff for transient batch-level and
    # numerical failures, the per-BinKey circuit breaker, and the
    # append-only poison ledger (None = records kept in-process only).
    max_depth: int | None = None
    retry: RequestRetryPolicy | None = None  # None -> defaults
    circuit: CircuitPolicy | None = None  # None -> defaults
    quarantine_path: str | None = None
    # The serving pipeline (docs/SERVING.md "The pipeline"): how many
    # batches may be in flight at once inside one drain pass. Depth 1
    # is the serial drain (assemble → dispatch → block → resolve, one
    # batch at a time); depth 2 (the default) double-buffers — batch
    # N+1's host assembly/dispatch overlaps batch N's device compute,
    # and batch N's fetch/resolve runs while N+1 computes. Results are
    # bitwise-equal at any depth (the stages reorder WAITING, never
    # work); every batch still resolves inside its own drain pass, so
    # the drain-boundary accounting invariant is depth-independent.
    pipeline_depth: int = 2
    # Host-side stage callbacks {stage: fn(stage, info)} for
    # {"assemble","dispatch","fetch","resolve"} — called AFTER the
    # stage, on the host, outside any traced region (a hook that
    # mutates service/module state inside a traced body is the GL02
    # hazard class; tests/analysis_fixtures/gl02_serving_pos.py). Used
    # by drills to inject deterministic host-stage latency.
    stage_hooks: dict | None = None
    # Continuous batching (docs/SERVING.md "Continuous batching"):
    # segments > 1 executes each batch as K fixed-size step segments of
    # ONE compiled program (segment length = steps_bucket // segments),
    # swapping resolved lanes out and queued same-class requests in at
    # segment boundaries — no recompile, every lane bitwise-equal to
    # its standalone run. 1 (the default) is the legacy
    # batch-synchronous drain. Single-controller only (swap-in decisions
    # read the local queue mid-drain); multi-controller services fall
    # back to batch-synchronous.
    segments: int = 1
    # The shape-padding ladder: pad eligible requests' space dims up a
    # rung (bins.ladder_shape) so near-rung shape classes share ONE
    # compiled program, within the committed padded-FLOPs tolerance
    # (None -> budgets "serving"/"padded_flops_tolerance" row).
    ladder: bool = False
    ladder_tolerance: float | None = None
    # Request-scoped tracing (telemetry/tracing.py): per-ticket latency-
    # decomposition marks plus tspan records on the rank stream. Off
    # means zero marks and zero tspans on the serving hot path — the
    # bench overhead rung's tracing-off arm.
    trace_requests: bool = True

    def resolved_floor(self) -> float:
        if self.occupancy_floor is not None:
            return float(self.occupancy_floor)
        row = load_serving_budgets().get("occupancy_floor")
        return float(row) if row else _bins.DEFAULT_OCCUPANCY_FLOOR

    def resolved_ladder_tolerance(self) -> float:
        if self.ladder_tolerance is not None:
            return float(self.ladder_tolerance)
        row = load_serving_budgets().get("padded_flops_tolerance")
        return float(row) if row else _bins.DEFAULT_LADDER_TOLERANCE


@dataclasses.dataclass
class ServeReport:
    """One trace/drain session's outcome."""

    served: int = 0
    failed: int = 0
    requeued: int = 0
    rejected: int = 0
    expired: int = 0
    quarantined: int = 0
    preempted: bool = False
    bins: dict = dataclasses.field(default_factory=dict)
    programs: list = dataclasses.field(default_factory=list)
    compiles: dict = dataclasses.field(default_factory=dict)
    elastic: list = dataclasses.field(default_factory=list)
    pipeline: dict = dataclasses.field(default_factory=dict)
    continuous: dict = dataclasses.field(default_factory=dict)

    @property
    def n_bins(self) -> int:
        return len(self.bins)

    @property
    def n_programs(self) -> int:
        return len(self.programs)

    def manifest_doc(self, queue_counters=None) -> dict:
        extra = {
            "served": self.served,
            "failed": self.failed,
            "requeued": self.requeued,
            "rejected": self.rejected,
            "expired": self.expired,
            "quarantined": self.quarantined,
            "preempted": self.preempted,
            "elastic": list(self.elastic),
            "compiles": dict(self.compiles),
            "pipeline": dict(self.pipeline),
        }
        if self.continuous:
            extra["continuous"] = dict(self.continuous)
        return _bins.manifest_doc(
            self.bins, list(self.programs),
            queue_counters=queue_counters,
            extra=extra,
        )


def _reshard(x, sharding):
    """Device array -> the batched mesh's aux sharding (a tiny jitted
    transfer, one per program class — compiled inside the class's own
    compile window, reused every batch). When the batched mesh spans
    MORE devices than the source's space mesh (an elastic grow added
    batch rows), XLA cannot jit across the device sets — stage through
    the host instead (single-controller by construction: multi-
    controller services never resize)."""
    import jax
    import numpy as np

    if set(sharding.device_set) == set(x.sharding.device_set):
        return jax.jit(lambda v: v, out_shardings=sharding)(x)
    if x.is_fully_addressable:
        return _to_global(np.asarray(x), sharding)
    raise ValueError(
        "cannot reshard a non-addressable array onto a different "
        "device set (multi-controller services must keep batch_dims × "
        "space within the space mesh's device set)"
    )


def _to_global(np_arr, sharding):
    """Host array -> global device array under `sharding` — works in
    multi-controller processes too (every rank holds the SAME full host
    array by the determinism contract; each contributes its addressable
    shards)."""
    import jax

    return jax.make_array_from_callback(
        np_arr.shape, sharding, lambda idx: np_arr[idx]
    )


class _Program:
    """One compiled program class: the batched advance bound to its
    space×batch grid, plus the cached base state the lanes scale.
    `base_dev` are the workload's standard-IC state leaves ON DEVICE
    (space-sharded); `base_np` their host copies (single-controller
    only — the lane-assembly fast path); `init` the lazily-jitted
    device-side lane initializer (scales → batched leaves) the
    multi-controller path uses instead."""

    def __init__(self, advance, bgrid, aux, base_dev, adapter,
                 ladder: bool = False):
        self.advance = advance
        self.bgrid = bgrid
        self.aux = aux  # device aux operand(s), lane-shared
        self.base_dev = tuple(base_dev)
        self.adapter = adapter
        # Ladder program: the advance takes per-lane geometry operands
        # (hold mask, dt terms, spacing terms) so lanes of different
        # ORIGINAL shapes share this one compiled class.
        self.ladder = bool(ladder)
        self._base_np = None
        self._init = None
        self._finite = None

    @property
    def base_np(self):
        import numpy as np

        if self._base_np is None:
            self._base_np = tuple(np.asarray(l) for l in self.base_dev)
        return self._base_np

    @property
    def n_leaves(self) -> int:
        return len(self.base_dev)

    @property
    def base_np_dtype(self):
        import numpy as np

        return np.dtype(self.base_dev[0].dtype)

    def init_batched(self, scales_dev):
        """Batched state from per-lane scales, entirely on device (the
        multi-controller lane assembly; one tiny program per class,
        compiled inside the class's own compile window)."""
        import functools

        import jax

        if self._init is None:
            shardings = (self.bgrid.sharding,) * self.n_leaves

            @functools.partial(jax.jit, out_shardings=shardings)
            def init(scales, *base):
                return tuple(
                    jax.vmap(lambda s, l=leaf: s * l)(scales)
                    for leaf in base
                )

            self._init = init
        return self._init(scales_dev, *self.base_dev)

    def lane_finite(self, leaves):
        """(width,) bool, lane j True iff every element of every state
        leaf in lane j is finite — the cheap compiled per-lane
        finiteness reduction that extends tenant isolation to
        NUMERICAL failure (docs/SERVING.md "SLOs and admission"). The
        result is REPLICATED so every controller reads the identical
        verdict from its addressable shards (an all-reduce, never a
        divergence hazard); compiled once per program class, inside the
        class's own compile window."""
        import functools

        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        if self._finite is None:
            rep = NamedSharding(self.bgrid.mesh, PartitionSpec())

            @functools.partial(jax.jit, out_shardings=rep)
            def finite(*ls):
                ok = None
                for leaf in ls:
                    f = jnp.all(
                        jnp.isfinite(leaf),
                        axis=tuple(range(1, leaf.ndim)),
                    )
                    ok = f if ok is None else ok & f
                return ok

            self._finite = finite
        return self._finite(*leaves)


class _Adapter:
    """Per-workload glue: config/model construction, the batched
    advance's calling convention, and the state-leaf layout (the
    session-checkpoint pytree is exactly `leaves`)."""

    name: str = ""
    # Ladder support (docs/SERVING.md "Continuous batching"): a
    # workload opts in by providing build_ladder/run_ladder/
    # ladder_state_leaves/ladder_geom. SWE stays out — its face-mask
    # aux is geometry-DEPENDENT (per-axis staggered masks derived from
    # the exact domain), so embedded lanes cannot share one aux.
    supports_ladder: bool = False

    def make_config(self, key: BinKey, space_dims):
        raise NotImplementedError

    def make_model(self, cfg):
        raise NotImplementedError

    def build(self, model, width, batch_dims, bgrid=None):
        """-> (advance, bgrid, aux_device, base_leaves_numpy)."""
        raise NotImplementedError

    def run(self, prog: _Program, leaves_dev, lane_steps_dev, n):
        """-> tuple of advanced state leaves (device)."""
        raise NotImplementedError

    def build_ladder(self, model, width, batch_dims):
        """-> (ladder advance, bgrid, aux_device, base_leaves) for the
        RUNG-shaped model (per-lane geometry rides operands)."""
        raise NotImplementedError(f"{self.name} has no ladder support")

    def run_ladder(self, prog: _Program, leaves_dev, hold_dev, a_dev,
                   g_dev, lane_steps_dev, n):
        """-> advanced state leaves; `hold_dev` the per-lane hold mask,
        `a_dev` the per-lane dt term, `g_dev` the per-lane per-axis
        spacing term (workload-specific; `ladder_geom`)."""
        raise NotImplementedError(f"{self.name} has no ladder support")

    def ladder_state_leaves(self, model):
        """Unscaled standard-IC STATE leaves (numpy) of the
        original-shape model — the per-original-shape IC the service
        embeds into rung-shaped lanes."""
        raise NotImplementedError(f"{self.name} has no ladder support")

    def ladder_geom(self, cfg):
        """(dt_term, per-axis spacing terms) for one lane, computed
        host-side with exactly the roundings the standalone python-float
        path produces (ops.diffusion.step_fused_padded_geom)."""
        raise NotImplementedError(f"{self.name} has no ladder support")


class _DiffusionAdapter(_Adapter):
    name = "diffusion"

    def make_config(self, key, space_dims):
        from rocm_mpi_tpu.config import DiffusionConfig

        phys = dict(key.physics)
        return DiffusionConfig(
            global_shape=key.shape,
            lengths=(10.0,) * len(key.shape),
            dtype=key.dtype,
            dims=space_dims,
            wire_mode=key.wire_mode,
            lam=phys.get("lam", 1.0),
            cp0=phys.get("cp0", 1.0),
        )

    def make_model(self, cfg):
        from rocm_mpi_tpu.models import HeatDiffusion

        return HeatDiffusion(cfg)

    def build(self, model, width, batch_dims, variant="shard"):
        bgrid = model.make_batched_grid(width, batch_dims)
        advance, _ = model.batched_advance_fn(bgrid=bgrid, variant=variant)
        T0, Cp = model.init_state()
        aux = (_reshard(Cp, bgrid.aux_sharding),)
        return advance, bgrid, aux, (T0,)

    def run(self, prog, leaves_dev, lane_steps_dev, n):
        out = prog.advance(
            leaves_dev[0], prog.aux[0], lane_steps_dev, n
        )
        return (out,)

    supports_ladder = True

    def build_ladder(self, model, width, batch_dims):
        bgrid = model.make_batched_grid(width, batch_dims)
        advance, _ = model.batched_ladder_advance_fn(bgrid=bgrid)
        T0, Cp = model.init_state()
        aux = (_reshard(Cp, bgrid.aux_sharding),)
        return advance, bgrid, aux, (T0,)

    def run_ladder(self, prog, leaves_dev, hold_dev, a_dev, g_dev,
                   lane_steps_dev, n):
        out = prog.advance(
            leaves_dev[0], prog.aux[0], hold_dev, a_dev, g_dev,
            lane_steps_dev, n,
        )
        return (out,)

    def ladder_state_leaves(self, model):
        import numpy as np

        T0, _Cp = model.init_state()
        return (np.asarray(T0),)

    def ladder_geom(self, cfg):
        # The exact roundings of the python-float standalone path: dt
        # is cast to the compute dtype BEFORE the λ multiply
        # (models.diffusion._make_batched_step does jax_dtype(dt), and
        # dt·λ is then an in-dtype multiply); spacing² is squared in
        # f64 and weak-cast at the divide
        # (ops.diffusion.step_fused_padded) — and XLA folds that
        # divide-by-constant into a multiply by the correctly-rounded
        # reciprocal, so the geom operand is EXACTLY that reciprocal
        # (step_fused_padded_geom's bitwise contract).
        import numpy as np

        ty = np.dtype(cfg.jax_dtype)
        dtv = ty.type(cfg.dt)
        a = ty.type(dtv * ty.type(cfg.lam))
        g = tuple(
            ty.type(1.0 / float(ty.type(float(s) * float(s))))
            for s in cfg.spacing
        )
        return a, g


class _WaveAdapter(_Adapter):
    name = "wave"

    def make_config(self, key, space_dims):
        from rocm_mpi_tpu.models.wave import WaveConfig

        phys = dict(key.physics)
        return WaveConfig(
            global_shape=key.shape,
            lengths=(10.0,) * len(key.shape),
            dtype=key.dtype,
            dims=space_dims,
            wire_mode=key.wire_mode,
            c0=phys.get("c0", 1.0),
            cfl=phys.get("cfl", 0.5),
        )

    def make_model(self, cfg):
        from rocm_mpi_tpu.models.wave import AcousticWave

        return AcousticWave(cfg)

    def build(self, model, width, batch_dims, variant="shard"):
        bgrid = model.make_batched_grid(width, batch_dims)
        advance, _ = model.batched_advance_fn(bgrid=bgrid, variant=variant)
        U0, Up0, C2 = model.init_state()
        aux = (_reshard(C2, bgrid.aux_sharding),)
        return advance, bgrid, aux, (U0, Up0)

    def run(self, prog, leaves_dev, lane_steps_dev, n):
        U, Up = prog.advance(
            leaves_dev[0], leaves_dev[1], prog.aux[0], lane_steps_dev, n
        )
        return (U, Up)

    supports_ladder = True

    def build_ladder(self, model, width, batch_dims):
        bgrid = model.make_batched_grid(width, batch_dims)
        advance, _ = model.batched_ladder_advance_fn(bgrid=bgrid)
        U0, Up0, C2 = model.init_state()
        aux = (_reshard(C2, bgrid.aux_sharding),)
        return advance, bgrid, aux, (U0, Up0)

    def run_ladder(self, prog, leaves_dev, hold_dev, a_dev, g_dev,
                   lane_steps_dev, n):
        U, Up = prog.advance(
            leaves_dev[0], leaves_dev[1], prog.aux[0], hold_dev,
            a_dev, g_dev, lane_steps_dev, n,
        )
        return (U, Up)

    def ladder_state_leaves(self, model):
        import numpy as np

        U0, Up0, _C2 = model.init_state()
        return (np.asarray(U0), np.asarray(Up0))

    def ladder_geom(self, cfg):
        # dt² in the compute dtype (the standalone path casts dt first,
        # then squares in-trace); 1/spacing² in f64 then cast
        # (ops.wave_kernels.wave_step_padded).
        import numpy as np

        ty = np.dtype(cfg.jax_dtype)
        dtv = ty.type(cfg.dt)
        a = ty.type(dtv * dtv)
        g = tuple(
            ty.type(1.0 / (float(s) * float(s))) for s in cfg.spacing
        )
        return a, g


class _SWEAdapter(_Adapter):
    name = "swe"

    def make_config(self, key, space_dims):
        from rocm_mpi_tpu.models.swe import SWEConfig

        phys = dict(key.physics)
        return SWEConfig(
            global_shape=key.shape,
            lengths=(10.0,) * len(key.shape),
            dtype=key.dtype,
            dims=space_dims,
            wire_mode=key.wire_mode,
            H0=phys.get("H0", 1.0),
            g=phys.get("g", 1.0),
            cfl=phys.get("cfl", 0.5),
        )

    def make_model(self, cfg):
        from rocm_mpi_tpu.models.swe import ShallowWater

        return ShallowWater(cfg)

    def build(self, model, width, batch_dims, variant="shard"):
        bgrid = model.make_batched_grid(width, batch_dims)
        advance, _ = model.batched_advance_fn(bgrid=bgrid, variant=variant)
        h0, us0 = model.init_state()
        Mus = model.face_masks()
        aux = tuple(_reshard(M, bgrid.aux_sharding) for M in Mus)
        return advance, bgrid, aux, (h0,) + tuple(us0)

    def run(self, prog, leaves_dev, lane_steps_dev, n):
        h, us = prog.advance(
            leaves_dev[0], tuple(leaves_dev[1:]), prog.aux,
            lane_steps_dev, n,
        )
        return (h,) + tuple(us)


_ADAPTERS = {
    a.name: a for a in (_DiffusionAdapter(), _WaveAdapter(), _SWEAdapter())
}


class _Breaker:
    """One BinKey's circuit state (docs/SERVING.md "SLOs and
    admission"): closed → (K consecutive batch failures) → open →
    (cooldown drains) → half-open probe → closed on success, re-open on
    failure. Purely a function of batch outcomes and drain counts —
    deterministic across controllers by construction."""

    __slots__ = ("consecutive", "state", "opened_drain")

    def __init__(self):
        self.consecutive = 0
        self.state = "closed"
        self.opened_drain = 0

    def note_failure(self, policy: CircuitPolicy, drain: int) -> bool:
        """Record one batch failure; True when this one OPENED (or
        re-opened, from half-open) the breaker."""
        self.consecutive += 1
        tripped = (
            policy.enabled
            and self.state != "open"
            and (self.state == "half-open"
                 or self.consecutive >= policy.k)
        )
        if tripped:
            self.state = "open"
            self.opened_drain = drain
        return tripped

    def note_success(self) -> bool:
        """Record one served batch; True when it CLOSED a half-open
        breaker (the probe proved recovery)."""
        recovered = self.state == "half-open"
        self.consecutive = 0
        self.state = "closed"
        return recovered

    def admit(self, policy: CircuitPolicy, drain: int, n: int) -> int:
        """How many of `n` popped tickets this class admits THIS drain:
        all of them (closed), none (open, cooling down), or exactly one
        probe (half-open)."""
        if not policy.enabled or self.state == "closed":
            return n
        if self.state == "open" \
                and drain - self.opened_drain >= policy.cooldown_drains:
            self.state = "half-open"
        return min(n, 1) if self.state == "half-open" else 0


@dataclasses.dataclass
class _InFlight:
    """One dispatched-but-unresolved batch riding the drain pipeline
    (docs/SERVING.md "The pipeline"): everything the resolve stage
    needs. `anchors` holds the batch's DONATED input leaves purely as
    a deletion anchor: on this stack, dropping the last Python
    reference to a donated-and-still-computing array blocks the host
    until the consuming computation finishes (measured — the silent
    re-serialization that would undo the whole pipeline), so the refs
    ride here untouched and are released at fetch time, when deletion
    is free. They are NEVER read — a retry/requeue after a dispatched
    batch re-assembles from host state only (the donated-buffer hazard
    the drill pins; reading one would raise jax's deleted-array
    error)."""

    key: BinKey
    width: int
    split: bool
    seq: int  # the service's global batch ordinal (fault site)
    prog: _Program
    live: list  # tickets that actually hold a lane
    starts: list  # per-live-lane resume start steps
    lane_steps: object  # numpy (width,) int32 per-lane step counts
    out: tuple  # advanced state leaves (device, async)
    fetch: bool  # resolve tickets with host results?
    need_host: bool  # fetch or session saves need the host copy
    anchors: tuple = ()  # donated inputs: deletion anchor ONLY


class SimulationService:
    """Multi-tenant batched simulation service (module docstring; the
    CLI driver is apps/serve.py)."""

    def __init__(self, queue: RequestQueue | None = None,
                 config: ServeConfig | None = None):
        self.config = config if config is not None else ServeConfig()
        self.queue = queue if queue is not None else RequestQueue(
            max_depth=self.config.max_depth
        )
        self._retry = self.config.retry if self.config.retry is not None \
            else RequestRetryPolicy()
        self._circuit = self.config.circuit \
            if self.config.circuit is not None else CircuitPolicy()
        self._floor = self.config.resolved_floor()
        self._batch_dims = int(self.config.batch_dims)
        self._models: dict = {}
        self._programs: dict[str, _Program] = {}
        self._ladder_tol = self.config.resolved_ladder_tolerance()
        self._ladder_bases: dict = {}  # per-original-shape IC leaves
        # Continuous-drain lifetime accounting (the manifest
        # `continuous` block): executed segmented batches/segments, the
        # swap counters, and the step-weighted occupancy numerator/
        # denominator the per-drain serve.occupancy gauge is cut from.
        self._continuous = {
            "batches": 0, "segments_run": 0, "swaps_in": 0,
            "swaps_out": 0, "occ_num": 0, "occ_den": 0,
        }
        self._drain_swaps = 0        # per-drain swap-ins (gauge)
        self._drain_occ = [0, 0]     # per-drain occupancy num/den
        self._stats: dict[BinKey, BinStats] = {}
        self._breakers: dict[BinKey, _Breaker] = {}
        self._elastic: list[dict] = []
        self._quarantined: list[dict] = []
        self._drains = 0
        self._idle_drains = 0
        self._last_resize_drain: int | None = None
        self._compiled_this_drain = False
        self._batch_seq = 0  # global executed-batch ordinal (fault site)
        self.retries_total = 0  # lifetime retry-requeues (SLO block)
        self._admission_sync = {"rejected": 0, "expired": 0}
        self._multi: bool | None = None
        # Pipeline accounting (docs/SERVING.md "The pipeline"):
        # cumulative per-stage host walls, device-busy wall (≥1 batch
        # dispatched-but-unfetched), and the drain execute wall the
        # bubble gauge is measured against. time.monotonic by design —
        # interval arithmetic on the scheduler clock, not a telemetry
        # measurement (the spans carry those).
        self._pipe = {
            "batches": 0, "assemble_s": 0.0, "dispatch_s": 0.0,
            "fetch_s": 0.0, "resolve_s": 0.0, "busy_s": 0.0,
            "wall_s": 0.0,
        }
        self._inflight_n = 0
        self._inflight_since: float | None = None
        self.last_bubble: float | None = None  # most recent drain's

    def _is_multi(self) -> bool:
        """Multi-controller? Resolved once; also flips the queue's
        wall-clock SLO decisions off (deadline expiry and retry backoff
        diverge with rank-local clocks — the GL08 class; depth-based
        admission stays on everywhere)."""
        if self._multi is None:
            import jax

            self._multi = jax.process_count() > 1
            if self._multi:
                self.queue.wall_slo = False
        return self._multi

    # ---- model / program caches ----------------------------------------

    def _space_dims(self, key: BinKey):
        import jax

        from rocm_mpi_tpu.parallel.mesh import plan_dims

        avail = max(len(jax.devices()) // self._batch_dims, 1)
        return plan_dims(key.shape, avail)

    def _model_for(self, key: BinKey):
        mkey = (key.workload, key.shape, key.dtype, key.physics,
                key.wire_mode, self._batch_dims)
        model = self._models.get(mkey)
        if model is None:
            adapter = _ADAPTERS[key.workload]
            unknown = [
                k for k, _ in key.physics
                if k not in PHYSICS_FIELDS[key.workload]
            ]
            if unknown:
                raise ValueError(
                    f"unknown physics field(s) {unknown} for workload "
                    f"{key.workload!r} (accepted: "
                    f"{PHYSICS_FIELDS[key.workload]})"
                )
            cfg = adapter.make_config(key, self._space_dims(key))
            model = adapter.make_model(cfg)
            self._models[mkey] = model
        return model

    def program_key(self, key: BinKey, width: int,
                    ladder: bool = False) -> str:
        base = f"{key.key_str()}|w{width}|bd{self._batch_dims}"
        return base + "|ladder" if ladder else base

    def _program_for(self, key: BinKey, width: int,
                     ladder: bool = False) -> _Program:
        pkey = self.program_key(key, width, ladder)
        prog = self._programs.get(pkey)
        if prog is None:
            from rocm_mpi_tpu import telemetry
            from rocm_mpi_tpu.telemetry import compiles

            # A NEW program class is a legitimate compile, not a
            # steady-state regression: open the window, compile, and let
            # the drain loop re-mark steady once every class it needs
            # exists.
            compiles.unmark_steady()
            self._compiled_this_drain = True
            adapter = _ADAPTERS[key.workload]
            model = self._model_for(key)
            # The batch rows must DIVIDE the (pow2) lane width — a
            # non-pow2 batch_dims rounds down, it never bricks a batch.
            bd = _bins.pow2_floor(min(width, self._batch_dims))
            with telemetry.span("serve.compile", phase="serve",
                                bin=key.key_str(), width=width):
                if ladder:
                    advance, bgrid, aux, base = adapter.build_ladder(
                        model, width, bd
                    )
                else:
                    advance, bgrid, aux, base = adapter.build(
                        model, width, bd, variant=key.variant
                    )
            prog = _Program(advance, bgrid, aux, base, adapter,
                            ladder=ladder)
            self._programs[pkey] = prog
        return prog

    # ---- the shape-padding ladder (docs/SERVING.md) ---------------------

    def _ladder_eligible(self, req: Request) -> bool:
        """May this request ride a ladder program? Workloads with
        geometry-independent aux ('diffusion', 'wave' — SWE's face
        masks are domain-derived), the 'shard' variant (the one whose
        batched advance has a ladder twin), the lossless 'f32' wire
        (lossy codecs quantize at shard boundaries, which MOVE under
        padding), no sessions (checkpoints are exact-shape), and
        single-controller (the per-lane host embedding path)."""
        return (
            bool(self.config.ladder)
            and _ADAPTERS[req.workload].supports_ladder
            and req.variant == "shard"
            and req.wire_mode == "f32"
            and not req.session
            and not req.resume
            and not self._is_multi()
        )

    def _group_key(self, req: Request) -> tuple[BinKey, bool]:
        """(bin key, rides-the-ladder) — the drain's grouping key. An
        eligible request's shape field is laddered up a rung, so
        near-rung shape classes MERGE; ladder and non-ladder traffic of
        the same BinKey stay separate groups (different compiled
        programs)."""
        if self._ladder_eligible(req):
            return (
                _bins.bin_key(req, ladder_tolerance=self._ladder_tol),
                True,
            )
        return _bins.bin_key(req), False

    def _ladder_base_np(self, key: BinKey, orig_shape: tuple):
        """Unscaled standard-IC state leaves (numpy) at `orig_shape` —
        built from the ORIGINAL-shape model, cached per shape class.
        The first request of a new original shape compiles that
        model's IC initializer: a legitimate NEW-class compile (the
        window opens exactly like _program_for's), documented under
        "what still recompiles"."""
        okey = dataclasses.replace(key, shape=tuple(orig_shape))
        ckey = (okey.workload, okey.shape, okey.dtype, okey.physics,
                okey.wire_mode)
        base = self._ladder_bases.get(ckey)
        if base is None:
            from rocm_mpi_tpu import telemetry
            from rocm_mpi_tpu.telemetry import compiles

            compiles.unmark_steady()
            self._compiled_this_drain = True
            adapter = _ADAPTERS[okey.workload]
            model = self._model_for(okey)
            with telemetry.span("serve.compile", phase="serve",
                                bin=okey.key_str(), width=0):
                base = adapter.ladder_state_leaves(model)
            self._ladder_bases[ckey] = base
        return base

    def _ladder_lane(self, req: Request, key: BinKey, prog: _Program):
        """(embedded leaves, hold mask, dt term, spacing terms) for one
        laddered lane: the original-shape IC (×ic_scale) embedded at
        the origin corner of a rung-shaped zero block, the hold mask
        True on the original domain's Dirichlet ring AND everywhere
        outside it, and the lane's host-precomputed geometry
        (adapter.ladder_geom). The held ring separates the embedded
        interior from the padding, so the lane is bitwise-equal to its
        standalone run."""
        import numpy as np

        orig = tuple(int(n) for n in req.global_shape)
        base = self._ladder_base_np(key, orig)
        okey = dataclasses.replace(key, shape=orig)
        ocfg = self._model_for(okey).config
        a, g = prog.adapter.ladder_geom(ocfg)
        region = tuple(slice(0, n) for n in orig)
        leaves = []
        for b, z in zip(base, prog.base_np):
            e = np.zeros_like(z)
            e[region] = b * req.ic_scale
            leaves.append(e)
        hold = np.ones(prog.base_np[0].shape, dtype=bool)
        hold[tuple(slice(1, n - 1) for n in orig)] = False
        return tuple(leaves), hold, a, g

    # ---- lane assembly --------------------------------------------------

    def _session_dir(self, session: str) -> pathlib.Path:
        root = self.config.sessions_dir
        if not root:
            raise ValueError(
                "request carries a session id but the service has no "
                "sessions_dir configured"
            )
        return pathlib.Path(root) / session

    def _resume_step(self, req: Request, prog: _Program) -> int:
        """The lane's resume point: the session's latest VALID saved
        step, 0 when nothing durable exists yet. A session already PAST
        the requested nt fails loudly — there is no checkpoint at nt to
        hand back, and restoring the later state would answer a
        different question than the request asked."""
        import jax

        if jax.process_count() > 1:
            raise ValueError("session resume is single-controller only")
        from rocm_mpi_tpu.utils import checkpoint as ckpt

        step = ckpt.latest_valid_step(self._session_dir(req.session))
        if step is None:
            return 0
        if int(step) > req.nt:
            raise ValueError(
                f"session {req.session!r} is already at step {step} > "
                f"requested nt {req.nt}; re-submit with nt >= {step}"
            )
        return int(step)

    def _lane_start_state(self, req: Request, prog: _Program,
                          start: int):
        """(leaves numpy tuple, start_step) for one lane: the session's
        checkpoint at `start` when resuming (template-less restore —
        the PR-6 cross-mesh path), else ic_scale × the workload's
        standard IC."""
        import numpy as np

        if req.resume and start > 0:
            from rocm_mpi_tpu.utils import checkpoint as ckpt

            sdir = self._session_dir(req.session)
            leaves = ckpt.restore_state(sdir, start, like=None)
            leaves = tuple(np.asarray(l) for l in leaves)
            if len(leaves) != prog.n_leaves:
                raise ValueError(
                    f"session {req.session}: checkpoint has "
                    f"{len(leaves)} leaves, workload {req.workload!r} "
                    f"carries {prog.n_leaves}"
                )
            return leaves, start
        return tuple(l * req.ic_scale for l in prog.base_np), 0

    def _save_session(self, ticket: Ticket, leaves,
                      prog: _Program) -> None:
        """Multiplex the lane's final state through the PR-6 manifest
        machinery: sessions/<id>/ gets a step-nt checkpoint whose
        manifest meta carries the request id."""
        import jax

        from rocm_mpi_tpu.utils import checkpoint as ckpt

        req = ticket.request
        sdir = self._session_dir(req.session)
        # Space-sharded leaves: the manifest's topology metadata (the
        # PR-6 cross-mesh restore contract) describes a mesh, so the
        # saved state must carry one — the bin's own space grid.
        space = prog.bgrid.space
        state = tuple(
            jax.device_put(l, space.sharding) for l in leaves
        )
        ckpt.save_state(sdir, req.nt, state)
        # Re-write the manifest with the serving meta riding along —
        # write_manifest recomputes the inventory, so this is the same
        # document plus the request attribution.
        ckpt.write_manifest(
            sdir, req.nt, state,
            extra_meta={"serving": {
                "request_id": req.request_id, "session": req.session,
            }},
        )

    # ---- execution (the drain pipeline, docs/SERVING.md) ----------------

    def _stage_hook(self, stage: str, **info) -> None:
        """Fire the host-side stage callback (ServeConfig.stage_hooks)
        AFTER `stage` — outside every traced region by construction."""
        hooks = self.config.stage_hooks
        if not hooks:
            return
        fn = hooks.get(stage)
        if fn is not None:
            fn(stage, info)

    def _now(self, now: float | None = None) -> float:
        """The service's single clock seam (graftlint GL10e): every
        monotonic read in the drain/pipeline path routes through here,
        so a fleet controller can inject its clock the same way the
        router's poll_health/expire_overdue(now) seams do and the
        serving plane keeps exactly one clock owner per process."""
        return time.monotonic() if now is None else now

    def _note_dispatched(self) -> None:
        """A batch entered flight (dispatched, unfetched): the device
        is busy while >= 1 batch is in flight — the complement is the
        bubble the serve.device_bubble gauge reports."""
        if self._inflight_n == 0:
            self._inflight_since = self._now()
        self._inflight_n += 1

    def _note_fetched(self) -> None:
        if self._inflight_n > 0:
            self._inflight_n -= 1
            if self._inflight_n == 0 and self._inflight_since is not None:
                self._pipe["busy_s"] += (
                    self._now() - self._inflight_since
                )
                self._inflight_since = None

    def _execute_batch(self, key: BinKey, tickets: list[Ticket],
                       width: int, split: bool) -> None:
        """The serial per-batch chokepoint (pipeline_depth == 1, and
        the override seam the failure drills monkeypatch): prepare,
        then resolve immediately — the staged pipeline with zero
        overlap. Bitwise-identical to the pipelined drain by
        construction: both run the same stages on the same batches in
        the same order; only the waiting is scheduled differently."""
        fl = self._prepare_batch(key, tickets, width, split)
        if fl is not None:
            self._resolve_batch(fl)

    def _prepare_batch(self, key: BinKey, tickets: list[Ticket],
                       width: int, split: bool) -> _InFlight | None:
        """Pipeline stages 1+2 — assemble (host) + dispatch (async).

        Assembles every lane's start state on the host, places the
        batch on device, and dispatches the batched advance plus a
        non-blocking device-to-host copy of the results (JAX async
        dispatch: both return immediately as futures; the per-lane
        finiteness verdict is deliberately NOT dispatched here — the
        fetch stage computes it, see _resolve_batch). Nothing here
        waits on the device, so batch N+1's prepare runs while batch N
        computes. Returns the in-flight record the resolve stage
        consumes, or None when no lane survived assembly. The input
        device leaves are donated to the advance and NOT carried on
        the record — a later retry can only re-assemble from host
        state, never read a donated buffer."""
        import numpy as np

        from rocm_mpi_tpu import telemetry
        from rocm_mpi_tpu.resilience import faults
        from rocm_mpi_tpu.telemetry import flight

        # The serve-batch fault site, BEFORE the flight step bump and
        # any collective: an infrastructure clause pinned here
        # (`kill@step=2,rank=1,at=serve-batch`) strikes a rank before
        # it bumps, so its peers advance past it and the health
        # watchdog names the victim BY PROGRESS — the same ordering
        # contract as the segment-pre site. The step bump itself feeds
        # the watchdog: one progress step per executed batch.
        self._batch_seq += 1
        seq = self._batch_seq
        faults.fault_point("serve-batch", step=seq)
        clause = faults.serving_fault("batch-error", step=seq)
        if clause is not None:
            raise RuntimeError(f"injected batch-error (batch {seq})")
        flight.progress(step_inc=1)
        slow = faults.serving_fault("slow-batch", step=seq)
        if slow is not None:
            time.sleep(slow.delay_s)

        tracing_on = bool(self.config.trace_requests)
        if tracing_on:
            from rocm_mpi_tpu.telemetry import tracing as _tracing

            tnow = self._now()
            for t in tickets:
                t.trace_mark("queue_wait", tnow)
        prog = self._program_for(key, width)
        if tracing_on:
            # Telescoping decomposition marks (tracing.DECOMP_STAGES):
            # each boundary charges the interval since the previous mark
            # to ONE stage, so the stages sum exactly to the terminal
            # latency. "compile" covers program-class acquisition (a hot
            # cache charges ~0 here); everything until the blocking
            # fetch lands in "device".
            tnow = self._now()
            for t in tickets:
                t.trace_mark("compile", tnow)
        bgrid = prog.bgrid
        multi = self._is_multi()

        # Per-lane assembly, per-lane failure isolation: one tenant's
        # bad session (corrupt checkpoint, wrong workload's leaves,
        # nt behind the saved step) fails ITS ticket only — the
        # co-batched neighbors keep their lanes; the failed lane stays
        # idle padding.
        t0 = self._now()
        live: list[Ticket] = []
        starts: list[int] = []
        with telemetry.span("serve.assemble", phase="serve",
                            bin=key.key_str(), width=width):
            lanes: list[tuple] = []
            scales = np.zeros(width, dtype=prog.base_np_dtype)
            lane_steps = np.zeros(width, dtype=np.int32)
            for t in tickets:
                try:
                    if multi and (t.request.resume or t.request.session):
                        raise ValueError(
                            "session checkpoints are single-controller "
                            "only"
                        )
                    start = (
                        self._resume_step(t.request, prog)
                        if t.request.resume else 0
                    )
                    if not multi:
                        leaves, _ = self._lane_start_state(
                            t.request, prog, start
                        )
                except ValueError as e:
                    # A per-request validation error (bad session,
                    # resume past nt): the request itself is wrong —
                    # terminal, never retried.
                    self._fail_ticket(t, str(e))
                    continue
                except Exception as e:  # noqa: BLE001 — tenant isolation
                    # Transient lane-assembly failure (corrupt
                    # checkpoint, storage flap on restore): retry
                    # within budget.
                    self._retry_or_quarantine(t, str(e))
                    continue
                j = len(live)
                live.append(t)
                starts.append(start)
                lane_steps[j] = t.request.nt - start
                scales[j] = t.request.ic_scale
                if not multi:
                    lanes.append(leaves)
                if faults.serving_fault("lane-nan", request=t.ordinal) \
                        is not None:
                    # Poison THIS lane's initial state (the numerical-
                    # failure drill): the finiteness reduction must
                    # fail only this ticket while its co-batched
                    # neighbors stay bitwise-equal to their standalone
                    # twins.
                    scales[j] = float("nan")
                    if not multi:
                        lanes[j] = tuple(
                            l * float("nan") for l in lanes[j]
                        )
                t.start_step = start
        self._pipe["assemble_s"] += self._now() - t0
        self._stage_hook("assemble", key=key.key_str(), width=width,
                         seq=seq, live=len(live))
        if not live:
            return None
        n = int(lane_steps.max())

        t0 = self._now()
        with telemetry.span(
            "serve.dispatch", phase="serve",
            bin=key.key_str(), width=width, live=len(live), steps=n,
        ):
            if multi:
                # Multi-controller lane assembly is entirely on device
                # (a host-assembled batch cannot be placed onto a
                # sharding spanning other processes).
                leaves_dev = prog.init_batched(
                    _to_global(scales, bgrid.batch_sharding)
                )
            else:
                # Idle pad lanes: zero state, zero steps (frozen from
                # step 0 — pure machine padding, the waste the
                # occupancy floor bounds).
                zero = tuple(np.zeros_like(l) for l in prog.base_np)
                while len(lanes) < width:
                    lanes.append(zero)
                leaves_dev = tuple(
                    _to_global(
                        np.stack([lanes[i][leaf] for i in range(width)]),
                        bgrid.sharding,
                    )
                    for leaf in range(prog.n_leaves)
                )
            steps_dev = _to_global(lane_steps, bgrid.batch_sharding)
            out = tuple(prog.adapter.run(prog, leaves_dev, steps_dev, n))
            fetch = self.config.fetch_results
            if fetch is None:
                fetch = not multi
            # Session persistence is independent of result fetching: a
            # fetch_results=False service must still honor the durable-
            # session contract (both need the host copy).
            need_host = fetch or any(t.request.session for t in live)
            if need_host and all(
                leaf.is_fully_addressable for leaf in out
            ):
                # Start the device->host copies NOW, without blocking:
                # by the time the resolve stage reads them the transfer
                # has been riding under the next batch's compute.
                for leaf in out:
                    copy_async = getattr(leaf, "copy_to_host_async",
                                         None)
                    if copy_async is None:
                        break
                    copy_async()
        self._pipe["dispatch_s"] += self._now() - t0
        self._stage_hook("dispatch", key=key.key_str(), width=width,
                         seq=seq, live=len(live))
        fl = _InFlight(
            key=key, width=width, split=split, seq=seq, prog=prog,
            live=live, starts=starts, lane_steps=lane_steps, out=out,
            fetch=fetch, need_host=need_host,
            anchors=(leaves_dev, steps_dev),
        )
        if tracing_on:
            # ONE batch-level trace record, not one per lane: the
            # members roster ({trace_id, lane}) lets the read side
            # derive every member's device span from this record plus
            # lane occupancy (telemetry/tracing.py), keeping the stream
            # O(batches). The roster also feeds the flight recorder: a
            # wedged rank's heartbeat names the requests stuck in
            # flight.
            members = [
                {"trace_id": t.trace.trace_id, "lane": j,
                 "span_id": t.trace.span_id, "hop": t.trace.hop}
                for j, t in enumerate(live) if t.trace is not None
            ]
            _tracing.emit_tspan(
                "trace.batch",
                next((t.trace for t in live if t.trace is not None),
                     None),
                seq=seq, bin=key.key_str(), width=width,
                members=members,
            )
            flight.trace_inflight_add(m["trace_id"] for m in members)
        # Busy-mark LAST, after the stage hook and record construction:
        # a raise between a _note_dispatched and its matching
        # _note_fetched (resolve's finally) would leave _inflight_n
        # stuck high and freeze the bubble accounting for the service's
        # lifetime.
        self._note_dispatched()
        return fl

    def _resolve_batch(self, fl: _InFlight) -> None:
        """Pipeline stages 3+4 — fetch (block) + resolve (host).

        The one place the drain waits on the device: ONE blocking call
        on the whole batch (never leaf-by-leaf in Python), then the
        host copies the dispatch stage already set in motion. The
        finiteness verdict, session saves, ticket resolution, and
        accounting all run here — while the NEXT batch computes, when
        the drain is pipelined."""
        import jax
        import numpy as np

        from rocm_mpi_tpu import telemetry
        from rocm_mpi_tpu.telemetry import flight

        key, width = fl.key, fl.width
        prog, live, starts = fl.prog, fl.live, fl.starts
        lane_steps = fl.lane_steps
        n = int(lane_steps.max())
        tracing_on = bool(self.config.trace_requests)
        if tracing_on:
            # Everything since the compile mark — assembly, upload,
            # dispatch, the device compute itself — charges to "device":
            # the interval ends where the drain starts WAITING.
            tnow = self._now()
            for t in live:
                t.trace_mark("device", tnow)
        t0 = self._now()
        try:
            with telemetry.span("serve.fetch", phase="serve",
                                bin=key.key_str(), width=width):
                jax.block_until_ready(fl.out)
                host = None
                if fl.need_host and all(
                    leaf.is_fully_addressable for leaf in fl.out
                ):
                    host = tuple(np.asarray(leaf) for leaf in fl.out)
                # The per-lane finiteness verdict (tenant isolation
                # extended to NUMERICAL failure): a NaN/Inf lane fails
                # only its own ticket — through the retry budget, so a
                # persistently-poison request ends quarantined, never
                # re-batched forever. Computed from the HOST copies
                # when the fetch already paid for them: dispatching the
                # compiled reduction here would serialize against the
                # NEXT batch's in-flight compute (one outstanding
                # dispatch on this stack — measured, the silent
                # re-serialization class) and undo the pipeline. The
                # compiled replicated all-reduce remains the no-host
                # path — multi-controller services need every rank to
                # read one identical verdict, and they host-fetch
                # nothing.
                if host is not None:
                    finite = np.array([
                        all(
                            bool(np.isfinite(leaf[j]).all())
                            for leaf in host
                        )
                        for j in range(width)
                    ])
                else:
                    finite = np.asarray(prog.lane_finite(fl.out))
        finally:
            # The busy interval ends even when the fetch raises —
            # a failed batch must not read as a forever-busy device.
            # The donated-input anchors release HERE: the advance has
            # finished (or failed), so dropping the last references no
            # longer blocks the host (_InFlight.anchors has the why).
            fl.anchors = ()
            self._pipe["fetch_s"] += self._now() - t0
            self._note_fetched()
        if tracing_on:
            tnow = self._now()
            for t in live:
                t.trace_mark("fetch", tnow)
            # Off-device: the heartbeat's in-flight roster drops the
            # batch here (a fetch that RAISES is dropped by
            # _batch_failed instead).
            flight.trace_inflight_drop(
                t.trace.trace_id for t in live if t.trace is not None
            )
        self._stage_hook("fetch", key=key.key_str(), width=width,
                         seq=fl.seq, live=len(live))

        t0 = self._now()
        done = 0
        with telemetry.span("serve.resolve", phase="serve",
                            bin=key.key_str(), width=width,
                            live=len(live)):
            for j, t in enumerate(live):
                if not bool(finite[j]):
                    telemetry.record_event(
                        "serve.lane.nan",
                        request_id=t.request.request_id,
                        bin=key.key_str(), width=width, lane=j,
                    )
                    self._retry_or_quarantine(
                        t, "non-finite state (NaN/Inf) in lane"
                    )
                    continue
                # Lane-isolated resolution: one tenant's failing
                # session save (unwritable dir, disk full) must not
                # fail its co-batched neighbors or skew the completion
                # accounting.
                try:
                    lane = (
                        tuple(leaf[j] for leaf in host)
                        if host is not None else None
                    )
                    if t.request.session and lane is not None:
                        self._save_session(t, lane, prog)
                except ValueError as e:
                    self._fail_ticket(t, str(e))
                    continue
                except Exception as e:  # noqa: BLE001 — tenant isolation
                    self._retry_or_quarantine(t, str(e))
                    continue
                t.steps_run = int(lane_steps[j])
                t._resolve(lane if fl.fetch else None)
                done += 1
                if tracing_on:
                    t.trace_mark("resolve", self._now())
                latency = t.age_s()
                telemetry.record_event(
                    "serve.request.done",
                    request_id=t.request.request_id,
                    bin=key.key_str(), width=width,
                    steps=int(lane_steps[j]), start=starts[j],
                    latency_s=round(latency, 6),
                    deadline_miss=bool(
                        t.request.deadline_s is not None
                        and latency > t.request.deadline_s
                    ),
                    **(
                        {"hop": t.trace.hop, "decomp": t.decomp_doc()}
                        if tracing_on and t.trace is not None else {}
                    ),
                )
            self.queue.note_completed(done)
            flight.progress(serve_completed=done)

            st = self._stats.get(key)
            if st is None:
                st = self._stats[key] = BinStats(key=key)
            st.note_batch(width,
                          [int(s) for s in lane_steps[:len(live)]],
                          n, split=fl.split)
        self._pipe["resolve_s"] += self._now() - t0
        self._pipe["batches"] += 1
        self._stage_hook("resolve", key=key.key_str(), width=width,
                         seq=fl.seq, live=len(live))

    def _run_segmented_batch(self, key: BinKey, tickets: list[Ticket],
                             width: int, ladder: bool) -> int:
        """The continuous drain's batch executor (docs/SERVING.md
        "Continuous batching"): ONE compiled program of `width` lanes
        executes the whole ticket group as fixed-size step segments
        (`steps_bucket // segments` steps each). Between segments where
        no lane finishes, the output chains straight back in ON DEVICE
        — no host fetch, no bubble; the boundary plan is host-side
        arithmetic on the remaining-step counts. At a boundary where
        lanes DO finish, one blocking fetch resolves them (same
        finiteness/retry/session semantics as _resolve_batch), their
        slots re-seat from the group's backlog and then the queue's
        matching arrivals (queue.pop_matching), and the batch
        continues. Every lane — first-seated or swapped in — is
        bitwise-equal to its standalone run: the compiled advance
        freezes a lane at its own `lane_steps`, so K chained segments
        of the one program ARE the lane's single long run (the PR-9
        run_segmented discipline folded inside the program). No
        recompile at any boundary; `compiles.steady_state` stays 0.
        Single-controller only (drain_once gates). Returns the
        completed-ticket count."""
        import jax
        import numpy as np

        from rocm_mpi_tpu import telemetry
        from rocm_mpi_tpu.resilience import faults
        from rocm_mpi_tpu.telemetry import flight

        # The batch-granular fault contract — same sites, same ordering
        # as _prepare_batch: one seq/progress bump per segmented batch
        # (segments are sub-batch machinery, not scheduler units).
        self._batch_seq += 1
        seq = self._batch_seq
        faults.fault_point("serve-batch", step=seq)
        clause = faults.serving_fault("batch-error", step=seq)
        if clause is not None:
            raise RuntimeError(f"injected batch-error (batch {seq})")
        flight.progress(step_inc=1)
        slow = faults.serving_fault("slow-batch", step=seq)
        if slow is not None:
            time.sleep(slow.delay_s)

        tracing_on = bool(self.config.trace_requests)
        if tracing_on:
            from rocm_mpi_tpu.telemetry import tracing as _tracing

            tnow = self._now()
            for t in tickets:
                t.trace_mark("queue_wait", tnow)
        prog = self._program_for(key, width, ladder=ladder)
        if tracing_on:
            # Same telescoping boundaries as _prepare_batch; the
            # continuous drain adds "swap_wait" — backlog tickets charge
            # their compile->seat wait to it (they wait for a LANE, not
            # for the queue).
            tnow = self._now()
            for t in tickets:
                t.trace_mark("compile", tnow)
        bgrid = prog.bgrid
        seg_len = max(
            1, key.steps_bucket // max(1, int(self.config.segments))
        )
        fetch = self.config.fetch_results
        if fetch is None:
            fetch = True  # single-controller by construction
        gk = (key, ladder)
        kstr = key.key_str()

        backlog = list(tickets)
        lane_t: list = [None] * width
        starts = [0] * width
        remaining = np.zeros(width, dtype=np.int64)
        lanes_np: list = [None] * width  # host leaves per seated slot
        zero = tuple(np.zeros_like(l) for l in prog.base_np)
        cdtype = prog.base_np_dtype
        ndim = len(key.shape)
        hold_rows = [np.ones(zero[0].shape, dtype=bool)
                     for _ in range(width)]
        a_rows = np.zeros(width, dtype=cdtype)
        g_rows = np.ones((width, ndim), dtype=cdtype)
        padded_cells = 1
        for nn in key.shape:
            padded_cells *= int(nn)

        done = 0
        swaps_in = 0
        swaps_out = 0
        segs_run = 0
        executed = 0  # machine steps (the occupancy denominator rides
        occ_num = 0   # width × this; the numerator is per-lane useful)
        tenant_nts: list[int] = []
        tenant_cells: list[tuple[int, int]] = []

        def seat(j: int, t: Ticket) -> bool:
            """Host-assemble ticket t into slot j; route its failure
            (same ValueError-terminal / transient-retry split as
            _prepare_batch's lane loop) and report success."""
            try:
                if ladder:
                    leaves, hold, a, g = self._ladder_lane(
                        t.request, key, prog
                    )
                    start = 0
                else:
                    start = (
                        self._resume_step(t.request, prog)
                        if t.request.resume else 0
                    )
                    leaves, _ = self._lane_start_state(
                        t.request, prog, start
                    )
            except ValueError as e:
                self._fail_ticket(t, str(e))
                return False
            except Exception as e:  # noqa: BLE001 — tenant isolation
                self._retry_or_quarantine(t, str(e))
                return False
            if faults.serving_fault("lane-nan", request=t.ordinal) \
                    is not None:
                leaves = tuple(l * float("nan") for l in leaves)
            t.start_step = start
            lane_t[j] = t
            starts[j] = start
            remaining[j] = t.request.nt - start
            lanes_np[j] = leaves
            if ladder:
                hold_rows[j] = hold
                a_rows[j] = a
                g_rows[j] = np.asarray(g, dtype=cdtype)
            if tracing_on:
                # Seated: the wait-for-a-lane interval ends (first
                # seats charge ~0; boundary swap-ins charge the
                # segments they sat out).
                t.trace_mark("swap_wait", self._now())
            return True

        def fill(allow_queue: bool) -> int:
            """Seat every free slot from the backlog, then (daemon
            arrivals) from same-class queued tickets. Swap eligibility
            IS the group key: same compiled program, same ladder
            routing."""
            n_seated = 0
            for j in range(width):
                if lane_t[j] is not None:
                    continue
                while lane_t[j] is None and backlog:
                    seat(j, backlog.pop(0))
                while lane_t[j] is None and allow_queue:
                    pulled = self.queue.pop_matching(
                        lambda r: self._group_key(r) == gk, max_n=1
                    )
                    if not pulled:
                        break
                    flight.progress(serve_submitted=1)
                    if tracing_on:
                        # A daemon arrival's queue wait ends at its
                        # pop, not at the group's drain entry.
                        pulled[0].trace_mark("queue_wait", self._now())
                    # Join the batch's ticket roster so a batch-level
                    # failure (_batch_failed) covers swap-ins too.
                    tickets.append(pulled[0])
                    seat(j, pulled[0])
                if lane_t[j] is not None:
                    n_seated += 1
            return n_seated

        def to_device():
            rows = [
                lanes_np[j] if lanes_np[j] is not None else zero
                for j in range(width)
            ]
            leaves = tuple(
                _to_global(
                    np.stack([rows[j][leaf] for j in range(width)]),
                    bgrid.sharding,
                )
                for leaf in range(prog.n_leaves)
            )
            if not ladder:
                return leaves, ()
            # inv_d2 uploads as ndim separate per-axis (width,) arrays
            # — the models' per-axis scalar-operand contract (the
            # fori-fusion ulp note in step_fused_padded_geom).
            geom = (
                _to_global(np.stack(hold_rows), bgrid.sharding),
                _to_global(np.asarray(a_rows), bgrid.batch_sharding),
                tuple(
                    _to_global(
                        np.ascontiguousarray(g_rows[:, ax]),
                        bgrid.batch_sharding,
                    )
                    for ax in range(ndim)
                ),
            )
            return leaves, geom

        def roster() -> list[dict]:
            """The seated lanes' trace membership ({trace_id, lane}) —
            what trace.batch/trace.segment records and the flight
            recorder's in-flight set are built from."""
            return [
                {"trace_id": lane_t[j].trace.trace_id, "lane": j}
                for j in range(width)
                if lane_t[j] is not None
                and lane_t[j].trace is not None
            ]

        t0 = self._now()
        with telemetry.span("serve.assemble", phase="serve",
                            bin=kstr, width=width):
            fill(allow_queue=False)
        self._pipe["assemble_s"] += self._now() - t0
        self._stage_hook(
            "assemble", key=kstr, width=width, seq=seq,
            live=sum(1 for t in lane_t if t is not None),
        )
        seated_ids: set = set()
        if tracing_on:
            members = roster()
            _tracing.emit_tspan(
                "trace.batch",
                next((lane_t[j].trace for j in range(width)
                      if lane_t[j] is not None
                      and lane_t[j].trace is not None), None),
                seq=seq, bin=kstr, width=width, segmented=True,
                members=members,
            )
            seated_ids = {m["trace_id"] for m in members}
            flight.trace_inflight_add(seated_ids)

        leaves_dev = None
        geom_dev = ()
        anchors: list = []
        preempted = False
        while any(t is not None for t in lane_t):
            live_j = [j for j in range(width) if lane_t[j] is not None]
            n_seg = int(min(
                seg_len, max(int(remaining[j]) for j in live_j)
            ))
            n_seg = max(1, n_seg)
            t0 = self._now()
            new_flight = leaves_dev is None
            if new_flight:
                # One busy-mark per CHAIN (upload .. blocking fetch),
                # not per segment: chained segments are one continuous
                # flight, and _note_dispatched/_note_fetched must pair
                # 1:1 or _inflight_n wedges and the bubble gauge dies.
                # Marked BEFORE the dispatch span: the pipelined
                # classic drain preps batch N+1 under batch N's open
                # window, so its upload wall lands inside busy time.
                # Segmented chains run serially — marking after the
                # dispatch span would charge every post-swap upload
                # as bubble, work the classic path hides for free.
                self._note_dispatched()
            with telemetry.span(
                "serve.dispatch", phase="serve", bin=kstr,
                width=width, live=len(live_j), steps=n_seg,
            ):
                if leaves_dev is None:
                    leaves_dev, geom_dev = to_device()
                steps_np = np.clip(remaining, 0, n_seg).astype(np.int32)
                steps_dev = _to_global(steps_np, bgrid.batch_sharding)
                if ladder:
                    out = tuple(prog.adapter.run_ladder(
                        prog, leaves_dev, *geom_dev, steps_dev, n_seg
                    ))
                else:
                    out = tuple(prog.adapter.run(
                        prog, leaves_dev, steps_dev, n_seg
                    ))
                # Donated-input deletion anchors (_InFlight.anchors has
                # the hazard): the chained inputs ride here until the
                # next blocking fetch, when deletion is free.
                anchors.append((leaves_dev, steps_dev))
            self._pipe["dispatch_s"] += self._now() - t0
            self._stage_hook("dispatch", key=kstr, width=width,
                             seq=seq, live=len(live_j))
            segs_run += 1
            executed += n_seg
            occ_num += sum(
                min(int(remaining[j]), n_seg) for j in live_j
            )
            # The boundary plan is HOST arithmetic — no fetch needed to
            # know who finished: remaining-step counts are deterministic.
            finishing = [
                j for j in live_j if int(remaining[j]) <= n_seg
            ]
            for j in live_j:
                remaining[j] = max(0, int(remaining[j]) - n_seg)
            if not finishing:
                # Pure chain: the advance's output feeds the next
                # segment ON DEVICE. Zero host sync, zero bubble.
                leaves_dev = out
                continue

            if tracing_on:
                # A finishing lane's whole chain — every segment it
                # rode, including intermediate boundary round trips —
                # is device time from ITS seat mark to this wait.
                tnow = self._now()
                for j in finishing:
                    lane_t[j].trace_mark("device", tnow)
            t0 = self._now()
            with telemetry.span("serve.fetch", phase="serve",
                                bin=kstr, width=width):
                jax.block_until_ready(out)
                host = tuple(np.asarray(leaf) for leaf in out)
            anchors.clear()
            self._pipe["fetch_s"] += self._now() - t0
            self._note_fetched()
            if tracing_on:
                tnow = self._now()
                for j in finishing:
                    lane_t[j].trace_mark("fetch", tnow)
            self._stage_hook("fetch", key=kstr, width=width, seq=seq,
                             live=len(live_j))

            t0 = self._now()
            done_here = 0
            with telemetry.span("serve.resolve", phase="serve",
                                bin=kstr, width=width,
                                live=len(finishing)):
                for j in finishing:
                    t = lane_t[j]
                    nt_run = int(t.request.nt - starts[j])
                    tenant_nts.append(nt_run)
                    if ladder:
                        orig_cells = 1
                        for nn in t.request.global_shape:
                            orig_cells *= int(nn)
                        tenant_cells.append((orig_cells, padded_cells))
                    finite = all(
                        bool(np.isfinite(leaf[j]).all())
                        for leaf in host
                    )
                    if not finite:
                        telemetry.record_event(
                            "serve.lane.nan",
                            request_id=t.request.request_id,
                            bin=kstr, width=width, lane=j,
                        )
                        self._retry_or_quarantine(
                            t, "non-finite state (NaN/Inf) in lane"
                        )
                        lane_t[j] = None
                        lanes_np[j] = None
                        continue
                    try:
                        lane = tuple(leaf[j] for leaf in host)
                        if ladder:
                            region = tuple(
                                slice(0, nn)
                                for nn in t.request.global_shape
                            )
                            lane = tuple(l[region] for l in lane)
                        if t.request.session:
                            self._save_session(t, lane, prog)
                    except ValueError as e:
                        self._fail_ticket(t, str(e))
                        lane_t[j] = None
                        lanes_np[j] = None
                        continue
                    except Exception as e:  # noqa: BLE001
                        self._retry_or_quarantine(t, str(e))
                        lane_t[j] = None
                        lanes_np[j] = None
                        continue
                    t.steps_run = nt_run
                    t._resolve(lane if fetch else None)
                    done_here += 1
                    if tracing_on:
                        t.trace_mark("resolve", self._now())
                    latency = t.age_s()
                    telemetry.record_event(
                        "serve.request.done",
                        request_id=t.request.request_id,
                        bin=kstr, width=width, steps=nt_run,
                        start=starts[j],
                        latency_s=round(latency, 6),
                        deadline_miss=bool(
                            t.request.deadline_s is not None
                            and latency > t.request.deadline_s
                        ),
                        **(
                            {"hop": t.trace.hop,
                             "decomp": t.decomp_doc()}
                            if tracing_on and t.trace is not None
                            else {}
                        ),
                    )
                    lane_t[j] = None
                    lanes_np[j] = None
                self.queue.note_completed(done_here)
                flight.progress(serve_completed=done_here)
                done += done_here
                # Surviving lanes cross the boundary through an exact
                # host round trip (fetch + re-upload is bitwise).
                for j in live_j:
                    if lane_t[j] is not None:
                        lanes_np[j] = tuple(leaf[j] for leaf in host)
                # A preemption notice stops SWAP-INS at this boundary
                # (the batch-boundary analog of the rc-75 contract);
                # already-seated lanes run to completion.
                if self._preempt_requested():
                    preempted = True
                if not preempted:
                    k = fill(allow_queue=True)
                    swaps_in += k
                if any(t is not None for t in lane_t):
                    swaps_out += len(finishing)
            self._pipe["resolve_s"] += self._now() - t0
            self._stage_hook("resolve", key=kstr, width=width,
                             seq=seq, live=len(finishing))
            if tracing_on:
                # The boundary record AFTER the swap: joined lanes
                # appear in the segment they joined at (the read side
                # derives their device spans from here), and the
                # flight recorder's in-flight set moves with the seats.
                members = roster()
                _tracing.emit_tspan(
                    "trace.segment",
                    next((lane_t[j].trace for j in range(width)
                          if lane_t[j] is not None
                          and lane_t[j].trace is not None), None),
                    seq=seq, seg=segs_run, bin=kstr, width=width,
                    members=members,
                )
                ids_now = {m["trace_id"] for m in members}
                flight.trace_inflight_drop(seated_ids - ids_now)
                flight.trace_inflight_add(ids_now - seated_ids)
                seated_ids = ids_now
            leaves_dev = None  # re-assemble from host rows next round
            geom_dev = ()

        if backlog:
            # Preemption (or a breaker-sized seat drought) left group
            # tickets unseated: park them back at the queue's front —
            # the same undispatched-work requeue the classic drain does
            # at its batch boundary.
            self.queue.requeue(backlog)
            flight.progress(serve_requeued=len(backlog))

        st = self._stats.get(key)
        if st is None:
            st = self._stats[key] = BinStats(key=key)
        st.note_continuous(
            width, tenant_nts, executed, swaps_in, segs_run,
            lane_cells=tenant_cells if ladder else None,
        )
        self._pipe["batches"] += 1
        c = self._continuous
        c["batches"] += 1
        c["segments_run"] += segs_run
        c["swaps_in"] += swaps_in
        c["swaps_out"] += swaps_out
        c["occ_num"] += occ_num
        c["occ_den"] += width * executed
        self._drain_swaps += swaps_in
        self._drain_occ[0] += occ_num
        self._drain_occ[1] += width * executed
        self._sync_admission_counters()
        return done

    def _batch_failed(self, key: BinKey, batch_ts: list[Ticket],
                      width: int, e: Exception) -> None:
        """The batch-level failure chokepoint (tenant isolation): a
        batch failure — at prepare (compile error, injected
        batch-error) or at resolve (device fault surfacing at fetch) —
        fails ITS tickets and lets the other bins' batches keep
        serving; an unhandled escape would strand every later popped
        ticket in 'running' forever and kill the daemon without the
        rc-75 requeue path. The tickets ride the retry budget
        (transient faults requeue bounded, then quarantine); K
        consecutive failures open the class's circuit breaker."""
        from rocm_mpi_tpu import telemetry
        from rocm_mpi_tpu.telemetry import flight

        telemetry.record_event(
            "serve.batch.error", bin=key.key_str(), width=width,
            error=str(e),
        )
        # The failed batch is off the device however it died: the
        # heartbeat's in-flight trace roster must not carry its
        # requests forever.
        flight.trace_inflight_drop(
            t.trace.trace_id for t in batch_ts if t.trace is not None
        )
        br = self._breakers[key]
        if br.note_failure(self._circuit, self._drains):
            telemetry.record_event(
                "serve.circuit.open", bin=key.key_str(),
                consecutive=br.consecutive,
            )
        for t in batch_ts:
            if not t.done() and t.state == "running":
                # Same routing as the lane level: a ValueError is a
                # per-request/program-class validation error (unknown
                # physics) — terminal, never retried; anything else is
                # transient and rides the retry budget.
                if isinstance(e, ValueError):
                    self._fail_ticket(t, str(e))
                else:
                    self._retry_or_quarantine(t, str(e))

    def pipeline_stats(self) -> dict:
        """Lifetime pipeline accounting (the manifest's `pipeline`
        block, docs/SERVING.md "The pipeline"): per-stage host walls,
        the resolved batches, and the device bubble — the fraction of
        the cumulative drain-execute wall with NO batch in flight."""
        p = self._pipe
        wall = p["wall_s"]
        bubble = max(0.0, 1.0 - p["busy_s"] / wall) if wall > 0 else 0.0
        return {
            "depth": max(1, int(self.config.pipeline_depth)),
            "batches": int(p["batches"]),
            "bubble": round(bubble, 4),
            "assemble_s": round(p["assemble_s"], 6),
            "dispatch_s": round(p["dispatch_s"], 6),
            "fetch_s": round(p["fetch_s"], 6),
            "resolve_s": round(p["resolve_s"], 6),
            "busy_s": round(p["busy_s"], 6),
            "wall_s": round(p["wall_s"], 6),
        }

    def _fail_ticket(self, t: Ticket, error: str) -> None:
        """The per-request-error chokepoint: ticket, queue counter, AND
        the serve_failed flight counter — the monitor's depth formula
        must see every outcome, or a failed request reads as backlog
        forever. Terminal: validation errors never retry."""
        from rocm_mpi_tpu.telemetry import flight

        t._fail(error)
        self.queue.note_completed(0, failed=1)
        flight.progress(serve_failed=1)

    def _retry_or_quarantine(self, t: Ticket, error: str) -> None:
        """The transient-failure chokepoint (docs/SERVING.md "SLOs and
        admission"): requeue with exponential backoff while the retry
        budget lasts; a request that exhausts it is quarantined —
        terminally, with its full record banked — never requeued
        again."""
        from rocm_mpi_tpu import telemetry
        from rocm_mpi_tpu.telemetry import flight

        if t.retries < self._retry.budget:
            t.retries += 1
            self.retries_total += 1
            if self.queue.wall_slo:
                backoff = self._retry.backoff_s(t.retries)
                t.not_before = self._now() + backoff
                # The park is charged to "backoff", not "queue_wait":
                # the next queue_wait mark peels this much off first
                # (Ticket.trace_mark — the decomposition contract).
                t.backoff_pending += backoff
            # wake=False: the submitter keeps waiting for the retried
            # batch's real resolution (unlike a preemption park).
            self.queue.requeue([t], wake=False)
            flight.progress(serve_retries=1)
            telemetry.record_event(
                "serve.request.retry",
                request_id=t.request.request_id,
                retries=t.retries, budget=self._retry.budget,
                error=error,
            )
            return
        self._quarantine_ticket(t, error)

    def _quarantine_ticket(self, t: Ticket, error: str) -> None:
        """Expel a poison request: terminal `quarantined` state, the
        full request record appended to the quarantine.jsonl ledger for
        offline repro, counters bumped — and NEVER requeued."""
        from rocm_mpi_tpu import telemetry
        from rocm_mpi_tpu.telemetry import flight

        record = quarantine_record(t.request, error, t.retries)
        self._quarantined.append(record)
        if self.config.quarantine_path and self._ledger_writer():
            append_quarantine(self.config.quarantine_path, record)
        t._terminal_fail(
            "quarantined",
            f"{error} (retry budget {self._retry.budget} exhausted)",
        )
        self.queue.note_quarantined(1)
        flight.progress(serve_quarantined=1)
        telemetry.record_event(
            "serve.request.quarantined",
            request_id=t.request.request_id,
            retries=t.retries, error=error,
        )

    def _ledger_writer(self) -> bool:
        """One writer per ledger: in a multi-controller service every
        rank reaches the same deterministic quarantine decision, so
        only rank 0 appends — N identical records from N concurrent
        writers would both inflate the poison count and risk
        interleaved lines."""
        if not self._is_multi():
            return True
        import jax

        return jax.process_index() == 0

    def _reject_ticket(self, t: Ticket, error: str) -> None:
        """Admission rejection of an already-popped ticket (the circuit
        breaker's fast-fail): terminal `rejected`."""
        from rocm_mpi_tpu import telemetry
        from rocm_mpi_tpu.telemetry import flight

        t._terminal_fail("rejected", error)
        self.queue.note_rejected(1)
        flight.progress(serve_rejected=1)
        telemetry.record_event(
            "serve.request.rejected",
            request_id=t.request.request_id, error=error,
        )

    def _sync_admission_counters(self) -> None:
        """Mirror queue-side admission outcomes (submit-time
        rejections, pop-time expiries) into the flight counters and the
        telemetry stream — the SERVE badge and the SLO accounting must
        see every outcome the queue decided without the service's
        help."""
        from rocm_mpi_tpu import telemetry
        from rocm_mpi_tpu.telemetry import flight

        c = self.queue.counters()
        d_rej = self.queue.rejected_at_submit \
            - self._admission_sync["rejected"]
        if d_rej > 0:
            self._admission_sync["rejected"] = \
                self.queue.rejected_at_submit
            # serve_submitted rides along: the badge's depth formula
            # subtracts every outcome from it, and these tickets were
            # never popped into a drain's serve_submitted bump (the
            # circuit-open rejections of POPPED tickets are counted by
            # _reject_ticket itself).
            flight.progress(serve_rejected=d_rej, serve_submitted=d_rej)
        for t in self.queue.take_expired():
            telemetry.record_event(
                "serve.request.expired",
                request_id=t.request.request_id,
                deadline_s=t.request.deadline_s, error=t.error,
            )
        d_exp = c["expired"] - self._admission_sync["expired"]
        if d_exp > 0:
            self._admission_sync["expired"] = c["expired"]
            flight.progress(serve_expired=d_exp, serve_submitted=d_exp)

    def _preempt_requested(self) -> bool:
        from rocm_mpi_tpu.resilience import preempt

        return preempt.requested()

    def drain_once(self) -> tuple[int, bool]:
        """One drain pass: pop everything pending, pack, execute.
        Returns (served_count, preempted) — on preemption the unserved
        tickets are requeued and dispatch stops at the batch boundary
        (the scheduler's rc-75 requeue signal, docs/SERVING.md)."""
        from rocm_mpi_tpu import telemetry
        from rocm_mpi_tpu.telemetry import compiles, flight

        self._drains += 1
        self._is_multi()
        tickets = self.queue.pop_pending()
        self._sync_admission_counters()
        telemetry.gauge("serve.queue_depth", float(len(tickets)))
        if not tickets:
            # Backoff-parked tickets are pending-but-ineligible work,
            # not idleness — they must not trigger the idle shrink.
            if self.queue.depth() == 0:
                self._idle_drains += 1
            return 0, False
        self._idle_drains = 0
        flight.progress(serve_submitted=len(tickets))
        self._compiled_this_drain = False
        self._drain_swaps = 0
        self._drain_occ = [0, 0]

        # Groups are keyed (BinKey, ladder): the ladder bool separates
        # the padded-program route from the exact route so a ladder-
        # ineligible request (session, lossy wire, multi) sharing the
        # BinKey never collides with the laddered program class.
        groups: dict[tuple[BinKey, bool], list[Ticket]] = {}
        bad: list[tuple[Ticket, str]] = []
        for t in tickets:
            try:
                groups.setdefault(self._group_key(t.request),
                                  []).append(t)
            except ValueError as e:
                bad.append((t, str(e)))
        for t, msg in bad:
            self._fail_ticket(t, msg)

        served = 0
        # (key, tickets, width, split, ladder, segmented)
        pending: list[tuple] = []
        multi = self._is_multi()
        for gk in sorted(groups, key=lambda g: (g[0], g[1])):
            key, ladder = gk
            ts = groups[gk]
            # The circuit breaker's admission gate: an OPEN class
            # rejects fast with circuit-open (one failing shape class
            # must not starve every other tenant's throughput); a
            # cooled-down class re-admits exactly ONE half-open probe.
            # Breakers stay keyed by BinKey: the failure domain is the
            # shape class, however it is routed.
            br = self._breakers.get(key)
            if br is None:
                br = self._breakers[key] = _Breaker()
            admit = br.admit(self._circuit, self._drains, len(ts))
            if admit < len(ts):
                telemetry.record_event(
                    "serve.circuit.reject", bin=key.key_str(),
                    state=br.state, rejected=len(ts) - admit,
                )
                for t in ts[admit:]:
                    self._reject_ticket(
                        t, f"circuit-open ({key.key_str()})"
                    )
                ts = ts[:admit]
            if not ts:
                continue
            segmented = (
                (int(self.config.segments) > 1 or ladder) and not multi
            )
            widths = _bins.plan_batches(
                len(ts), self.config.max_width, self._floor
            )
            canonical = widths[0]
            if segmented:
                # The continuous drain runs the WHOLE group as one
                # segmented batch of the canonical width: overflow
                # tickets are the swap-in backlog, not separate
                # (possibly split) batches.
                pending.append((key, ts, canonical, False, ladder,
                                True))
                continue
            i = 0
            for w in widths:
                take = min(w, len(ts) - i)
                pending.append((key, ts[i:i + take], w,
                                w != canonical, ladder, False))
                i += take

        # The drain pipeline (docs/SERVING.md "The pipeline"): at
        # depth 1, each batch runs assemble → dispatch → fetch →
        # resolve serially through the _execute_batch chokepoint; at
        # depth D >= 2, up to D-1 batches ride dispatched-but-
        # unresolved, so batch N+1's host assembly and transfer overlap
        # batch N's device compute, and batch N's fetch/resolve runs
        # while N+1 computes. Every batch still resolves INSIDE this
        # drain pass (the bounded tail drain below), so the
        # drain-boundary accounting invariant and the retry/breaker/
        # preemption semantics are depth-independent — and the results
        # bitwise-equal, since the stages reorder waiting, never work.
        preempted = False
        depth = max(1, int(self.config.pipeline_depth))
        inflight: list[tuple] = []  # FIFO: (key, tickets, width, fl)
        exec_t0 = self._now()
        busy0 = self._pipe["busy_s"]

        def _finish(entry) -> None:
            nonlocal served
            fkey, fts, fw, fl = entry
            fbr = self._breakers[fkey]
            try:
                self._resolve_batch(fl)
                served += sum(1 for t in fts if t.state == "done")
                if fbr.note_success():
                    telemetry.record_event(
                        "serve.circuit.close", bin=fkey.key_str(),
                    )
            except Exception as e:  # noqa: BLE001 — tenant isolation
                self._batch_failed(fkey, fts, fw, e)

        for bi, (key, batch_ts, w, split, ladder, segmented) \
                in enumerate(pending):
            if self._preempt_requested():
                # Undispatched work requeues at the batch boundary (the
                # rc-75 contract); already-dispatched batches FINISH in
                # the tail drain below — in-flight lanes always
                # complete their batch.
                preempted = True
                rest = [
                    t for entry in pending[bi:] for t in entry[1]
                ]
                self.queue.requeue(rest)
                flight.progress(serve_requeued=len(rest))
                break
            br = self._breakers[key]
            if segmented:
                # The continuous batch IS its own pipeline (device
                # chaining between boundaries): flush the classic
                # in-flight batches first — both for the session
                # read-after-write ordering and so the two executors
                # never interleave their busy accounting.
                while inflight:
                    _finish(inflight.pop(0))
                try:
                    served += self._run_segmented_batch(
                        key, batch_ts, w, ladder
                    )
                    if br.note_success():
                        telemetry.record_event(
                            "serve.circuit.close", bin=key.key_str(),
                        )
                except Exception as e:  # noqa: BLE001 — tenant isolation
                    self._batch_failed(key, batch_ts, w, e)
                continue
            if depth == 1:
                try:
                    self._execute_batch(key, batch_ts, w, split)
                    served += sum(
                        1 for t in batch_ts if t.state == "done"
                    )
                    if br.note_success():
                        telemetry.record_event(
                            "serve.circuit.close", bin=key.key_str(),
                        )
                except Exception as e:  # noqa: BLE001 — tenant isolation
                    self._batch_failed(key, batch_ts, w, e)
                continue
            if inflight and any(t.request.resume for t in batch_ts):
                # Session read-after-write barrier: a resume lane's
                # assembly reads its session dir, and an in-flight
                # batch's resolve may still be ABOUT to write it (the
                # session save lives in the resolve stage). Flush the
                # pipeline first so the resume batch assembles against
                # exactly the state the serial drain would see — the
                # bitwise-equal contract; a rare, bounded stall.
                while inflight:
                    _finish(inflight.pop(0))
            try:
                fl = self._prepare_batch(key, batch_ts, w, split)
            except Exception as e:  # noqa: BLE001 — tenant isolation
                self._batch_failed(key, batch_ts, w, e)
                continue
            if fl is None:
                # No lane survived assembly: the serial path books this
                # as a (no-op) served batch too.
                if br.note_success():
                    telemetry.record_event(
                        "serve.circuit.close", bin=key.key_str(),
                    )
                continue
            inflight.append((key, batch_ts, w, fl))
            while len(inflight) >= depth:
                _finish(inflight.pop(0))
        # The bounded tail drain: everything still in flight resolves
        # before the drain returns.
        for entry in inflight:
            _finish(entry)

        if pending:
            d_wall = self._now() - exec_t0
            self._pipe["wall_s"] += d_wall
            d_busy = self._pipe["busy_s"] - busy0
            bubble = (
                max(0.0, 1.0 - d_busy / d_wall) if d_wall > 0 else 0.0
            )
            self.last_bubble = bubble
            telemetry.gauge("serve.pipeline_depth", float(depth))
            telemetry.gauge("serve.device_bubble", round(bubble, 4))
        if self._drain_occ[1]:
            # Per-drain continuous gauges: step-weighted slot occupancy
            # (live lane-steps / width × machine steps) and the swap-in
            # count — the two numbers the continuous-vs-batch-sync
            # regress gate reads.
            telemetry.gauge(
                "serve.occupancy",
                round(self._drain_occ[0] / self._drain_occ[1], 4),
            )
            telemetry.gauge("serve.swap", float(self._drain_swaps))

        if not preempted and not self._compiled_this_drain \
                and self._programs:
            # Every program class the live traffic needs exists: any
            # recompile from here is a steady-state regression the
            # compiles.* zero-pin gates.
            compiles.mark_steady()
        return served, preempted

    # ---- elasticity (the ElasticPolicy consumer) ------------------------

    def maybe_resize(self) -> bool:
        """Queue-driven elasticity: grow the batch rows when the queue
        is deep and the policy + device budget agree; shrink when idle.
        Resize drops every compiled program/model (they are bound to the
        old mesh — the PR-6 rebuild discipline) and reopens the compile
        window (a resize compile is elastic, not a steady regression)."""
        import jax

        policy = self.config.policy
        if policy is None or jax.process_count() > 1:
            return False
        budget_fn = self.config.device_budget
        budget = int(budget_fn() if budget_fn else len(jax.devices()))
        depth = self.queue.depth()
        bd = self._batch_dims
        target = None
        kind = None
        if depth >= self.config.grow_queue_depth and policy.wants_grow(
            bd, budget,
            step=self._drains,
            last_change_step=self._last_resize_drain,
        ):
            grown = policy.grow_target(bd, budget, _bins.pow2_floor)
            if grown > bd:
                target, kind = grown, "grow"
        elif (
            depth == 0
            and self._idle_drains >= self.config.idle_shrink_drains
            and bd > max(1, int(getattr(policy, "min_ranks", 1)))
        ):
            target, kind = max(bd // 2,
                               int(getattr(policy, "min_ranks", 1))), \
                "shrink"
        if target is None or target == bd:
            return False
        self._resize(target, kind, depth=depth, budget=budget)
        return True

    def _resize(self, new_bd: int, kind: str, **attrs) -> None:
        from rocm_mpi_tpu import telemetry
        from rocm_mpi_tpu.telemetry import compiles, flight

        old = self._batch_dims
        self._batch_dims = int(new_bd)
        self._models.clear()
        self._programs.clear()
        compiles.unmark_steady()
        self._last_resize_drain = self._drains
        event = {
            "event": f"serve.{kind}", "old_batch_dims": old,
            "new_batch_dims": int(new_bd), "drain": self._drains,
            **attrs,
        }
        self._elastic.append(event)
        telemetry.record_event(f"serve.{kind}", **event)
        flight.progress(serve_resizes=1)

    # ---- drivers --------------------------------------------------------

    def run_trace(self, requests) -> ServeReport:
        """Serve a request list to completion (the acceptance driver):
        submit everything, drain until the queue is empty (or a
        preemption notice stops dispatch), return the report."""
        tickets = [self.queue.submit(r) for r in requests]
        report = self._drain_all()
        del tickets
        return report

    def _drain_all(self) -> ServeReport:
        report = ServeReport()
        while True:
            # Resize BEFORE draining: the decision input is the backlog,
            # and drain_once pops the whole queue.
            self.maybe_resize()
            served, preempted = self.drain_once()
            report.served += served
            if preempted:
                report.preempted = True
                break
            if self.queue.depth() == 0:
                break
            # A preemption notice between drain passes (the remaining
            # work is all backoff-parked) must stop the loop at this
            # boundary — queued work stays queued, nothing requeues.
            if self._preempt_requested():
                report.preempted = True
                break
            # Pending work may all be backoff-parked: wait out the
            # earliest retry eligibility instead of spinning.
            delay = self.queue.next_ready_delay()
            if delay:
                time.sleep(min(delay, 0.25))
        self._finish_report(report)
        self._assert_accounting()
        return report

    def serve_forever(self, poll_s: float = 0.05,
                      idle_exit_s: float | None = None) -> ServeReport:
        """Daemon drain loop: serve until idle for `idle_exit_s`
        (None = only a preemption notice stops it)."""
        report = ServeReport()
        idle_since = None
        while True:
            # A SIGTERM can land BETWEEN drain passes (the daemon is
            # idle-polling, not mid-batch): notice it here, requeue
            # nothing (nothing was popped), and exit rc 75 — without
            # this check an idle daemon would poll straight through
            # its preemption grace and die to the scheduler's SIGKILL
            # with a clean-looking exit path.
            if self._preempt_requested():
                report.preempted = True
                break
            self.maybe_resize()
            served, preempted = self.drain_once()
            report.served += served
            if preempted:
                report.preempted = True
                break
            if self.queue.depth() == 0:
                now = self._now()
                if idle_since is None:
                    idle_since = now
                elif idle_exit_s is not None \
                        and now - idle_since >= idle_exit_s:
                    break
                time.sleep(poll_s)
            else:
                idle_since = None
                delay = self.queue.next_ready_delay()
                if delay:
                    time.sleep(min(delay, poll_s))
        self._finish_report(report)
        self._assert_accounting()
        return report

    def _assert_accounting(self) -> None:
        """The drain-time terminal-accounting invariant (docs/
        SERVING.md "SLOs and admission"): at a drain boundary nothing
        is in flight, so every submitted ticket must be terminally
        accounted or still queued — a leak here means some ticket
        vanished into 'running' forever, the exact bug class the
        invariant exists to catch loudly."""
        problems = self.queue.check_accounting(in_flight=0)
        if problems:
            raise RuntimeError(
                "serve accounting invariant violated at drain: "
                + "; ".join(problems)
            )

    def _finish_report(self, report: ServeReport) -> None:
        from rocm_mpi_tpu import telemetry
        from rocm_mpi_tpu.telemetry import compiles

        counters = self.queue.counters()
        report.failed = counters["failed"]
        report.requeued = counters["requeued"]
        report.rejected = counters["rejected"]
        report.expired = counters["expired"]
        report.quarantined = counters["quarantined"]
        report.bins = dict(self._stats)
        report.programs = sorted(self._programs)
        report.elastic = list(self._elastic)
        report.pipeline = self.pipeline_stats()
        c = self._continuous
        if c["batches"]:
            report.continuous = {
                "segments": max(1, int(self.config.segments)),
                "batches": c["batches"],
                "segments_run": c["segments_run"],
                "swaps_in": c["swaps_in"],
                "swaps_out": c["swaps_out"],
                "occupancy": (
                    round(c["occ_num"] / c["occ_den"], 6)
                    if c["occ_den"] else 0.0
                ),
            }
        snap = compiles.snapshot()
        report.compiles = {
            "total": snap["totals"]["backend_compiles"],
            "steady_state": snap["steady_recompiles"],
        }
        if telemetry.enabled():
            telemetry.gauge("serve.bins", float(len(report.bins)))
            telemetry.gauge("serve.programs", float(report.n_programs))
            if report.bins:
                telemetry.gauge(
                    "serve.occupancy",
                    # The continuous drain's step-weighted occupancy is
                    # the truthful lifetime number when it ran — the
                    # classic min-over-bins slot occupancy otherwise.
                    report.continuous["occupancy"]
                    if report.continuous and c["occ_den"]
                    else min(
                        st.occupancy for st in report.bins.values()
                    ),
                )
                telemetry.gauge(
                    "serve.padding_waste",
                    max(st.padding_waste for st in report.bins.values()),
                )
            compiles.emit_gauges()

    def write_manifest(self, path) -> dict:
        """Bank the bin manifest sidecar (atomic; schema-checked by
        lint.sh / `telemetry regress --check-schema`)."""
        report = ServeReport()
        self._finish_report(report)
        # The manifest's lifetime view: everything this service has
        # completed (report.served is per-drain-session), and whether
        # a preemption notice is pending at banking time — the rc-75
        # exit path banks the manifest, and a manifest that said
        # preempted=False there would misreport the daemon's exit.
        report.served = self.queue.counters()["completed"]
        report.preempted = self._preempt_requested()
        doc = report.manifest_doc(queue_counters=self.queue.counters())
        _bins.write_manifest(path, doc)
        return doc
