"""SLO accounting and the soak report (docs/RESILIENCE.md §8 "The
soak"; docs/SERVING.md "SLOs and admission").

Stdlib-at-import like the rest of the serving read side: `telemetry
regress --check-schema` validates archived `soak-report.json` artifacts
through `validate_soak_report` without importing jax, and the SLO
aggregation reads the per-rank telemetry JSONL streams directly (the
`serve.request.done` events carry `latency_s`/`deadline_miss` per
request — the report's latency percentiles come from REAL telemetry,
never from numbers the driver made up).

The report is written tmp+rename (`write_soak_report`) — it is the one
artifact a multi-hour soak leaves behind, and a torn report after a
mid-soak flap would be worse than none (GL09's whole argument).
"""

from __future__ import annotations

import json
import os
import pathlib
import time

SOAK_SCHEMA = "rmt-soak-report"
SOAK_VERSION = 1

# The terminal outcomes the SLO block accounts (serving/queue.py
# TERMINAL_STATES, spelled flat for the stdlib read side; pinned
# against the queue module by tests/test_soak.py).
SLO_COUNT_FIELDS = (
    "submitted", "done", "failed", "rejected", "expired", "quarantined",
    "retries",
)


def percentile(values, q: float) -> float | None:
    """Interpolating percentile (the telemetry.aggregate convention);
    None on no data. `q` in [0, 100]."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return None
    if len(vals) == 1:
        return vals[0]
    pos = (len(vals) - 1) * q / 100.0
    lo = int(pos)
    hi = min(lo + 1, len(vals) - 1)
    frac = pos - lo
    return vals[lo] * (1 - frac) + vals[hi] * frac


def latencies_from_streams(paths) -> dict:
    """Harvest per-request latency/deadline facts from telemetry rank
    streams: every `serve.request.done` event's `latency_s` and
    `deadline_miss`, deduped by request id (in a multi-controller
    service every rank emits the same event — one request is one
    observation, not one per rank). Done events that carry a
    per-request latency decomposition (`decomp`, `hop` — the PR-20
    request-tracing fields) are harvested alongside, same dedup. Torn
    lines are skipped (live JSONL streams)."""
    lat: dict[str, float] = {}
    misses: set[str] = set()
    decomps: dict[str, dict] = {}
    hops: dict[str, int] = {}
    for raw in paths:
        path = pathlib.Path(raw)
        if not path.is_file():
            continue
        try:
            text = path.read_text()
        except OSError:
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue  # torn tail
            if doc.get("kind") != "event" \
                    or doc.get("name") != "serve.request.done":
                continue
            rid = doc.get("request_id")
            v = doc.get("latency_s")
            if not isinstance(rid, str) \
                    or not isinstance(v, (int, float)):
                continue
            lat.setdefault(rid, float(v))
            if doc.get("deadline_miss"):
                misses.add(rid)
            if isinstance(doc.get("decomp"), dict):
                decomps.setdefault(rid, dict(doc["decomp"]))
            hop = doc.get("hop")
            if isinstance(hop, int) and not isinstance(hop, bool):
                hops.setdefault(rid, hop)
    return {
        "latencies": lat,
        "deadline_missed_done": sorted(misses),
        "decomps": decomps,
        "hops": hops,
    }


def slo_block(counters: dict, stream_paths) -> dict:
    """The report's SLO block: terminal accounting totals (summed
    serve-queue counters) + latency percentiles and the deadline-miss
    rate from the telemetry streams. A deadline miss is a request that
    either EXPIRED pending or completed past its deadline (in-flight
    lanes always finish their batch — finishing late still missed)."""
    facts = latencies_from_streams(stream_paths)
    lats = list(facts["latencies"].values())
    late_done = len(facts["deadline_missed_done"])
    submitted = int(counters.get("submitted", 0))
    expired = int(counters.get("expired", 0))
    misses = expired + late_done
    decomp_block = decomposition_block(
        facts.get("decomps") or {}, facts.get("hops") or {}
    )
    out = {
        "submitted": submitted,
        "done": int(counters.get("completed", 0)),
        "failed": int(counters.get("failed", 0)),
        "rejected": int(counters.get("rejected", 0)),
        "expired": expired,
        "quarantined": int(counters.get("quarantined", 0)),
        "retries": int(counters.get("retries", 0)),
        "latency_s": {
            "n": len(lats),
            "p50": percentile(lats, 50),
            "p99": percentile(lats, 99),
        },
        "deadline_misses": misses,
        "deadline_miss_rate": (
            round(misses / submitted, 6) if submitted else 0.0
        ),
    }
    if decomp_block is not None:
        out["decomposition"] = decomp_block
    return out


def decomposition_block(decomps: dict, hops: dict) -> dict | None:
    """The tail-latency decomposition aggregate: per-stage mean/p50/p99
    across every done request that banked a decomposition, plus the
    hop summary (how many requests re-routed across replicas). None
    when no request carried one (tracing off, or a legacy stream) —
    the soak-report schema treats the block as optional for exactly
    that reason."""
    from rocm_mpi_tpu.telemetry import tracing as _tracing

    if not decomps:
        return None
    stages: dict[str, dict] = {}
    for stage in _tracing.DECOMP_STAGES:
        vals = [
            float(d[stage]) for d in decomps.values()
            if isinstance(d.get(stage), (int, float))
        ]
        if not vals:
            continue
        stages[stage] = {
            "n": len(vals),
            "mean": round(sum(vals) / len(vals), 6),
            "p50": round(percentile(vals, 50), 6),
            "p99": round(percentile(vals, 99), 6),
        }
    hop_vals = list(hops.values())
    return {
        "n": len(decomps),
        "stages": stages,
        "hops": {
            "max": max(hop_vals) if hop_vals else 0,
            "rerouted": sum(1 for h in hop_vals if h > 0),
        },
    }


def soak_report_doc(episodes, slo: dict, *, bounded: bool,
                    accounting_ok: bool, fault_kinds=()) -> dict:
    """The schema-versioned soak report (docs/RESILIENCE.md §8):
    one row per episode of the rolling fault schedule, the aggregated
    SLO block, and the accounting verdict."""
    return {
        "schema": SOAK_SCHEMA,
        "v": SOAK_VERSION,
        # Record wall STAMP (the `t` field every telemetry record
        # carries), not an interval measurement — nothing to sync.
        # graftlint: disable-next=GL06
        "t": time.time(),
        "bounded": bool(bounded),
        "fault_kinds": sorted(set(fault_kinds)),
        "episodes": list(episodes),
        "slo": dict(slo),
        "accounting_ok": bool(accounting_ok),
    }


def validate_soak_report(doc: dict) -> list[str]:
    """Problem strings for a soak-report.json document (stdlib; shared
    with telemetry.regress --check-schema). The SLO block must be
    POPULATED — a soak that banked no latency observations proves
    nothing (the acceptance bar: real telemetry, not a shell)."""
    problems: list[str] = []
    if doc.get("schema") != SOAK_SCHEMA:
        problems.append(f"schema {doc.get('schema')!r} != {SOAK_SCHEMA}")
    if not isinstance(doc.get("v"), int):
        problems.append("missing int v")
    if not isinstance(doc.get("bounded"), bool):
        problems.append("missing bool bounded")
    if not isinstance(doc.get("accounting_ok"), bool):
        problems.append("missing bool accounting_ok")
    eps = doc.get("episodes")
    if not isinstance(eps, list) or not eps:
        problems.append("missing non-empty episodes list")
    else:
        for i, ep in enumerate(eps):
            if not isinstance(ep, dict):
                problems.append(f"episodes[{i}] not an object")
                continue
            if not isinstance(ep.get("name"), str) or not ep.get("name"):
                problems.append(f"episodes[{i}] missing name")
            if not isinstance(ep.get("ok"), bool):
                problems.append(f"episodes[{i}] missing bool ok")
    slo = doc.get("slo")
    if not isinstance(slo, dict):
        return problems + ["missing slo block"]
    for field in SLO_COUNT_FIELDS:
        v = slo.get(field)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            problems.append(f"slo.{field} {v!r} is not a count")
    lat = slo.get("latency_s")
    if not isinstance(lat, dict) or not isinstance(lat.get("n"), int):
        problems.append("slo.latency_s missing its n")
    else:
        if lat["n"] < 1:
            problems.append(
                "slo.latency_s.n == 0: the SLO block must be populated "
                "from real telemetry (no latency observations banked)"
            )
        for q in ("p50", "p99"):
            v = lat.get(q)
            if lat["n"] >= 1 and (
                not isinstance(v, (int, float)) or isinstance(v, bool)
                or v < 0
            ):
                problems.append(f"slo.latency_s.{q} {v!r} not a latency")
    rate = slo.get("deadline_miss_rate")
    if not isinstance(rate, (int, float)) or isinstance(rate, bool) \
            or not 0.0 <= rate <= 1.0:
        problems.append(
            f"slo.deadline_miss_rate {rate!r} outside [0, 1]"
        )
    problems += validate_decomposition_block(slo.get("decomposition"))
    return problems


def validate_decomposition_block(block) -> list[str]:
    """Problem strings for an slo.decomposition aggregate (None is
    fine — the block is optional: tracing off or legacy streams)."""
    from rocm_mpi_tpu.telemetry import tracing as _tracing

    if block is None:
        return []
    if not isinstance(block, dict):
        return [f"slo.decomposition {block!r} is not an object"]
    problems: list[str] = []
    n = block.get("n")
    if not isinstance(n, int) or isinstance(n, bool) or n < 1:
        problems.append(
            "slo.decomposition.n must be a positive count (an empty "
            "block should be absent, not empty)"
        )
    stages = block.get("stages")
    if not isinstance(stages, dict):
        problems.append("slo.decomposition.stages missing")
    else:
        for stage, row in stages.items():
            if stage not in _tracing.DECOMP_STAGES:
                problems.append(
                    f"slo.decomposition stage {stage!r} unknown "
                    f"(known: {list(_tracing.DECOMP_STAGES)})"
                )
            if not isinstance(row, dict):
                problems.append(
                    f"slo.decomposition.stages.{stage} not an object"
                )
                continue
            for q in ("mean", "p50", "p99"):
                v = row.get(q)
                if not isinstance(v, (int, float)) \
                        or isinstance(v, bool) or v < 0:
                    problems.append(
                        f"slo.decomposition.{stage}.{q} {v!r} not a "
                        "non-negative time"
                    )
    hops = block.get("hops")
    if not isinstance(hops, dict) or not isinstance(
        hops.get("max"), int
    ) or not isinstance(hops.get("rerouted"), int):
        problems.append("slo.decomposition.hops missing max/rerouted")
    return problems


def write_soak_report(path, doc: dict) -> None:
    """Atomic tmp+rename write (GL09 discipline: the soak report is a
    schema-versioned artifact an out-of-process reader — chip_watcher's
    archive step, the next triage — may pick up while the soak is still
    finishing)."""
    problems = validate_soak_report(doc)
    if problems:
        raise ValueError("bad soak report: " + "; ".join(problems))
    path = os.fspath(path)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
